"""Radix prefix cache over the serving engine (PR 11) (docs/SERVING.md "Radix
prefix cache"): admission math charges only the non-shared suffix, an
HTTP-valid request always fits an empty pool (cached blocks are
reclaimable, never capacity), parked requests pin their tree path,
register_prefix survives as a pinned pre-insert wrapper, op-stream
followers converge on identical tree state, and the observability
surface (/v1/stats radix block, tpuslice_serve_prefix_* metrics,
loadgen --prefix-pool) reports it all. Token identity of radix hits is
pinned in tests/test_engine_hotpath.py::TestRadixTokenIdentity; the
pure tree accounting in tests/test_kvcache.py::TestRadixIndex."""

import json
import threading
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from instaslice_tpu.metrics.metrics import ServingMetrics, render
from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.serving import AdmissionRequest, ServingEngine
from instaslice_tpu.serving.api_server import ApiServer


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


def greedy_reference(model, params, prompt, n_new):
    toks = list(prompt)
    out = []
    for _ in range(n_new):
        logits = model.apply(params, jnp.asarray(toks, jnp.int32)[None])
        t = int(jnp.argmax(logits[0, -1]))
        out.append(t)
        toks.append(t)
    return out


def _engine(model, **kw):
    m, params = model
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_len", 8)
    kw.setdefault("kv_block_size", 8)
    return ServingEngine(m, params, **kw)


def _complete(eng, prompt, steps=4):
    """Admit, decode, finish — the completion that feeds the tree."""
    rid = eng.add_request(prompt)
    eng.decode_block(steps)
    for slot, req in list(eng.slots.items()):
        if req.request_id == rid:
            eng.finish_slot(slot)
    return rid


class TestAdmissionMath:
    HEAD = list(range(1, 17))                    # two granules

    def test_cost_charges_only_the_non_shared_suffix(self, model):
        """The satellite-4 regression gate (the PR 9 over-charge class
        of bug): with the head cached, admission charges blocks for
        the suffix alone — and the scheduler-facing cost model agrees
        with what _alloc_tables actually pulls from the pool."""
        eng = _engine(model)
        _complete(eng, self.HEAD + [40, 41])
        prompt = self.HEAD + [50, 51, 52]
        cold = eng.kv.blocks_for(len(prompt) + 1)
        assert cold == 3
        assert eng.admit_block_cost(prompt, 1) == 1   # suffix only
        free0 = eng.kv.free_blocks()
        eng.add_request(prompt)
        assert free0 - eng.kv.free_blocks() == 1      # the model held
        # adapterless fork math unchanged: +1 boundary block per fork
        assert eng.admit_block_cost(prompt, 3) == 3

    def test_adapter_requests_pay_full_price(self, model):
        m, params = model
        from instaslice_tpu.models.lora import LoraConfig, init_lora

        ad = init_lora(jax.random.key(1), m.cfg, LoraConfig(rank=4))
        eng = _engine(model, lora_adapters=[ad])
        _complete(eng, self.HEAD + [40])
        prompt = self.HEAD + [50]
        assert eng.admit_block_cost(prompt, 1, adapter=1) == \
            eng.kv.blocks_for(len(prompt) + 1)

    def test_http_valid_request_always_fits_an_empty_pool(self, model):
        """Fill the pool with cached (unreferenced) tree state, then
        admit a maximum-length prompt: can_admit says yes and the
        admission op reclaims deterministically instead of failing."""
        eng = _engine(model, max_batch=2)
        # churn distinct prompts until the tree owns most of the pool
        # (each 20-token completion caches 2 granule blocks)
        for i in range(6):
            _complete(eng, [i + 1] * 20, steps=2)
        assert eng.radix.pool_blocks() >= 10
        assert not eng.slots and not eng.parked
        big = [63] * (eng.max_len - 1)                # HTTP-valid max
        assert eng.can_admit(big, 1)
        evicted0 = eng.prefix_evicted
        rid = eng.add_request(big)                    # must not raise
        # a max_len-1 prompt finishes ON admission (cache edge) — the
        # admission itself is what must have succeeded
        assert rid in {r.request_id for r in eng.finished} | \
            {r.request_id for r in eng.slots.values()}
        # the admission reclaimed cached blocks to make room
        assert eng.prefix_evicted > evicted0

    def test_can_admit_charges_the_matched_paths_own_supply(self,
                                                            model):
        """Locking the matched path removes ITS blocks from the
        evictable supply — can_admit must charge that reserve, or a
        prompt whose own cached prefix is most of what reclaim could
        free passes the check and then hard-fails allocation (the
        review-pass double-count bug). And the contract stands: a True
        can_admit always admits."""
        eng = _engine(model, max_batch=2, radix_decoded=False)
        _complete(eng, [1] * 48, steps=2)             # 6-block path
        assert eng.radix.pool_blocks() == 6
        rid = eng.add_request([5] * 61)               # 8 blocks
        slot = next(s for s, r in eng.slots.items()
                    if r.request_id == rid)
        eng.preempt_slot(slot)                        # parked: 8 held
        assert eng.kv.free_blocks() == 2
        prompt = [1] * 48 + [3] * 13                  # matches 48
        # n=2 needs 3 fresh blocks; only 2 exist once the path locks
        # (its 6 evictable blocks are the match itself) — must refuse
        assert not eng.can_admit(prompt, 2)
        # n=1 needs 2: genuinely fits, and admission must succeed
        assert eng.can_admit(prompt, 1)
        eng.add_request(prompt)                       # must not raise
        assert len(eng.slots) == 1

    def test_burst_reclaim_never_evicts_a_coadmitted_match(self,
                                                           model):
        """Review-pass repro: in one burst, request 1's reclaim (under
        block pressure) must not LRU-evict the node request 2 matched
        — every path is locked BEFORE any allocation, so request 2
        forks live blocks and its hit stays oracle-exact instead of
        serving a dead node's KV."""
        eng = _engine(model, max_batch=6, radix_decoded=False)
        # [1]*24 is the LRU path; 9 more churns crowd the pool
        for i in range(10):
            _complete(eng, [i + 1] * 24, steps=2)
        for f in (41, 42, 43):                        # live fillers
            eng.add_request([f] * 30)
        assert eng.kv.free_blocks() < 7               # r1 must reclaim
        r2_prompt = [1] * 24 + [3] * 8
        oracle = greedy_reference(*model, r2_prompt, 4)
        rid_lists = eng.add_requests([
            AdmissionRequest([50] * 55),              # no match: 7 blk
            AdmissionRequest(r2_prompt),              # matches [1]*24
        ])
        assert eng.prefix_hits == 1
        # the matched path survived the co-admitted reclaim
        assert eng.radix.match([1] * 24, 24).length == 24
        eng.decode_block(3)
        (rid2,) = rid_lists[1]
        req = next(r for r in eng.slots.values()
                   if r.request_id == rid2)
        assert req.generated == oracle

    def test_utilization_counts_shared_positions_once(self, model):
        """A hit's prefix positions live in blocks charged once — the
        gauge must not add them for the live table AND the tree (the
        old double count saturated at 1.0 for any prefix traffic)."""
        eng = _engine(model, radix_decoded=False)
        _complete(eng, [1] * 24, steps=2)             # tree: 24 tok/3 blk
        eng.add_request([1] * 24 + [3] * 8)           # hit: +2 blocks
        # resident = 33 live (24 shared counted once in the tree's 24)
        # over 5 blocks * 8 = 40 capacity
        assert eng.kv_utilization() == pytest.approx(33 / 40)

    def test_decode_growth_reclaims_cache_not_parked(self, model):
        """_sync_tables growth yields cached blocks before ensure()
        could ever see exhaustion."""
        eng = _engine(model, max_batch=2)
        for i in range(6):
            _complete(eng, [i + 1] * 12, steps=2)
        eng.add_request([50] * 30)
        eng.add_request([51] * 30)
        evicted0 = eng.prefix_evicted
        for _ in range(6):
            eng.decode_block(4)                       # grows past free
        assert eng.prefix_evicted >= evicted0         # never raised
        assert len(eng.slots) <= 2


class TestParkedPinsTree:
    def test_parked_table_locks_its_path(self, model):
        eng = _engine(model)
        head = list(range(1, 17))
        _complete(eng, head + [40, 41])
        rid = eng.add_request(head + [50, 51])        # radix hit
        assert eng.prefix_hits == 1
        slot = next(s for s, r in eng.slots.items()
                    if r.request_id == rid)
        eng.preempt_slot(slot)
        # the parked table's matched path is locked: a full reclaim
        # cannot evict the head it references
        blocks0 = eng.radix.pool_blocks()
        eng.radix.reclaim(10 ** 6)
        assert eng.radix.pool_blocks() > 0
        assert eng.radix.pool_blocks() <= blocks0
        # dropping the parked request unlocks; the path evicts
        eng.drop_parked(rid)
        assert not eng._radix_locks
        eng.radix.reclaim(10 ** 6)
        assert eng.radix.pool_blocks() == 0
        assert eng.kv.used_blocks() == 0

    def test_resume_after_park_keeps_lock_balanced(self, model):
        eng = _engine(model)
        head = list(range(1, 17))
        _complete(eng, head + [40, 41])
        rid = eng.add_request(head + [50, 51])
        slot = next(s for s, r in eng.slots.items()
                    if r.request_id == rid)
        eng.preempt_slot(slot)
        eng.resume_request(rid)
        eng.decode_block(2)
        s2 = next(s for s, r in eng.slots.items()
                  if r.request_id == rid)
        eng.finish_slot(s2)
        assert not eng._radix_locks
        deepest = eng.radix.match(head + [50, 51], 16)
        assert all(n.locks == 0 for n in deepest.path)


class TestRegisteredWrapper:
    PREFIX = list(range(1, 17))

    def test_registered_is_pinned_and_reclaim_exempt(self, model):
        eng = _engine(model)
        pinned0 = eng.kv.pinned_blocks()
        eng.register_prefix(self.PREFIX)
        assert eng.kv.pinned_blocks() == pinned0 + 2  # outside pool
        assert eng.kv.used_blocks() == 0
        assert eng.radix.reclaim(10 ** 6) == 0        # exempt
        eng.add_request(self.PREFIX + [40])
        assert eng.prefix_hits == 1
        assert eng.prefix_tokens_saved == len(self.PREFIX)

    def test_register_adopts_an_organic_path_without_prefill(self,
                                                             model):
        """When the organic cache already learned the prefix,
        registration pins it in place — no slot, no prefill, and the
        path's pool blocks MOVE outside the allocatable pool (an
        eviction-exempt path counted as allocatable capacity would
        silently break the 'registration never shrinks capacity'
        contract)."""
        eng = _engine(model)
        _complete(eng, self.PREFIX + [40, 41])
        used0 = eng.kv.used_blocks()
        # occupy EVERY slot: registration would raise if it needed one
        for i in range(eng.max_batch):
            eng.add_request([i + 30] * 4)
        live = eng.kv.used_blocks() - used0
        eng.register_prefix(self.PREFIX)
        # the 2 path blocks left the pool ledger for the pinned one
        assert eng.kv.pinned_blocks() == 2
        assert eng.kv.used_blocks() == used0 + live - 2
        assert eng.radix.pool_blocks() == used0 - 2   # rest stays pool
        assert tuple(self.PREFIX) in eng.prefixes
        assert eng.radix.reclaim(10 ** 6) == 0        # now exempt

    def test_drop_prefix_evicts_and_misses(self, model):
        eng = _engine(model)
        eng.register_prefix(self.PREFIX)
        assert eng.drop_prefix(self.PREFIX)
        assert not eng.drop_prefix(self.PREFIX)
        assert eng.kv.pinned_blocks() == 0
        eng.add_request(self.PREFIX + [7])
        assert eng.prefix_hits == 0

    def test_radix_off_keeps_exact_match_semantics(self, model):
        """--no-radix-cache: completions teach nothing, registered
        prefixes still hit — the PR 9 behavior for one release."""
        eng = _engine(model, radix_cache=False)
        _complete(eng, self.PREFIX + [40, 41])
        assert eng.prefix_inserted == 0
        assert eng.radix.node_count() == 0
        eng.add_request(self.PREFIX + [50])
        assert eng.prefix_hits == 0                   # organic: no
        eng.register_prefix(self.PREFIX)
        eng.add_request(self.PREFIX + [51])
        assert eng.prefix_hits == 1                   # registered: yes


class TestFollowerConvergence:
    def test_tree_state_converges_over_the_op_stream(self, model):
        """No radix ops exist on the wire: insertions ride the decode/
        finish ops, hits ride admissions, evictions ride whichever op
        needed blocks — replay must land both replicas on the identical
        tree (structure, blocks, ledger)."""
        from conftest import free_port
        from instaslice_tpu.serving.distributed import (
            DistributedEngine,
            run_follower,
        )

        def mk():
            return _engine(model, max_batch=4)

        driver_eng, follower_eng = mk(), mk()
        port = free_port()
        t = threading.Thread(
            target=run_follower,
            args=(follower_eng, "127.0.0.1", port), daemon=True,
        )
        t.start()
        deng = DistributedEngine(driver_eng, n_followers=1, port=port)
        head = list(range(1, 17))
        deng.add_requests([AdmissionRequest(head + [40, 41]),
                           AdmissionRequest([9, 8, 7])])
        deng.decode_block(4)
        for slot in list(driver_eng.slots):
            deng.finish_slot(slot)                    # inserts on both
        deng.add_request(head + [50, 51])             # hit on both
        deng.decode_block(2)
        deng.register_prefix([21] * 8)
        deng.shutdown()
        t.join(timeout=15)
        assert not t.is_alive()
        ds, fs = driver_eng.radix_stats(), follower_eng.radix_stats()
        assert ds == fs
        assert ds["hits"] == 1 and ds["inserted"] >= 1
        assert (driver_eng.kv.used_blocks()
                == follower_eng.kv.used_blocks())

        def shape(idx):
            out = []
            for n in sorted(idx._walk(), key=lambda n: (n.start,
                                                        n.granules[0])):
                out.append((n.start, n.end, tuple(n.granules),
                            n.locks, n.registered, n.last_used))
            return out

        assert shape(driver_eng.radix) == shape(follower_eng.radix)


class TestObservability:
    def test_stats_and_metrics_surface(self, model):
        eng = _engine(model)
        metrics = ServingMetrics()
        with ApiServer(eng, block_size=4, metrics=metrics) as srv:
            head = list(range(1, 17))
            for tail in ([40, 41], [50, 51]):
                body = json.dumps({"prompt": head + tail,
                                   "max_tokens": 4}).encode()
                req = urllib.request.Request(
                    f"{srv.url}/v1/completions", data=body,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=60) as r:
                    assert r.status == 200
            with urllib.request.urlopen(f"{srv.url}/v1/stats",
                                        timeout=10) as r:
                stats = json.loads(r.read())
        radix = stats["radix"]
        assert radix["enabled"] is True
        assert radix["hits"] == 1                     # second request
        assert radix["inserted"] >= 1
        assert radix["tokens_saved"] >= 16
        assert stats["kv"]["prefix_blocks"] == radix["blocks"] > 0
        body = render(metrics)
        if body:
            for name in ("tpuslice_serve_prefix_hits_total",
                         "tpuslice_serve_prefix_misses_total",
                         "tpuslice_serve_prefix_inserted_total",
                         "tpuslice_serve_prefix_evicted_total",
                         "tpuslice_kv_blocks_prefix"):
                assert name in body

    def test_headroom_guard_counts_evictable(self, model):
        """_ensure_block_headroom must not shed parked clients while
        the radix cache holds reclaimable blocks."""
        from instaslice_tpu.serving.scheduler import Pending, Scheduler

        eng = _engine(model, max_batch=2)
        for i in range(6):
            _complete(eng, [i + 1] * 12, steps=2)
        sched = Scheduler(eng, block_size=4)
        rid = eng.add_request([50] * 20)
        slot = next(iter(eng.slots))
        eng.preempt_slot(slot)
        parked = Pending([50] * 20, 8)
        sched._parked[rid] = parked
        sched._by_rid[rid] = parked
        eng.add_request([51] * 20)
        sched._ensure_block_headroom(8)
        assert sched.parked_shed == 0                 # cache yields 1st

    def test_loadgen_prefix_pool_report(self, model):
        from instaslice_tpu.serving.loadgen import (
            parse_prefix_pool,
            run as loadgen_run,
        )

        assert parse_prefix_pool("4:64") == (4, 64)
        with pytest.raises(ValueError, match="N:L"):
            parse_prefix_pool("4x64")
        with pytest.raises(ValueError, match=">= 1"):
            parse_prefix_pool("0:64")
        eng = _engine(model, max_len=64)
        with ApiServer(eng, block_size=4) as srv:
            report = loadgen_run(
                srv.url, requests=8, concurrency=2, prompt_len=4,
                max_tokens=4, vocab=64, stream=False, timeout=60,
                seed=3, prefix_pool="2:16",
            )
        pool = report["prefix_pool"]
        assert pool["n"] == 2 and pool["len"] == 16
        # 8 draws from 2 prefixes: at least 6 re-draws of a seen one
        assert pool["reused"] >= 6
        assert pool["reused_fraction"] == round(pool["reused"] / 8, 4)
        assert report["ok"] == 8
        assert eng.prefix_hits > 0                    # server-side too

    def test_loadgen_cli_flag(self, model, capsys):
        from instaslice_tpu.serving.loadgen import main as lg_main

        assert lg_main(["--url", "http://x", "--prefix-pool",
                        "nope"]) == 1
        out = json.loads(capsys.readouterr().out)
        assert "bad --prefix-pool" in out["error"]
