"""Slice health monitoring e2e on the simulated cluster.

No reference analog: SURVEY.md §5 flags "no health monitoring of slices"
as a gap this framework closes. The agent's periodic sweep publishes
failed chips to the CR status, the controller's placement avoids them,
in-flight allocations touching them are failed-and-retried, and granted
pods are annotated or (opt-in) evicted for elastic recovery.
"""

import time

import pytest

from instaslice_tpu.controller.gates import (
    RESTART_ON_FAILURE_ANNOTATION,
    UNHEALTHY_ANNOTATION,
)
from instaslice_tpu.sim import SimCluster


@pytest.fixture
def cluster():
    c = SimCluster(n_nodes=1, generation="v5e",
                   deletion_grace_seconds=0.2,
                   health_interval=0.1).start()
    yield c
    c.stop()


def wait_for(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.03)
    return False


class TestHealthPublication:
    def test_failed_chip_published_and_healed(self, cluster):
        cluster.backends["node-0"].fail_chip(5)
        assert wait_for(lambda: cluster.unhealthy_chips("node-0") == [5])
        cluster.backends["node-0"].heal_chip(5)
        assert wait_for(lambda: cluster.unhealthy_chips("node-0") == [])


class TestPlacementAvoidance:
    def test_new_grants_avoid_failed_chip(self, cluster):
        cluster.backends["node-0"].fail_chip(0)
        assert wait_for(lambda: cluster.unhealthy_chips("node-0") == [0])
        cluster.submit("p", "v5e-1x1")
        assert cluster.wait_phase("p", "Running", timeout=10)
        res = cluster.backends["node-0"].list_reservations()
        assert len(res) == 1 and 0 not in res[0].chip_ids

    def test_full_host_profile_unplaceable_with_dead_chip(self, cluster):
        cluster.backends["node-0"].fail_chip(3)
        assert wait_for(lambda: cluster.unhealthy_chips("node-0") == [3])
        cluster.submit("big", "v5e-4x2")  # needs all 8 chips
        time.sleep(0.5)
        assert cluster.pod_phase("big") == "Pending"
        # healing the chip lets the pending pod through
        cluster.backends["node-0"].heal_chip(3)
        assert cluster.wait_phase("big", "Running", timeout=10)


class TestGrantedSliceFailure:
    def test_pod_annotated_by_default(self, cluster):
        cluster.submit("victim", "v5e-2x2")
        assert cluster.wait_phase("victim", "Running", timeout=10)
        chips = cluster.backends["node-0"].list_reservations()[0].chip_ids
        cluster.backends["node-0"].fail_chip(chips[0])

        def annotated():
            ann = cluster.pod("victim")["metadata"].get("annotations", {})
            return "unhealthy" in ann.get(UNHEALTHY_ANNOTATION, "")

        assert wait_for(annotated)
        # no opt-in → not evicted
        assert cluster.pod_phase("victim") == "Running"
        # healing the chip must clear the stale degraded marker
        cluster.backends["node-0"].heal_chip(chips[0])
        assert wait_for(
            lambda: UNHEALTHY_ANNOTATION
            not in cluster.pod("victim")["metadata"].get("annotations", {})
        )

    def test_opt_in_eviction_and_regrant_on_healthy_chips(self, cluster):
        """Elastic recovery: the evicted pod's replacement (Deployment
        respawn analog) lands on healthy chips only."""
        cluster.submit(
            "victim", "v5e-2x2",
            annotations={RESTART_ON_FAILURE_ANNOTATION: "true"},
        )
        assert cluster.wait_phase("victim", "Running", timeout=10)
        dead = cluster.backends["node-0"].list_reservations()[0].chip_ids[0]
        cluster.backends["node-0"].fail_chip(dead)
        assert cluster.wait_gone("victim", timeout=10)
        # old reservation fully released
        assert wait_for(
            lambda: cluster.backends["node-0"].list_reservations() == []
        )
        # respawn: same workload, fresh pod
        cluster.submit(
            "victim", "v5e-2x2",
            annotations={RESTART_ON_FAILURE_ANNOTATION: "true"},
        )
        assert cluster.wait_phase("victim", "Running", timeout=10)
        res = cluster.backends["node-0"].list_reservations()
        assert len(res) == 1 and dead not in res[0].chip_ids


class TestMultiHostSliceHealth:
    """A multi-host slice is only healthy as a whole: chip death on ONE
    host must signal (or evict) the worker pods on EVERY host."""

    @pytest.fixture
    def cluster2(self):
        c = SimCluster(n_nodes=2, generation="v5e", shared_torus=True,
                       deletion_grace_seconds=0.2,
                       health_interval=0.1).start()
        yield c
        c.stop()

    def test_all_group_pods_annotated(self, cluster2):
        cluster2.submit("w-0", "v5e-4x4", group="j", group_size=2)
        cluster2.submit("w-1", "v5e-4x4", group="j", group_size=2)
        assert cluster2.wait_phase("w-0", "Running", timeout=20)
        assert cluster2.wait_phase("w-1", "Running", timeout=20)
        cluster2.backends["node-0"].fail_chip(2)

        def both_annotated():
            return all(
                "node-0" in (
                    cluster2.pod(n)["metadata"].get("annotations", {})
                    .get(UNHEALTHY_ANNOTATION, "")
                )
                for n in ("w-0", "w-1")
            )

        assert wait_for(both_annotated)
        # both keep running (no opt-in), including the healthy-host pod
        assert cluster2.pod_phase("w-0") == "Running"
        assert cluster2.pod_phase("w-1") == "Running"

    def test_opt_in_evicts_whole_group(self, cluster2):
        ann = {RESTART_ON_FAILURE_ANNOTATION: "true"}
        cluster2.submit("w-0", "v5e-4x4", group="j", group_size=2,
                        annotations=ann)
        cluster2.submit("w-1", "v5e-4x4", group="j", group_size=2,
                        annotations=ann)
        assert cluster2.wait_phase("w-0", "Running", timeout=20)
        assert cluster2.wait_phase("w-1", "Running", timeout=20)
        cluster2.backends["node-1"].fail_chip(0)
        # BOTH workers evicted — including the one on the healthy host
        assert cluster2.wait_gone("w-0", timeout=15)
        assert cluster2.wait_gone("w-1", timeout=15)
        assert wait_for(lambda: all(
            not b.list_reservations()
            for b in cluster2.backends.values()
        ))


class TestInFlightFailure:
    def test_creating_allocation_failed_and_retried(self, cluster):
        """A chip dying between placement and realization fails the
        allocation; the controller tears it down and retries on healthy
        chips (the reference logged device errors and carried on —
        instaslice_daemonset.go:172-189)."""
        # Make the first reserve fail as if the chip died mid-flight; the
        # retry must succeed and avoid nothing (chip healed by then).
        cluster.backends["node-0"].inject_failures("reserve", 1)
        cluster.submit("p", "v5e-1x1")
        assert cluster.wait_phase("p", "Running", timeout=15)
        assert len(cluster.backends["node-0"].list_reservations()) == 1


class TestGrantMetricOnce:
    def test_grant_latency_observed_once_despite_recovery_rerun(self):
        """The crash-recovery path re-runs _ungate_all with a stale
        in-memory CREATED status; the grant histogram must key on the CR
        transition actually landing, not the stale copy."""
        import copy

        from instaslice_tpu.api.types import AllocationStatus
        from instaslice_tpu.metrics.metrics import OperatorMetrics
        from instaslice_tpu.sim import SimCluster

        m = OperatorMetrics()
        if m.registry is None:
            pytest.skip("prometheus_client unavailable")
        with SimCluster(n_nodes=1, metrics=m) as sim:
            sim.submit("demo", "v5e-1x1")
            assert sim.wait_phase("demo", "Running", timeout=10)
            alloc = None
            for ts in sim.kube.list("TpuSlice", namespace=sim.namespace):
                from instaslice_tpu.api.types import TpuSlice

                for a in TpuSlice.from_manifest(ts).spec.allocations.values():
                    alloc = a
            assert alloc is not None
            assert alloc.status == AllocationStatus.UNGATED
            # replay the recovery path's stale view: in-memory CREATED,
            # CR already UNGATED → mutate is a no-op → no second observe
            stale = copy.deepcopy(alloc)
            stale.status = AllocationStatus.CREATED  # bypass legality: simulating staleness
            sim.controller._ungate_all(stale)
        count = m.registry.get_sample_value("tpuslice_grant_seconds_count")
        assert count == 1.0, count
