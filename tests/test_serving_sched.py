"""Continuous-batching scheduler over the real engine: preempt/resume
KV round-trips (token-identical to uninterrupted decode), block-pool
admission pressure, SLO preemption end to end over HTTP, the fixed-
round baseline mode, tenanted loadgen reports, and the paged-vs-legacy
kv-utilization split (docs/SERVING.md "Continuous batching & tenant
SLOs")."""

import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from instaslice_tpu.api.constants import (
    REASON_PREEMPTED,
    REASON_RESUMED,
    REASON_SLO_MISSED,
)
from instaslice_tpu.metrics.metrics import ServingMetrics, render
from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.obs.journal import get_journal, reset_journal
from instaslice_tpu.serving import ServingEngine
from instaslice_tpu.serving.api_server import ApiServer


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


@pytest.fixture(autouse=True)
def fresh_journal():
    reset_journal()
    yield
    reset_journal()


def greedy_reference(model, params, prompt, n_new):
    toks = list(prompt)
    out = []
    for _ in range(n_new):
        logits = model.apply(params, jnp.asarray(toks, jnp.int32)[None])
        t = int(jnp.argmax(logits[0, -1]))
        out.append(t)
        toks.append(t)
    return out


def post(url, payload, timeout=120, headers=None):
    h = {"Content-Type": "application/json"}
    h.update(headers or {})
    req = urllib.request.Request(
        f"{url}/v1/completions", data=json.dumps(payload).encode(),
        headers=h, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


class TestPreemptResumeEngine:
    def test_roundtrip_token_identical(self, model):
        """Park a mid-decode request, run someone else through its
        slot, resume — the final chain must equal uninterrupted greedy
        decode (the stripe write restored position-exact KV)."""
        m, params = model
        oracle = greedy_reference(m, params, [5, 9, 2, 7], 12)
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8, kv_block_size=8)
        rid = eng.add_request([5, 9, 2, 7])
        for _ in range(4):
            eng.step()
        assert eng.preempt_slot(0) == rid
        assert not eng.slots and rid in eng.parked
        assert eng.preempted_total == 1
        # the slot serves someone else meanwhile (dirties the stripe)
        other = eng.add_request([11, 13, 17])
        for _ in range(6):
            eng.step()
        eng.finish_slot(next(iter(eng.slots)))
        assert eng.finished[-1].request_id == other
        slot = eng.resume_request(rid)
        assert slot == 0 and eng.resumed_total == 1
        for _ in range(7):
            eng.step()
        req = eng.slots[0]
        assert req.generated == oracle

    def test_parked_blocks_held_then_freed(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=32,
                            prefill_len=8, kv_block_size=8)
        rid = eng.add_request(list(range(1, 9)))
        used = eng.kv.used_blocks()
        assert used >= 1
        eng.preempt_slot(0)
        # parked keeps its blocks (cheap resume)...
        assert eng.kv.used_blocks() == used
        assert eng.kv_stats()["parked"] == 1
        # ...and dropping frees them on the spot
        assert eng.drop_parked(rid)
        assert eng.kv.used_blocks() == 0
        assert not eng.drop_parked(rid)

    def test_can_admit_gates_on_blocks_not_just_slots(self, model):
        m, params = model
        # pool: (2 * 32) / 8 = 8 blocks
        eng = ServingEngine(m, params, max_batch=2, max_len=32,
                            prefill_len=8, kv_block_size=8)
        r1 = eng.add_request(list(range(1, 25)))     # 3-4 blocks
        eng.preempt_slot(0)
        r2 = eng.add_request(list(range(1, 25)))
        eng.preempt_slot(0)
        # both slots free, but parked state holds most of the pool
        assert eng.free_slots() == 2
        assert not eng.can_admit(24, 2)
        eng.drop_parked(r1)
        eng.drop_parked(r2)
        assert eng.can_admit(24, 2)

    def test_resume_requires_free_slot_and_parked_rid(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=32,
                            prefill_len=8)
        rid = eng.add_request([1, 2, 3])
        with pytest.raises(ValueError, match="not parked"):
            eng.resume_request(rid + 99)
        eng.preempt_slot(0)
        eng.add_request([4, 5, 6])
        with pytest.raises(RuntimeError, match="free slot"):
            eng.resume_request(rid)

    def test_failed_resume_leaves_rid_droppable(self, model):
        """A device failure mid-resume must not leak the block table:
        the rid stays parked until the stripe writes land, so the
        scheduler's cleanup (drop_parked) still finds and frees it."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=32,
                            prefill_len=8, kv_block_size=8)
        rid = eng.add_request([1, 2, 3, 4])
        eng.preempt_slot(0)
        calls = {"n": 0}
        real = eng._write_stripe

        def flaky(cache, stripe, slot, start):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("RESOURCE_EXHAUSTED: injected")
            return real(cache, stripe, slot, start)

        eng._write_stripe = flaky
        with pytest.raises(RuntimeError, match="injected"):
            eng.resume_request(rid)
        assert rid in eng.parked          # still findable
        assert eng.drop_parked(rid)       # blocks come back
        assert eng.kv.used_blocks() == 0

    def test_recover_keeps_parked_stripes(self, model):
        """Parked stripes are independent copies like prefixes: an
        engine recovery (poisoned cache) must not lose them."""
        m, params = model
        oracle = greedy_reference(m, params, [5, 9, 2, 7], 8)
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        rid = eng.add_request([5, 9, 2, 7])
        for _ in range(3):
            eng.step()
        eng.preempt_slot(0)
        victim = eng.add_request([9, 9, 9])
        lost = eng.recover()
        assert lost == [victim]
        assert rid in eng.parked
        eng.resume_request(rid)
        for _ in range(4):
            eng.step()
        assert eng.slots[0].generated == oracle


class TestSloSchedulerHttp:
    def test_latency_class_preempts_best_effort(self, model):
        """One slot; a best-effort request decoding 48 tokens; a
        latency-class request arrives and must be served via
        preemption LONG before the best-effort one finishes — and the
        preempted request still completes with oracle-exact tokens."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8, kv_block_size=8)
        metrics = ServingMetrics()
        tenants = "gold:4:latency:5.0,bronze:1:best-effort"
        with ApiServer(eng, block_size=4, metrics=metrics,
                       tenants=tenants, preempt_margin=0.02,
                       request_timeout=60) as srv:
            # warm the compiled programs so preemption timing below is
            # about scheduling, not jit compiles
            code, _ = post(srv.url, {"prompt": [1, 2, 3],
                                     "max_tokens": 2})
            assert code == 200
            results = {}

            def bronze():
                results["bronze"] = post(
                    srv.url, {"prompt": [5, 9, 2, 7],
                              "max_tokens": 48},
                    headers={"X-Tenant": "bronze"},
                )

            t = threading.Thread(target=bronze, daemon=True)
            t.start()
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and not eng.slots:
                time.sleep(0.01)
            assert eng.slots, "bronze never admitted"
            t0 = time.monotonic()
            code, out = post(srv.url, {"prompt": [9, 3, 1],
                                       "max_tokens": 4},
                             headers={"X-Tenant": "gold"})
            gold_latency = time.monotonic() - t0
            assert code == 200, out
            assert out["choices"][0]["token_ids"] == greedy_reference(
                m, params, [9, 3, 1], 4
            )
            t.join(timeout=60)
            assert not t.is_alive(), "preempted request hung"
            code, out = results["bronze"]
            assert code == 200, out
            # the parked-and-resumed chain is exact
            assert out["choices"][0]["token_ids"] == greedy_reference(
                m, params, [5, 9, 2, 7], 48
            )
            stats = srv.scheduler.stats()
            assert stats["preempted"] >= 1
            assert stats["resumed"] >= 1
            assert srv.scheduler.preempted == eng.preempted_total
            # journal ledger reconciles with the scheduler counters
            jc = get_journal().counts()
            assert jc.get(REASON_PREEMPTED, 0) == stats["preempted"]
            assert jc.get(REASON_RESUMED, 0) == stats["resumed"]
            # gold didn't wait out bronze's 48 tokens
            assert gold_latency < 30
            body = render(metrics)
            if body:
                assert "tpuslice_serve_preemptions_total" in body
                assert ('tpuslice_serve_class_ttft_seconds_count'
                        '{tenant_class="latency"}') in body

    def test_slo_miss_journaled(self, model):
        """An impossible TTFT target must produce an SLOMissed event
        and count on the slo_missed ledger — attainment is measured,
        not assumed."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        tenants = "instant:1:latency:0.000001"
        with ApiServer(eng, block_size=4, tenants=tenants) as srv:
            code, _ = post(srv.url, {"prompt": [5, 9, 2], "max_tokens": 4},
                           headers={"X-Tenant": "instant"})
            assert code == 200
            assert srv.scheduler.slo_misses >= 1
            evs = get_journal().events(reason=REASON_SLO_MISSED)
            assert evs and "ttft" in evs[0].message

    def test_fixed_mode_still_serves_oracle(self, model):
        """The bench baseline: FIFO + full-block rounds — slower, but
        byte-identical results and a visible mode in /v1/stats."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        with ApiServer(eng, block_size=4, mode="fixed") as srv:
            code, out = post(srv.url, {"prompt": [5, 9, 2, 7],
                                       "max_tokens": 6})
            assert code == 200
            assert out["choices"][0]["token_ids"] == greedy_reference(
                m, params, [5, 9, 2, 7], 6
            )
            with urllib.request.urlopen(f"{srv.url}/v1/stats",
                                        timeout=10) as r:
                stats = json.loads(r.read())
            assert stats["mode"] == "fixed"
            assert stats["preempted"] == 0

    def test_stats_expose_kv_and_tenants(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, kv_block_size=16)
        with ApiServer(eng, tenants="gold:2:latency:1.0") as srv:
            code, _ = post(srv.url, {"prompt": [1, 2, 3, 4],
                                     "max_tokens": 2})
            assert code == 200
            with urllib.request.urlopen(f"{srv.url}/v1/stats",
                                        timeout=10) as r:
                stats = json.loads(r.read())
            assert stats["tenant_classes"] == {"gold": "latency"}
            kv = stats["kv"]
            assert kv["total"] == (2 * 64) // 16
            assert {"free", "used", "cow", "utilization"} <= set(kv)
            # the one-release migration window PR 9 promised is over
            assert "utilization_legacy" not in kv


class TestKvUtilizationSplit:
    def test_paged_metric_truthful_and_legacy_retired(self, model):
        """The paged metric reports occupancy of the blocks actually
        held (high at mixed sequence lengths); the pre-paging stripe
        metric finished its one-release migration window and is GONE —
        from the engine, the gauge set, and /v1/stats."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8, kv_block_size=8)
        eng.add_request([1, 2, 3])                     # short
        eng.add_request(list(range(1, 41)))            # long
        paged = eng.kv_utilization()
        assert paged >= 0.5
        # would have read (4 + 41) / (4 * 64) ≈ 0.18 on the retired
        # whole-rectangle metric — the paged one sees real occupancy
        assert not hasattr(eng, "kv_utilization_legacy")
        assert "utilization_legacy" not in eng.kv_stats()
        from instaslice_tpu.metrics.metrics import ServingMetrics
        assert not hasattr(ServingMetrics(), "kv_cache_utilization_legacy")

    def test_prefix_fork_shows_cow_blocks(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8, kv_block_size=8)
        prefix = list(range(1, 17))                    # two chunks
        eng.register_prefix(prefix)
        assert eng.kv.pinned_blocks() == 2
        eng.add_request(prefix + [40, 41])
        assert eng.prefix_hits == 1
        stats = eng.kv_stats()
        assert stats["cow"] >= 1                       # shared blocks


class TestTenantLoadgen:
    def test_report_has_per_tenant_slo_attainment(self, model):
        from instaslice_tpu.serving.loadgen import run

        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8)
        spec = "gold:3:latency:30,bronze:1:best-effort:30"
        with ApiServer(eng, block_size=4, tenants=spec) as srv:
            out = run(srv.url, requests=10, concurrency=3,
                      prompt_len=6, max_tokens=5, vocab=64,
                      stream=True, timeout=120, seed=3, tenants=spec)
            assert out["ok"] == 10 and out["errors"] == 0
            tens = out["tenants"]
            assert set(tens) == {"gold", "bronze"}
            total = sum(t["requests"] for t in tens.values())
            assert total == 10
            for t in tens.values():
                assert t["ok"] == t["requests"]
                assert 0.0 <= t["slo_attainment"] <= 1.0
                assert t["ttft_p95"] >= t["ttft_p50"] >= 0
            # generous 30 s targets on a warm tiny model: attainment
            # must be perfect, or the measurement itself is broken
            assert tens["gold"]["slo_attainment"] == 1.0

    def test_cli_flag_round_trip(self, model, capsys):
        from instaslice_tpu.serving.loadgen import main as lg_main

        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8)
        with ApiServer(eng, block_size=4) as srv:
            rc = lg_main(["--url", srv.url, "--requests", "4",
                          "--concurrency", "2", "--prompt-len", "6",
                          "--max-tokens", "4", "--vocab", "64",
                          "--tenants", "a:1:latency:30,b:1:standard"])
        out = json.loads(capsys.readouterr().out.strip())
        assert rc == 0
        assert set(out["tenants"]) <= {"a", "b"}
        bad = lg_main(["--url", "http://x", "--tenants", "a:z:latency"])
        err = json.loads(capsys.readouterr().out.strip())
        assert bad == 1 and "bad --tenants" in err["error"]


class TestDistributedPreemptOps:
    def test_follower_replays_preempt_resume_drop(self, model):
        """preempt/resume/drop ride the op stream: after a preempt →
        fill → resume sequence the follower's slot, parked, and block-
        pool state converge to the driver's exactly (the SPMD
        requirement — slot occupancy feeds the compiled decode)."""
        from conftest import free_port
        from instaslice_tpu.serving.distributed import (
            DistributedEngine,
            run_follower,
        )

        m, params = model
        driver_eng = ServingEngine(m, params, max_batch=2, max_len=64,
                                   prefill_len=8, kv_block_size=8)
        follower_eng = ServingEngine(m, params, max_batch=2, max_len=64,
                                     prefill_len=8, kv_block_size=8)
        port = free_port()
        t = threading.Thread(
            target=run_follower,
            args=(follower_eng, "127.0.0.1", port), daemon=True,
        )
        t.start()
        deng = DistributedEngine(driver_eng, n_followers=1, port=port)
        rid = deng.add_request([5, 9, 2, 7])
        deng.decode_block(3)
        assert deng.preempt_slot(0) == rid
        other = deng.add_request([11, 13, 17])
        deng.decode_block(2)
        deng.evict_slot(0)
        slot = deng.resume_request(rid)
        deng.decode_block(2)
        rid2 = deng.add_request([1, 2, 3])
        assert deng.preempt_slot(
            next(s for s, r in driver_eng.slots.items()
                 if r.request_id == rid2)
        ) == rid2
        assert deng.drop_parked(rid2)
        deng.shutdown()
        t.join(timeout=15)
        assert not t.is_alive()
        # replica convergence: same slots, same tokens, same parked
        # set, same block-pool occupancy
        assert set(follower_eng.slots) == set(driver_eng.slots) == {slot}
        assert (follower_eng.slots[slot].generated
                == driver_eng.slots[slot].generated)
        assert set(follower_eng.parked) == set(driver_eng.parked) == set()
        assert (follower_eng.kv.used_blocks()
                == driver_eng.kv.used_blocks())
        assert other not in follower_eng.slots


class TestBlockPressureRelief:
    def test_block_starved_latency_waiter_sheds_parked(self, model):
        """The livelock guard: a parked best-effort request holds the
        pool, a slot is FREE, and a latency-class waiter cannot admit
        for lack of blocks — slot-preemption doesn't apply (nothing to
        preempt) and resume refuses to hand the blocks' owner the
        slot, so the scheduler must shed the parked state or the
        waiter spins to its HTTP timeout."""
        from instaslice_tpu.serving.scheduler import Pending, Scheduler

        m, params = model
        # pool: 1 * ceil(32/8) = 4 blocks
        eng = ServingEngine(m, params, max_batch=1, max_len=32,
                            prefill_len=8, kv_block_size=8)
        sched = Scheduler(
            eng, block_size=4, preempt_margin=0.0,
            tenants="gold:1:latency:5.0,bronze:1:best-effort",
        )
        pb = Pending(list(range(1, 22)), 2, tenant="bronze")
        sched.submit(pb)
        sched._pump()
        sched._admit()
        assert len(eng.slots) == 1
        # park bronze exactly as _maybe_preempt would: engine parks,
        # scheduler tracks — it now holds 3 of 4 blocks, slot free
        rid = next(iter(eng.slots.values())).request_id
        rid_parked = eng.preempt_slot(next(iter(eng.slots)))
        assert rid_parked == rid
        sched._parked[rid] = pb
        assert eng.free_slots() == 1
        pg = Pending(list(range(1, 10)), 4, tenant="gold")
        sched.submit(pg)
        assert not eng.can_admit(len(pg.prompt), 1)   # block-starved
        for _ in range(30):
            sched._round()
            if pg.done.is_set():
                break
        assert pg.done.is_set(), "latency waiter livelocked"
        assert not pg.error and pg.results
        # bronze was shed cleanly under block pressure, blocks freed
        assert pb.done.is_set()
        assert pb.shed == "evicted"
        assert "block pressure" in pb.error
        assert rid not in eng.parked
        assert sched.parked_shed == 1
