"""Partition-tolerant control & serving planes (docs/RECOVERY.md
"Partitions & gray failures"): network nemesis, lease-epoch write
fencing, degraded/static mode, and gray-failure ejection.

Fast units cover the :class:`NemesisPlan` grammar/determinism/timed
heal, the :class:`NemesisKubeClient` one-way partition semantics, the
informer under duplicated/reordered watch deliveries + an injected 410
(satellite: the stale-replay rv guard and index memos must hold), the
lease-epoch fence refusing a deposed leader's writes, the circuit
breaker's single half-open probe, the router's jittered poll backoff +
Retry-After handling, gray ejection/readmission/hedged polls, the
agent's static mode, and ``validate_events.check_nemesis``.

The ``smoke`` tests (the ``make chaos-partition-smoke`` gate inside
``make test``) run the two acceptance scenarios end to end: partition
the controller → failover/heal → converge with zero double
allocations; inject 3× latency into a 100%-success replica → EWMA
ejection → sessions drain via migration → re-admit after heal.
"""

import json
import os
import sys
import threading
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import validate_events  # noqa: E402

from instaslice_tpu.api.constants import (
    REASON_APISERVER_UNREACHABLE,
    REASON_DEGRADED_ENTERED,
    REASON_DEGRADED_EXITED,
    REASON_REPLICA_EJECTED,
    REASON_REPLICA_READMITTED,
    REASON_WRITE_FENCED,
    WRITER_EPOCH_ANNOTATION,
)
from instaslice_tpu.faults.netchaos import (
    NemesisKubeClient,
    NemesisPlan,
    PartitionError,
    get_nemesis,
    set_nemesis,
)
from instaslice_tpu.kube.client import Fenced, update_with_retry
from instaslice_tpu.kube.fake import FakeKube
from instaslice_tpu.kube.informer import Informer
from instaslice_tpu.kube.real import CircuitBreaker, CircuitOpen
from instaslice_tpu.obs.journal import get_journal, reset_journal
from instaslice_tpu.serving.router import Replica, Router, _median
from instaslice_tpu.utils.election import EpochFence, LeaderElector

CHAOS_SEED = int(os.environ.get("CHAOS_SEED", "1234"))


@pytest.fixture(autouse=True)
def _clean_nemesis():
    set_nemesis(None)
    reset_journal()
    yield
    set_nemesis(None)
    reset_journal()


def journal_reasons():
    return [e.reason for e in get_journal().events()]


def wait_for(pred, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ------------------------------------------------------------- the plan


class TestNemesisPlan:
    def test_env_grammar(self):
        plan = NemesisPlan.from_env(
            "seed=7;controller>apiserver:kind=partition,duration=2;"
            "apiserver>agent-*:kind=dup,p=0.5;"
            "router>replica:http://x:1:kind=latency,delay=0.1,jitter=0.05"
        )
        assert plan.seed == 7
        kinds = {(r.src, r.dst): r.kind for r in plan.rules}
        assert kinds[("controller", "apiserver")] == "partition"
        assert kinds[("apiserver", "agent-*")] == "dup"
        # the LAST ':' splits rule body from the link, so URL-bearing
        # destinations survive
        assert kinds[("router", "replica:http://x:1")] == "latency"
        assert NemesisPlan.from_env("") is None
        with pytest.raises(ValueError):
            NemesisPlan.from_env("controller>apiserver:p=0.5")  # no kind
        with pytest.raises(ValueError):
            NemesisPlan.from_env("garbage")

    def test_partition_symmetric_vs_oneway(self):
        plan = NemesisPlan(CHAOS_SEED)
        plan.partition("a", "b")
        assert plan.is_partitioned("a", "b")
        assert plan.is_partitioned("b", "a")  # symmetric severs both
        plan.heal()
        plan.partition_oneway("a", "b")
        assert plan.is_partitioned("a", "b")
        assert not plan.is_partitioned("b", "a")
        with pytest.raises(PartitionError):
            plan.before_request("a", "b")
        plan.before_request("b", "a")  # reverse direction flows

    def test_seeded_determinism(self):
        def fires(seed):
            plan = NemesisPlan(seed)
            rule = plan.drop("a", "b", p=0.5)
            out = []
            for _ in range(50):
                try:
                    plan.before_request("a", "b")
                    out.append(0)
                except PartitionError:
                    out.append(1)
            assert rule.fired == sum(out)
            return out

        assert fires(CHAOS_SEED) == fires(CHAOS_SEED)
        assert fires(CHAOS_SEED) != fires(CHAOS_SEED + 1)

    def test_timed_heal(self):
        plan = NemesisPlan(CHAOS_SEED)
        plan.partition("a", "b", duration=0.15)
        with pytest.raises(PartitionError):
            plan.before_request("a", "b")
        assert wait_for(lambda: not plan.is_partitioned("a", "b"),
                        timeout=2.0)
        plan.before_request("a", "b")  # healed: flows again

    def test_force_heal_and_stats(self):
        plan = NemesisPlan(CHAOS_SEED)
        plan.partition("a", "b")
        plan.drop("c", "d", p=1.0)
        assert plan.heal("a", "b") == 1
        assert not plan.is_partitioned("a", "b")
        with pytest.raises(PartitionError):
            plan.before_request("c", "d")
        assert plan.heal() == 1  # remaining drop rule
        links = {s["link"]: s for s in plan.stats()}
        assert links["a>b"]["healed"] and links["c>d"]["fired"] == 1

    def test_throttle_and_max_fires(self):
        plan = NemesisPlan(CHAOS_SEED)
        plan.throttle("a", "b", rate_bps=1e6)
        t0 = time.monotonic()
        plan.throttle_sleep("a", "b", 100_000)  # 0.1s at 1MB/s
        assert time.monotonic() - t0 >= 0.09
        plan.heal()
        plan.drop("a", "b", p=1.0, max_fires=1)
        with pytest.raises(PartitionError):
            plan.before_request("a", "b")
        plan.before_request("a", "b")  # cap exhausted


class TestNemesisKubeClient:
    def _client(self, plan, ident="controller"):
        kube = FakeKube()
        return kube, NemesisKubeClient(kube, plan, ident)

    def _mk(self, kube, name, rv_churn=0):
        kube.create("TpuSlice", {
            "apiVersion": "v1", "kind": "TpuSlice",
            "metadata": {"namespace": "ns", "name": name},
            "spec": {},
        })
        for _ in range(rv_churn):
            obj = kube.get("TpuSlice", "ns", name)
            kube.update("TpuSlice", obj)

    def test_oneway_partition_is_asymmetric(self):
        plan = NemesisPlan(CHAOS_SEED)
        kube, client = self._client(plan)
        self._mk(kube, "n0")
        # cut ONLY controller→apiserver: verbs fail...
        plan.partition_oneway("controller", "apiserver")
        with pytest.raises(PartitionError):
            client.get("TpuSlice", "ns", "n0")
        plan.heal()
        # ...cut ONLY apiserver→controller: verbs flow, the watch
        # stream is disconnected mid-flight instead
        plan.partition_oneway("apiserver", "controller")
        assert client.get("TpuSlice", "ns", "n0")
        seen = list(client.watch("TpuSlice", namespace="ns",
                                 timeout=0.1))
        assert seen == []  # stream cut before the first delivery

    def test_dup_and_expire_injection(self):
        from instaslice_tpu.kube.client import ResourceVersionExpired

        plan = NemesisPlan(CHAOS_SEED)
        kube, client = self._client(plan)
        self._mk(kube, "n0")
        plan.rule("apiserver", "controller", "dup")
        evs = [e for e, o in client.watch("TpuSlice", namespace="ns",
                                          timeout=0.1)
               if e != "BOOKMARK"]
        assert len(evs) == 2  # every delivery duplicated
        plan.heal()
        plan.rule("apiserver", "controller", "expire", max_fires=1)
        with pytest.raises(ResourceVersionExpired):
            list(client.watch("TpuSlice", namespace="ns", timeout=0.1))


# --------------------------------------------- informer under nemesis


class TestInformerUnderNemesis:
    def _group(self, obj):
        return [obj.get("spec", {}).get("group", "")]

    def test_dup_reorder_and_410_converge(self):
        """Duplicated + reordered deliveries and an injected 410
        mid-stream must not regress the rv guard (stale replays
        ignored) or spuriously invalidate index memos of untouched
        buckets."""
        plan = NemesisPlan(CHAOS_SEED)
        kube = FakeKube()
        client = NemesisKubeClient(kube, plan, "controller")

        def mk(name, group, gen=0):
            kube.create("TpuSlice", {
                "apiVersion": "v1", "kind": "TpuSlice",
                "metadata": {"namespace": "ns", "name": name},
                "spec": {"group": group, "gen": gen},
            })

        mk("stable-0", "a")
        mk("churn-0", "b")
        inf = Informer(client, "TpuSlice", namespace="ns",
                       resync_period=0.5,
                       indexers={"group": self._group}).start()
        try:
            assert inf.wait_synced(10)
            v_a = inf.index_version("group", "a")
            plan.watch_chaos("apiserver", "controller",
                             dup_p=0.5, reorder_p=0.3)
            plan.rule("apiserver", "controller", "expire", max_fires=1)
            for i in range(20):
                obj = kube.get("TpuSlice", "ns", "churn-0")
                obj["spec"]["gen"] = i + 1
                kube.update("TpuSlice", obj)
            assert wait_for(
                lambda: (inf.get("ns", "churn-0") or {})
                .get("spec", {}).get("gen") == 20,
                timeout=15,
            ), (inf.get("ns", "churn-0"), plan.stats())
            plan.heal()
            # rv guard: a duplicated delivery of churn's final version
            # never bumped the store again, and the untouched bucket's
            # memo version is EXACTLY where it started — chaos on "b"
            # didn't invalidate "a"
            assert inf.index_version("group", "a") == v_a
            truth = {o["metadata"]["name"]
                     for o in kube.list("TpuSlice", namespace="ns")}
            assert {o["metadata"]["name"]
                    for o in inf.list()} == truth
        finally:
            inf.stop()

    def test_disconnect_then_heal_replays_missed_events(self):
        plan = NemesisPlan(CHAOS_SEED)
        kube = FakeKube()
        client = NemesisKubeClient(kube, plan, "controller")
        kube.create("TpuSlice", {
            "apiVersion": "v1", "kind": "TpuSlice",
            "metadata": {"namespace": "ns", "name": "n0"},
            "spec": {"gen": 0},
        })
        inf = Informer(client, "TpuSlice", namespace="ns",
                       resync_period=30.0).start()
        try:
            assert inf.wait_synced(10)
            plan.partition("controller", "apiserver", duration=0.4)
            obj = kube.get("TpuSlice", "ns", "n0")
            obj["spec"]["gen"] = 1
            kube.update("TpuSlice", obj)  # emitted while cut off
            assert wait_for(
                lambda: (inf.get("ns", "n0") or {})
                .get("spec", {}).get("gen") == 1,
                timeout=15,
            )
        finally:
            inf.stop()


# --------------------------------------------------- lease-epoch fence


class TestEpochFence:
    def _mk_cr(self, kube):
        kube.create("TpuSlice", {
            "apiVersion": "v1", "kind": "TpuSlice",
            "metadata": {"namespace": "ns", "name": "n0"},
            "spec": {"x": 0},
        })

    def test_deposed_writer_fenced_and_epochs_stamped(self):
        kube = FakeKube()
        self._mk_cr(kube)
        a = LeaderElector(kube, "ns", "ctl", "a", lease_seconds=0.2)
        b = LeaderElector(kube, "ns", "ctl", "b", lease_seconds=0.2)
        assert a._try_acquire_or_renew()
        a.is_leader.set()
        fence_a = EpochFence(lambda: a)
        fence_b = EpochFence(lambda: b)

        def bump(obj):
            obj["spec"]["x"] += 1
            return obj

        out = update_with_retry(kube, "TpuSlice", "ns", "n0", bump,
                                fence=fence_a)
        assert out["metadata"]["annotations"][
            WRITER_EPOCH_ANNOTATION] == "0"
        # the lease expires unrenewed; the successor takes over and
        # bumps leaseTransitions — the epoch the fence compares
        time.sleep(0.25)
        assert b._try_acquire_or_renew()
        b.is_leader.set()
        assert b.epoch == 1
        with pytest.raises(Fenced):
            update_with_retry(kube, "TpuSlice", "ns", "n0", bump,
                              fence=fence_a)
        assert REASON_WRITE_FENCED in journal_reasons()
        # zero double-writes: the deposed attempt landed nothing
        assert kube.get("TpuSlice", "ns", "n0")["spec"]["x"] == 1
        out = update_with_retry(kube, "TpuSlice", "ns", "n0", bump,
                                fence=fence_b)
        assert out["metadata"]["annotations"][
            WRITER_EPOCH_ANNOTATION] == "1"

    def test_fence_fails_closed_when_unverifiable(self):
        plan = NemesisPlan(CHAOS_SEED)
        kube = FakeKube()
        self._mk_cr(kube)
        client = NemesisKubeClient(kube, plan, "ctl-a")
        a = LeaderElector(client, "ns", "ctl", "a", lease_seconds=0.2)
        assert a._try_acquire_or_renew()
        a.is_leader.set()
        fence = EpochFence(lambda: a)
        assert fence() and fence.epoch == 0
        # partitioned AND past the freshness window: the fence cannot
        # re-prove the lease, so it must fail CLOSED
        time.sleep(0.25)
        plan.partition("ctl-a", "apiserver")
        assert not fence()

    def test_open_without_election(self):
        fence = EpochFence(lambda: None)
        assert fence() and fence.epoch is None
        obj = {"metadata": {}}
        from instaslice_tpu.kube.client import stamp_writer_epoch
        stamp_writer_epoch(obj, fence)
        assert "annotations" not in obj["metadata"]  # no-op stamp


# --------------------------------------- breaker half-open single probe


class TestBreakerHalfOpenProbe:
    def _open(self, br):
        for _ in range(br.threshold):
            br.fail()
        assert br.is_open()

    def test_exactly_one_probe(self):
        br = CircuitBreaker(threshold=2, cooldown=0.05, name="t")
        self._open(br)
        with pytest.raises(CircuitOpen):
            br.check()
        time.sleep(0.06)
        br.check()  # this caller IS the half-open probe
        with pytest.raises(CircuitOpen) as ei:
            br.check()  # concurrent caller fast-fails
        assert "probe already in flight" in str(ei.value)
        br.ok()  # probe succeeded: circuit closes for everyone
        br.check()

    def test_failed_probe_reopens_immediately(self):
        br = CircuitBreaker(threshold=2, cooldown=0.05, name="t")
        self._open(br)
        time.sleep(0.06)
        br.check()
        br.fail()  # the probe failed: count was one short → reopen
        with pytest.raises(CircuitOpen):
            br.check()

    def test_stale_probe_claim_expires(self):
        br = CircuitBreaker(threshold=2, cooldown=0.05, name="t")
        self._open(br)
        time.sleep(0.06)
        br.check()  # probe claimed, then its thread dies silently
        time.sleep(0.06)
        br.check()  # claim older than a cooldown: next caller probes


# ----------------------------------------- router poll backoff + hedging


def unstarted_router(*reps, **kw) -> Router:
    r = Router(port=0, **kw)
    for rep in reps:
        r._replicas[rep.url] = rep
    r._srv.server_close()
    return r


def fed_replica(url, lat_samples=(), **stats) -> Replica:
    rep = Replica(url)
    rep.adopt_stats({
        "replica_id": stats.pop("replica_id", url), "uptime_seconds": 10.0,
        "queued": 0, "live_slots": 0, "parked": 0, "max_batch": 8,
        "kv": {"free": 100, "total": 100},
        "radix": {"digest": {"granule": 8, "paths": []}},
        "tenant_classes": {},
    })
    for dt in lat_samples:
        rep.observe_latency(dt)
    return rep


class TestPollBackoff:
    def test_jittered_growth_and_cap(self):
        r = unstarted_router()
        prev, seen = 0.0, set()
        for _ in range(64):
            prev = r._next_backoff(prev)
            assert r.poll_backoff_base <= prev <= r.poll_backoff_cap
            seen.add(round(prev, 6))
        assert prev == r.poll_backoff_cap or len(seen) > 8  # jittered

    def test_retry_after_stretches_and_caps(self):
        r = unstarted_router()
        assert r._next_backoff(0.0, retry_after=5.0) >= 5.0
        # a hostile/huge Retry-After cannot park the poll for an hour
        assert r._next_backoff(0.0, retry_after=3600.0) \
            <= r.retry_after_cap

    def test_retry_after_header_parse(self):
        from email.message import Message

        from instaslice_tpu.serving.router import _retry_after_seconds
        h = Message()
        h["Retry-After"] = "3"
        assert _retry_after_seconds(h) == 3.0
        assert _retry_after_seconds(Message()) is None

    def test_poll_failure_sets_jittered_gate(self):
        r = unstarted_router()
        rep = fed_replica("http://x:1")
        r._note_poll_failure(rep, None)
        assert rep.poll_next > time.monotonic() - 0.001
        first = rep.poll_backoff
        r._note_poll_failure(rep, None)
        assert rep.poll_backoff <= r.poll_backoff_cap
        assert first > 0


class TestHedgedStats:
    def test_hedge_wins_when_primary_stalls(self):
        r = unstarted_router(hedge_after=0.05)
        rep = fed_replica("http://x:1")
        calls = {"n": 0}

        def fake_http(method, rp, path, body, timeout=10.0):
            calls["n"] += 1
            if calls["n"] == 1:
                time.sleep(0.4)  # the gray primary answer
                return 200, {"slow": True}
            return 200, {"slow": False}

        r.http_json = fake_http
        code, payload, lat = r._hedged_stats(rep)
        assert code == 200 and payload == {"slow": False}
        assert r.hedges["fired"] == 1 and r.hedges["won"] == 1
        assert r.requests.get("hedged-ok") == 1

    def test_fast_primary_never_hedges(self):
        r = unstarted_router(hedge_after=0.5)
        rep = fed_replica("http://x:1")
        r.http_json = lambda *a, **k: (200, {})
        code, payload, lat = r._hedged_stats(rep)
        assert code == 200 and r.hedges["fired"] == 0


# ----------------------------------------------- gray-failure ejection


class TestGrayEjection:
    def test_median_helper(self):
        assert _median([1.0]) == 1.0
        assert _median([1.0, 3.0]) == 2.0
        assert _median([1.0, 2.0, 9.0]) == 2.0

    def test_ewma_p95_tracks_latency(self):
        rep = Replica("http://x:1")
        for _ in range(16):
            rep.observe_latency(0.01)
        assert 0.008 <= rep.lat_p95() <= 0.02
        for _ in range(16):
            rep.observe_latency(0.2)
        assert rep.lat_p95() > 0.1

    def test_eject_and_readmit_cycle(self):
        slow = fed_replica("http://slow:1", lat_samples=[0.3] * 10,
                           replica_id="s")
        fast = fed_replica("http://fast:1", lat_samples=[0.004] * 10,
                           replica_id="f")
        r = unstarted_router(slow, fast, eject_min_samples=8)
        r.http_json = lambda *a, **k: (200, {})  # drain/undrain stub
        r._gray_sweep()
        assert slow.ejected
        assert not slow.alive(time.monotonic(), r.stale_after)
        assert not fast.ejected
        assert r.ejections["http://slow:1"] == 1
        assert REASON_REPLICA_EJECTED in journal_reasons()
        # ejected ≠ removed: the router keeps polling it, and routing
        # skips it
        rep, policy = r.route([1, 2, 3], "", "")
        assert rep.url == "http://fast:1"
        # latency recovers → hysteresis readmission
        for _ in range(32):
            slow.observe_latency(0.004)
        r._gray_sweep()
        assert wait_for(lambda: not slow.ejected, timeout=2.0)
        assert REASON_REPLICA_READMITTED in journal_reasons()

    def test_never_ejects_below_two_healthy(self):
        only = fed_replica("http://only:1", lat_samples=[0.5] * 10)
        r = unstarted_router(only)
        r._gray_sweep()
        assert not only.ejected

    def test_eject_drops_session_affinity(self):
        slow = fed_replica("http://slow:1", lat_samples=[0.3] * 10)
        fast = fed_replica("http://fast:1", lat_samples=[0.004] * 10)
        r = unstarted_router(slow, fast)
        r.http_json = lambda *a, **k: (200, {})
        r.pin_session("conv", "http://slow:1")
        r._gray_sweep()
        rep, policy = r.route([1, 2, 3], "", "conv")
        assert rep.url == "http://fast:1"  # affinity dropped on eject

    def test_disabled_by_zero_factor(self):
        slow = fed_replica("http://slow:1", lat_samples=[0.5] * 10)
        fast = fed_replica("http://fast:1", lat_samples=[0.004] * 10)
        r = unstarted_router(slow, fast, eject_factor=0.0)
        r._gray_sweep()
        assert not slow.ejected


# -------------------------------------------------- agent static mode


class TestAgentStaticMode:
    def _agent(self):
        from instaslice_tpu.agent.reconciler import NodeAgent
        from instaslice_tpu.device import FakeTpuBackend

        plan = NemesisPlan(CHAOS_SEED)
        kube = FakeKube()
        client = NemesisKubeClient(kube, plan, "agent-n0")
        agent = NodeAgent(client, FakeTpuBackend(generation="v5e"),
                          "n0", "ns", health_interval=0)
        agent.boot()
        return plan, kube, agent

    def test_partition_enters_static_mode_once(self):
        plan, kube, agent = self._agent()
        plan.partition("agent-n0", "apiserver")
        out = agent.reconcile("n0")
        assert out == agent.degraded_retry_s and agent.degraded
        agent.reconcile("n0")  # re-probe while still partitioned
        rs = journal_reasons()
        assert rs.count(REASON_APISERVER_UNREACHABLE) == 1
        assert rs.count(REASON_DEGRADED_ENTERED) == 1

    def test_heal_runs_boot_sweep_and_exits(self):
        plan, kube, agent = self._agent()
        plan.partition("agent-n0", "apiserver")
        agent.reconcile("n0")
        assert agent.degraded
        plan.heal()
        assert agent.reconcile("n0") is None
        assert not agent.degraded
        assert REASON_DEGRADED_EXITED in journal_reasons()
        # durable truth re-published by the boot sweep
        assert kube.get("TpuSlice", "ns", "n0")

    def test_injected_api_errors_do_not_trigger_static_mode(self):
        from instaslice_tpu.faults import FaultPlan, FaultyKubeClient
        from instaslice_tpu.kube.client import ApiError

        plan, kube, agent = self._agent()
        flaky = FaultyKubeClient(
            agent.client,
            FaultPlan.from_env("kube.request:p=1.0,kinds=http-503"),
        )
        agent.client = flaky
        with pytest.raises(ApiError):
            agent.reconcile("n0")
        assert not agent.degraded  # a 5xx is not a partition


# ------------------------------------------------- invariant checker


def _ev(seq, component, reason, ref="", trace="", **attrs):
    rec = {"seq": seq, "ts": float(seq), "component": component,
           "reason": reason, "objectRef": ref, "traceId": trace}
    if attrs:
        rec["attrs"] = attrs
    return rec


class TestCheckNemesis:
    def test_clean_journal_passes(self):
        evs = [
            _ev(1, "agent-n0", REASON_APISERVER_UNREACHABLE, "node/n0"),
            _ev(2, "agent-n0", REASON_DEGRADED_ENTERED, "node/n0"),
            _ev(3, "agent-n0", REASON_DEGRADED_EXITED, "node/n0"),
            _ev(4, "controller", "Admitted", "Pod/ns/p", trace="t1"),
            _ev(5, "allocation", "SliceCreating", "alloc/a1", trace="t1"),
            _ev(6, "allocation", "SliceCreated", "alloc/a1", trace="t1"),
            _ev(7, "allocation", "SliceUngated", "alloc/a1", trace="t1"),
        ]
        assert validate_events.check_nemesis(evs) == []

    def test_unpaired_degraded_entry_fails(self):
        evs = [
            _ev(1, "agent-n0", REASON_APISERVER_UNREACHABLE, "node/n0"),
            _ev(2, "agent-n0", REASON_DEGRADED_ENTERED, "node/n0"),
        ]
        errs = validate_events.check_nemesis(evs)
        assert any("never paired" in e for e in errs)

    def test_exit_without_entry_fails(self):
        errs = validate_events.check_nemesis(
            [_ev(1, "agent-n0", REASON_DEGRADED_EXITED, "node/n0")]
        )
        assert any("without a matching" in e for e in errs)

    def test_double_place_detected(self):
        evs = [
            _ev(1, "controller", "Admitted", "Pod/ns/p", trace="t1"),
            _ev(2, "allocation", "SliceCreating", "alloc/a1", trace="t1"),
            _ev(3, "allocation", "SliceUngated", "alloc/a1", trace="t1"),
            # the deposed leader's parallel grant for the SAME pod
            _ev(4, "allocation", "SliceCreating", "alloc/a2", trace="t1"),
            _ev(5, "allocation", "SliceUngated", "alloc/a2", trace="t1"),
        ]
        errs = validate_events.check_nemesis(evs)
        assert any("double-placed" in e for e in errs)

    def test_retry_after_delete_is_not_double_place(self):
        evs = [
            _ev(1, "controller", "Admitted", "Pod/ns/p", trace="t1"),
            _ev(2, "allocation", "SliceCreating", "alloc/a1", trace="t1"),
            _ev(3, "allocation", "SliceUngated", "alloc/a1", trace="t1"),
            _ev(4, "allocation", "SliceDeleted", "alloc/a1", trace="t1"),
            _ev(5, "allocation", "SliceCreating", "alloc/a2", trace="t1"),
            _ev(6, "allocation", "SliceUngated", "alloc/a2", trace="t1"),
        ]
        assert validate_events.check_nemesis(evs) == []

    def test_slice_leak_detected(self):
        evs = [
            _ev(1, "allocation", "SliceCreating", "alloc/a1", trace="t1"),
            _ev(2, "allocation", "SliceCreated", "alloc/a1", trace="t1"),
        ]
        errs = validate_events.check_nemesis(evs)
        assert any("slice leak" in e for e in errs)

    def test_write_fenced_requires_component(self):
        errs = validate_events.check_nemesis(
            [_ev(1, "", REASON_WRITE_FENCED, "TpuSlice/ns/n0")]
        )
        assert any("WriteFenced" in e for e in errs)


class TestLoadgenClassify:
    def test_partition_era_outcomes(self):
        from instaslice_tpu.serving.loadgen import OUTCOMES, _classify

        assert "hedged-ok" in OUTCOMES and "replica-ejected" in OUTCOMES
        assert _classify(None, 200, 5, hedged=True) == "hedged-ok"
        assert _classify(None, 200, 5) == "ok"
        assert _classify(
            "HTTPError 503: no replica accepted; 1 gray-ejected", 503
        ) == "replica-ejected"
        assert _classify("x", 503, 0) == "timeout-503"


# ------------------------------------------------------------- smokes


def _journal_dicts():
    return [e.to_dict() for e in get_journal().events()]


@pytest.mark.slow
class TestPartitionSmoke:
    def _sim(self, plan, **kw):
        from instaslice_tpu.sim import SimCluster

        defaults = dict(
            n_nodes=2, generation="v5e", nodes_per_group=2,
            deletion_grace_seconds=0.2, health_interval=0,
            nemesis=plan,
        )
        defaults.update(kw)
        return SimCluster(**defaults)

    def test_smoke_controller_partition_heal_converges(self):
        """Partition the controller from the apiserver mid-run: grants
        stall (never split-brain), agents keep serving, and on heal the
        cluster converges with zero double allocations and a clean
        nemesis journal."""
        from test_crash_chaos import assert_no_orphans, assert_no_overlaps

        plan = NemesisPlan(CHAOS_SEED)
        with self._sim(plan) as c:
            c.submit("pre-partition", "v5e-1x1")
            assert c.wait_phase("pre-partition", "Running", timeout=30)
            plan.partition("controller", "apiserver")
            c.submit("mid-partition", "v5e-1x1")
            time.sleep(1.0)
            # the cut-off controller must not have granted anything
            assert c.pod_phase("mid-partition") != "Running"
            plan.heal()
            assert c.wait_phase("mid-partition", "Running", timeout=30), (
                c.pod_phase("mid-partition"), plan.stats())
            assert_no_overlaps(c)
            assert_no_orphans(c)
            errs = validate_events.check_nemesis(_journal_dicts())
            assert not errs, errs
            errs = validate_events.check_chains(_journal_dicts(),
                                                strict=True)
            assert not errs, errs

    def test_smoke_agent_partition_static_mode(self):
        """Cut an agent off mid-run: realized slices keep serving
        (device reservations untouched), the agent journals its
        degraded entry exactly once, and the heal-side boot sweep
        reconciles CR truth with device truth."""
        from test_crash_chaos import assert_no_orphans

        plan = NemesisPlan(CHAOS_SEED)
        with self._sim(plan, health_interval=0.3) as c:
            c.submit("static-pod", "v5e-1x1")
            assert c.wait_phase("static-pod", "Running", timeout=30)
            held = {node: len(b.list_reservations())
                    for node, b in c.backends.items()}
            victim = next(n for n, count in held.items() if count)
            plan.partition(f"agent-{victim}", "apiserver")
            assert wait_for(
                lambda: REASON_DEGRADED_ENTERED in journal_reasons(),
                timeout=15,
            ), journal_reasons()
            # STATIC mode: the realized slice is still on the device
            assert len(c.backends[victim].list_reservations()) \
                == held[victim]
            plan.heal()
            assert wait_for(
                lambda: REASON_DEGRADED_EXITED in journal_reasons(),
                timeout=15,
            ), journal_reasons()
            c.submit("post-heal", "v5e-1x1")
            assert c.wait_phase("post-heal", "Running", timeout=30)
            assert_no_orphans(c)  # CR state == device state post-heal
            errs = validate_events.check_nemesis(_journal_dicts())
            assert not errs, errs

    def test_smoke_gray_replica_ejected_and_readmitted(self):
        """The serving-plane acceptance scenario: a replica that still
        answers every request (100% success) but 25x slower is ejected
        on latency EWMA alone, its sessions drain through the
        migration path, traffic keeps flowing with zero hung requests,
        and after the latency heals it is re-admitted."""
        import jax
        import jax.numpy as jnp

        from instaslice_tpu.models.lm import ModelConfig, TpuLM
        from instaslice_tpu.serving import ServingEngine, loadgen
        from instaslice_tpu.serving.api_server import ApiServer

        cfg = ModelConfig(vocab_size=64, d_model=32, n_heads=2,
                          n_layers=2, d_ff=64, dtype=jnp.float32,
                          remat=False)
        m = TpuLM(cfg)
        params = m.init(jax.random.key(0))

        def engine():
            return ServingEngine(m, params, max_batch=4, max_len=96,
                                 prefill_len=8)

        servers = [ApiServer(engine(), block_size=4).start()
                   for _ in range(2)]
        plan = NemesisPlan(CHAOS_SEED)
        set_nemesis(plan)
        router = Router([s.url for s in servers], poll_interval=0.05,
                        eject_min_samples=6, eject_floor_s=0.02,
                        hedge_after=0.0).start()
        try:
            report = loadgen.run(router.url, requests=6, concurrency=2,
                                 prompt_len=4, max_tokens=4, vocab=64,
                                 stream=False, timeout=60)
            assert report["outcomes"]["hung"] == 0, report
            victim_url = servers[0].url.rstrip("/")
            plan.latency("router", f"replica:{victim_url}", delay=0.5)
            victim = router._replicas[victim_url]
            assert wait_for(lambda: victim.ejected, timeout=20), (
                victim.lat_p95(), plan.stats())
            assert REASON_REPLICA_EJECTED in journal_reasons()
            # traffic keeps flowing around the gray replica
            report = loadgen.run(router.url, requests=6, concurrency=2,
                                 prompt_len=4, max_tokens=4, vocab=64,
                                 stream=False, timeout=60, seed=1)
            assert report["outcomes"]["hung"] == 0, report
            assert report["ok"] == 6, report
            # every request avoided the ejected replica
            assert all(not router._replicas[u].ejected
                       for u, ts in router._sessions.values())
            plan.heal()
            assert wait_for(lambda: not victim.ejected, timeout=20), (
                victim.lat_p95(), plan.stats())
            assert REASON_REPLICA_READMITTED in journal_reasons()
            # healed fleet under the loadgen nemesis arm: client-side
            # latency/drops/partition schedule, hedge-retried, no hangs
            set_nemesis(None)
            report = loadgen.run(router.url, requests=6, concurrency=2,
                                 prompt_len=4, max_tokens=4, vocab=64,
                                 stream=False, timeout=60, seed=2,
                                 nemesis_seed=CHAOS_SEED)
            assert report["outcomes"]["hung"] == 0, report
            assert report["nemesis"]["seed"] == CHAOS_SEED, report
            assert get_nemesis() is None  # arm uninstalled its plan
            errs = validate_events.check_nemesis(_journal_dicts())
            assert not errs, errs
        finally:
            set_nemesis(None)
            router.stop()
            for s in servers:
                s.stop()


@pytest.mark.slow
class TestGrayEjectionComparative:
    # deliberately NOT named *smoke*: the <60s gate skips this
    # two-arm comparison; the 3-seed `make chaos` sweep runs it

    def test_gray_ejection_beats_no_ejection_baseline(self):
        """Same replayed trace, same injected 1s gray latency on one of
        two replicas: the arm WITH EWMA ejection must beat the
        eject_factor=0 baseline on client p95 latency — the whole point
        of ejecting a replica that never errors. Load is kept light
        (the surviving replica absorbs it without queueing) so the
        injected stall, not lost capacity, dominates the tail."""
        import tempfile

        import jax
        import jax.numpy as jnp

        from instaslice_tpu.models.lm import ModelConfig, TpuLM
        from instaslice_tpu.serving import ServingEngine, loadgen
        from instaslice_tpu.serving.api_server import ApiServer

        cfg = ModelConfig(vocab_size=64, d_model=32, n_heads=2,
                          n_layers=2, d_ff=64, dtype=jnp.float32,
                          remat=False)
        m = TpuLM(cfg)
        params = m.init(jax.random.key(0))
        trace = tempfile.mktemp(prefix="tpuslice-nemesis-trace.",
                                suffix=".jsonl")

        def arm(eject_factor, record=False):
            servers = [ApiServer(
                ServingEngine(m, params, max_batch=4, max_len=96,
                              prefill_len=8), block_size=4).start()
                for _ in range(2)]
            plan = NemesisPlan(CHAOS_SEED)
            set_nemesis(plan)
            router = Router([s.url for s in servers],
                            poll_interval=0.05, eject_min_samples=6,
                            eject_floor_s=0.02, hedge_after=0.0,
                            eject_factor=eject_factor).start()
            try:
                victim = servers[0].url.rstrip("/")
                plan.latency("router", f"replica:{victim}", delay=1.0)
                if eject_factor:
                    # deterministic warm-up: the EWMA must trip before
                    # the measured window starts
                    assert wait_for(
                        lambda: router._replicas[victim].ejected,
                        timeout=20,
                    ), (router._replicas[victim].lat_p95(), plan.stats())
                else:
                    time.sleep(2.0)  # same poll seasoning, no ejection
                kw = dict(record_trace=trace) if record \
                    else dict(replay_trace=trace)
                report = loadgen.run(
                    router.url, requests=8, concurrency=2,
                    prompt_len=4, max_tokens=4, vocab=64,
                    stream=False, timeout=60, **kw)
                assert report["outcomes"]["hung"] == 0, report
                ejected = router._replicas[victim].ejected
                return report, ejected
            finally:
                set_nemesis(None)
                router.stop()
                for s in servers:
                    s.stop()

        try:
            baseline, ejected0 = arm(0.0, record=True)
            treated, ejected1 = arm(3.0)
        finally:
            if os.path.exists(trace):
                os.unlink(trace)
        assert not ejected0 and ejected1
        # the ejection arm routes around the 1s injected stall; the
        # baseline keeps landing ~half its requests on it
        assert treated["p95_latency"] < baseline["p95_latency"], (
            treated, baseline)


@pytest.mark.slow
class TestOpStreamNemesis:
    def test_partitioned_follower_dropped_like_dead(self):
        """A partition on the op-stream edge reads as a dead follower:
        the leader drops it loudly and keeps serving (PartitionError is
        an OSError — same path a reset socket takes)."""
        import socket as sk

        from instaslice_tpu.serving.distributed import (
            HELLO_MAGIC,
            DistributedEngine,
        )

        class _Eng:
            def add_request(self, *a, **k):
                return 1

        follower = sk.socket(sk.AF_INET, sk.SOCK_STREAM)
        follower.bind(("127.0.0.1", 0))
        follower.listen(1)
        port = follower.getsockname()[1]

        accepted = {}

        def connect():
            conn, addr = follower.accept()
            accepted["conn"] = conn

        t = threading.Thread(target=connect, daemon=True)
        t.start()
        client = sk.create_connection(("127.0.0.1", port))
        t.join(5)

        d = DistributedEngine.__new__(DistributedEngine)
        object.__setattr__(d, "engine", _Eng())
        object.__setattr__(
            d, "_conns", [(accepted["conn"], "peer:1")])
        plan = NemesisPlan(CHAOS_SEED)
        set_nemesis(plan)
        try:
            d._bcast({"op": "noop"})
            assert len(d._conns) == 1  # healthy link: kept
            plan.partition("opstream", "follower:peer:1")
            d._bcast({"op": "noop"})
            assert d._conns == []  # partitioned follower dropped
        finally:
            set_nemesis(None)
            client.close()
            follower.close()
