"""RealKubeClient under a misbehaving API server: transient retry with
backoff, Retry-After honoring, the circuit breaker, and watch streams
that resume from the last seen resourceVersion after a mid-stream drop.

Scripted HTTP servers (not the fake API) so each test controls the
exact failure sequence on the wire — 503 bursts, 429 with Retry-After,
TCP RSTs mid-watch — and asserts on what the client put on the wire
(request counts, resume resourceVersions).
"""

import json
import socket
import struct
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from instaslice_tpu.kube.client import ApiError
from instaslice_tpu.kube.real import CircuitOpen, RealKubeClient

OK_BODY = {"kind": "Pod", "metadata": {"name": "x"}}


class _ScriptedServer:
    """Pops one scripted response per request; records every request.

    A response is ``(code, headers, body_dict)``; the string ``"rst"``
    aborts the connection with a TCP reset; an exhausted script serves
    200 OK_BODY.
    """

    def __init__(self, script=()):
        self.script = list(script)
        self.seen = []          # (method, path) per request
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"

            def log_message(self, *a):
                pass

            def _serve(self):
                outer.seen.append((self.command, self.path))
                step = outer.script.pop(0) if outer.script else (
                    200, {}, OK_BODY
                )
                if step == "rst":
                    _abort(self.connection)
                    return
                code, headers, body = step
                payload = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                for k, v in headers.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(payload)

            do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = _serve

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def url(self):
        host, port = self._srv.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=5)


def _abort(conn) -> None:
    """Close with SO_LINGER 0 → the peer sees ECONNRESET, not EOF."""
    conn.setsockopt(
        socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
    )
    conn.close()


def _fast_client(url, **overrides) -> RealKubeClient:
    c = RealKubeClient(url)
    c.max_attempts = overrides.pop("max_attempts", 4)
    c.backoff_base = 0.01
    c.backoff_cap = 0.05
    for k, v in overrides.items():
        setattr(c, k, v)
    return c


class TestRetry:
    def test_transient_5xx_retried_to_success(self):
        srv = _ScriptedServer([
            (503, {}, {"message": "apiserver overloaded"}),
            (502, {}, {"message": "bad gateway"}),
        ])
        try:
            c = _fast_client(srv.url)
            out = c.get("Pod", "default", "x")
            assert out["metadata"]["name"] == "x"
            assert len(srv.seen) == 3           # 2 failures + 1 success
        finally:
            srv.stop()

    def test_connection_reset_retried(self):
        srv = _ScriptedServer(["rst", "rst"])
        try:
            c = _fast_client(srv.url)
            out = c.get("Pod", "default", "x")
            assert out["metadata"]["name"] == "x"
            assert len(srv.seen) == 3
        finally:
            srv.stop()

    def test_429_honors_retry_after(self):
        srv = _ScriptedServer([
            (429, {"Retry-After": "1"}, {"message": "slow down"}),
        ])
        try:
            c = _fast_client(srv.url)
            t0 = time.monotonic()
            out = c.get("Pod", "default", "x")
            elapsed = time.monotonic() - t0
            assert out["metadata"]["name"] == "x"
            # the client's own jittered backoff tops out at 0.05 s here:
            # a >= 0.9 s pause proves the header drove the wait
            assert elapsed >= 0.9, elapsed
            assert len(srv.seen) == 2
        finally:
            srv.stop()

    def test_gives_up_after_max_attempts(self):
        srv = _ScriptedServer([(503, {}, {"message": "down"})] * 10)
        try:
            c = _fast_client(srv.url, max_attempts=3)
            with pytest.raises(ApiError) as ei:
                c.get("Pod", "default", "x")
            assert not isinstance(ei.value, CircuitOpen)
            assert len(srv.seen) == 3
        finally:
            srv.stop()

    def test_semantic_errors_not_retried(self):
        from instaslice_tpu.kube.client import NotFound

        srv = _ScriptedServer([
            (404, {}, {"message": "nope", "reason": "NotFound"}),
        ])
        try:
            c = _fast_client(srv.url)
            with pytest.raises(NotFound):
                c.get("Pod", "default", "x")
            assert len(srv.seen) == 1          # no second attempt
        finally:
            srv.stop()


class TestCircuitBreaker:
    def test_five_consecutive_503s_open_the_breaker(self):
        srv = _ScriptedServer([(503, {}, {"message": "down"})] * 20)
        try:
            # max_attempts=1: each call is exactly one wire request
            c = _fast_client(srv.url, max_attempts=1,
                             breaker_threshold=5, breaker_cooldown=30.0)
            for _ in range(5):
                with pytest.raises(ApiError):
                    c.get("Pod", "default", "x")
            assert len(srv.seen) == 5
            # breaker open: fail fast, nothing reaches the wire
            with pytest.raises(CircuitOpen):
                c.get("Pod", "default", "x")
            with pytest.raises(CircuitOpen):
                c.get("Pod", "default", "x")
            assert len(srv.seen) == 5
        finally:
            srv.stop()

    def test_half_open_probe_recovers(self):
        srv = _ScriptedServer([(503, {}, {"message": "down"})] * 5)
        try:
            c = _fast_client(srv.url, max_attempts=1,
                             breaker_threshold=5, breaker_cooldown=0.15)
            for _ in range(5):
                with pytest.raises(ApiError):
                    c.get("Pod", "default", "x")
            with pytest.raises(CircuitOpen):
                c.get("Pod", "default", "x")
            time.sleep(0.2)                    # past the cooldown
            # half-open probe hits a now-healthy server and closes the
            # breaker; follow-ups flow normally
            assert c.get("Pod", "default", "x")["metadata"]["name"] == "x"
            assert c.get("Pod", "default", "x")["metadata"]["name"] == "x"
        finally:
            srv.stop()

    def test_failed_half_open_probe_reopens(self):
        srv = _ScriptedServer([(503, {}, {"message": "down"})] * 6)
        try:
            c = _fast_client(srv.url, max_attempts=1,
                             breaker_threshold=5, breaker_cooldown=0.15)
            for _ in range(5):
                with pytest.raises(ApiError):
                    c.get("Pod", "default", "x")
            time.sleep(0.2)
            # the probe fails → breaker reopens without more traffic
            with pytest.raises(ApiError):
                c.get("Pod", "default", "x")
            n = len(srv.seen)
            with pytest.raises(CircuitOpen):
                c.get("Pod", "default", "x")
            assert len(srv.seen) == n
        finally:
            srv.stop()


class _WatchServer:
    """Scripted watch endpoint: each connection sends its scripted
    events then either RSTs (``drop=True``) or closes cleanly. Records
    the resourceVersion query of every establishment."""

    def __init__(self, connections):
        # connections: list of (events, drop) — events are (type, rv)
        self.connections = list(connections)
        self.rvs_seen = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"

            def log_message(self, *a):
                pass

            def do_GET(self):
                q = urllib.parse.parse_qs(
                    urllib.parse.urlsplit(self.path).query
                )
                outer.rvs_seen.append(
                    q.get("resourceVersion", [None])[0]
                )
                events, drop = (outer.connections.pop(0)
                                if outer.connections else ([], False))
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                for etype, rv in events:
                    rec = {"type": etype, "object": {
                        "kind": "Pod",
                        "metadata": {"name": f"p{rv}",
                                     "resourceVersion": str(rv)},
                    }}
                    self.wfile.write((json.dumps(rec) + "\n").encode())
                    self.wfile.flush()
                if drop:
                    _abort(self.connection)

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def url(self):
        host, port = self._srv.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=5)


class TestWatchResume:
    def test_dropped_watch_resumes_from_last_rv(self):
        srv = _WatchServer([
            ([("ADDED", 1), ("ADDED", 2)], True),    # RST mid-stream
            ([("MODIFIED", 3)], False),              # clean close
        ])
        try:
            c = _fast_client(srv.url)
            events = [
                (etype, obj["metadata"]["resourceVersion"])
                for etype, obj in c.watch(
                    "Pod", namespace="default", replay=False,
                    resource_version="0", timeout=1.0,
                )
                if etype != "BOOKMARK"
            ]
            # every event delivered exactly once — the drop cost
            # nothing and replayed nothing
            assert events == [
                ("ADDED", "1"), ("ADDED", "2"), ("MODIFIED", "3"),
            ]
            # the reconnect resumed from the LAST SEEN rv, not cold
            assert srv.rvs_seen == ["0", "2"]
        finally:
            srv.stop()

    def test_drop_budget_exhausted_raises(self):
        # connections that deliver NOTHING before dropping: delivered
        # events reset the reconnect budget (a server that still makes
        # progress deserves patience), so only a zero-progress drop
        # storm exhausts it
        srv = _WatchServer([([], True)] * 10)
        try:
            c = _fast_client(srv.url, watch_reconnects=2)
            with pytest.raises(ApiError):
                list(c.watch("Pod", namespace="default", replay=False,
                             resource_version="0", timeout=1.0))
        finally:
            srv.stop()
