"""One-claimant TPU lock (``utils/tpulock.py``).

The wedge mode this guards against: two concurrent processes
initializing the TPU backend wedge the tunnel for hours
(``docs/PERF.md`` "Caveat"). The lock must make the second claimant
fail fast with a clear error — and a killed holder must release by
construction (flock drops with the fd), because hard-killed claimants
are exactly how the wedge historically started.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

from instaslice_tpu.utils.tpulock import (
    TpuBusyError,
    TpuClaim,
    claim_or_force_cpu,
    claim_tpu,
    tpu_is_cpu_forced,
)

from conftest import wait_until


def test_second_claimant_fails_fast_in_process(tmp_path):
    lock = str(tmp_path / "tpu.lock")
    first = TpuClaim(lock).acquire(timeout=0)
    try:
        t0 = time.monotonic()
        with pytest.raises(TpuBusyError) as ei:
            TpuClaim(lock).acquire(timeout=0.4)
        assert time.monotonic() - t0 < 5
        # the error names the holder and the remedy
        assert f"pid={os.getpid()}" in str(ei.value)
        assert "wedge" in str(ei.value)
    finally:
        first.release()
    # freed: a new claimant gets it immediately
    TpuClaim(lock).acquire(timeout=0).release()


def test_reacquire_after_release_same_object(tmp_path):
    lock = str(tmp_path / "tpu.lock")
    c = TpuClaim(lock)
    with c:
        assert c.held
    assert not c.held
    with c:
        assert c.held


HOLDER = """
import sys, time
from instaslice_tpu.utils.tpulock import TpuClaim
claim = TpuClaim(sys.argv[1]).acquire(timeout=0)
print("HELD", flush=True)
time.sleep(120)
"""


def _spawn_holder(lock: str) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep)
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", HOLDER, lock],
        stdout=subprocess.PIPE, env=env,
    )
    assert proc.stdout.readline().strip() == b"HELD"
    return proc


def test_cross_process_block_and_dead_holder_release(tmp_path):
    lock = str(tmp_path / "tpu.lock")
    proc = _spawn_holder(lock)
    try:
        # second claimant (this process) fails fast while the holder
        # lives, and the error names the holder's pid
        with pytest.raises(TpuBusyError) as ei:
            TpuClaim(lock).acquire(timeout=0.3)
        assert f"pid={proc.pid}" in str(ei.value)
        # SIGKILL the holder — the historical wedge trigger. The flock
        # drops with the fd: the next claimant must win promptly with
        # no stale-lockfile cleanup.
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
        wait_until(
            lambda: _try_claim(lock), timeout=5,
            what="claim after holder death",
        )
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def _try_claim(lock: str) -> bool:
    try:
        TpuClaim(lock).acquire(timeout=0).release()
        return True
    except TpuBusyError:
        return False


def test_cpu_forced_process_skips_the_lock(monkeypatch, tmp_path):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert tpu_is_cpu_forced()
    assert claim_tpu(path=str(tmp_path / "tpu.lock")) is None
    # a TPU-bound process does claim
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    assert not tpu_is_cpu_forced()
    c = claim_tpu(timeout=0, path=str(tmp_path / "tpu.lock"))
    assert c is not None and c.held
    c.release()


def test_claim_or_force_cpu_policy(monkeypatch, tmp_path):
    """The entry-point policy helper: CPU modes pin jax in-process and
    take no lock; TPU-bound processes claim (or raise TpuBusyError)."""
    import jax

    monkeypatch.setenv("TPUSLICE_TPU_LOCK", str(tmp_path / "tpu.lock"))
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    # explicit force_cpu (the smoke mains' CPU modes): no lock taken,
    # jax pinned to cpu in-process (conftest already pinned it; the
    # call must leave that intact)
    assert claim_or_force_cpu(force_cpu=True) is None
    assert jax.config.jax_platforms == "cpu"
    # env-cpu: same, no lock
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert claim_or_force_cpu() is None
    # TPU-bound: claims — and a held lock raises TpuBusyError
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    c = claim_or_force_cpu(timeout=0)
    assert c is not None and c.held
    try:
        with pytest.raises(TpuBusyError):
            claim_or_force_cpu(timeout=0)
    finally:
        c.release()


def test_lock_file_survives_release(tmp_path):
    """Never unlink: a removed path would let a third process lock a
    different inode under the same name (split-brain)."""
    lock = str(tmp_path / "tpu.lock")
    TpuClaim(lock).acquire(timeout=0).release()
    assert os.path.exists(lock)


def test_default_path_refuses_planted_lock(monkeypatch, tmp_path):
    """The implicit per-uid default must not contend on a file planted by
    another uid (advisory-lock DoS surface) — it refuses with a clear
    error instead. Explicit paths skip the check: they are the caller's
    declared claim domain."""
    import instaslice_tpu.utils.tpulock as tl

    planted = tmp_path / "tpu.lock"
    planted.touch()
    if os.getuid() != 0:
        pytest.skip("needs root to chown a planted lock file")
    os.chown(planted, 1234, 1234)
    monkeypatch.setattr(tl, "_default_lock_path", lambda: str(planted))
    monkeypatch.delenv("TPUSLICE_TPU_LOCK", raising=False)
    with pytest.raises(TpuBusyError, match="planted"):
        TpuClaim().acquire(timeout=0)
    # explicit path: contends normally (and wins, nobody holds it)
    TpuClaim(str(planted)).acquire(timeout=0).release()


INHERIT_CHILD = r"""
import os, sys
from instaslice_tpu.utils.tpulock import claim_tpu, TpuClaim, TpuBusyError
claim = claim_tpu(timeout=0)
assert claim is not None and claim.held, "inherited claim not recognized"
assert claim._inherited, "should have taken the inherited-fd path"
# an INDEPENDENT open of the same path must still see the flock held
try:
    TpuClaim(os.environ["TPUSLICE_TPU_LOCK"]).acquire(timeout=0)
    print("INDEPENDENT-ACQUIRED")          # would be a bug
except TpuBusyError:
    print("INDEPENDENT-BLOCKED")
claim.release()                            # closes the fd copy only
print("CHILD-OK")
"""


def test_inherited_claim_shares_parent_flock(tmp_path):
    """A child handed the locked fd (watchdog burst pattern) co-holds the
    claim: it does not re-acquire (which would self-deadlock), an
    independent claimant stays blocked, and the child's release must NOT
    drop the parent's lock (flock is per open-file-description)."""
    from instaslice_tpu.utils.tpulock import INHERITED_FD_ENV

    lock = str(tmp_path / "tpu.lock")
    parent = TpuClaim(lock).acquire(timeout=0)
    try:
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # cpu-forced skips claims entirely
        env["TPUSLICE_TPU_LOCK"] = lock
        env[INHERITED_FD_ENV] = str(parent.fd)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + env.get("PYTHONPATH", "").split(os.pathsep)
        )
        out = subprocess.run(
            [sys.executable, "-c", INHERIT_CHILD],
            env=env, pass_fds=(parent.fd,),
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stderr
        assert "INDEPENDENT-BLOCKED" in out.stdout
        assert "CHILD-OK" in out.stdout
        # child exited (fd copy closed) — the parent must STILL hold it
        with pytest.raises(TpuBusyError):
            TpuClaim(lock).acquire(timeout=0)
    finally:
        parent.release()
    TpuClaim(lock).acquire(timeout=0).release()   # now free


def test_stale_inherited_fd_falls_through(monkeypatch, tmp_path):
    """A stale/closed TPUSLICE_TPU_LOCK_FD must not be trusted: claim
    falls through to a normal acquire."""
    from instaslice_tpu.utils import tpulock as tl

    lock = str(tmp_path / "tpu.lock")
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setenv(tl.INHERITED_FD_ENV, "963")  # nothing open there
    c = tl.claim_tpu(timeout=0, path=lock)
    assert c is not None and c.held and not c._inherited
    c.release()
