"""Continuous profiler: round-timer/ring mechanics, CompileWatch grace
semantics, the scheduler's profiler ledger reconciling with its own
round counter and the profile_rounds metric, Chrome trace-event export
validity, and per-request waterfalls across every terminal outcome
(docs/OBSERVABILITY.md "Profiling")."""

import json
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from instaslice_tpu.api.constants import (
    REASON_DRAINED,
    REASON_SESSION_EXPORTED,
    REASON_SHED,
)
from instaslice_tpu.metrics.metrics import ServingMetrics, render
from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.obs.journal import Journal, get_journal, reset_journal
from instaslice_tpu.obs.profiler import (
    NOOP_TIMER,
    SEGMENTS,
    CompileWatch,
    Profiler,
    RoundTimer,
    chrome_trace,
    debug_profile_payload,
    get_profiler,
    reset_profiler,
    waterfall_payload,
)
from instaslice_tpu.serving import ServingEngine
from instaslice_tpu.serving.api_server import ApiServer
from instaslice_tpu.utils.trace import Tracer, reset_tracer


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


@pytest.fixture(autouse=True)
def fresh_rings():
    reset_profiler()
    reset_tracer()
    reset_journal()
    yield
    reset_profiler()
    reset_tracer()
    reset_journal()


def post(url, payload, timeout=120):
    req = urllib.request.Request(
        f"{url}/v1/completions", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode())


def seg_sum_ms(rec) -> float:
    return sum(d for _n, _s, d in rec.segs)


class TestRoundTimer:
    def test_segments_bounded_by_wall(self):
        p = Profiler(armed=True)
        t = p.round_timer()
        with t.seg("admission"):
            time.sleep(0.002)
            with t.seg("prefill"):    # nested: parent excludes child
                time.sleep(0.002)
        with t.seg("dispatch"):
            time.sleep(0.004)
        mark = time.monotonic()
        time.sleep(0.001)
        t.add("readback", mark, time.monotonic() - mark)
        rec = p.finish_round(t, phase="decode")
        assert rec is not None
        # the segment ledger can never exceed the round wall (each
        # segment is a sub-interval of [t0, finish]; rounding is ms/3)
        assert seg_sum_ms(rec) <= rec.wall_ms + 0.01 * len(rec.segs)
        totals = rec.seg_totals()
        assert totals["dispatch"] >= 3.0
        assert set(totals) <= set(SEGMENTS)

    def test_add_skips_nonpositive(self):
        t = RoundTimer()
        t.add("readback", time.monotonic(), 0.0)
        t.add("readback", time.monotonic(), -1.0)
        assert t.segs == []

    def test_note_and_bump(self):
        t = RoundTimer()
        t.note(batch=3, rids=[7])
        t.bump("admitted")
        t.bump("admitted", 2)
        assert t.meta == {"batch": 3, "rids": [7], "admitted": 3}

    def test_noop_timer_records_nothing(self):
        p = Profiler(armed=False)
        with NOOP_TIMER.seg("dispatch"):
            pass
        NOOP_TIMER.add("host", 0.0, 1.0)
        NOOP_TIMER.bump("admitted")
        assert p.finish_round(NOOP_TIMER, phase="decode") is None
        assert p.rounds_recorded == 0 and p.rounds() == []

    def test_disarmed_round_timer_is_shared_noop(self):
        p = Profiler(armed=False)
        assert p.round_timer() is NOOP_TIMER
        p.arm()
        assert p.round_timer() is not NOOP_TIMER
        p.disarm()
        assert p.round_timer() is NOOP_TIMER


class TestProfilerRing:
    def test_capacity_bound_and_counters(self):
        p = Profiler(capacity=16, armed=True)
        for i in range(40):
            t = p.round_timer()
            t.note(i=i)
            p.finish_round(t, phase="decode")
        assert p.rounds_recorded == 40
        assert len(p.rounds()) == 16     # ring bounded
        assert p.rounds()[-1].meta["i"] == 39
        for i in range(40):
            p.event("dispatch", "decode_block", n_steps=4)
        assert p.events_recorded == 40
        assert len(p.events()) == 16
        p.clear()
        assert p.rounds() == [] and p.events() == []
        # counters survive clear: they are ledgers, not ring views
        assert p.rounds_recorded == 40

    def test_event_disarmed_is_noop(self):
        p = Profiler(armed=False)
        p.event("dispatch", "decode_block")
        assert p.events_recorded == 0


class _FakeCompileEngine:
    def __init__(self):
        self.programs = {"_decode": 1}

    def compiled_programs(self):
        return dict(self.programs)


class TestCompileWatch:
    def test_silent_before_traffic(self):
        eng = _FakeCompileEngine()
        w = CompileWatch(eng, grace=0.0)
        eng.programs["_decode"] = 5
        assert w.check() == []       # warm window: never reported

    def test_growth_after_grace_reported_once(self):
        eng = _FakeCompileEngine()
        w = CompileWatch(eng, grace=0.0)
        w.mark_traffic()
        eng.programs["_decode"] = 3
        eng.programs["_prefill_16"] = 1
        out = w.check()
        assert [(c["program"], c["count"]) for c in out] == [
            ("_decode", 2), ("_prefill_16", 1),
        ]
        # re-baselined: the same growth is not re-reported
        assert w.check() == []

    def test_growth_inside_grace_rebaselines_silently(self):
        eng = _FakeCompileEngine()
        w = CompileWatch(eng, grace=60.0)
        w.mark_traffic()
        eng.programs["_decode_block_8"] = 1   # lazy first dispatch
        assert w.check() == []
        # and it stays baselined once the grace window closes
        w._traffic_t0 -= 120.0
        assert w.check() == []
        eng.programs["_decode_block_8"] = 2   # genuine mid-run compile
        assert [c["program"] for c in w.check()] == ["_decode_block_8"]


class TestSchedulerLedger:
    def test_rounds_reconcile_and_ring_quiesces(self, model):
        """Armed end to end over HTTP: the profiler ring, the
        scheduler's rounds_total, and the profile_rounds metric are ONE
        ledger; idle wait-loops after quiesce leak zero records; every
        record's segment sum fits its wall; a completed request
        waterfalls with outcome ok."""
        m, params = model
        prof = Profiler(armed=True)
        reset_profiler(prof)
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, kv_block_size=8)
        metrics = ServingMetrics()
        with ApiServer(eng, block_size=4, metrics=metrics,
                       request_timeout=60) as srv:
            sched = srv.scheduler
            assert sched.profiler is prof
            for i in range(3):
                code, out = post(srv.url, {"prompt": [1 + i, 2, 3],
                                           "max_tokens": 4})
                assert code == 200
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and (
                eng.slots or sched.queue.qsize()
            ):
                time.sleep(0.01)
            assert not eng.slots
            settle = prof.rounds_recorded
            time.sleep(0.2)     # idle wait-loop rounds must not record
            assert prof.rounds_recorded == settle
            assert prof.rounds_recorded == sched.rounds_total > 0
            stats = sched.stats()
            assert stats["profile"]["armed"] is True
            assert stats["profile"]["rounds_total"] == sched.rounds_total
            body = render(metrics)
            if body:
                assert (f"tpuslice_serve_profile_rounds_total "
                        f"{float(sched.rounds_total)}") in body
                assert "tpuslice_serve_round_segment_seconds" in body
            for rec in prof.rounds():
                assert seg_sum_ms(rec) <= rec.wall_ms + 0.01 * len(rec.segs)
                assert {n for n, _s, _d in rec.segs} <= set(SEGMENTS)
            # dispatch/readback actually split (satellite: the gap
            # anchor lands at device_get, not after host bookkeeping)
            dispatched = [r for r in prof.rounds()
                          if r.meta.get("batch")]
            assert dispatched
            rids = []
            for rec in dispatched:
                rids.extend(rec.meta.get("rids") or [])
            w = waterfall_payload(str(rids[-1]))
            assert w["outcome"] == "ok"
            assert any(s["stage"].endswith("round") for s in w["stages"])
            assert w["rounds"]
            # the HTTP surface serves the same payload
            with urllib.request.urlopen(
                srv.url + f"/v1/debug/profile?rid={rids[-1]}", timeout=5
            ) as r:
                assert json.loads(r.read())["traceId"] == w["traceId"]


class TestWaterfallOutcomes:
    """Every terminal outcome stitches: ok, shed, drained,
    preempted-resumed, migrated."""

    def _rings(self):
        return Profiler(armed=True), Tracer(), Journal()

    def test_ok(self):
        p, t, j = self._rings()
        t.record("serve.queue", 1.0, trace_id="t1", start=100.0)
        t.record("serve.prefill", 2.0, trace_id="t1", start=100.001)
        t.record("serve.decode_round", 3.0, trace_id="t1",
                 start=100.003, phase="decode")
        t.record("serve.request", 6.0, trace_id="t1", start=100.0,
                 outcome="ok")
        w = waterfall_payload("t1", profiler=p, tracer=t, journal=j)
        assert w["outcome"] == "ok" and w["preemptions"] == 0
        assert [s["stage"] for s in w["stages"]] == [
            "queue", "prefill", "decode round"]
        assert w["totalMs"] == 6.0

    def test_preempted_resumed(self):
        p, t, j = self._rings()
        t.record("serve.preempt", 0.5, trace_id="t2", start=100.0)
        t.record("serve.resume", 0.5, trace_id="t2", start=100.01)
        t.record("serve.request", 20.0, trace_id="t2", start=100.0,
                 outcome="ok")
        w = waterfall_payload("t2", profiler=p, tracer=t, journal=j)
        assert w["outcome"] == "preempted-resumed"
        assert w["preemptions"] == 1

    @pytest.mark.parametrize("reason,outcome", [
        (REASON_SHED, "shed"),
        (REASON_DRAINED, "drained"),
        (REASON_SESSION_EXPORTED, "migrated"),
    ])
    def test_terminal_journal_outcomes(self, reason, outcome):
        """No root span recorded (the request never finished on this
        replica) — the journal's terminal event names the outcome."""
        p, t, j = self._rings()
        j.emit("scheduler", reason=reason, object_ref="rid:9",
               message="x", trace_id="t3")
        w = waterfall_payload("t3", profiler=p, tracer=t, journal=j)
        assert w["outcome"] == outcome
        assert w["markers"][0]["reason"] == reason

    def test_rid_maps_through_round_meta(self):
        p, t, j = self._rings()
        timer = p.round_timer()
        timer.note(rids=[42], trace_ids=["tX"])
        p.finish_round(timer, phase="decode")
        t.record("serve.request", 4.0, trace_id="tX", outcome="ok")
        w = waterfall_payload("42", profiler=p, tracer=t, journal=j)
        assert w["traceId"] == "tX" and w["outcome"] == "ok"
        assert len(w["rounds"]) == 1

    def test_unknown_rid_raises(self):
        p, t, j = self._rings()
        with pytest.raises(LookupError):
            waterfall_payload("no-such-request", profiler=p, tracer=t,
                              journal=j)


class TestChromeTrace:
    def test_structure_lanes_and_clock_shift(self):
        rounds = [{
            "idx": 1, "ts": 100.0, "wallMs": 5.0, "phase": "spec",
            "segs": [["dispatch", 0.5, 3.0], ["host", 3.5, 1.0]],
            "meta": {"batch": 2},
        }]
        events = [
            {"kind": "readback", "name": "spec_round", "ts": 100.004,
             "durMs": 3.0, "attrs": {"k": "2"}},
            {"kind": "dispatch", "name": "spec_round", "ts": 100.0005,
             "durMs": 0.0, "attrs": {}},
        ]
        spans = [{"name": "serve.request", "start": 100.0,
                  "durationMs": 5.0, "traceId": "t1",
                  "attrs": {"outcome": "ok"}}]
        doc = chrome_trace(rounds=rounds, events=events, spans=spans)
        doc = json.loads(json.dumps(doc))
        evs = doc["traceEvents"]
        assert doc["displayTimeUnit"] == "ms"
        procs = {e["args"]["name"] for e in evs
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert {"scheduler", "engine", "serve"} <= procs
        rnd = next(e for e in evs if e.get("cat") == "round")
        assert rnd["name"] == "round/spec" and rnd["ph"] == "X"
        assert rnd["dur"] == 5000.0 and rnd["args"]["batch"] == "2"
        seg = next(e for e in evs if e.get("cat") == "segment"
                   and e["name"] == "dispatch")
        assert seg["ts"] == rnd["ts"] + 500.0 and seg["dur"] == 3000.0
        # a duration event is stamped at its END: shifted back by dur
        rb = next(e for e in evs if e.get("cat") == "readback")
        assert rb["ph"] == "X"
        assert rb["ts"] == pytest.approx(1000.0, abs=1.0)
        inst = next(e for e in evs if e.get("cat") == "dispatch")
        assert inst["ph"] == "i" and "dur" not in inst
        for e in evs:
            assert e["ts"] >= 0

    def test_empty_inputs(self):
        assert chrome_trace()["traceEvents"] == []


class TestDebugPayload:
    def test_default_payload_keys(self):
        p = Profiler(armed=True)
        timer = p.round_timer()
        p.finish_round(timer, phase="decode")
        p.event("dispatch", "decode_block")
        out = debug_profile_payload({}, profiler=p)
        assert out["armed"] is True
        assert out["rounds"] == 1 and out["events"] == 1
        assert out["recent"][0]["phase"] == "decode"
        assert out["recentEvents"][0]["kind"] == "dispatch"
        assert "round" in out["segments"]

    def test_bad_n_raises_valueerror(self):
        for bad in (["0"], ["-3"], ["x"]):
            with pytest.raises(ValueError):
                debug_profile_payload({"n": bad},
                                      profiler=Profiler(armed=True))

    def test_process_default_singleton(self):
        assert get_profiler() is get_profiler()
        mine = Profiler(armed=True)
        reset_profiler(mine)
        assert get_profiler() is mine
        reset_profiler()
        assert get_profiler() is not mine
