"""End-to-end trace propagation tier (docs/OBSERVABILITY.md).

Two contracts, one per plane:

- **Control plane**: the trace id the controller mints when it admits a
  gated pod is persisted on the allocation record and carried by every
  span of the grant — ``controller.allocate`` → ``agent.realize`` →
  ``device.reserve`` (a child of the realize span) →
  ``controller.ungate`` — and the teardown spans of the same
  allocation, so one grant is queryable end to end.

- **Serving plane**: the trace id minted (or accepted from
  ``X-Trace-Id``) at HTTP admission is echoed on the response and
  shared by every span of the request's lifecycle — root
  ``serve.request`` plus ``serve.queue`` / ``serve.prefill`` /
  ``engine.prefill`` / ``serve.decode_round`` children — INCLUDING
  requests that terminate in shed (429), timeout (503), and drain
  (503) outcomes: a shed request must be traceable, not just counted.

Also covers ``GET /v1/debug/trace`` (the live drill-down surface the
``X-Trace-Id`` header points at) and the profiler metrics appearing in
exposition output via ``metrics.render()``.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from instaslice_tpu.faults import FaultPlan
from instaslice_tpu.metrics.metrics import ServingMetrics, render
from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.serving import ServingEngine
from instaslice_tpu.serving.api_server import ApiServer
from instaslice_tpu.sim import SimCluster
from instaslice_tpu.utils.trace import get_tracer, reset_tracer

VOCAB = 64


@pytest.fixture(autouse=True)
def fresh_tracer():
    """Each test gets a fresh process-default tracer (and components
    constructed inside the test bind to it): span assertions must not
    see another test's ring."""
    reset_tracer()
    yield
    reset_tracer()


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(
        vocab_size=VOCAB, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


def post(url, payload, path="/v1/completions", headers=None,
         timeout=60):
    """Returns (status, body dict, response headers dict)."""
    h = {"Content-Type": "application/json"}
    if headers:
        h.update(headers)
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(), headers=h,
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode()), dict(e.headers)


def get(url, path, timeout=10):
    try:
        with urllib.request.urlopen(url + path, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def wait_span(tracer, name, trace_id, timeout=10.0):
    """Spans land asynchronously (scheduler thread): poll the ring."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        mine = [s for s in tracer.trace(trace_id) if s.name == name]
        if mine:
            return mine[0]
        time.sleep(0.02)
    raise AssertionError(
        f"span {name!r} never appeared in trace {trace_id!r}; have "
        f"{[s.name for s in tracer.trace(trace_id)]}"
    )


class TestGrantTrace:
    def test_one_trace_id_pod_gate_to_ungate_and_teardown(self):
        tracer = get_tracer()
        with SimCluster(n_nodes=1, deletion_grace_seconds=0.2) as c:
            c.submit("t1", "v5e-1x1")
            assert c.wait_phase("t1", "Running", timeout=10)
            allocs = c.allocations()
            assert len(allocs) == 1
            tid = next(iter(allocs.values())).get("traceId", "")
            assert tid, "allocation record carries no trace id"
            c.delete_pod("t1")
            assert c.wait_gone("t1", timeout=10)
        spans = tracer.trace(tid)
        names = {s.name for s in spans}
        # the grant: admission → placement → realize → device → ungate
        assert {"controller.allocate", "agent.realize",
                "device.reserve", "controller.ungate"} <= names, names
        # ... and the teardown of the SAME allocation joins the trace
        assert {"controller.teardown", "agent.teardown",
                "device.release"} <= names, names
        # parentage: the device call is a child of the agent's realize
        realize = next(s for s in spans if s.name == "agent.realize")
        reserve = next(s for s in spans if s.name == "device.reserve")
        assert reserve.parent_id == realize.span_id
        assert realize.trace_id == reserve.trace_id == tid
        # exactly one grant trace: no other allocation trace bled in
        allocate = [s for s in spans if s.name == "controller.allocate"]
        assert len(allocate) == 1 and not allocate[0].parent_id


class TestServingTrace:
    def test_request_spans_share_client_trace_id(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8)
        metrics = ServingMetrics()
        tracer = get_tracer()
        with ApiServer(eng, block_size=4, metrics=metrics) as srv:
            code, out, hdrs = post(
                srv.url, {"prompt": [1, 2, 3], "max_tokens": 4},
                headers={"X-Trace-Id": "req-abc"},
            )
            assert code == 200, out
            assert hdrs.get("X-Trace-Id") == "req-abc"
            root = wait_span(tracer, "serve.request", "req-abc")
            assert not root.parent_id and root.attrs["outcome"] == "ok"
            spans = tracer.trace("req-abc")
            names = {s.name for s in spans}
            assert {"serve.request", "serve.queue", "serve.prefill",
                    "engine.prefill", "serve.decode_round"} <= names, \
                names
            # every lifecycle span shares the request's trace id, and
            # the direct children parent to the root's span id
            assert all(s.trace_id == "req-abc" for s in spans)
            for name in ("serve.queue", "serve.prefill",
                         "serve.decode_round"):
                s = next(x for x in spans if x.name == name)
                assert s.parent_id == root.span_id, (name, s.parent_id)
            # engine.prefill nests under serve.prefill (ambient ctx)
            ep = next(s for s in spans if s.name == "engine.prefill")
            sp = next(s for s in spans if s.name == "serve.prefill")
            assert ep.parent_id == sp.span_id

            # profiler metrics made it to exposition output
            text = render(metrics)
            for metric in ("tpuslice_serve_ttft_seconds",
                           "tpuslice_serve_tpot_seconds",
                           "tpuslice_serve_step_seconds",
                           "tpuslice_serve_phase_seconds_total",
                           "tpuslice_serve_batch_occupancy",
                           "tpuslice_serve_kv_cache_utilization"):
                assert metric in text, metric
            assert metrics.registry.get_sample_value(
                "tpuslice_serve_ttft_seconds_count"
            ) == 1
            assert metrics.registry.get_sample_value(
                "tpuslice_serve_step_seconds_count",
                {"phase": "prefill"},
            ) >= 1

    def test_trace_id_minted_when_header_absent_or_malformed(
        self, model
    ):
        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8)
        tracer = get_tracer()
        with ApiServer(eng, block_size=4) as srv:
            code, _, hdrs = post(srv.url, {"prompt": [1],
                                           "max_tokens": 2})
            assert code == 200
            minted = hdrs.get("X-Trace-Id", "")
            assert minted  # server minted one
            wait_span(tracer, "serve.request", minted)

            bad = "not a valid id!!"
            code, _, hdrs = post(srv.url,
                                 {"prompt": [1], "max_tokens": 2},
                                 headers={"X-Trace-Id": bad})
            assert code == 200
            assert hdrs.get("X-Trace-Id") not in ("", bad)

    def test_shed_timeout_drain_outcomes_are_traced(self, model):
        """The failure outcomes each get a root span carrying the
        client's trace id: 429 queue-full shed, 503 queue timeout, and
        503 drain refusal."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8)
        tracer = get_tracer()
        plan = FaultPlan(7)
        with ApiServer(eng, block_size=4, request_timeout=60,
                       max_queue=1, fault_plan=plan) as srv:
            # warm the compiled programs so the stall below is the
            # injected delay, not a jit compile (the warm-up runs with
            # a generous timeout; the timeout contract under test is
            # tightened only once the programs are hot)
            code, out, _ = post(srv.url, {"prompt": [1, 2],
                                          "max_tokens": 2})
            assert code == 200, out
            srv._srv.RequestHandlerClass.request_timeout = 0.75
            # arm AFTER warm-up: the next prefill stalls the scheduler
            # thread for 3 s — a deterministic busy window
            plan.site("engine.prefill", probability=1.0,
                      kinds=("delay",), delay_s=3.0, max_fires=1)

            # A: admitted into the stalled prefill → its client wait
            # expires → outcome "timeout"
            ta = threading.Thread(
                target=post,
                args=(srv.url, {"prompt": [3, 4], "max_tokens": 2}),
                kwargs={"headers": {"X-Trace-Id": "t-timeout"}},
                daemon=True,
            )
            ta.start()
            time.sleep(0.3)  # let A reach the scheduler
            # B: queued behind the stall (fills the 1-deep queue),
            # also times out
            tb = threading.Thread(
                target=post,
                args=(srv.url, {"prompt": [5, 6], "max_tokens": 2}),
                kwargs={"headers": {"X-Trace-Id": "t-timeout2"}},
                daemon=True,
            )
            tb.start()
            time.sleep(0.3)
            # C: queue full → 429 shed, traced synchronously
            code, _, hdrs = post(srv.url,
                                 {"prompt": [7], "max_tokens": 2},
                                 headers={"X-Trace-Id": "t-shed"})
            assert code == 429
            assert hdrs.get("X-Trace-Id") == "t-shed"
            shed = wait_span(tracer, "serve.request", "t-shed",
                             timeout=2)
            assert shed.attrs["outcome"] == "shed"

            ta.join(timeout=10)
            tb.join(timeout=10)
            to = wait_span(tracer, "serve.request", "t-timeout")
            assert to.attrs["outcome"] == "timeout"
            to2 = wait_span(tracer, "serve.request", "t-timeout2")
            assert to2.attrs["outcome"] == "timeout"

            # drain: admission refused with a traced 503
            code, body, _ = post(srv.url, {"budget": 5.0},
                                 path="/v1/drain")
            assert code == 200 and body["draining"], body
            code, _, hdrs = post(srv.url,
                                 {"prompt": [8], "max_tokens": 2},
                                 headers={"X-Trace-Id": "t-drain"})
            assert code == 503
            assert hdrs.get("X-Trace-Id") == "t-drain"
            dr = wait_span(tracer, "serve.request", "t-drain",
                           timeout=2)
            assert dr.attrs["outcome"] == "drained"


class TestDebugTraceEndpoint:
    def test_summary_slowest_recent_and_drilldown(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8)
        tracer = get_tracer()
        with ApiServer(eng, block_size=4) as srv:
            code, out, _ = post(
                srv.url, {"prompt": [1, 2, 3], "max_tokens": 4},
                headers={"X-Trace-Id": "dbg-1"},
            )
            assert code == 200, out
            wait_span(tracer, "serve.request", "dbg-1")

            code, body = get(srv.url, "/v1/debug/trace")
            assert code == 200
            assert "serve.request" in body["summary"]
            assert body["summary"]["serve.request"]["count"] >= 1
            assert {"p50Ms", "p95Ms", "maxMs"} <= set(
                body["summary"]["serve.request"]
            )
            assert body["recent"], "recent spans missing"
            roots = body["slowest"]
            assert roots and all(not s.get("parentId") for s in roots)

            # drill-down by the id the X-Trace-Id header advertised
            code, body = get(srv.url, "/v1/debug/trace?trace_id=dbg-1")
            assert code == 200 and body["traceId"] == "dbg-1"
            names = {s["name"] for s in body["spans"]}
            assert {"serve.request", "serve.prefill"} <= names
            # spans come back in start order
            starts = [s["start"] for s in body["spans"]]
            assert starts == sorted(starts)

            code, _ = get(srv.url,
                          "/v1/debug/trace?trace_id=nope-missing")
            assert code == 404
            code, _ = get(srv.url, "/v1/debug/trace?n=bogus")
            assert code == 400
