"""Pipeline parallelism: GPipe stages over a 'pipe' mesh axis.

The reference has no parallelism layer at all (SURVEY.md §2b); this
completes the SDK's DP/TP/PP/SP/EP set. Correctness bar: the pipelined
forward AND backward must match the single-device layer scan to fp
tolerance — the schedule, the ppermute hops, and autodiff through them
must be exactly equivalent math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.models.train import make_train_step
from instaslice_tpu.parallel.compat import supports_partial_manual
from instaslice_tpu.parallel.pipeline import pipeline_blocks

# GPipe composes a manual pipe axis with GSPMD-auto data/model axes;
# jax 0.4.x's shard_map cannot differentiate that composition (its
# auto= spelling mis-specs autodiff residuals), so the whole tier
# skips there — the capability gate lives in parallel/compat.py
pytestmark = pytest.mark.skipif(
    not supports_partial_manual(),
    reason="partial-manual shard_map (jax >= 0.5) required for GPipe",
)


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=4, d_ff=64,
        dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


def pipe_mesh(n):
    return Mesh(np.array(jax.devices()[:n]).reshape(n), ("pipe",))


class TestPipelineForward:
    def test_matches_unpipelined(self, model):
        m, params = model
        toks = jax.random.randint(jax.random.key(1), (8, 16), 0, 64)
        ref = m.apply(params, toks)
        out = m.apply_pipelined(params, toks, mesh=pipe_mesh(4), n_micro=4)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-4

    def test_microbatch_count_independent(self, model):
        # M=2 (deep bubble) and M=8 (one row per microbatch) must agree
        m, params = model
        toks = jax.random.randint(jax.random.key(2), (8, 16), 0, 64)
        mesh = pipe_mesh(2)
        a = m.apply_pipelined(params, toks, mesh=mesh, n_micro=2)
        b = m.apply_pipelined(params, toks, mesh=mesh, n_micro=8)
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4

    @pytest.mark.parametrize("policy", ["full", "dots"])
    def test_remat_stage_matches(self, policy):
        cfg = ModelConfig(
            vocab_size=64, d_model=32, n_heads=2, n_layers=4, d_ff=64,
            dtype=jnp.float32, remat=True, remat_policy=policy,
        )
        m = TpuLM(cfg)
        params = m.init(jax.random.key(0))
        toks = jax.random.randint(jax.random.key(3), (4, 16), 0, 64)
        ref = m.apply(params, toks)
        out = m.apply_pipelined(params, toks, mesh=pipe_mesh(4), n_micro=2)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-4

    def test_layer_count_not_divisible_raises(self, model):
        m, params = model  # 4 layers
        toks = jnp.zeros((4, 8), jnp.int32)
        with pytest.raises(ValueError, match="divisible"):
            m.apply_pipelined(params, toks, mesh=pipe_mesh(3), n_micro=2)

    def test_batch_not_divisible_raises(self, model):
        m, params = model
        toks = jnp.zeros((5, 8), jnp.int32)
        with pytest.raises(ValueError, match="n_micro"):
            m.apply_pipelined(params, toks, mesh=pipe_mesh(2), n_micro=4)


class TestPipelineBackward:
    def test_grads_match_unpipelined(self, model):
        m, params = model
        toks = jax.random.randint(jax.random.key(4), (8, 16), 0, 64)
        mesh = pipe_mesh(4)

        def loss_pp(p):
            return jnp.sum(
                m.apply_pipelined(p, toks, mesh=mesh, n_micro=4) ** 2
            ) / 1e4

        def loss_ref(p):
            return jnp.sum(m.apply(p, toks) ** 2) / 1e4

        g_pp = jax.grad(loss_pp)(params)
        g_ref = jax.grad(loss_ref)(params)
        worst = max(
            jax.tree.leaves(jax.tree.map(
                lambda a, b: float(jnp.max(jnp.abs(a - b))), g_pp, g_ref
            ))
        )
        assert worst < 1e-4, worst


class TestPipelinedTrainStep:
    def test_train_step_pipe_data_model_mesh(self, model):
        """Full 3-axis composition: PP over 'pipe', DP over 'data', TP
        over 'model' — one jitted step, loss finite and matching the
        unpipelined step at identical init."""
        m, _ = model
        devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
        mesh = Mesh(devs, ("pipe", "data", "model"))
        init_fn, step_fn = make_train_step(m, mesh, n_micro=2)
        state = init_fn(jax.random.key(0))
        # stacked layer weights shard one stage per pipe device
        wq = state.params["blocks"]["wq"]
        shard = next(iter(wq.addressable_shards))
        assert shard.data.shape[0] == wq.shape[0] // 2
        toks = jax.random.randint(jax.random.key(5), (4, 16), 0, 64)
        state, loss = step_fn(state, toks)
        assert bool(jnp.isfinite(loss))

        flat_mesh = Mesh(
            np.array(jax.devices()[:1]).reshape(1, 1, 1),
            ("data", "seq", "model"),
        )
        init2, step2 = make_train_step(m, flat_mesh)
        state2 = init2(jax.random.key(0))
        _, loss2 = step2(state2, toks)
        assert abs(float(loss) - float(loss2)) < 1e-3

    def test_n_micro_without_pipe_axis_raises(self, model):
        m, _ = model
        mesh = Mesh(
            np.array(jax.devices()[:2]).reshape(2, 1, 1),
            ("data", "seq", "model"),
        )
        with pytest.raises(ValueError, match="pipe"):
            make_train_step(m, mesh, n_micro=2)


class TestPipelineBlocksUnit:
    def test_identity_blocks(self):
        """Trivial per-layer fn: y = x + w_l; pipelined result must be
        x + sum(w) regardless of stage split."""
        mesh = pipe_mesh(4)
        L, B, S, D = 8, 4, 4, 8
        w = jnp.arange(L, dtype=jnp.float32).reshape(L, 1, 1, 1)
        params = {"w": w}
        x = jax.random.normal(jax.random.key(0), (B, S, D))

        def block(layer, h):
            return h + layer["w"][0]

        out = pipeline_blocks(block, params, x, mesh=mesh, n_micro=2,
                              remat=False)
        ref = x + float(sum(range(L)))
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-5
