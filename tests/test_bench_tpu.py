"""Timing-harness units from ``bench_tpu.py`` (CPU-runnable pieces).

The r3 harness once shipped a physically impossible 275 TFLOP/s on a
197-peak chip because a ~45 ms compute chain was timed against a
65-94 ms tunnel RTT. These tests pin the r4 guarantees: chains
auto-scale until compute dwarfs RTT, above-peak numbers are refused,
and the direct-int8 init used by the 7B serving phase produces a tree
the model actually runs (matching ``quantize_params`` layout).
"""

import datetime
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from instaslice_tpu.bench_tpu import (
    MIN_RTT_MULT,
    _chained_per_call,
    _init_quantized_params,
    _report_tflops,
)
from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.models.quant import QuantizedTensor, quantize_params


class TestChainedPerCall:
    def test_autoscale_reaches_rtt_floor_and_reports_evidence(self):
        stats = {}
        t = _chained_per_call(
            lambda x: x * 1.0000001, jnp.ones((8, 128)), n=1,
            stats=stats, budget_s=20.0,
        )
        assert t > 0
        # evidence keys the artifact carries
        assert set(stats) == {"chain_n", "rtt_ms", "wall_median_s",
                              "spread_pct", "reps"}
        assert stats["reps"] >= 2
        # the chain must have grown until compute >= MIN_RTT_MULT x RTT
        # (on CPU the RTT is microseconds, so even n=1 may pass — but
        # the invariant must hold for whatever n it settled on)
        rtt = stats["rtt_ms"] / 1000
        assert stats["wall_median_s"] - rtt >= MIN_RTT_MULT * rtt * 0.5 \
            or stats["chain_n"] > 1

    def test_chain_has_data_dependence(self):
        # n chained increments through one readback: per-call time is
        # wall/n, so doubling n must NOT double the reported per-call
        # time (it would if iterations were measured additively wrong)
        s1, s2 = {}, {}
        _chained_per_call(lambda x: x + 1, jnp.zeros((4, 4)), n=4,
                          stats=s1, budget_s=5.0)
        _chained_per_call(lambda x: x + 1, jnp.zeros((4, 4)), n=8,
                          stats=s2, budget_s=5.0)
        assert s1["chain_n"] >= 4 and s2["chain_n"] >= 8


class TestReportTflops:
    def test_plausible_number_published_with_evidence(self, monkeypatch):
        monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v5e")
        out = {}
        _report_tflops(out, "x_tflops", 150.0, {"chain_n": 64})
        assert out["x_tflops"] == 150.0
        assert out["x_tflops_timing"] == {"chain_n": 64}
        assert "x_tflops_error" not in out

    def test_above_peak_number_refused(self, monkeypatch):
        monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v5e")
        out = {}
        # return value gates derived metrics (speedups) on publication
        assert _report_tflops(out, "x_tflops", 275.1) is False  # r3 value
        assert _report_tflops(out, "y_tflops", 150.0) is True
        assert "x_tflops" not in out            # never published
        assert out["x_tflops_rejected"] == 275.1
        assert "impossible" in out["x_tflops_error"]

    def test_peak_depends_on_generation(self, monkeypatch):
        monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v5p")
        out = {}
        _report_tflops(out, "x_tflops", 275.1)  # fine on a 459-peak v5p
        assert out["x_tflops"] == 275.1


class TestInitQuantizedParams:
    CFG = ModelConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=3, d_ff=64,
        max_seq_len=64, dtype=jnp.bfloat16, remat=False,
    )

    @pytest.mark.parametrize("kv", [0, 2])
    def test_layout_matches_quantize_params(self, kv):
        """The direct-int8 tree must be indistinguishable (structure,
        shapes, dtypes) from init -> quantize_params, or the model's
        weight()/embed_lookup paths would diverge — for MHA and for the
        GQA layout the 7B phase serves."""
        import dataclasses

        cfg = dataclasses.replace(self.CFG, n_kv_heads=kv)
        direct = _init_quantized_params(cfg)
        via = quantize_params(TpuLM(cfg).init(jax.random.key(0)))

        d_leaves = jax.tree.leaves(direct)
        v_leaves = jax.tree.leaves(via)
        assert jax.tree.structure(direct) == jax.tree.structure(via)
        for dl, vl in zip(d_leaves, v_leaves):
            assert dl.shape == vl.shape
            assert dl.dtype == vl.dtype

    def test_model_runs_decode_on_direct_tree(self):
        params = _init_quantized_params(self.CFG)
        model = TpuLM(self.CFG)
        cache = model.init_cache(2, 16, quant=True)
        logits, cache = model.apply_with_cache(
            params, jnp.ones((2, 4), jnp.int32), cache,
            jnp.zeros((2,), jnp.int32),
        )
        assert logits.shape == (2, 4, 64)
        assert bool(jnp.isfinite(logits.astype(jnp.float32)).all())

    def test_scales_are_per_output_channel(self):
        params = _init_quantized_params(self.CFG)
        w_in = params["blocks"]["w_in"]
        assert isinstance(w_in, QuantizedTensor)
        # stacked (L, 1, F): one scale per (layer, output channel)
        assert w_in.s.shape == (3, 1, 64)
        embed = params["embed"]
        assert embed.s.shape == (64, 1)       # per-row (vocab) scale
        # int8 values actually span the range (not degenerate zeros)
        assert int(jnp.abs(w_in.q.astype(jnp.int32)).max()) > 50


class TestWedgeResilientBench:
    """bench.py's watchdog/store layer: per-phase persistence, the
    fold-in that lets the driver's run report phases captured earlier in
    the round, and the --once watchdog cycle (CPU-refusal path)."""

    def _bench_mod(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench_root", os.path.join(_REPO, "bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_store_roundtrip_atomic(self, tmp_path, monkeypatch):
        mod = self._bench_mod()
        monkeypatch.setattr(mod, "RESULTS_STORE",
                            str(tmp_path / "store.json"))
        store = mod._load_store()
        assert store["phases"] == {}
        store["phases"]["probe"] = {"readback_rtt_ms": 42.0}
        store["phase_ts"]["probe"] = mod._utcnow()
        mod._save_store(store)
        again = mod._load_store()
        assert again["phases"]["probe"]["readback_rtt_ms"] == 42.0
        assert not os.path.exists(str(tmp_path / "store.json.tmp"))

    def test_corrupt_store_is_ignored(self, tmp_path, monkeypatch):
        mod = self._bench_mod()
        p = tmp_path / "store.json"
        p.write_text("{not json")
        monkeypatch.setattr(mod, "RESULTS_STORE", str(p))
        assert mod._load_store()["phases"] == {}

    def test_fold_store_recovers_phases_with_provenance(self):
        mod = self._bench_mod()
        out = {
            "tpu_error": "probe dead",
            "tpu_probe_error": "probe dead",
            "tpu_flash_fwd_error": "skipped: probe failed",
            "tpu_mfu_error": "skipped: probe failed",
        }
        store = {
            "phases": {
                "flash_fwd": {"flash_fwd_tflops": 91.2,
                              "jax_backend": "tpu"},
                "mfu": {"train_mfu": 0.52},
            },
            "phase_ts": {"flash_fwd": "2026-07-30T10:00:00Z",
                         "mfu": "2026-07-30T10:05:00Z"},
        }
        mod._fold_store(out, store)
        assert out["flash_fwd_tflops"] == 91.2
        assert out["train_mfu"] == 0.52
        assert "tpu_flash_fwd_error" not in out
        assert "tpu_mfu_error" not in out
        # the probe failure itself stays reported — honesty about NOW
        assert "tpu_error" in out
        prov = out["tpu_results_provenance"]
        assert "flash_fwd@2026-07-30T10:00:00Z" in prov
        assert "mfu@2026-07-30T10:05:00Z" in prov

    def test_watchdog_once_journals_cpu_refusal(self, tmp_path):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["TPUSLICE_BENCH_STORE"] = str(tmp_path / "store.json")
        env["TPUSLICE_TPU_HEALTH_JOURNAL"] = str(tmp_path / "h.jsonl")
        env["TPUSLICE_TPU_LOCK"] = str(tmp_path / "tpu.lock")
        out = subprocess.run(
            [sys.executable, os.path.join(_REPO, "bench.py"),
             "--watchdog", "--once"],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert out.returncode == 0, out.stderr
        lines = [json.loads(ln) for ln in
                 (tmp_path / "h.jsonl").read_text().splitlines()]
        assert len(lines) == 1
        assert lines[0]["alive"] is False
        assert lines[0]["source"] == "watchdog"
        assert "ts" in lines[0]
        # nothing captured → no store written
        assert not (tmp_path / "store.json").exists()

    def test_drop_phases_flag(self, tmp_path):
        """--drop-phases removes named fragments (so the next watchdog
        cycle re-captures them after a code change) and rejects unknown
        names loudly."""
        env = dict(os.environ)
        env["TPUSLICE_BENCH_STORE"] = str(tmp_path / "store.json")
        # fresh timestamps: the store's max-age gate drops old phases
        # at load, which would vacuously pass the removal assertions
        now = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ")
        (tmp_path / "store.json").write_text(json.dumps({
            "phases": {"probe": {"readback_rtt_ms": 1.0},
                       "serving_7b": {"serving_7b_tokens_per_sec_b8": 9}},
            "phase_ts": {"probe": now, "serving_7b": now},
        }))
        bench = os.path.join(_REPO, "bench.py")
        out = subprocess.run(
            [sys.executable, bench, "--drop-phases", "serving_7b"],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stderr
        store = json.loads((tmp_path / "store.json").read_text())
        assert "serving_7b" not in store["phases"]
        assert "serving_7b" not in store["phase_ts"]
        assert "probe" in store["phases"]
        bad = subprocess.run(
            [sys.executable, bench, "--drop-phases", "nonsense"],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert bad.returncode == 2
        assert "unknown phases" in bad.stderr

    def test_store_drops_stale_and_unstamped_phases(self, tmp_path,
                                                    monkeypatch):
        """The store is committed, so the NEXT round would otherwise
        fold last round's numbers as 'captured earlier in the round'
        and its watchdog would see nothing missing. Phases past the
        max-age (or missing a timestamp) must vanish at load."""
        import datetime as dt

        mod = self._bench_mod()
        p = tmp_path / "store.json"
        monkeypatch.setattr(mod, "RESULTS_STORE", str(p))
        now = dt.datetime.now(dt.timezone.utc)
        old = (now - dt.timedelta(hours=20)).strftime("%Y-%m-%dT%H:%M:%SZ")
        new = now.strftime("%Y-%m-%dT%H:%M:%SZ")
        p.write_text(json.dumps({
            "phases": {"flash_fwd": {"flash_fwd_tflops": 91.2},
                       "mfu": {"train_mfu": 0.52},
                       "probe": {"readback_rtt_ms": 40.0}},
            "phase_ts": {"flash_fwd": old, "mfu": new},  # probe unstamped
        }))
        store = mod._load_store()
        assert set(store["phases"]) == {"mfu"}
        assert store["phase_ts"]["mfu"] == new


class TestMoeBenchPhase:
    def test_phase_runs_on_cpu_with_tiny_dims(self):
        """The whole moe phase end-to-end on the CPU path: both models
        compile, the chained-forward timing runs, and the output carries
        the matched-FLOPs evidence keys the artifact needs."""
        from instaslice_tpu.bench_tpu import bench_moe

        out = {}
        bench_moe(out, d_model=32, n_heads=4, n_layers=2, dense_ff=64,
                  n_experts=4, top_k=2, batch=2, seq=16, vocab=64,
                  chain_budget_s=5.0)
        assert out["moe_bench_dense_fwd_seconds"] > 0
        assert out["moe_bench_moe_fwd_seconds"] > 0
        for kind in ("dense", "moe"):
            ev = out[f"moe_bench_{kind}_fwd_seconds_timing"]
            assert set(ev) == {"chain_n", "rtt_ms", "wall_median_s",
                               "spread_pct", "reps"}
        assert "moe_bench_overhead_pct" in out
        assert "matched active FLOPs" in out["moe_bench_config"]

    def test_flop_parity_is_enforced(self):
        from instaslice_tpu.bench_tpu import bench_moe

        with pytest.raises(ValueError, match="parity"):
            bench_moe({}, dense_ff=63, top_k=2)

    def test_phase_registered_everywhere(self):
        """A phase missing from any of the three registries (subprocess
        dispatch, driver caps, watchdog priority) silently never runs."""
        from instaslice_tpu.bench_tpu import PHASES

        mod = self._bench_mod()
        assert "moe" in PHASES
        assert "moe" in dict(mod.TPU_PHASES)
        assert "moe" in mod.WATCHDOG_PRIORITY
        assert set(mod.WATCHDOG_PRIORITY) == set(dict(mod.TPU_PHASES))

    _bench_mod = TestWedgeResilientBench._bench_mod


class TestServingLoraBenchPhase:
    def test_phase_runs_on_cpu_with_tiny_dims(self):
        from instaslice_tpu.bench_tpu import bench_serving_lora

        out = {}
        bench_serving_lora(out, n_adapters=2, rank=2, d_model=32,
                           n_heads=4, n_layers=2, d_ff=64, vocab=64,
                           batch=3, max_len=64, prefill_len=8,
                           n_steps=8)
        assert out["serving_lora_base_tokens_per_sec"] > 0
        assert out["serving_lora_tokens_per_sec"] > 0
        assert "serving_lora_overhead_pct" in out
        assert "2 adapters rank 2" in out["serving_lora_config"]

    def test_phase_registered_everywhere(self):
        from instaslice_tpu.bench_tpu import PHASES

        mod = TestWedgeResilientBench._bench_mod(self)
        assert "serving_lora" in PHASES
        assert "serving_lora" in dict(mod.TPU_PHASES)
        assert "serving_lora" in mod.WATCHDOG_PRIORITY


class TestStoreConcurrency:
    def test_concurrent_record_phase_loses_nothing(self, tmp_path):
        """Two processes hammering _record_phase on one store must not
        clobber each other's phases (the fresh-load merge in
        _record_phase): every phase written by either process survives
        in the final file."""
        script = r"""
import os, sys
sys.path.insert(0, sys.argv[3])
import importlib.util
spec = importlib.util.spec_from_file_location(
    "bench_root", os.path.join(sys.argv[3], "bench.py"))
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
mod.RESULTS_STORE = sys.argv[1]
start = int(sys.argv[2])
for i in range(start, start + 20):
    mod._record_phase(f"phase{i}", {"v": i})
print("done")
"""
        store = str(tmp_path / "store.json")
        env = dict(os.environ)
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", script, store, str(base), _REPO],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            )
            for base in (0, 100)
        ]
        for p in procs:
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, err.decode()
        final = json.loads(open(store).read())
        have = set(final["phases"])
        # interleaved whole-file writes can drop at most the phases a
        # LOSING load-save window held — with merge-on-save the union
        # must be complete
        want = {f"phase{i}" for i in range(20)} | {
            f"phase{i}" for i in range(100, 120)
        }
        missing = want - have
        assert not missing, f"lost phases: {sorted(missing)}"
