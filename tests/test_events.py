"""Flight-recorder tier (docs/OBSERVABILITY.md "Events & audit trail"):
journal ring/sink/metrics behavior, the ``set_status`` audit trail and
its CR round-trip, Kubernetes Event mirroring, the debug endpoints, the
``tpuslice describe pod`` timeline stitcher, validate_events invariants,
and the doc-drift gate (every emitted reason AND span name must appear
in docs/OBSERVABILITY.md)."""

import json
import os
import re
import sys
import time
import urllib.request

import pytest

from instaslice_tpu.api.constants import (
    EVENT_REASONS,
    REASON_ADMITTED,
    REASON_NO_CAPACITY,
    REASON_PLACED,
    REASON_SLICE_CREATING,
    REASON_SLICE_FAILED,
    REASON_SLICE_UNGATED,
    REASON_UNGATED,
    TRACE_ID_ANNOTATION,
    TRANSITION_REASONS,
)
from instaslice_tpu.api.types import (
    AUDIT_TRAIL_MAX,
    AllocationDetails,
    AllocationStatus,
    PodRef,
)
from instaslice_tpu.kube.fake import FakeKube
from instaslice_tpu.metrics import metrics as metrics_mod
from instaslice_tpu.obs.journal import (
    Event,
    Journal,
    debug_events_payload,
    emit_pod_event,
    get_journal,
    reset_journal,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import validate_events  # noqa: E402


@pytest.fixture(autouse=True)
def fresh_journal():
    """Process-wide journal isolation (the reset_tracer analog)."""
    reset_journal()
    yield
    reset_journal()


def _alloc(trace="t-0000", alloc_id="a1"):
    return AllocationDetails(
        alloc_id=alloc_id,
        pods=[PodRef(pod_uuid="u1", pod_name="p1", namespace="d")],
        profile="v5e-1x1",
        torus_group="g",
        box="0,0,0+1x1x1",
        parts={"node-0": (0, "0,0,0+1x1x1")},
        trace_id=trace,
    )


class TestJournal:
    def test_emit_query_and_seq(self):
        clock = iter(float(i) for i in range(1, 100))
        j = Journal(clock=lambda: next(clock))
        j.emit("controller", reason=REASON_ADMITTED,
               object_ref="Pod/d/p1", trace_id="t1", message="m1")
        j.emit("serving", reason=REASON_NO_CAPACITY,
               object_ref="Pod/d/p2", trace_id="t2")
        j.emit("controller", reason=REASON_ADMITTED,
               object_ref="Pod/d/p3", extra="42")
        evs = j.events()
        assert [e.seq for e in evs] == [1, 2, 3]
        assert [e.ts for e in evs] == [1.0, 2.0, 3.0]  # injected clock
        assert [e.reason for e in j.events(reason=REASON_ADMITTED)] == \
            [REASON_ADMITTED, REASON_ADMITTED]
        assert [e.object_ref for e in j.events(object_ref="Pod/d/p2")] \
            == ["Pod/d/p2"]
        assert [e.trace_id for e in j.events(trace_id="t1")] == ["t1"]
        assert [e.seq for e in j.events(component="serving")] == [2]
        assert [e.seq for e in j.events(since_seq=2)] == [3]
        assert [e.seq for e in j.tail(2)] == [2, 3]
        assert evs[2].attrs == {"extra": "42"}
        assert j.counts() == {REASON_ADMITTED: 2, REASON_NO_CAPACITY: 1}

    def test_ring_bounded_counts_unbounded(self):
        j = Journal(capacity=4)
        for _ in range(10):
            j.emit("c", reason=REASON_ADMITTED)
        assert len(j.events()) == 4
        assert j.counts()[REASON_ADMITTED] == 10
        assert [e.seq for e in j.events()] == [7, 8, 9, 10]

    def test_unknown_reason_warns_but_records(self, caplog):
        j = Journal()
        with caplog.at_level("WARNING", logger="instaslice_tpu.obs"):
            j.emit("c", reason="NotInTheCatalog")
        assert j.events()[0].reason == "NotInTheCatalog"
        assert any("NotInTheCatalog" in r.message for r in caplog.records)

    def test_jsonl_sink_and_close(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        j = Journal(event_file=path)
        j.emit("c", reason=REASON_ADMITTED, object_ref="Pod/d/p",
               message="hello", trace_id="t9")
        j.close()
        j.close()  # idempotent
        recs = [json.loads(line) for line in open(path)]
        assert recs[0]["reason"] == REASON_ADMITTED
        assert recs[0]["objectRef"] == "Pod/d/p"
        assert recs[0]["traceId"] == "t9"
        assert Event.from_dict(recs[0]).message == "hello"
        # post-close emit still records to the ring, silently dropped
        # from the file
        j.emit("c", reason=REASON_ADMITTED)
        assert len(j.events()) == 2

    def test_env_file_binding(self, tmp_path, monkeypatch):
        path = str(tmp_path / "env-events.jsonl")
        monkeypatch.setenv("TPUSLICE_EVENT_FILE", path)
        reset_journal()  # re-read the env
        get_journal().emit("c", reason=REASON_ADMITTED)
        reset_journal()  # close the handle
        assert json.loads(open(path).read())["reason"] == REASON_ADMITTED

    @pytest.mark.skipif(not metrics_mod._PROM,
                        reason="prometheus_client missing")
    def test_metrics_counters_and_render(self):
        m = metrics_mod.EventMetrics()
        j = Journal(metrics=m)
        j.emit("controller", reason=REASON_ADMITTED)
        j.emit("controller", reason=REASON_ADMITTED)
        assert m.registry.get_sample_value(
            "tpuslice_events_total",
            {"component": "controller", "reason": REASON_ADMITTED},
        ) == 2
        assert m.registry.get_sample_value(
            "tpuslice_last_event_timestamp_seconds",
            {"component": "controller"},
        ) == pytest.approx(j.events()[-1].ts)
        text = metrics_mod.render(m)  # portless fallback
        assert "tpuslice_events_total" in text

    @pytest.mark.skipif(not metrics_mod._PROM,
                        reason="prometheus_client missing")
    def test_attach_metrics_fans_out_and_survives_reset(self):
        from instaslice_tpu.obs import journal as journal_mod

        def count(m):
            return m.registry.get_sample_value(
                "tpuslice_events_total",
                {"component": "controller", "reason": REASON_ADMITTED},
            )

        # controller + agent runners in one process: BOTH /metrics
        # registries carry the event counters (attach, not replace)
        m1 = metrics_mod.EventMetrics()
        m2 = metrics_mod.EventMetrics()
        journal_mod.attach_metrics(m2)
        try:
            j = Journal(metrics=m1)
            j.emit("controller", reason=REASON_ADMITTED)
            assert count(m1) == 1 and count(m2) == 1
            # attachment follows the PROCESS, not one instance: after a
            # reset_journal() swap the runner's counters keep counting
            reset_journal()
            get_journal().emit("controller", reason=REASON_ADMITTED)
            assert count(m2) == 2
        finally:
            journal_mod.detach_metrics(m2)
        get_journal().emit("controller", reason=REASON_ADMITTED)
        assert count(m2) == 2  # detached: no further counts


class TestAuditTrail:
    def test_set_status_records_and_journals(self):
        a = _alloc()
        a.set_status(AllocationStatus.CREATED)
        a.set_status(AllocationStatus.UNGATED)
        assert [t["status"] for t in a.transitions] == \
            ["created", "ungated"]
        evs = get_journal().events(object_ref="alloc/a1")
        assert [e.reason for e in evs] == [
            TRANSITION_REASONS["created"], REASON_SLICE_UNGATED,
        ]
        assert {e.trace_id for e in evs} == {"t-0000"}

    def test_same_status_records_nothing(self):
        a = _alloc()
        a.set_status(AllocationStatus.CREATING, "still here")
        assert a.transitions == []
        assert get_journal().events() == []

    def test_message_survives_cr_round_trip(self):
        # satellite contract: the human-readable message passed to
        # set_status persists through to_dict/from_dict, so the audit
        # trail survives controller restarts
        a = _alloc()
        a.set_status(AllocationStatus.FAILED,
                     "node-0: chip reservation failed")
        b = AllocationDetails.from_dict(a.to_dict())
        assert b == a
        assert b.transitions[-1]["message"] == \
            "node-0: chip reservation failed"
        assert b.transitions[-1]["status"] == "failed"
        assert b.transitions[-1]["ts"] > 0

    def test_trail_bounded(self):
        a = _alloc()
        for _ in range(AUDIT_TRAIL_MAX):
            a.set_status(AllocationStatus.FAILED, "boom")
            a.set_status(AllocationStatus.CREATING)
        assert len(a.transitions) == AUDIT_TRAIL_MAX

    def test_empty_trail_omitted_from_dict(self):
        assert "transitions" not in _alloc().to_dict()


class TestKubeEventMirroring:
    def test_event_object_shape(self):
        kube = FakeKube()
        emit_pod_event(
            kube, "d", "p1", reason=REASON_PLACED,
            message="placed v5e-1x1", component="controller",
            pod_uid="u1", trace_id="t42",
        )
        evs = kube.list("Event", namespace="d")
        assert len(evs) == 1
        ev = evs[0]
        assert ev["reason"] == REASON_PLACED
        assert ev["type"] == "Normal"
        assert ev["involvedObject"] == {
            "kind": "Pod", "namespace": "d", "name": "p1", "uid": "u1",
        }
        assert ev["source"] == {"component": "controller"}
        assert ev["metadata"]["annotations"][TRACE_ID_ANNOTATION] == "t42"
        assert ev["metadata"]["name"].startswith("p1.")
        assert "T" in ev["firstTimestamp"]  # RFC3339 for real clusters

    def test_mirror_failure_is_best_effort(self):
        class ExplodingKube:
            def create(self, kind, obj):
                raise RuntimeError("api down")

        ev = emit_pod_event(
            ExplodingKube(), "d", "p1", reason=REASON_PLACED,
            message="m", component="controller",
        )
        assert ev.reason == REASON_PLACED  # journaled despite the API
        assert get_journal().events()[-1].seq == ev.seq

    def test_warning_type_propagates(self):
        kube = FakeKube()
        emit_pod_event(
            kube, "d", "p1", reason=REASON_NO_CAPACITY, message="m",
            component="controller", event_type="Warning",
        )
        assert kube.list("Event")[0]["type"] == "Warning"


class TestDebugEndpoints:
    def test_payload_filters_and_bounds(self):
        j = get_journal()
        for i in range(5):
            j.emit("controller", reason=REASON_ADMITTED,
                   object_ref=f"Pod/d/p{i}", trace_id=f"t{i}")
        out = debug_events_payload({"reason": [REASON_ADMITTED],
                                    "n": ["2"]})
        assert out["total"] == 5
        assert [e["objectRef"] for e in out["events"]] == \
            ["Pod/d/p3", "Pod/d/p4"]
        out = debug_events_payload({"trace_id": ["t1"]})
        assert [e["traceId"] for e in out["events"]] == ["t1"]
        out = debug_events_payload({"object": ["Pod/d/p2"]})
        assert [e["objectRef"] for e in out["events"]] == ["Pod/d/p2"]
        out = debug_events_payload({"since_seq": ["4"]})
        assert [e["seq"] for e in out["events"]] == [5]
        with pytest.raises(ValueError):
            debug_events_payload({"n": ["0"]})

    def test_probe_server_serves_events(self):
        from instaslice_tpu.utils.probes import ProbeServer

        get_journal().emit("agent-node-0", reason=REASON_SLICE_CREATING,
                           object_ref="alloc/x", trace_id="tp")
        srv = ProbeServer("127.0.0.1:0").start()
        try:
            url = (f"http://127.0.0.1:{srv.port}/v1/debug/events"
                   f"?component=agent-node-0")
            with urllib.request.urlopen(url, timeout=5) as r:
                out = json.loads(r.read().decode())
            assert out["total"] == 1
            assert out["events"][0]["reason"] == REASON_SLICE_CREATING
            # malformed query → 400, probes stay up
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/v1/debug/events?n=-1",
                    timeout=5,
                )
                assert False, "expected HTTP 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=5
            ) as r:
                assert r.status == 200
        finally:
            srv.stop()


class TestDescribeTimeline:
    def test_sim_grant_stitched(self, tmp_path, monkeypatch):
        from instaslice_tpu.cli.tpuslicectl import (
            describe_pod,
            render_describe,
        )
        from instaslice_tpu.sim import SimCluster
        from instaslice_tpu.utils.trace import reset_tracer

        events_path = str(tmp_path / "events.jsonl")
        trace_path = str(tmp_path / "trace.jsonl")
        monkeypatch.setenv("TPUSLICE_EVENT_FILE", events_path)
        monkeypatch.setenv("TPUSLICE_TRACE_FILE", trace_path)
        reset_journal()
        reset_tracer()
        try:
            with SimCluster(n_nodes=1,
                            deletion_grace_seconds=0.2) as c:
                c.submit("describe-me", "v5e-1x1")
                assert c.wait_phase("describe-me", "Running", timeout=30)
                # Running means the gate dropped; the CREATED→UNGATED
                # CR status write can land a beat later — poll for the
                # settled state
                deadline = time.monotonic() + 10
                while True:
                    info = describe_pod(
                        c.kube, "describe-me", events_path=events_path,
                        trace_path=trace_path,
                    )
                    al = info["allocation"]
                    if (al and al["status"] == "ungated") or \
                            time.monotonic() > deadline:
                        break
                    time.sleep(0.05)
        finally:
            reset_journal()
            reset_tracer()
        assert info["phase"] == "Running"
        assert not info["gated"]
        al = info["allocation"]
        assert al is not None and al["status"] == "ungated"
        assert al["realizedOn"] == ["node-0"]
        assert info["traceId"]
        sources = {t["source"] for t in info["timeline"]}
        # surfaces stitched: CR audit trail, kube Events, trace spans
        # (journal entries mirror the first two for a clean grant and
        # are deduped away; journal-only events — kube transport,
        # erased retry epochs — would appear under "journal")
        assert sources >= {"audit", "event", "span"}, sources
        # cross-source dedup: each decision renders exactly once even
        # though it lands on 2-3 surfaces (journal + kube Event +
        # audit trail)
        reasons = [t["reason"] for t in info["timeline"]]
        for once in (REASON_ADMITTED, REASON_PLACED, REASON_UNGATED,
                     REASON_SLICE_UNGATED, "SliceCreating",
                     "SliceCreated"):
            assert reasons.count(once) == 1, (once, reasons)
        for want in (REASON_ADMITTED, REASON_PLACED, REASON_UNGATED,
                     REASON_SLICE_UNGATED, "controller.allocate",
                     "agent.realize"):
            assert want in reasons, (want, reasons)
        # ordered by timestamp
        stamps = [t["ts"] for t in info["timeline"]]
        assert stamps == sorted(stamps)
        text = render_describe(info)
        assert "SliceUngated" in text
        assert "controller.allocate" in text
        assert "phase=Running" in text

    def test_multihost_audit_trail_dedupes(self):
        # a 2-host allocation is fanned out to both holder CRs, and
        # each holder stamps its OWN transition timestamps — the
        # timeline must still show each transition once
        from instaslice_tpu.api.constants import API_VERSION, KIND
        from instaslice_tpu.cli.tpuslicectl import describe_pod

        kube = FakeKube()
        a = _alloc(trace="tmh", alloc_id="mh1")
        a.parts = {"node-0": (0, "0,0,0+2x2x1"),
                   "node-1": (1, "0,0,0+2x2x1")}
        a.set_status(AllocationStatus.CREATED)
        a.set_status(AllocationStatus.UNGATED)
        for node, skew in (("node-0", 0.0), ("node-1", 0.0042)):
            copy = AllocationDetails.from_dict(a.to_dict())
            for t in copy.transitions:
                t["ts"] += skew  # per-holder clocks diverge
            kube.create(KIND, {
                "apiVersion": API_VERSION, "kind": KIND,
                "metadata": {"name": node,
                             "namespace": "instaslice-tpu-system"},
                "spec": {"allocations": {copy.alloc_id: copy.to_dict()}},
                "status": {},
            })
        info = describe_pod(kube, "p1", namespace="d")
        reasons = [t["reason"] for t in info["timeline"]]
        assert reasons.count("SliceCreated") == 1, reasons
        assert reasons.count(REASON_SLICE_UNGATED) == 1, reasons

    def test_events_cmd_reads_and_filters(self, tmp_path, capsys):
        from instaslice_tpu.cli.tpuslicectl import main

        path = str(tmp_path / "ev.jsonl")
        j = Journal(event_file=path)
        j.emit("controller", reason=REASON_ADMITTED,
               object_ref="Pod/d/a", trace_id="t1")
        j.emit("serving", reason=REASON_NO_CAPACITY,
               object_ref="Pod/d/b", trace_id="t2")
        j.close()
        assert main(["events", path, "--reason", REASON_ADMITTED]) == 0
        out = [json.loads(line)
               for line in capsys.readouterr().out.splitlines()]
        assert [r["objectRef"] for r in out] == ["Pod/d/a"]
        assert main(["events", path, "--trace", "t2"]) == 0
        out = [json.loads(line)
               for line in capsys.readouterr().out.splitlines()]
        assert [r["reason"] for r in out] == [REASON_NO_CAPACITY]


class TestValidateEvents:
    def _write(self, tmp_path, records):
        path = str(tmp_path / "v.jsonl")
        with open(path, "w") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")
        return path

    def _transition(self, seq, status, ref="alloc/a", trace="t1"):
        return {
            "seq": seq, "ts": float(seq), "component": "allocation",
            "reason": TRANSITION_REASONS[status], "objectRef": ref,
            "traceId": trace,
        }

    def test_good_chain_passes_strict(self, tmp_path):
        path = self._write(tmp_path, [
            self._transition(1, "creating"),
            self._transition(2, "created"),
            self._transition(3, "created"),  # conflict-retry re-emit
            self._transition(4, "ungated"),
            self._transition(5, "deleted"),
        ])
        report = validate_events.validate(path)
        assert report["errors"] == [], report["errors"]

    def test_retry_epochs_split(self, tmp_path):
        path = self._write(tmp_path, [
            self._transition(1, "creating", trace="t1"),
            self._transition(2, "failed", trace="t1"),
            self._transition(3, "deleted", trace="t1"),
            self._transition(4, "creating", trace="t2"),
            self._transition(5, "created", trace="t2"),
            self._transition(6, "ungated", trace="t2"),
        ])
        assert validate_events.validate(path)["errors"] == []

    def test_illegal_chain_flagged(self, tmp_path):
        path = self._write(tmp_path, [
            self._transition(1, "creating"),
            self._transition(2, "ungated"),  # skips created
        ])
        errors = validate_events.validate(path)["errors"]
        assert any("illegal transition" in e for e in errors)
        assert any("creating->created->ungated" in e for e in errors)

    def test_phantom_tolerated_only_lenient(self, tmp_path):
        # created landed in the journal after failed (stale-read
        # phantom whose CR write lost the race)
        recs = [
            self._transition(1, "creating"),
            self._transition(2, "failed"),
            self._transition(3, "created"),
        ]
        strict = validate_events.validate(self._write(tmp_path, recs))
        assert any("illegal" in e for e in strict["errors"])
        lenient = validate_events.validate(
            self._write(tmp_path, recs), strict=False
        )
        assert lenient["errors"] == []

    def test_phantom_before_real_chain_tolerated_lenient(self, tmp_path):
        # the phantom can be the EARLIER event too: an agent's failed
        # that lost to a concurrent promote reads as creating → failed
        # → created → ungated → deleted (observed under make chaos) —
        # the lenient checker must re-anchor on the real continuation
        recs = [
            self._transition(1, "creating"),
            self._transition(2, "failed"),
            self._transition(3, "created"),
            self._transition(4, "ungated"),
            self._transition(5, "deleted"),
        ]
        strict = validate_events.validate(self._write(tmp_path, recs))
        assert any("illegal" in e for e in strict["errors"])
        lenient = validate_events.validate(
            self._write(tmp_path, recs), strict=False
        )
        assert lenient["errors"] == [], lenient["errors"]

    def test_missing_trace_and_unknown_reason(self, tmp_path):
        bad = self._transition(1, "creating", trace="")
        bad.pop("traceId")
        path = self._write(tmp_path, [
            bad,
            {"seq": 2, "ts": 2.0, "component": "x",
             "reason": "NotARealReason"},
            {"seq": 2, "ts": 3.0, "component": "x",
             "reason": REASON_ADMITTED},
        ])
        errors = validate_events.validate(path)["errors"]
        assert any("without a traceId" in e for e in errors)
        assert any("unknown reason" in e for e in errors)
        assert any("duplicate seq" in e for e in errors)

    def test_journal_ring_dicts_validate_like_the_file(self):
        # the chaos tier runs check_chains on the in-memory ring; keep
        # the two shapes interchangeable
        a = _alloc(trace="tt", alloc_id="ring1")
        a.set_status(AllocationStatus.FAILED, "x")
        a.set_status(AllocationStatus.CREATING)
        a.set_status(AllocationStatus.CREATED)
        a.set_status(AllocationStatus.UNGATED)
        errs = validate_events.check_chains(
            [e.to_dict() for e in get_journal().events()]
        )
        # the first event here is FAILED (no initial creating seeded by
        # from_placement in this synthetic alloc) — only that is flagged
        assert errs == [
            "alloc/ring1 epoch 0: chain starts at 'failed', "
            "not 'creating'",
        ]


class TestDocDrift:
    DOC = os.path.join(REPO, "docs", "OBSERVABILITY.md")

    def test_every_reason_documented(self):
        doc = open(self.DOC).read()
        missing = sorted(r for r in EVENT_REASONS if r not in doc)
        assert missing == [], (
            f"event reasons missing from docs/OBSERVABILITY.md: "
            f"{missing}"
        )

    def test_every_span_name_documented(self):
        span_re = re.compile(r'\.(?:span|record)\(\s*"([a-z][\w.]*)"')
        names = set()
        for dirpath, dirnames, files in os.walk(
            os.path.join(REPO, "instaslice_tpu")
        ):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in files:
                if fn.endswith(".py"):
                    with open(os.path.join(dirpath, fn)) as f:
                        names |= set(span_re.findall(f.read()))
        assert names, "span-name extraction regex found nothing"
        doc = open(self.DOC).read()
        missing = sorted(n for n in names if n not in doc)
        assert missing == [], (
            f"span names missing from docs/OBSERVABILITY.md: {missing}"
        )

    def test_reason_catalog_covers_transitions(self):
        assert set(TRANSITION_REASONS.values()) <= EVENT_REASONS
        from instaslice_tpu.api.types import AllocationStatus

        assert set(TRANSITION_REASONS) == \
            {s.value for s in AllocationStatus}

    def test_failed_reason_in_catalog(self):
        assert REASON_SLICE_FAILED in EVENT_REASONS
