"""Device-plugin tests: real gRPC over unix sockets with a fake kubelet.

The reference has zero device-plugin coverage (it assumes the GPU
operator's plugin exists — SURVEY.md §2a row 3); this tier exercises the
full registration → ListAndWatch → GetPreferredAllocation → Allocate
conversation kubelet would have with the plugin.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent import futures

import grpc
import pytest

from instaslice_tpu.device.fake import FakeTpuBackend
from instaslice_tpu.deviceplugin import deviceplugin_pb2 as pb
from instaslice_tpu.deviceplugin.server import (
    TpuDevicePlugin,
    chip_of,
    device_id,
    preferred_rectangle,
)
from instaslice_tpu.deviceplugin.wire import (
    HEALTHY,
    KUBELET_SOCKET,
    UNHEALTHY,
    DevicePluginClient,
    registration_handler,
)


class FakeKubelet:
    """Serves v1beta1.Registration and records registrations."""

    def __init__(self, plugin_dir: str) -> None:
        self.plugin_dir = plugin_dir
        self.registrations = []
        self.event = threading.Event()
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        self._server.add_generic_rpc_handlers((registration_handler(self),))
        self._server.add_insecure_port(
            f"unix://{os.path.join(plugin_dir, KUBELET_SOCKET)}"
        )
        self._server.start()

    def Register(self, request, context):
        self.registrations.append(request)
        self.event.set()
        return pb.Empty()

    def stop(self) -> None:
        self._server.stop(grace=0.5).wait()


@pytest.fixture()
def plugin_dir(tmp_path):
    d = tmp_path / "device-plugins"
    d.mkdir()
    return str(d)


@pytest.fixture()
def kubelet(plugin_dir):
    k = FakeKubelet(plugin_dir)
    yield k
    k.stop()


@pytest.fixture()
def plugin(plugin_dir, kubelet):
    p = TpuDevicePlugin(
        FakeTpuBackend(generation="v5e"),
        plugin_dir=plugin_dir,
        health_poll_seconds=0.1,
    )
    p.start()
    yield p
    p.stop()


@pytest.fixture()
def client(plugin):
    with grpc.insecure_channel(f"unix://{plugin.socket_path}") as ch:
        yield DevicePluginClient(ch)


class TestRegistration:
    def test_registers_with_kubelet(self, plugin, kubelet):
        assert kubelet.event.wait(5)
        (reg,) = kubelet.registrations
        assert reg.version == "v1beta1"
        assert reg.resource_name == "google.com/tpu"
        assert reg.endpoint == "tpuslice.sock"
        assert reg.options.get_preferred_allocation_available

    def test_reregisters_after_kubelet_restart(self, plugin, kubelet):
        assert kubelet.event.wait(5)
        kubelet.event.clear()
        # kubelet restart wipes the plugin's socket
        os.unlink(plugin.socket_path)
        assert kubelet.event.wait(5), "plugin did not re-register"
        # kubelet records the second registration before the plugin's
        # client call returns, so assert on the kubelet's ledger and poll
        # for the re-created socket rather than the plugin-side counter
        assert len(kubelet.registrations) == 2
        deadline = time.monotonic() + 5
        while not os.path.exists(plugin.socket_path):
            assert time.monotonic() < deadline, "socket not re-created"
            time.sleep(0.05)


class TestListAndWatch:
    def test_initial_inventory(self, plugin, client):
        stream = client.list_and_watch()
        resp = next(iter(stream))
        ids = [d.ID for d in resp.devices]
        assert ids == [device_id(i) for i in range(8)]  # v5e: 8 chips/host
        assert all(d.health == HEALTHY for d in resp.devices)
        stream.cancel()

    def test_health_transition_pushes_update(self, plugin, client):
        stream = client.list_and_watch()
        it = iter(stream)
        next(it)
        plugin.set_chip_health(3, healthy=False)
        resp = next(it)
        by_id = {d.ID: d.health for d in resp.devices}
        assert by_id[device_id(3)] == UNHEALTHY
        assert by_id[device_id(0)] == HEALTHY
        plugin.set_chip_health(3, healthy=True)
        resp = next(it)
        assert {d.health for d in resp.devices} == {HEALTHY}
        stream.cancel()

    def test_backend_failure_marks_all_unhealthy(self, plugin, client):
        stream = client.list_and_watch()
        it = iter(stream)
        next(it)
        plugin.backend.inject_failures("list", count=2)  # healthy() + next poll
        plugin.notify_health()
        resp = next(it)
        assert all(d.health == UNHEALTHY for d in resp.devices)
        stream.cancel()


class TestAllocate:
    def test_injects_device_nodes_and_env(self, plugin, client):
        resp = client.allocate([device_id(1), device_id(2)])
        (cresp,) = resp.container_responses
        assert [d.host_path for d in cresp.devices] == [
            "/dev/accel1", "/dev/accel2",
        ]
        assert all(d.container_path == d.host_path for d in cresp.devices)
        assert all(d.permissions == "rw" for d in cresp.devices)
        assert cresp.envs["TPU_KUBELET_ASSIGNED_CHIPS"] == "1,2"
        assert cresp.envs["TPU_PLATFORM"] == "v5e"
        assert cresp.annotations["tpu.instaslice.dev/chips"] == "1,2"

    def test_unknown_device_rejected(self, plugin, client):
        with pytest.raises(grpc.RpcError) as ei:
            client.allocate([device_id(99)])
        assert ei.value.code() == grpc.StatusCode.NOT_FOUND

    def test_non_tpu_id_rejected(self, plugin, client):
        with pytest.raises(grpc.RpcError) as ei:
            client.allocate(["gpu-0"])
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT


class TestPreferredAllocation:
    def test_prefers_contiguous_rectangle(self, plugin, client):
        # v5e host grid is 2x4x1 (ids row-major x-fastest): asking for 4 of
        # the 8 free chips must give an axis-aligned 2x2 box, not a strip.
        resp = client.preferred([device_id(i) for i in range(8)], size=4)
        (cresp,) = resp.container_responses
        chips = sorted(chip_of(d) for d in cresp.deviceIDs)
        assert chips == [0, 1, 2, 3]  # (0,0),(1,0),(0,1),(1,1) = 2x2 box

    def test_honours_must_include(self, client):
        resp = client.preferred(
            [device_id(i) for i in range(8)],
            size=2,
            must_include=[device_id(5)],
        )
        (cresp,) = resp.container_responses
        chips = sorted(chip_of(d) for d in cresp.deviceIDs)
        assert 5 in chips and len(chips) == 2
        # still a contiguous pair on the grid
        assert chips in ([4, 5], [5, 7], [3, 5])

    def test_fragmented_falls_back_to_fill(self, client):
        # only a non-rectangular scatter is available
        avail = [device_id(i) for i in (0, 3, 5, 6)]
        resp = client.preferred(avail, size=3)
        (cresp,) = resp.container_responses
        assert len(cresp.deviceIDs) == 3
        assert set(cresp.deviceIDs) <= set(avail)

    def test_options_advertise_preferred_allocation(self, client):
        opts = client.options()
        assert opts.get_preferred_allocation_available
        assert not opts.pre_start_required


class TestPreferredRectangleUnit:
    HB = (2, 4, 1)  # v5e host grid

    def test_full_host(self):
        assert preferred_rectangle(range(8), 8, self.HB) == list(range(8))

    def test_pair_is_adjacent(self):
        got = preferred_rectangle(range(8), 2, self.HB)
        # (1,2,1) shape at origin: (0,0) and (0,1) = ids 0 and 2 — an
        # ICI-adjacent pair along y on the 2x4 host grid
        assert got == [0, 2]

    def test_size_larger_than_available(self):
        assert preferred_rectangle([0, 1], 4, self.HB) == [0, 1]

    def test_must_include_not_available_ignored_gracefully(self):
        got = preferred_rectangle([0, 1, 2], 2, self.HB, must_include=[7])
        assert got == [0, 1]


class TestSliceMode:
    """Slice-mode plugin: realized reservations as per-profile devices."""

    def _plugin(self, backend, plugin_dir, profile):
        p = TpuDevicePlugin(
            backend, plugin_dir=plugin_dir,
            resource_name=f"google.com/tpu-{profile}",
            socket_name=f"tpuslice-{profile}.sock",
            register_with_kubelet=False,
            mode="slices", profile=profile,
        )
        p.start()
        return p

    def test_advertises_only_matching_profile(self, plugin_dir):
        backend = FakeTpuBackend(generation="v5e")
        backend.reserve("sl-a", [0, 1, 2, 3])        # 2x2 box
        backend.reserve("sl-b", [4])                 # 1x1
        p = self._plugin(backend, plugin_dir, "v5e-2x2")
        try:
            devs = p.device_list()
            assert [d.ID for d in devs] == ["slice-sl-a"]
        finally:
            p.stop()

    def test_multihost_parts_never_advertised(self, plugin_dir):
        """A node-local part of a multi-host allocation is a full-host
        tile; advertising it would let kubelet grant another job's chips."""
        backend = FakeTpuBackend(generation="v5e")
        backend.reserve("sl-mh-group1", list(range(8)))  # full 2x4 host
        p = self._plugin(backend, plugin_dir, "v5e-4x2")
        try:
            assert p.device_list() == []
            # a standalone whole-host reservation IS advertised
            backend.release("sl-mh-group1")
            backend.reserve("sl-solo", list(range(8)))
            assert [d.ID for d in p.device_list()] == ["slice-sl-solo"]
        finally:
            p.stop()

    def test_allocate_injects_reservation_chips(self, plugin_dir):
        backend = FakeTpuBackend(generation="v5e")
        backend.reserve("sl-x", [0, 1, 2, 3])
        p = self._plugin(backend, plugin_dir, "v5e-2x2")
        try:
            with grpc.insecure_channel(f"unix://{p.socket_path}") as ch:
                resp = DevicePluginClient(ch).allocate(["slice-sl-x"])
            cresp = resp.container_responses[0]
            inv = backend.discover()
            assert sorted(d.host_path for d in cresp.devices) == sorted(
                inv.chip_paths[c] for c in (0, 1, 2, 3)
            )
            assert cresp.envs["TPU_VISIBLE_CHIPS"] == "0,1,2,3"
            assert cresp.envs["TPU_KUBELET_ASSIGNED_CHIPS"] == "0,1,2,3"
        finally:
            p.stop()

    def test_allocate_unknown_reservation_rejected(self, plugin_dir):
        backend = FakeTpuBackend(generation="v5e")
        p = self._plugin(backend, plugin_dir, "v5e-2x2")
        try:
            with grpc.insecure_channel(f"unix://{p.socket_path}") as ch:
                with pytest.raises(grpc.RpcError):
                    DevicePluginClient(ch).allocate(["slice-nope"])
        finally:
            p.stop()
