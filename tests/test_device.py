"""Device layer tests: ONE behavioral suite run over all three backends
(fake, native-C++-via-ctypes against a synthetic /dev tree, and the
cloudtpu queued-resources client against a mocked API server), so no
backend can drift from the shared device semantics — the fidelity
requirement from SURVEY.md §7 ("Fake-TPU fidelity so e2e means
something without hardware").
"""

import os
import subprocess
import threading

import pytest

from instaslice_tpu.device import (
    ChipsBusy,
    CloudTpuBackend,
    DeviceError,
    FakeTpuBackend,
    NativeBackend,
    select_backend,
)
from instaslice_tpu.device.backend import SliceExists, SliceNotFound
from instaslice_tpu.device.cloudtpu_mock import CloudTpuMockServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "native", "build", "libtpuslice.so")


@pytest.fixture(scope="session")
def native_lib():
    if not os.path.exists(LIB):
        subprocess.run(
            ["make", "-C", os.path.join(REPO, "native")],
            check=True, capture_output=True,
        )
    return LIB


@pytest.fixture
def sim_root(tmp_path):
    (tmp_path / "dev").mkdir()
    for i in range(8):
        (tmp_path / "dev" / f"accel{i}").touch()
    return str(tmp_path)


@pytest.fixture
def cloud_mock():
    with CloudTpuMockServer() as srv:
        yield srv


def make_backend(kind, native_lib, sim_root, cloud_mock=None):
    if kind == "fake":
        return FakeTpuBackend(generation="v5e")
    if kind == "cloudtpu":
        return CloudTpuBackend(api_base=cloud_mock.url, generation="v5e")
    return NativeBackend(
        library_path=native_lib, root=sim_root, generation="v5e"
    )


@pytest.fixture(params=["fake", "native", "cloudtpu"])
def backend(request, native_lib, sim_root, cloud_mock):
    return make_backend(request.param, native_lib, sim_root, cloud_mock)


class TestBackendContract:
    def test_discover(self, backend):
        inv = backend.discover()
        assert inv.generation == "v5e"
        assert inv.chip_count == 8
        # path scheme is backend-specific (/dev node vs cloud resource);
        # the contract is a stable per-chip identifier
        assert inv.chip_paths[0].endswith(("accel0", "chip0"))

    def test_reserve_release_cycle(self, backend):
        r = backend.reserve("s-1", [0, 1, 2, 3])
        assert r.chip_ids == (0, 1, 2, 3)
        assert [x.slice_uuid for x in backend.list_reservations()] == ["s-1"]
        backend.release("s-1")
        assert backend.list_reservations() == []

    def test_overlap_rejected(self, backend):
        backend.reserve("s-1", [0, 1])
        with pytest.raises(ChipsBusy):
            backend.reserve("s-2", [1, 2])
        backend.reserve("s-2", [2, 3])  # disjoint is fine

    def test_duplicate_uuid_rejected(self, backend):
        backend.reserve("s-1", [0])
        with pytest.raises(SliceExists):
            backend.reserve("s-1", [4])

    def test_release_unknown(self, backend):
        with pytest.raises(SliceNotFound):
            backend.release("nope")

    def test_empty_args_rejected(self, backend):
        with pytest.raises(DeviceError):
            backend.reserve("", [0])
        with pytest.raises(DeviceError):
            backend.reserve("s", [])

    def test_unknown_chip_rejected(self, backend):
        with pytest.raises(DeviceError, match="not on this host"):
            backend.reserve("s", [99])

    def test_concurrent_reserves_no_double_grant(self, backend):
        """8 threads race for single chips; every chip granted once."""
        granted, errs = [], []

        def worker(i):
            try:
                granted.append(backend.reserve(f"c-{i}", [i]).chip_ids)
            except DeviceError as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        flat = [c for ids in granted for c in ids]
        assert sorted(flat) == list(range(8))


class TestChipHealth:
    def test_all_healthy_by_default(self, backend):
        h = backend.chip_health()
        assert len(h) == 8 and all(h.values())

    def test_fake_fail_and_heal(self):
        b = FakeTpuBackend(generation="v5e")
        b.fail_chip(3)
        h = b.chip_health()
        assert h[3] is False and h[0] is True
        with pytest.raises(DeviceError, match="unhealthy"):
            b.reserve("s", [2, 3])
        b.heal_chip(3)
        assert b.chip_health()[3] is True
        b.reserve("s", [2, 3])

    def test_native_missing_device_node(self, native_lib, sim_root):
        """A reserved chip whose /dev node vanishes (driver unbound the
        failed chip) must be reported unhealthy, not dropped."""
        b = NativeBackend(library_path=native_lib, root=sim_root,
                          generation="v5e")
        b.reserve("s", [0, 1])
        os.unlink(os.path.join(sim_root, "dev", "accel0"))
        h = b.chip_health()
        assert h[0] is False
        assert h[1] is True and len(h) == 8

    def test_native_unreserved_vanished_chip_still_reported(
        self, native_lib, sim_root
    ):
        """An UNRESERVED chip whose device node vanishes must also appear
        unhealthy (via the inventory persisted at discover) — otherwise
        placement retries the phantom chip forever."""
        b = NativeBackend(library_path=native_lib, root=sim_root,
                          generation="v5e")
        b.discover()  # persists the 8-chip inventory baseline
        os.unlink(os.path.join(sim_root, "dev", "accel7"))
        h = b.chip_health()
        assert h[7] is False and len(h) == 8


class TestNativeSpecifics:
    def test_registry_survives_restart(self, native_lib, sim_root):
        b1 = NativeBackend(library_path=native_lib, root=sim_root,
                           generation="v5e")
        b1.reserve("s-1", [0, 1])
        # "restart": a brand-new binding against the same root
        b2 = NativeBackend(library_path=native_lib, root=sim_root,
                           generation="v5e")
        live = b2.list_reservations()
        assert [(r.slice_uuid, r.chip_ids) for r in live] == [("s-1", (0, 1))]
        with pytest.raises(ChipsBusy):
            b2.reserve("s-2", [1])

    def test_discover_no_generation_fails_clearly(self, native_lib, sim_root,
                                                  monkeypatch):
        monkeypatch.delenv("TPUSLICE_GENERATION", raising=False)
        b = NativeBackend(library_path=native_lib, root=sim_root)
        with pytest.raises(DeviceError, match="TPUSLICE_GENERATION"):
            b.discover()

    def test_env_hints(self, native_lib, sim_root, monkeypatch):
        monkeypatch.setenv("TPUSLICE_GENERATION", "v5e")
        monkeypatch.setenv("TPUSLICE_TORUS_GROUP", "pod-7")
        monkeypatch.setenv("TPUSLICE_HOST_OFFSET", "2,0,0")
        b = NativeBackend(library_path=native_lib, root=sim_root)
        inv = b.discover()
        assert inv.torus_group == "pod-7"
        assert inv.host_offset == (2, 0, 0)

    def test_missing_library(self, monkeypatch):
        monkeypatch.setenv("TPUSLICE_LIBRARY", "/nonexistent/lib.so")
        with pytest.raises(DeviceError, match="libtpuslice"):
            NativeBackend()

    def test_empty_dev_tree(self, native_lib, tmp_path):
        (tmp_path / "dev").mkdir()
        b = NativeBackend(library_path=native_lib, root=str(tmp_path),
                          generation="v5e")
        inv = b.discover()
        assert inv.chip_count == 0 and inv.source == "none"


class TestFakeSpecifics:
    def test_failure_injection(self):
        b = FakeTpuBackend()
        b.inject_failures("reserve", 2)
        for _ in range(2):
            with pytest.raises(DeviceError, match="injected"):
                b.reserve("s", [0])
        b.reserve("s", [0])  # third attempt succeeds

    def test_dangling_seed_and_restart(self):
        b = FakeTpuBackend()
        b.seed_dangling("zombie", [4, 5])
        with pytest.raises(ChipsBusy):
            b.reserve("s", [5])
        snap = b.snapshot()
        b2 = FakeTpuBackend()
        b2.restore(snap)
        assert b2.list_reservations()[0].slice_uuid == "zombie"

class TestCloudTpuSpecifics:
    def test_registry_is_the_cloud_restart_safe(self, cloud_mock):
        b1 = CloudTpuBackend(api_base=cloud_mock.url, generation="v5e")
        b1.reserve("s-1", [0, 1])
        # "restart": a brand-new client against the same control plane
        b2 = CloudTpuBackend(api_base=cloud_mock.url, generation="v5e")
        live = b2.list_reservations()
        assert [(r.slice_uuid, r.chip_ids) for r in live] == \
            [("s-1", (0, 1))]
        with pytest.raises(ChipsBusy):
            b2.reserve("s-2", [1])

    def test_failed_provisioning_surfaces_and_uuid_reusable(
        self, cloud_mock
    ):
        cloud_mock.fail_next_create()
        b = CloudTpuBackend(api_base=cloud_mock.url, generation="v5e")
        with pytest.raises(DeviceError, match="FAILED"):
            b.reserve("s-1", [0])
        # the failed resource was cleaned up: the agent's retry with the
        # same uuid must not hit SliceExists
        r = b.reserve("s-1", [0])
        assert r.chip_ids == (0,)

    def test_failed_resource_marks_chips_unhealthy(self, cloud_mock):
        b = CloudTpuBackend(api_base=cloud_mock.url, generation="v5e")
        cloud_mock.fail_next_create()
        # leave the FAILED resource in place (bypass reserve's cleanup)
        # to model the cloud reporting bad accelerators
        orig = b.release
        b.release = lambda uuid: None
        with pytest.raises(DeviceError):
            b.reserve("s-bad", [2, 3])
        b.release = orig
        h = b.chip_health()
        assert h[2] is False and h[3] is False and h[0] is True
        assert len(h) == 8

    def test_provision_timeout_releases_the_resource(self):
        # provisioning never completes: reserve must fail AND clean up,
        # or the uuid hits SliceExists on the agent's retry and the
        # chips stay reserved server-side forever
        with CloudTpuMockServer(provision_polls=10 ** 6) as srv:
            b = CloudTpuBackend(api_base=srv.url, generation="v5e",
                                provision_timeout=0.3, poll_interval=0.02)
            with pytest.raises(DeviceError, match="not ACTIVE within"):
                b.reserve("s-stall", [0])
            assert b.list_reservations() == []

    def test_bearer_token_round_trip(self):
        with CloudTpuMockServer(required_token="tok-123") as srv:
            good = CloudTpuBackend(api_base=srv.url, generation="v5e",
                                   token="tok-123")
            good.reserve("s-1", [0])
            assert good.list_reservations()[0].slice_uuid == "s-1"
            bad = CloudTpuBackend(api_base=srv.url, generation="v5e",
                                  token="wrong")
            with pytest.raises(DeviceError, match="401"):
                bad.reserve("s-2", [1])

    def test_unreachable_api_is_device_error(self):
        b = CloudTpuBackend(api_base="http://127.0.0.1:1",
                            generation="v5e", provision_timeout=1)
        with pytest.raises(DeviceError, match="unreachable"):
            b.list_reservations()
        assert b.healthy() is False

    def test_env_configuration(self, cloud_mock, monkeypatch):
        monkeypatch.setenv("TPUSLICE_CLOUDTPU_API", cloud_mock.url)
        monkeypatch.setenv("TPUSLICE_GENERATION", "v4")
        b = select_backend("cloudtpu")
        inv = b.discover()
        assert inv.generation == "v4" and inv.chip_count == 4
        assert inv.source == "cloudtpu"

    def test_auto_prefers_cloudtpu_when_endpoint_set(
        self, cloud_mock, monkeypatch, tmp_path
    ):
        monkeypatch.setenv("TPUSLICE_CLOUDTPU_API", cloud_mock.url)
        # no /dev chips under this root → native is out, cloudtpu wins
        (tmp_path / "dev").mkdir()
        b = select_backend("auto", root=str(tmp_path))
        assert b.name == "cloudtpu"

    def test_missing_endpoint_fails_clearly(self, monkeypatch):
        monkeypatch.delenv("TPUSLICE_CLOUDTPU_API", raising=False)
        with pytest.raises(DeviceError, match="TPUSLICE_CLOUDTPU_API"):
            select_backend("cloudtpu")


class TestSelect:
    def test_select_fake(self, monkeypatch):
        monkeypatch.setenv("TPUSLICE_GENERATION", "v4")
        b = select_backend("fake")
        assert b.discover().generation == "v4"
        assert b.discover().chip_count == 4

    def test_select_unknown(self):
        with pytest.raises(DeviceError):
            select_backend("bogus")

    def test_select_native(self, native_lib, sim_root):
        b = select_backend("native", library_path=native_lib, root=sim_root,
                           generation="v5e")
        assert b.name == "native"
