"""Fleet serving tier (serving/router.py) + live KV session migration.

Four stories (docs/SERVING.md "Fleet router & session migration"):

- the **session wire format**: export→import on a fresh engine is
  token-identical to the uninterrupted run — greedy AND sampled
  (the RNG key rides the blob) — and a version/model/sampling mismatch
  is REJECTED, never resumed as garbage;
- the **/v1/stats fleet inputs**: ``replica_id`` (stable per-process
  nonce) + monotonic ``uptime_seconds`` + the ``sessions`` ledger;
- the **router's routing policy** (session affinity → prefix-cache
  affinity via the shadow digest index → least-loaded weighted by KV
  pressure), restart detection, and per-replica circuit breaking —
  pure unit tests over hand-fed stats, no engines;
- the **HTTP migration flow** end-to-end: two live replicas behind a
  router, a mid-stream session exported off one and spliced onto the
  other with zero re-prefill, token-identical to the oracle, with
  clean block/lock ledgers afterwards.

Plus the loadgen trace record/replay satellite (identical request
streams across bench arms).
"""

import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.serving import ServingEngine
from instaslice_tpu.serving.api_server import ApiServer
from instaslice_tpu.serving.kvcache import (
    SESSION_WIRE_VERSION,
    granule_hash,
    tree_to_wire,
    wire_to_tree,
)
from instaslice_tpu.serving.router import NoReplica, Replica, Router


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


def greedy_reference(model, params, prompt, n_new):
    toks = list(prompt)
    out = []
    for _ in range(n_new):
        logits = model.apply(params, jnp.asarray(toks, jnp.int32)[None])
        t = int(jnp.argmax(logits[0, -1]))
        out.append(t)
        toks.append(t)
    return out


def make_engine(model, **kw):
    m, params = model
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_len", 96)
    kw.setdefault("prefill_len", 8)
    return ServingEngine(m, params, **kw)


def migrate_once(src: ServingEngine, dst: ServingEngine,
                 interrupt_at: int, total: int, prompt):
    """Decode ``interrupt_at`` tokens on ``src``, export→import the
    session, finish on ``dst``; returns the full stitched chain of
    ``total`` tokens. (A parked request carries interrupt_at + 1
    tokens: ``generated[-1]`` is the sampled-but-unwritten pending
    token — preempt_slot's documented shape.)"""
    rid = src.add_request(list(prompt))
    got = list(src.decode_block(interrupt_at)[rid])
    slot = next(s for s, r in src.slots.items()
                if r.request_id == rid)
    src.preempt_slot(slot)
    blob = src.export_session(rid)
    # the wire format must be JSON-clean END TO END: what crosses the
    # DCN path is exactly what a peer imports
    blob = json.loads(json.dumps(blob))
    src.drop_parked(rid)
    rid2 = dst.import_session(blob)
    parked_gen = list(dst.parked[rid2].req.generated)
    assert parked_gen[:interrupt_at] == got
    assert len(parked_gen) == interrupt_at + 1
    dst.resume_request(rid2)
    dst.decode_block(total - interrupt_at - 1)
    req = next(r for r in dst.slots.values()
               if r.request_id == rid2)
    out = list(req.generated)
    assert out[:interrupt_at] == got
    return out


class TestSessionWireFormat:
    def test_greedy_roundtrip_token_identical(self, model):
        m, params = model
        oracle = greedy_reference(m, params, [5, 9, 2, 7], 12)
        src = make_engine(model)
        dst = make_engine(model)
        rid = src.add_request([5, 9, 2, 7])
        src.decode_block(5)
        slot = next(s for s, r in src.slots.items()
                    if r.request_id == rid)
        src.preempt_slot(slot)
        blob = src.export_session(rid)
        assert blob["version"] == SESSION_WIRE_VERSION
        assert src.exported_total == 1
        # the blob is pure JSON — ship-ready with no pickle anywhere
        blob = json.loads(json.dumps(blob))
        src.drop_parked(rid)
        rid2 = dst.import_session(blob)
        assert dst.imported_total == 1
        dst.resume_request(rid2)
        # parked state already carries 6 tokens (5 decoded + the
        # pending one); 6 more resumed steps complete the 12
        dst.decode_block(6)
        req = next(r for r in dst.slots.values()
                   if r.request_id == rid2)
        assert list(req.generated) == oracle

    def test_sampled_roundtrip_replays_source_stream(self, model):
        """temperature > 0: the RNG key rides the blob, so the
        migrated continuation equals the UNINTERRUPTED sampled run on
        the source — even though the destination engine was built with
        a different seed."""
        uninterrupted = make_engine(model, temperature=0.8, seed=3)
        rid = uninterrupted.add_request([5, 9, 2, 7])
        oracle = list(uninterrupted.decode_block(12)[rid])
        src = make_engine(model, temperature=0.8, seed=3)
        dst = make_engine(model, temperature=0.8, seed=99)
        chain = migrate_once(src, dst, 5, 12, [5, 9, 2, 7])
        assert chain == oracle

    def test_version_mismatch_rejected(self, model):
        src = make_engine(model)
        dst = make_engine(model)
        rid = src.add_request([5, 9, 2, 7])
        src.decode_block(3)
        src.preempt_slot(next(iter(src.slots)))
        blob = src.export_session(rid)
        bad = dict(blob, version=SESSION_WIRE_VERSION + 1)
        with pytest.raises(ValueError, match="wire version"):
            dst.import_session(bad)
        # model-shape mismatch: a differently-shaped replica must
        # refuse the stripe outright
        small = make_engine(model, max_len=64)
        with pytest.raises(ValueError, match="incompatible"):
            small.import_session(blob)
        # sampling mismatch: resuming under a different distribution
        # would silently change the output
        hot = make_engine(model, temperature=1.5, seed=1)
        with pytest.raises(ValueError, match="sampling"):
            hot.import_session(blob)
        assert dst.imported_total == 0

    def test_import_is_parked_and_droppable(self, model):
        """An imported session holds pool blocks like any parked
        request — and drop_parked releases every one of them."""
        src = make_engine(model)
        dst = make_engine(model)
        rid = src.add_request([5, 9, 2, 7])
        src.decode_block(3)
        src.preempt_slot(next(iter(src.slots)))
        blob = src.export_session(rid)
        free0 = dst.kv.free_blocks()
        rid2 = dst.import_session(blob)
        assert dst.kv.free_blocks() < free0
        assert rid2 in dst.parked
        dst.drop_parked(rid2)
        assert dst.kv.free_blocks() == free0

    def test_tree_wire_roundtrip_structure(self):
        import numpy as np

        tree = {
            "k": (np.arange(6, dtype=np.float32).reshape(2, 3),
                  np.ones((1, 2), np.int32)),
            "nested": [{"v": np.zeros((2,), np.float32)}],
            "scalar": 3,
        }
        back = wire_to_tree(json.loads(json.dumps(tree_to_wire(tree))))
        assert isinstance(back["k"], tuple)          # tuples survive
        assert np.array_equal(back["k"][0], tree["k"][0])
        assert back["k"][0].dtype == np.float32
        assert np.array_equal(back["nested"][0]["v"],
                              tree["nested"][0]["v"])
        assert back["scalar"] == 3


class TestStatsFleetInputs:
    def test_replica_id_and_uptime(self, model):
        from instaslice_tpu.serving.scheduler import REPLICA_ID

        eng = make_engine(model)
        with ApiServer(eng, block_size=4) as srv:
            s1 = json.loads(urllib.request.urlopen(
                srv.url + "/v1/stats", timeout=10).read())
            time.sleep(0.05)
            s2 = json.loads(urllib.request.urlopen(
                srv.url + "/v1/stats", timeout=10).read())
        assert s1["replica_id"] == s2["replica_id"] == REPLICA_ID
        assert len(s1["replica_id"]) == 12
        # monotonic: the router's staleness/restart detector
        assert s2["uptime_seconds"] > s1["uptime_seconds"] >= 0
        ledger = s1["sessions"]
        assert ledger == {
            "exported": 0, "imported": 0, "migrated_out": 0,
            "migrated_in": 0, "migrate_preempts": 0,
            "imports_pending": 0,
        }
        assert "digest" in s1["radix"]


def fed_replica(url="http://stub:1", queued=0, live=0, parked=0,
                kv_free=100, kv_total=100, max_batch=8, chains=(),
                granule=8, replica_id="r", uptime=10.0,
                tenant_classes=None) -> Replica:
    """A Replica fed a hand-built /v1/stats poll (no HTTP anywhere)."""
    rep = Replica(url)
    rep.adopt_stats({
        "replica_id": replica_id, "uptime_seconds": uptime,
        "queued": queued, "live_slots": live, "parked": parked,
        "max_batch": max_batch,
        "kv": {"free": kv_free, "total": kv_total},
        "radix": {"digest": {"granule": granule,
                             "paths": [list(c) for c in chains]}},
        "tenant_classes": tenant_classes or {},
    })
    return rep


def unstarted_router(*reps: Replica, **kw) -> Router:
    """A Router that never opens sockets to anything: replicas are
    injected post-construction with their stats already adopted."""
    r = Router(port=0, **kw)
    for rep in reps:
        r._replicas[rep.url] = rep
    # close the (never-started) HTTP socket so tests don't leak fds
    r._srv.server_close()
    return r


def chain_for(prompt, granule):
    return [granule_hash(tuple(prompt[i * granule:(i + 1) * granule]))
            for i in range(len(prompt) // granule)]


class TestRoutingPolicy:
    def test_policy_order_session_beats_prefix_beats_load(self):
        prompt = list(range(1, 17))
        g = 8
        idle = fed_replica("http://idle:1", replica_id="a")
        cached = fed_replica("http://cached:1", replica_id="b",
                             chains=[chain_for(prompt, g)], queued=3)
        r = unstarted_router(idle, cached)
        # no session, no prefix → least-loaded picks the idle one
        rep, policy = r.route([99, 98, 97], "", "")
        assert (rep.url, policy) == ("http://idle:1", "least-loaded")
        # prefix affinity beats load: cached replica is busier but
        # holds the prompt's granule chain
        rep, policy = r.route(prompt, "", "")
        assert (rep.url, policy) == ("http://cached:1", "prefix")
        # session affinity beats both
        r.pin_session("conv", "http://idle:1")
        rep, policy = r.route(prompt, "", "conv")
        assert (rep.url, policy) == ("http://idle:1", "session")

    def test_prefix_match_longest_chain_wins(self):
        g = 8
        p = list(range(1, 25))               # 3 granules
        short = fed_replica("http://s:1", replica_id="a",
                            chains=[chain_for(p[:8], g)])
        long = fed_replica("http://l:1", replica_id="b",
                           chains=[chain_for(p, g)], queued=5)
        r = unstarted_router(short, long)
        rep, policy = r.route(p, "", "")
        assert (rep.url, policy) == ("http://l:1", "prefix")
        # sub-granule prompts can't match anything → least-loaded
        rep, policy = r.route(p[:4], "", "")
        assert policy == "least-loaded"

    def test_kv_pressure_and_tenant_class_weighting(self):
        # same queue depth; the KV-starved replica loses
        starved = fed_replica("http://starved:1", replica_id="a",
                              kv_free=5, kv_total=100)
        roomy = fed_replica("http://roomy:1", replica_id="b",
                            kv_free=95, kv_total=100)
        r = unstarted_router(starved, roomy)
        rep, _ = r.route([1, 2, 3], "", "")
        assert rep.url == "http://roomy:1"
        # latency-class tenants penalize queue depth harder
        q = fed_replica("http://queued:1", replica_id="c", queued=4,
                        kv_free=100,
                        tenant_classes={"gold": "latency"})
        busy = fed_replica("http://busy:1", replica_id="d", live=6,
                           kv_free=60, kv_total=100)
        assert (q.load_score("latency") > q.load_score("standard"))

    def test_restart_detection_drops_affinity(self):
        rep = fed_replica("http://a:1", replica_id="one", uptime=50.0)
        r = unstarted_router(rep)
        r.pin_session("conv", rep.url)
        # same nonce, clock moved forward: no restart
        assert not rep.adopt_stats({"replica_id": "one",
                                    "uptime_seconds": 60.0})
        # new nonce = restarted process (cache and sessions died)
        assert rep.adopt_stats({"replica_id": "two",
                                "uptime_seconds": 1.0})
        # uptime going BACKWARDS under one nonce is also a restart
        # signal (nonce collision after a crash-loop respawn)
        assert rep.adopt_stats({"replica_id": "two",
                                "uptime_seconds": 0.2})

    def test_breaker_and_draining_drop_out(self):
        a = fed_replica("http://a:1", replica_id="a")
        b = fed_replica("http://b:1", replica_id="b")
        r = unstarted_router(a, b)
        for _ in range(a.breaker.threshold):
            a.breaker.fail()
        rep, _ = r.route([1], "", "")
        assert rep.url == "http://b:1"
        b.draining = True
        with pytest.raises(NoReplica):
            r.route([1], "", "")

    def test_stale_poll_drops_out(self):
        a = fed_replica("http://a:1", replica_id="a")
        r = unstarted_router(a, stale_after=0.05)
        time.sleep(0.08)
        with pytest.raises(NoReplica):
            r.route([1], "", "")

    def test_migration_destinations_prefer_prefix(self):
        g = 8
        p = list(range(1, 17))
        cached = fed_replica("http://cached:1", replica_id="a",
                             chains=[chain_for(p, g)], queued=5)
        idle = fed_replica("http://idle:1", replica_id="b")
        src = fed_replica("http://src:1", replica_id="c")
        r = unstarted_router(cached, idle, src)
        dests = r.migration_destinations(exclude=["http://src:1"],
                                         prompt=p)
        assert [d.url for d in dests] == ["http://cached:1",
                                          "http://idle:1"]


def post(url, payload, timeout=120):
    req = urllib.request.Request(
        f"{url}/v1/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def stream_tokens(url, payload, result, timeout=120):
    req = urllib.request.Request(
        f"{url}/v1/completions",
        data=json.dumps(dict(payload, stream=True)).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    toks = []
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        buf = b""
        while True:
            chunk = resp.read1(65536)
            if not chunk:
                result["error"] = "stream ended without [DONE]"
                return
            buf += chunk
            while b"\n\n" in buf:
                ev, buf = buf.split(b"\n\n", 1)
                line = ev.decode().strip()
                if not line.startswith("data: "):
                    continue
                data = line[len("data: "):]
                if data == "[DONE]":
                    result["tokens"] = toks
                    return
                p = json.loads(data)
                if "error" in p:
                    result["error"] = p["error"]
                    return
                for c in p.get("choices", []):
                    toks.extend(c.get("token_ids") or [])


class TestRouterHttpE2E:
    @pytest.fixture()
    def fleet(self, model):
        servers = [ApiServer(make_engine(model), block_size=4).start()
                   for _ in range(2)]
        router = Router([s.url for s in servers],
                        poll_interval=0.1).start()
        yield router, servers
        router.stop()
        for s in servers:
            s.stop()

    def wait_live(self, servers, timeout=5.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for s in servers:
                if s.scheduler.stats()["live_slots"]:
                    return s
            time.sleep(0.01)
        raise AssertionError("no replica ever held a live slot")

    def test_routed_completion_matches_oracle(self, model, fleet):
        m, params = model
        router, _servers = fleet
        oracle = greedy_reference(m, params, [1, 2, 3, 4], 10)
        code, out = post(router.url, {"prompt": [1, 2, 3, 4],
                                      "max_tokens": 10})
        assert code == 200
        assert out["choices"][0]["token_ids"] == oracle
        # the outcome is counted AFTER the response reaches the
        # client, on the router's handler thread — wait for it
        deadline = time.monotonic() + 5
        while (router.requests.get("ok") is None
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert router.requests.get("ok") == 1

    def test_midstream_migration_token_identical(self, model, fleet):
        """The tentpole flow: a streaming request's session is
        exported off its replica mid-decode; the router imports it
        into the peer and splices the resumed stream — the client
        sees ONE continuous, oracle-exact completion."""
        m, params = model
        router, servers = fleet
        oracle = greedy_reference(m, params, [7, 8, 9], 60)
        result: dict = {}
        t = threading.Thread(target=stream_tokens, args=(
            router.url, {"prompt": [7, 8, 9], "max_tokens": 60},
            result))
        t.start()
        victim = self.wait_live(servers)
        # trigger the export through the replica's own endpoint
        req = urllib.request.Request(
            victim.url + "/v1/sessions/export", data=b"{}",
            headers={"Content-Type": "application/json"},
            method="POST")
        moved = json.loads(urllib.request.urlopen(
            req, timeout=10).read())
        assert moved["migrated"] == 1
        t.join(timeout=120)
        assert "error" not in result, result
        assert result["tokens"] == oracle
        assert router.migrations.get("resumed", 0) >= 1
        # the outcome is counted AFTER the terminal [DONE] reaches the
        # client, on the router's handler thread — wait for it
        deadline = time.monotonic() + 5
        while (router.requests.get("ok-migrated") is None
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert router.requests.get("ok-migrated") == 1
        # ledgers: exported on one replica, imported on the other,
        # nothing parked or leaked anywhere after quiesce
        stats = [s.scheduler.stats() for s in servers]
        assert sum(s["sessions"]["exported"] for s in stats) == 1
        assert sum(s["sessions"]["imported"] for s in stats) == 1
        for s in servers:
            st = s.scheduler.stats()
            assert st["live_slots"] == 0 and st["parked"] == 0
            assert st["sessions"]["imports_pending"] == 0
            # every still-used block belongs to the radix tree (no
            # leaked tables), and no request pins a tree path anymore
            eng = s.scheduler.engine
            assert not eng._radix_locks
            assert eng.kv.used_blocks() == eng.radix.pool_blocks()

    def test_remove_replica_drains_without_503(self, model, fleet):
        m, params = model
        router, servers = fleet
        oracle = greedy_reference(m, params, [11, 12], 60)
        result: dict = {}
        t = threading.Thread(target=stream_tokens, args=(
            router.url, {"prompt": [11, 12], "max_tokens": 60},
            result))
        t.start()
        victim = self.wait_live(servers)
        out = router.remove_replica(victim.url)
        assert out["removed"] and out["migrated"] == 1
        t.join(timeout=120)
        assert "error" not in result, result
        assert result["tokens"] == oracle
        assert len(router.replicas()) == 1

    def test_sync_migration_token_identical(self, model, fleet):
        """Non-streaming requests migrate too: the sync terminal
        carries the blob and the router merges the resumed tokens."""
        m, params = model
        router, servers = fleet
        oracle = greedy_reference(m, params, [3, 1, 4], 60)
        result: dict = {}

        def go():
            code, out = post(router.url, {"prompt": [3, 1, 4],
                                          "max_tokens": 60})
            result["code"], result["out"] = code, out

        t = threading.Thread(target=go)
        t.start()
        victim = self.wait_live(servers)
        req = urllib.request.Request(
            victim.url + "/v1/sessions/export", data=b"{}",
            headers={"Content-Type": "application/json"},
            method="POST")
        moved = json.loads(urllib.request.urlopen(
            req, timeout=10).read())
        assert moved["migrated"] == 1
        t.join(timeout=120)
        assert result["code"] == 200, result
        assert result["out"]["choices"][0]["token_ids"] == oracle
        assert result["out"]["usage"]["completion_tokens"] == 60

    def test_failed_export_parks_instead_of_stranding(self, model,
                                                      fleet):
        """Review-pass regression: export_session failing AFTER the
        preempt landed must degrade to ordinary parked state (the
        request resumes on this replica) — never a stranded client
        whose stripe the engine holds but nobody will resume."""
        m, params = model
        router, servers = fleet
        oracle = greedy_reference(m, params, [9, 9, 1], 60)
        result: dict = {}
        t = threading.Thread(target=stream_tokens, args=(
            router.url, {"prompt": [9, 9, 1], "max_tokens": 60},
            result))
        t.start()
        victim = self.wait_live(servers)
        eng = victim.scheduler.engine

        def boom(rid):
            raise RuntimeError("injected export failure")

        eng.export_session = boom
        req = urllib.request.Request(
            victim.url + "/v1/sessions/export", data=b"{}",
            headers={"Content-Type": "application/json"},
            method="POST")
        moved = json.loads(urllib.request.urlopen(
            req, timeout=10).read())
        assert moved["migrated"] == 0
        t.join(timeout=120)
        assert "error" not in result, result
        assert result["tokens"] == oracle
        st = victim.scheduler.stats()
        assert st["live_slots"] == 0 and st["parked"] == 0
        assert st["sessions"]["migrated_out"] == 0

    def test_malformed_import_releases_pool_blocks(self, model):
        """Review-pass regression: a blob that passes the signature
        checks but carries a corrupt payload must not leak the blocks
        import allocated before deserialization failed."""
        src = make_engine(model)
        dst = make_engine(model)
        rid = src.add_request([5, 9, 2, 7])
        src.decode_block(3)
        src.preempt_slot(next(iter(src.slots)))
        blob = src.export_session(rid)
        free0 = dst.kv.free_blocks()
        bad = dict(blob)
        del bad["stripe"]
        with pytest.raises(ValueError, match="malformed"):
            dst.import_session(bad)
        assert dst.kv.free_blocks() == free0
        bad2 = dict(blob)
        bad2["stripe"] = {"__nd__": True, "dtype": "float32",
                          "shape": [2, 2], "data": "!!notb64!!"}
        with pytest.raises(ValueError, match="malformed"):
            dst.import_session(bad2)
        assert dst.kv.free_blocks() == free0
        # the good blob still imports after the failed attempts
        rid2 = dst.import_session(blob)
        assert rid2 in dst.parked

    def test_late_payload_failure_releases_pool_blocks(self, model):
        """slicecheck regression: adapter/rng parsing used to run AFTER
        the block table landed in ``_tables`` — a corrupt rng payload
        (or a blob with no adapter key at all, which validation accepts
        as 0) raised mid-registration and permanently shrank the
        destination pool on every retry."""
        src = make_engine(model)
        dst = make_engine(model)
        rid = src.add_request([5, 9, 2, 7])
        src.decode_block(3)
        src.preempt_slot(next(iter(src.slots)))
        blob = src.export_session(rid)
        free0 = dst.kv.free_blocks()
        bad = dict(blob)
        bad["rng"] = {"__nd__": True, "dtype": "uint32",
                      "shape": [4], "data": "!!notb64!!"}
        with pytest.raises(ValueError, match="malformed"):
            dst.import_session(bad)
        assert dst.kv.free_blocks() == free0
        assert not dst.parked and not dst._tables
        # no adapter key: validation reads .get("adapter", 0), so the
        # import must land as the base model — not KeyError halfway
        # through registration
        ok = dict(blob)
        ok.pop("adapter", None)
        rid2 = dst.import_session(ok)
        assert dst.parked[rid2].adapter == 0

    def test_client_resume_field_is_stripped(self, model, fleet):
        """Review-pass regression: ``resume`` is the ROUTER'S protocol
        field — a client sending it through the router must not be
        able to claim a pending imported session on some replica."""
        router, servers = fleet
        code, out = post(router.url, {"resume": 0})
        # with the field stripped this is just a promptless completion
        assert code == 400, out
        assert "prompt" in out["error"]

    def test_import_version_mismatch_is_http_400(self, model, fleet):
        router, servers = fleet
        req = urllib.request.Request(
            servers[0].url + "/v1/sessions/import",
            data=json.dumps({"session": {
                "version": SESSION_WIRE_VERSION + 7}}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400
        assert "wire version" in json.loads(ei.value.read())["error"]


class TestLoadgenTraceReplay:
    def test_record_then_replay_identical_stream(self, tmp_path):
        """The satellite's whole point: a replayed trace regenerates
        byte-identical prompts/budgets/tenants in the same arrival
        order — no live server needed to prove it (the stream is
        deterministic before any HTTP happens)."""
        from instaslice_tpu.serving.loadgen import (
            _prompt_from,
            _read_trace,
            _write_trace,
        )

        records = [
            {"i": 0, "t": 0.0, "tenant": "gold", "pseed": 123,
             "prompt_len": 6, "max_tokens": 4, "pick": 1},
            {"i": 1, "t": 0.02, "tenant": "", "pseed": 456,
             "prompt_len": 3, "max_tokens": 2, "pick": None},
        ]
        pool = [[9, 9, 9, 9], [8, 8, 8, 8]]
        path = str(tmp_path / "t.jsonl")
        _write_trace(path, 64, pool, records)
        vocab, pool2, recs2 = _read_trace(path)
        assert (vocab, pool2) == (64, pool)
        assert recs2 == records
        p0 = pool[1] + _prompt_from(123, 6, 64)
        assert len(p0) == 10
        # regeneration is deterministic
        assert _prompt_from(123, 6, 64) == _prompt_from(123, 6, 64)

    def test_version_mismatch_rejected(self, tmp_path):
        from instaslice_tpu.serving.loadgen import (
            TRACE_VERSION,
            _read_trace,
        )

        path = tmp_path / "bad.jsonl"
        path.write_text(json.dumps(
            {"trace_version": TRACE_VERSION + 1, "vocab": 64}
        ) + "\n" + json.dumps({"i": 0}) + "\n")
        with pytest.raises(ValueError, match="version"):
            _read_trace(str(path))

    def test_live_record_replay_roundtrip(self, model):
        """Record against a live replica, replay the file: same
        request count, zero errors, and the trace survives its own
        round-trip (arrival offsets sorted, pool carried)."""
        from instaslice_tpu.serving.loadgen import _read_trace, run
        import tempfile

        eng = make_engine(model)
        with ApiServer(eng, block_size=4) as srv, \
                tempfile.NamedTemporaryFile(suffix=".jsonl") as f:
            rec = run(srv.url, 6, 3, 10, 4, 64, False, 60.0, seed=5,
                      jitter=0.5, prefix_pool="2:16",
                      record_trace=f.name)
            assert rec["ok"] == 6
            assert rec["trace"] == {"recorded": f.name, "requests": 6}
            vocab, pool, recs = _read_trace(f.name)
            assert len(recs) == 6 and len(pool) == 2
            assert [r["t"] for r in recs] == sorted(
                r["t"] for r in recs)
            rep = run(srv.url, 999, 3, 999, 999, 999, False, 60.0,
                      seed=777, replay_trace=f.name)
            assert rep["ok"] == 6 and rep["errors"] == 0
            assert rep["trace"] == {"replayed": f.name, "requests": 6}
            # identical stream: the prefix-pool reuse fraction (a pure
            # function of the picks) must match the recorded run's
            assert rep["prefix_pool"]["reused"] == \
                rec["prefix_pool"]["reused"]
