"""Workload layer: mesh-from-env, sharded model, ring attention, train step.

Runs on the virtual 8-device CPU mesh from conftest.py — the CI stand-in
for a granted multi-chip slice (SURVEY.md §4 "BASELINE.json configs[0]
... CPU emulator OK").
"""


import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from instaslice_tpu.parallel.compat import supports_partial_manual

from instaslice_tpu.parallel.meshenv import (
    SliceTopology,
    slice_mesh,
)
from instaslice_tpu.models.lm import (
    ModelConfig,
    TpuLM,
    _attention,
    param_specs,
)
from instaslice_tpu.parallel.ring import ring_attention
from instaslice_tpu.models.train import make_train_step


def tiny(ring=False, experts=0):
    return ModelConfig(
        vocab_size=128,
        d_model=32,
        n_heads=4,
        n_layers=2,
        d_ff=64,
        dtype=jnp.float32,  # exactness for CPU tests
        ring_attention=ring,
        n_experts=experts,
        remat=False,
    )


class TestSliceTopology:
    def test_from_env_single_host(self):
        env = {
            "TPU_WORKER_ID": "0",
            "TPU_CHIPS_PER_HOST_BOUNDS": "2,2,1",
            "TPU_HOST_BOUNDS": "1,1,1",
            "TPU_WORKER_HOSTNAMES": "pod-a",
        }
        t = SliceTopology.from_env(env)
        assert t.num_chips == 4
        assert t.num_workers == 1
        assert t.slice_shape == (2, 2, 1)

    def test_from_env_multi_host(self):
        env = {
            "TPU_WORKER_ID": "1",
            "TPU_CHIPS_PER_HOST_BOUNDS": "2,2,1",
            "TPU_HOST_BOUNDS": "1,2,1",
            "TPU_WORKER_HOSTNAMES": "w0,w1",
        }
        t = SliceTopology.from_env(env)
        assert t.num_workers == 2
        assert t.slice_shape == (2, 4, 1)
        assert t.chips_per_worker == 4

    def test_slice_mesh_respects_axis_sizes(self):
        mesh = slice_mesh(
            axes=("data", "seq", "model"), axis_sizes=(-1, 2, 2)
        )
        assert mesh.shape == {"data": 2, "seq": 2, "model": 2}

    def test_slice_mesh_wildcard_errors(self):
        with pytest.raises(ValueError):
            slice_mesh(axes=("data",), axis_sizes=(3,))


class TestRingAttention:
    def test_matches_full_attention(self):
        """Ring output == plain attention on the gathered sequence."""
        n_seq = 4
        devs = jax.devices()[:n_seq]
        mesh = Mesh(np.array(devs).reshape(1, n_seq), ("data", "seq"))
        B, S, H, hd = 2, 32, 2, 8
        k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
        q = jax.random.normal(k1, (B, S, H, hd), jnp.float32)
        k = jax.random.normal(k2, (B, S, H, hd), jnp.float32)
        v = jax.random.normal(k3, (B, S, H, hd), jnp.float32)

        want = _attention(q, k, v, causal=True)

        import functools

        from instaslice_tpu.parallel.compat import shard_map

        ring = jax.jit(
            shard_map(
                functools.partial(ring_attention, axis_name="seq"),
                mesh=mesh,
                in_specs=(P(None, "seq", None, None),) * 3,
                out_specs=P(None, "seq", None, None),
            )
        )
        got = ring(q, k, v)
        np.testing.assert_allclose(np.array(got), np.array(want),
                                   rtol=2e-4, atol=2e-5)


class TestModel:
    def test_forward_shapes(self):
        model = TpuLM(tiny())
        params = model.init(jax.random.key(0))
        logits = jax.jit(model.apply)(params, jnp.ones((2, 16), jnp.int32))
        assert logits.shape == (2, 16, 128)
        assert bool(jnp.isfinite(logits).all())

    def test_moe_forward(self):
        model = TpuLM(tiny(experts=4))
        params = model.init(jax.random.key(0))
        logits = jax.jit(model.apply)(params, jnp.ones((2, 8), jnp.int32))
        assert logits.shape == (2, 8, 128)
        assert bool(jnp.isfinite(logits).all())

    def test_moe_top1_matches_per_token_expert(self):
        """With k=1 and unbounded capacity, every token's MoE output
        must equal its argmax expert's FF scaled by the RAW top gate
        (Switch semantics — the gate stays in the output so the router
        keeps a gradient path)."""
        from instaslice_tpu.models.lm import _moe_mlp

        E, D, F = 4, 8, 16
        ks = jax.random.split(jax.random.key(2), 4)
        x = jax.random.normal(ks[0], (2, 6, D))
        router = jax.random.normal(ks[1], (D, E))
        w_in = jax.random.normal(ks[2], (E, D, F)) * 0.2
        w_out = jax.random.normal(ks[3], (E, F, D)) * 0.2
        got, aux = _moe_mlp(x, router, w_in, w_out, top_k=1,
                            capacity_factor=float(E))  # C>=S: no drops
        # E·Σ f_e·p_e is bounded by (0, E]; 1.0 is only the value AT
        # perfect balance, not a lower bound (f and p can anti-correlate)
        assert 0.0 < float(aux) <= E
        gates = jax.nn.softmax(x @ router, -1)
        eid = jnp.argmax(gates, -1)                       # (B,S)
        for b in range(2):
            for s in range(6):
                e = int(eid[b, s])
                ref = (jax.nn.gelu(x[b, s] @ w_in[e]) @ w_out[e]
                       ) * gates[b, s, e]
                assert float(jnp.abs(got[b, s] - ref).max()) < 1e-4

    def test_moe_top1_router_gets_gradient(self):
        """The Switch-style raw gate is the router's ONLY gradient
        path; it must be nonzero (a renormalized top-1 would zero it)."""
        from instaslice_tpu.models.lm import _moe_mlp

        E, D, F = 4, 8, 16
        ks = jax.random.split(jax.random.key(4), 4)
        x = jax.random.normal(ks[0], (2, 6, D))
        w_in = jax.random.normal(ks[2], (E, D, F)) * 0.2
        w_out = jax.random.normal(ks[3], (E, F, D)) * 0.2

        def loss(router):
            y, _ = _moe_mlp(x, router, w_in, w_out, top_k=1,
                            capacity_factor=float(E))
            return jnp.mean(y ** 2)

        g = jax.grad(loss)(jax.random.normal(ks[1], (D, E)))
        assert float(jnp.abs(g).max()) > 0.0

    def test_moe_capacity_drops_overflow_to_zero(self):
        """Tokens beyond an expert's capacity contribute nothing (the
        residual carries them) — and earlier tokens win the buffer."""
        from instaslice_tpu.models.lm import _moe_mlp

        E, D, F = 2, 8, 16
        ks = jax.random.split(jax.random.key(3), 3)
        x = jnp.broadcast_to(
            jax.random.normal(ks[0], (1, 1, D)), (1, 6, D)
        )  # identical tokens → all route to the same expert
        router = jax.random.normal(ks[1], (D, E))
        w_in = jax.random.normal(ks[2], (E, D, F)) * 0.2
        w_out = jnp.ones((E, F, D)) * 0.1
        # k=1, capacity_factor chosen so C = ceil(cf*1*6/2) = 2
        got, _ = _moe_mlp(x, router, w_in, w_out, top_k=1,
                          capacity_factor=2 / 3)
        # first 2 tokens served, the other 4 dropped to exactly zero
        assert float(jnp.abs(got[0, 2:]).max()) == 0.0
        assert float(jnp.abs(got[0, :2]).min()) > 0.0

    def test_moe_top2_forward_and_grads(self):
        model = TpuLM(tiny(experts=4))
        params = model.init(jax.random.key(0))
        toks = jax.random.randint(jax.random.key(1), (2, 8), 0, 128)

        def loss(p):
            lg = model.apply(p, toks)
            return jnp.mean(lg.astype(jnp.float32) ** 2)

        val, grads = jax.value_and_grad(loss)(params)
        assert np.isfinite(float(val))
        g = grads["blocks"]["w_in"]
        assert bool(jnp.isfinite(g).all())
        # routing is sparse, but SOME expert gradient must be nonzero
        assert float(jnp.abs(g).max()) > 0.0

    def test_moe_aux_reaches_the_loss_and_router_grad(self):
        """The load-balance term must show up in loss_fn (loss differs
        with/without it) and give the router a gradient path even
        through the top-2 renormalized combine."""
        from instaslice_tpu.models.train import loss_fn

        model = TpuLM(tiny(experts=4))
        params = model.init(jax.random.key(0))
        toks = jax.random.randint(jax.random.key(1), (2, 8), 0, 128)
        with_aux = float(loss_fn(model, params, toks,
                                 moe_aux_weight=0.01))
        without = float(loss_fn(model, params, toks,
                                moe_aux_weight=0.0))
        # aux in (0, E] scaled by the weight bounds the difference
        assert 0.0 < with_aux - without <= 0.01 * 4.0
        g = jax.grad(
            lambda p: loss_fn(model, p, toks, moe_aux_weight=0.01)
        )(params)["blocks"]["router"]
        assert float(jnp.abs(g).max()) > 0.0

    @pytest.mark.skipif(
        not supports_partial_manual(),
        reason="partial-manual shard_map autodiff needs jax >= 0.5",
    )
    def test_moe_pipeline_aux_reaches_loss_and_router_grad(self):
        """The pipeline path now carries the MoE load-balance aux
        (stage-summed over valid ticks, psum'd over the pipe axis):
        the loss must differ with/without the weight, bounded by
        w·E, and the router must get a gradient through it."""
        from instaslice_tpu.models.train import loss_fn

        devs = jax.devices()[:4]
        mesh = Mesh(np.array(devs).reshape(2, 1, 1, 2),
                    ("pipe", "data", "seq", "model"))
        model = TpuLM(tiny(experts=4))
        params = model.init(jax.random.key(0))
        toks = jax.random.randint(jax.random.key(1), (2, 8), 0, 128)

        def loss(p, w):
            return loss_fn(model, p, toks, mesh, n_micro=2,
                           moe_aux_weight=w)

        with_aux = float(loss(params, 0.01))
        without = float(loss(params, 0.0))
        # aux ∈ (0, E] scaled by the weight bounds the difference
        assert 0.0 < with_aux - without <= 0.01 * 4.0
        g = jax.grad(lambda p: loss(p, 0.01))(params)["blocks"]["router"]
        assert float(jnp.abs(g).max()) > 0.0
        # and the estimator is close to the scan-stack aux at these
        # tiny shapes (microbatch-mean vs full-batch; not identical)
        scan_aux = float(loss_fn(model, params, toks,
                                 moe_aux_weight=0.01)) - float(
            loss_fn(model, params, toks, moe_aux_weight=0.0)
        )
        np.testing.assert_allclose(with_aux - without, scan_aux,
                                   rtol=0.5)

    def test_param_specs_cover_params(self):
        cfg = tiny(experts=2)
        model = TpuLM(cfg)
        params = model.init(jax.random.key(0))
        specs = param_specs(cfg)
        # identical tree structure
        jax.tree.map(
            lambda p, s: None,
            params,
            specs,
            is_leaf=lambda x: isinstance(x, P),
        )

    def test_causality(self):
        """Changing a future token must not change past logits."""
        model = TpuLM(tiny())
        params = model.init(jax.random.key(1))
        t1 = jnp.zeros((1, 16), jnp.int32)
        t2 = t1.at[0, 10].set(5)
        l1 = model.apply(params, t1)
        l2 = model.apply(params, t2)
        np.testing.assert_allclose(
            np.array(l1[0, :10]), np.array(l2[0, :10]), atol=1e-5
        )


class TestTrainStep:
    def test_sharded_train_step_runs(self):
        devs = jax.devices()[:8]
        mesh = Mesh(np.array(devs).reshape(2, 2, 2),
                    ("data", "seq", "model"))
        model = TpuLM(tiny(ring=True, experts=2))
        init_fn, step_fn = make_train_step(model, mesh)
        state = init_fn(jax.random.key(0))
        tokens = jax.random.randint(
            jax.random.key(1), (4, 64), 0, 128, jnp.int32
        )
        state, loss = step_fn(state, tokens)
        state, loss2 = step_fn(state, tokens)
        assert float(loss2) < float(loss) + 1.0
        assert int(state.step) == 2
        assert np.isfinite(float(loss))

    def test_zero1_shards_moments_and_matches_replicated_losses(self):
        """ZeRO-1: the Adam mu/nu moments must actually land sharded
        over the data axis (that's the memory win), params must stay
        replicated across it (every dp rank forwards with them), and
        the loss trajectory must match the replicated-optimizer run —
        the sharding annotation changes WHERE the update math runs,
        never what it computes."""
        devs = jax.devices()[:8]
        mesh = Mesh(np.array(devs).reshape(4, 1, 2),
                    ("data", "seq", "model"))
        model = TpuLM(tiny())
        tokens = jax.random.randint(
            jax.random.key(1), (4, 32), 0, 128, jnp.int32
        )

        losses = {}
        for z in (False, True):
            init_fn, step_fn = make_train_step(model, mesh, zero1=z)
            state = init_fn(jax.random.key(0))
            if z:
                def find_mu(s):
                    if hasattr(s, "mu"):
                        return s.mu
                    if isinstance(s, (tuple, list)):
                        for sub in s:
                            r = find_mu(sub)
                            if r is not None:
                                return r
                    return None

                mu = find_mu(state.opt_state)
                assert mu is not None, "no ScaleByAdamState found"
                sharded = [
                    leaf for leaf in jax.tree.leaves(mu)
                    if "data" in tuple(leaf.sharding.spec)
                ]
                assert sharded, "no moment leaf sharded over data"
                for leaf in jax.tree.leaves(state.params):
                    assert "data" not in tuple(leaf.sharding.spec), (
                        "params must stay replicated over data"
                    )
            seq = []
            for _ in range(3):
                state, loss = step_fn(state, tokens)
                seq.append(float(loss))
            losses[z] = seq
        np.testing.assert_allclose(losses[True], losses[False],
                                   rtol=1e-5)

    def test_grad_accum_matches_full_batch(self):
        """Micro-batched accumulation is pure memory restructuring: the
        averaged micro-batch gradients equal the full-batch gradient
        (equal token counts per micro-batch), so the loss trajectory
        must match the accum=1 run."""
        devs = jax.devices()[:4]
        mesh = Mesh(np.array(devs).reshape(2, 1, 2),
                    ("data", "seq", "model"))
        model = TpuLM(tiny())
        tokens = jax.random.randint(
            jax.random.key(1), (4, 32), 0, 128, jnp.int32
        )
        losses = {}
        for accum in (1, 2):
            init_fn, step_fn = make_train_step(model, mesh,
                                               grad_accum=accum)
            state = init_fn(jax.random.key(0))
            seq = []
            for _ in range(3):
                state, loss = step_fn(state, tokens)
                seq.append(float(loss))
            losses[accum] = seq
        np.testing.assert_allclose(losses[2], losses[1], rtol=1e-4)

    def test_grad_accum_rejects_pipeline_combo(self):
        devs = jax.devices()[:4]
        mesh = Mesh(np.array(devs).reshape(2, 1, 1, 2),
                    ("pipe", "data", "seq", "model"))
        with pytest.raises(ValueError, match="micro-batching"):
            make_train_step(TpuLM(tiny()), mesh, grad_accum=2, n_micro=2)

    def test_warmup_schedule_starts_at_zero_lr(self):
        """warmup_cosine: step 0 runs at lr=0, so the first update must
        leave params untouched (the schedule is actually wired into the
        optimizer, not just accepted)."""
        devs = jax.devices()[:2]
        mesh = Mesh(np.array(devs).reshape(1, 1, 2),
                    ("data", "seq", "model"))
        model = TpuLM(tiny())
        init_fn, step_fn = make_train_step(
            model, mesh, warmup_steps=5, decay_steps=20, grad_clip=1.0,
        )
        state = init_fn(jax.random.key(0))
        before = jax.tree.map(np.asarray, state.params)
        tokens = jax.random.randint(
            jax.random.key(1), (2, 32), 0, 128, jnp.int32
        )
        state, _ = step_fn(state, tokens)
        for a, b in zip(jax.tree.leaves(before),
                        jax.tree.leaves(state.params)):
            np.testing.assert_array_equal(a, np.asarray(b))
        # second step: lr > 0, params must move
        state, _ = step_fn(state, tokens)
        moved = any(
            not np.array_equal(a, np.asarray(b))
            for a, b in zip(jax.tree.leaves(before),
                            jax.tree.leaves(state.params))
        )
        assert moved

    def test_fp32_master_weights(self):
        """param_dtype=fp32 + dtype=bf16 (the mixed-precision recipe):
        weights store in fp32, compute casts to bf16 at use — so the
        forward is bit-identical to storing bf16 (init casts the same
        fp32 draw), while updates smaller than a bf16 ulp survive in
        the master copy."""
        import dataclasses

        base = ModelConfig(
            vocab_size=128, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            dtype=jnp.bfloat16, remat=False,
        )
        mixed = dataclasses.replace(base, param_dtype=jnp.float32)
        toks = jax.random.randint(jax.random.key(1), (2, 16), 0, 128)

        p_bf = TpuLM(base).init(jax.random.key(0))
        p_mx = TpuLM(mixed).init(jax.random.key(0))
        assert p_bf["blocks"]["wq"].dtype == jnp.bfloat16
        assert p_mx["blocks"]["wq"].dtype == jnp.float32
        # ln scales are fp32 in both layouts
        assert p_mx["blocks"]["ln1"]["scale"].dtype == jnp.float32

        out_bf = TpuLM(base).apply(p_bf, toks)
        out_mx = TpuLM(mixed).apply(p_mx, toks)
        assert out_bf.dtype == out_mx.dtype
        np.testing.assert_array_equal(np.asarray(out_bf, np.float32),
                                      np.asarray(out_mx, np.float32))

        # the reason master weights exist: a sub-ulp update vanishes in
        # bf16 storage but persists in fp32
        delta = jnp.float32(1e-4)          # < bf16 ulp at 1.0 (~0.0078)
        one_bf = jnp.ones((), jnp.bfloat16)
        assert float((one_bf + delta.astype(jnp.bfloat16))
                     .astype(jnp.float32)) == 1.0
        assert float(jnp.float32(1.0) + delta) > 1.0

    def test_remat_policies_agree(self):
        """remat none / full / dots are pure memory-vs-FLOPs trades —
        the loss (and thus gradients up to fp reassociation) must match."""
        devs = jax.devices()[:2]
        mesh = Mesh(np.array(devs).reshape(1, 1, 2),
                    ("data", "seq", "model"))
        tokens = jax.random.randint(
            jax.random.key(1), (2, 32), 0, 128, jnp.int32
        )
        losses = {}
        for label, remat, policy in (
            ("none", False, "full"),
            ("full", True, "full"),
            ("dots", True, "dots"),
        ):
            cfg = ModelConfig(
                vocab_size=128, d_model=32, n_heads=4, n_layers=2,
                d_ff=64, dtype=jnp.float32, remat=remat,
                remat_policy=policy,
            )
            init_fn, step_fn = make_train_step(TpuLM(cfg), mesh)
            state = init_fn(jax.random.key(0))
            state, loss = step_fn(state, tokens)
            _, loss2 = step_fn(state, tokens)
            losses[label] = (float(loss), float(loss2))
        ref = losses["none"]
        for label, pair in losses.items():
            assert pair == pytest.approx(ref, rel=1e-5), (label, losses)

    def test_chunked_loss_matches_full(self):
        """The chunked cross-entropy is a pure memory optimization: the
        loss AND the gradients must match the one-shot (B, S, V)
        formulation, including a chunk that does not divide S (padding
        path)."""
        from instaslice_tpu.models.train import loss_fn

        cfg = ModelConfig(
            vocab_size=128, d_model=32, n_heads=4, n_layers=2,
            d_ff=64, dtype=jnp.float32, remat=False,
        )
        model = TpuLM(cfg)
        params = model.init(jax.random.key(0))
        tokens = jax.random.randint(
            jax.random.key(1), (2, 24), 0, 128, jnp.int32
        )
        full = jax.value_and_grad(
            lambda p: loss_fn(model, p, tokens, loss_chunk=0)
        )(params)
        for chunk in (8, 7, 24, 64):   # divides, pads, exact, > S
            got = jax.value_and_grad(
                lambda p: loss_fn(model, p, tokens, loss_chunk=chunk)
            )(params)
            assert float(got[0]) == pytest.approx(float(full[0]),
                                                  rel=1e-6), chunk
            diffs = jax.tree.map(
                lambda a, b: float(jnp.abs(a - b).max()),
                full[1], got[1],
            )
            assert max(jax.tree.leaves(diffs)) < 1e-4, (chunk, diffs)

    def test_chunked_loss_in_sharded_step(self):
        """Chunked loss under dp/tp sharding: same convergence behavior
        as the full formulation (exercises the scan under the mesh)."""
        devs = jax.devices()[:4]
        mesh = Mesh(np.array(devs).reshape(2, 1, 2),
                    ("data", "seq", "model"))
        cfg = ModelConfig(
            vocab_size=128, d_model=32, n_heads=4, n_layers=2,
            d_ff=64, dtype=jnp.float32, remat=False,
        )
        tokens = jax.random.randint(
            jax.random.key(1), (4, 32), 0, 128, jnp.int32
        )
        losses = {}
        for chunk in (0, 16):
            init_fn, step_fn = make_train_step(
                TpuLM(cfg), mesh, loss_chunk=chunk
            )
            state = init_fn(jax.random.key(0))
            state, l1 = step_fn(state, tokens)
            _, l2 = step_fn(state, tokens)
            losses[chunk] = (float(l1), float(l2))
        assert losses[16] == pytest.approx(losses[0], rel=1e-5)

    def test_remat_policy_unknown_raises_at_construction(self):
        # even with remat off: flipping it on later must not be the
        # first place a typo surfaces
        with pytest.raises(ValueError, match="remat_policy"):
            ModelConfig(
                vocab_size=128, d_model=32, n_heads=4, n_layers=2,
                d_ff=64, dtype=jnp.float32, remat=False,
                remat_policy="bogus",
            )

    def test_params_actually_sharded(self):
        devs = jax.devices()[:8]
        mesh = Mesh(np.array(devs).reshape(2, 1, 4),
                    ("data", "seq", "model"))
        model = TpuLM(tiny())
        init_fn, _ = make_train_step(model, mesh)
        state = init_fn(jax.random.key(0))
        # tp weights sharded over the 4 model-axis devices: each shard
        # holds 1/4 of the head dim (addressable_shards device count is
        # always = mesh size even when replicated, so assert shard shape)
        wq = state.params["blocks"]["wq"]
        full = wq.shape[-1]
        shard_cols = {s.data.shape[-1] for s in wq.addressable_shards}
        assert shard_cols == {full // 4}


class TestGraftEntry:
    def test_entry_compiles(self):
        import __graft_entry__ as ge

        fn, args = ge.entry()
        out = jax.jit(fn)(*args)
        assert bool(jnp.isfinite(out.astype(jnp.float32)).all())

    def test_dryrun_multichip(self):
        import __graft_entry__ as ge

        ge.dryrun_multichip(8)


class TestWorkloadImports:
    def test_canonical_import_paths(self):
        from instaslice_tpu.models.lm import ModelConfig
        from instaslice_tpu.models.train import make_train_step
        from instaslice_tpu.parallel.meshenv import slice_mesh
        from instaslice_tpu.parallel.ring import ring_attention

        assert callable(slice_mesh) and callable(ring_attention)
        assert callable(make_train_step)
        assert ModelConfig is not None
