"""Paged KV-cache block pool (serving/kvcache.py) and the tenant/SLO
scheduling policy units (serving/scheduler.py) — the pure host-side
halves of the continuous-batching subsystem. The engine-integrated
paths (preempt/resume round-trips, SLO preemption over HTTP) live in
tests/test_serving_sched.py (slow tier)."""

from __future__ import annotations

import pytest

from instaslice_tpu.serving.kvcache import (
    BlockPoolExhausted,
    KVBlockPool,
    RadixIndex,
    radix_granule,
)
from instaslice_tpu.serving.scheduler import (
    CLASS_RANK,
    DEFAULT_SPEC,
    Pending,
    Scheduler,
    TenantSpec,
    class_rank,
    parse_tenant_specs,
)


class TestBlockPool:
    def test_allocate_rounds_up_and_frees(self):
        pool = KVBlockPool(8, 16)
        t = pool.allocate(17)               # 2 blocks
        assert len(t.blocks) == 2 and pool.used_blocks() == 2
        assert pool.free_blocks() == 6
        pool.release(t)
        assert pool.used_blocks() == 0 and pool.free_blocks() == 8
        assert len(t.blocks) == 0

    def test_zero_token_table(self):
        pool = KVBlockPool(4, 16)
        t = pool.allocate(0)
        assert len(t.blocks) == 0 and pool.used_blocks() == 0

    def test_ensure_grows_incrementally(self):
        pool = KVBlockPool(8, 4)
        t = pool.allocate(3)
        assert len(t.blocks) == 1
        pool.ensure(t, 4)                   # exactly full: no new block
        assert len(t.blocks) == 1
        pool.ensure(t, 5)
        assert len(t.blocks) == 2
        pool.ensure(t, 5)                   # idempotent
        assert len(t.blocks) == 2

    def test_exhaustion_raises_table_unchanged(self):
        pool = KVBlockPool(2, 4)
        t = pool.allocate(8)                # both blocks
        t2 = pool.allocate(0)
        with pytest.raises(BlockPoolExhausted):
            pool.ensure(t2, 1)
        assert len(t2.blocks) == 0 and t2.tokens == 0
        pool.release(t)
        pool.ensure(t2, 1)                  # now it fits

    def test_fork_shares_and_cow_copies_boundary(self):
        pool = KVBlockPool(8, 4)
        parent = pool.allocate(6)           # 2 blocks, boundary half full
        assert pool.used_blocks() == 2
        child = pool.fork(parent)
        # zero pool cost: the child references the parent's blocks
        assert pool.used_blocks() == 2
        stats = pool.stats({1: parent, 2: child})
        assert stats["cow"] == 2
        # the child's first divergent token copies ONLY the boundary
        pool.ensure(child, 7)
        assert pool.used_blocks() == 3
        assert pool.cow_copies == 1
        assert child.blocks[0] is parent.blocks[0]      # still shared
        assert child.blocks[1] is not parent.blocks[1]  # copied
        # parent growing afterwards must also copy ITS boundary — the
        # child still references the original
        pool.release(child)
        assert pool.used_blocks() == 2

    def test_parent_growth_cows_when_child_references(self):
        pool = KVBlockPool(8, 4)
        parent = pool.allocate(6)
        child = pool.fork(parent)
        old_boundary = parent.blocks[1]
        pool.ensure(parent, 7)
        assert parent.blocks[1] is not old_boundary
        assert child.blocks[1] is old_boundary
        assert pool.cow_copies == 1

    def test_fork_prefix_share_is_trimmed(self):
        pool = KVBlockPool(8, 4)
        parent = pool.allocate(8)           # 2 full blocks
        child = pool.fork(parent, 4)        # share only the first
        assert len(child.blocks) == 1 and child.tokens == 4
        pool.ensure(child, 5)               # full boundary: plain grow
        assert pool.cow_copies == 0
        assert len(child.blocks) == 2

    def test_pinned_tables_outside_pool(self):
        pool = KVBlockPool(4, 4)
        pre = pool.pin(8)                   # 2 pinned blocks
        assert pool.used_blocks() == 0      # no pool cost
        assert pool.pinned_blocks() == 2
        assert pool.free_blocks() == 4
        child = pool.fork(pre)
        pool.ensure(child, 9)               # grows past the pin
        assert pool.used_blocks() == 1
        pool.release(pre)
        assert pool.pinned_blocks() == 2    # child still references
        pool.release(child)
        assert pool.pinned_blocks() == 0
        assert pool.used_blocks() == 0

    def test_pinned_boundary_write_adopts_pool_block(self):
        pool = KVBlockPool(4, 4)
        pre = pool.pin(6)                   # boundary half full
        child = pool.fork(pre)
        pool.ensure(child, 7)               # writes INTO the pinned block
        assert pool.cow_copies == 1
        assert child.blocks[1] is not pre.blocks[1]
        assert pool.used_blocks() == 1      # the adopted copy

    def test_utilization_true_block_occupancy(self):
        pool = KVBlockPool(8, 16)
        t = pool.allocate(24)               # 2 blocks = 32 capacity
        assert t.tokens == 24
        assert pool.utilization(24) == 24 / 32
        assert pool.utilization(0) == 0.0
        pool.release(t)
        assert pool.utilization(0) == 0.0   # empty pool: no capacity

    def test_utilization_counts_pinned_capacity(self):
        """Prefix-covered resident tokens live in pinned blocks: the
        capacity they divide by must include them, or any prefix-hit
        traffic saturates the gauge at 1.0."""
        pool = KVBlockPool(8, 4)
        pool.pin(8)                         # 2 pinned blocks
        t = pool.allocate(2)                # 1 allocated block
        # 10 resident tokens (8 prefix + 2 own) over 3 blocks of 4
        assert pool.utilization(10) == 10 / 12
        pool.release(t)

    def test_bad_construction(self):
        with pytest.raises(ValueError):
            KVBlockPool(0, 16)
        with pytest.raises(ValueError):
            KVBlockPool(8, 0)

    def test_bump_fast_path_semantics(self):
        """bump() is the incremental _sync_tables fast path: free when
        growth stays inside the current blocks, refused (False, table
        untouched) the moment a new block or a shared-boundary copy
        would be needed — ensure() then does the real work."""
        pool = KVBlockPool(8, 4)
        t = pool.allocate(2)                  # 1 block, 2 tokens
        assert pool.bump(t, 3)                # within the tail block
        assert t.tokens == 3 and len(t.blocks) == 1
        assert pool.bump(t, 2)                # shrink/no-op: True
        assert t.tokens == 3
        assert not pool.bump(t, 5)            # needs a second block
        assert t.tokens == 3 and pool.used_blocks() == 1
        pool.ensure(t, 5)
        assert len(t.blocks) == 2
        # shared partial boundary: growth must COW, bump refuses
        child = pool.fork(t, 5)
        assert not pool.bump(child, 6)
        before = pool.cow_copies
        pool.ensure(child, 6)
        assert pool.cow_copies == before + 1
        pool.release(t)
        pool.release(child)


class TestRadixIndex:
    """The radix prefix cache's pure accounting half: granule-keyed
    trie, disjoint segment tables in the pool, exact evictable math,
    leaf-first LRU, lock/registered pinning. Device stripes are the
    engine's business (tests/test_radix.py)."""

    def _index(self, blocks=32, bs=8, granule=8):
        pool = KVBlockPool(blocks, bs)
        return pool, RadixIndex(pool, granule)

    def _insert(self, r, tokens, matched_hint=None):
        """Insert tokens (granule-floored) the way the engine does."""
        granules = r.granules_of(tokens, len(tokens))
        parent, matched = r.ensure_path(granules)
        if matched == len(granules):
            return parent
        return r.add_child(parent, granules[matched:])

    def test_granule_is_the_prefill_chunk(self):
        # block alignment is NOT required (full-prefix node tables
        # fork position-exactly; a mid-block match just boundary-COWs)
        assert radix_granule(8, 16) == 8
        assert radix_granule(16, 8) == 16
        assert radix_granule(128, 16) == 128

    def test_match_is_granule_exact_and_pure(self):
        pool, r = self._index()
        self._insert(r, list(range(24)))
        clock0 = r.clock
        m = r.match(list(range(30)), 24)
        assert m.length == 24 and len(m.path) == 1
        # partial granule never matches; divergent granule never matches
        assert r.match(list(range(20)), 16).length == 16
        div = list(range(8)) + [99] * 8
        assert r.match(div, 16).length == 8
        # match() is PURE (scheduler planning must not tick the LRU
        # clock, or op-stream followers diverge)
        assert r.clock == clock0

    def test_split_on_divergence_shares_the_head(self):
        pool, r = self._index()
        a = self._insert(r, list(range(24)))          # 3 granules
        used0 = pool.used_blocks()
        b = self._insert(r, list(range(16)) + [7] * 8)
        # head (2 granules) stored ONCE: the second insert only paid
        # its divergent tail granule
        assert pool.used_blocks() == used0 + 1
        assert r.node_count() == 3                    # upper + 2 tails
        assert a.start == 16 and b.start == 16        # both are tails
        m = r.match(list(range(16)) + [7] * 8 + [1], 24)
        assert m.length == 24

    def test_split_preserves_locks_and_stripes(self):
        pool, r = self._index()
        node = self._insert(r, list(range(24)))
        node.stripes = ["s0", "s1", "s2"]
        r.lock(node)
        self._insert(r, list(range(8)) + [5] * 8)     # splits at 1
        upper = node.parent
        assert upper.stripes == ["s0"] and node.stripes == ["s1", "s2"]
        assert upper.locks == 1 and node.locks == 1
        # unlock through the original node walks the new ancestor too
        r.unlock(node)
        assert upper.locks == 0 and node.locks == 0

    def test_evictable_exact_and_lock_aware(self):
        pool, r = self._index()
        node = self._insert(r, list(range(24)))       # 3 blocks
        self._insert(r, list(range(16)) + [7] * 8)    # +1 block
        assert r.pool_blocks() == 4
        assert r.evictable_blocks() == 4
        r.lock(node)
        # node's path (upper + node) is pinned; the sibling tail is not
        assert r.evictable_blocks() == 1
        free0 = pool.free_blocks()
        assert r.reclaim(10) == 1                     # only the sibling
        assert pool.free_blocks() == free0 + 1
        r.unlock(node)
        assert r.reclaim(10) == 3                     # leaf then parent
        assert r.node_count() == 0
        assert pool.used_blocks() == 0

    def test_lru_leaf_first_deterministic(self):
        pool, r = self._index()
        a = self._insert(r, [1] * 8)
        b = self._insert(r, [2] * 8)
        r.touch(a)                                    # b is now LRU
        assert r._lru_evictable_leaf() is b
        r.touch(b)
        assert r._lru_evictable_leaf() is a
        # an interior node only evicts after its children: deep chain
        tail = r.add_child(a, [(9,) * 8])
        r.touch(a)                                    # a older than... tick
        got = []
        while True:
            leaf = r._lru_evictable_leaf()
            if leaf is None:
                break
            got.append(leaf)
            r.evict(leaf)
        assert got[0] is b or got[0] is tail          # never `a` first
        assert a in got and got.index(a) > got.index(tail)

    def test_registered_pinned_outside_pool_and_exempt(self):
        pool, r = self._index()
        node = r.add_child(r.root, r.granules_of([3] * 16, 16),
                           pinned=True)
        node.registered = True
        assert pool.used_blocks() == 0                # pinned: no pool
        assert pool.pinned_blocks() == 2
        assert r.pool_blocks() == 0
        assert r.evictable_blocks() == 0
        assert r.reclaim(10) == 0                     # exempt
        # organic child under a registered parent IS evictable
        child = r.add_child(node, [(4,) * 8])
        assert r.evictable_blocks() == 1
        assert r.reclaim(10) == 1
        assert child.parent is None                   # gone
        # un-register → the pinned segment evicts (frees pinned refs)
        node.registered = False
        r.evict(node)
        assert pool.pinned_blocks() == 0

    def test_hit_forks_the_deepest_table_at_zero_cost(self):
        pool, r = self._index(bs=8, granule=8)
        upper = self._insert(r, list(range(16)))
        tail = r.add_child(upper, r.granules_of([9] * 8, 8))
        used0 = pool.used_blocks()
        # a hit forks the deepest matched node's FULL-PREFIX table
        t = pool.fork(tail.table, 24)
        assert pool.used_blocks() == used0            # zero pool cost
        assert len(t.blocks) == 3 and t.tokens == 24
        # growth past a block-aligned share appends, never COWs
        before = pool.cow_copies
        pool.ensure(t, 25)
        assert pool.cow_copies == before
        assert pool.used_blocks() == used0 + 1
        pool.release(t)
        assert pool.used_blocks() == used0

    def test_sub_block_granule_boundary_cows(self):
        """granule 8 under block size 16: a one-granule match ends
        mid-block, so the hit's growth copies the boundary — the cost
        the engine's admit model charges for exactly this case."""
        pool, r = self._index(blocks=32, bs=16, granule=8)
        node = self._insert(r, list(range(8)))        # 1 block, half
        t = pool.fork(node.table, 8)
        before = pool.cow_copies
        pool.ensure(t, 9)                             # into the share
        assert pool.cow_copies == before + 1
        pool.release(t)
        assert r.evictable_blocks() == 1
        assert r.reclaim(10) == 1

    def test_bad_granule_rejected(self):
        pool = KVBlockPool(8, 16)
        with pytest.raises(ValueError, match="granule"):
            RadixIndex(pool, 0)


class TestTenantSpecs:
    def test_full_grammar(self):
        specs = parse_tenant_specs(
            "gold:4:latency:0.5:0.05,free:1:best-effort:30,plain"
        )
        assert specs["gold"] == TenantSpec("gold", 4.0, "latency",
                                           0.5, 0.05)
        assert specs["free"].tenant_class == "best-effort"
        assert specs["free"].ttft_slo == 30.0
        assert specs["plain"].tenant_class == "standard"
        assert specs["plain"].weight == 1.0

    def test_errors(self):
        with pytest.raises(ValueError, match="class"):
            parse_tenant_specs("a:1:platinum")
        with pytest.raises(ValueError, match="weight"):
            parse_tenant_specs("a:0:latency")
        with pytest.raises(ValueError, match="numbers"):
            parse_tenant_specs("a:heavy:latency")
        with pytest.raises(ValueError, match="twice"):
            parse_tenant_specs("a:1,a:2")
        with pytest.raises(ValueError, match="empty name"):
            parse_tenant_specs(":1:latency")

    def test_class_rank_default(self):
        assert class_rank("latency") < class_rank("standard")
        assert class_rank("standard") < class_rank("best-effort")
        assert class_rank("nonsense") == CLASS_RANK["standard"]


class _StubEngine:
    """Just enough engine for the pure scheduling-order units."""

    def __init__(self):
        self.slots = {}
        self._slot_adapter_host = {}
        self.draft_model = None
        self.max_batch = 4
        self.max_len = 64


class TestAdmissionOrder:
    def _sched(self, tenants="", mode="continuous"):
        return Scheduler(_StubEngine(), tenants=tenants, mode=mode)

    def _pend(self, tenant, sched, seq, max_tokens=8, adapter=0):
        p = Pending([1, 2], max_tokens, tenant=tenant, adapter=adapter)
        sched._bind_tenant(p)
        p.seq = seq
        return p

    def test_class_rank_orders_admission(self):
        s = self._sched("gold:1:latency,bronze:1:best-effort")
        be = self._pend("bronze", s, 1)
        std = self._pend("", s, 2)
        gold = self._pend("gold", s, 3)
        s._ready = [be, std, gold]
        assert [p.tenant for p in s._admission_order()] == \
            ["gold", "", "bronze"]

    def test_weighted_fair_share_within_class(self):
        s = self._sched("heavy:4:standard,light:1:standard")
        # heavy admitted twice already: its vtime advanced by
        # 2 * 8/4 = 4; light once: 8/1 = 8 → heavy still goes first
        for _ in range(2):
            s._charge(self._pend("heavy", s, 0))
        s._charge(self._pend("light", s, 0))
        h = self._pend("heavy", s, 5)
        li = self._pend("light", s, 4)
        s._ready = [li, h]
        assert [p.tenant for p in s._admission_order()] == \
            ["heavy", "light"]
        # one more heavy admission tips the balance past light's 8
        for _ in range(3):
            s._charge(self._pend("heavy", s, 0))
        assert [p.tenant for p in s._admission_order()] == \
            ["light", "heavy"]

    def test_adapter_affinity_tiebreak(self):
        s = self._sched()
        s.engine.slots = {0: object()}
        s.engine._slot_adapter_host = {0: 2}
        # same tenant (same vtime), different adapters, FIFO says a
        # first — affinity with the live adapter 2 wins the tiebreak
        a = self._pend("", s, 1, adapter=1)
        b = self._pend("", s, 2, adapter=2)
        s._ready = [a, b]
        assert s._admission_order()[0] is b

    def test_fixed_mode_is_fifo(self):
        s = self._sched("gold:1:latency", mode="fixed")
        gold = self._pend("gold", s, 2)
        std = self._pend("", s, 1)
        s._ready = [gold, std]
        assert [p.seq for p in s._admission_order()] == [1, 2]

    def test_unknown_tenant_gets_default_class(self):
        s = self._sched("gold:1:latency")
        p = self._pend("mystery", s, 1)
        assert p.spec.tenant_class == "standard"
        assert p.spec.weight == 1.0
        anon = self._pend("", s, 2)
        assert anon.spec is DEFAULT_SPEC

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError, match="mode"):
            Scheduler(_StubEngine(), mode="sometimes")
