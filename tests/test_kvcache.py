"""Paged KV-cache block pool (serving/kvcache.py) and the tenant/SLO
scheduling policy units (serving/scheduler.py) — the pure host-side
halves of the continuous-batching subsystem. The engine-integrated
paths (preempt/resume round-trips, SLO preemption over HTTP) live in
tests/test_serving_sched.py (slow tier)."""

from __future__ import annotations

import pytest

from instaslice_tpu.serving.kvcache import (
    BlockPoolExhausted,
    KVBlockPool,
)
from instaslice_tpu.serving.scheduler import (
    CLASS_RANK,
    DEFAULT_SPEC,
    Pending,
    Scheduler,
    TenantSpec,
    class_rank,
    parse_tenant_specs,
)


class TestBlockPool:
    def test_allocate_rounds_up_and_frees(self):
        pool = KVBlockPool(8, 16)
        t = pool.allocate(17)               # 2 blocks
        assert len(t.blocks) == 2 and pool.used_blocks() == 2
        assert pool.free_blocks() == 6
        pool.release(t)
        assert pool.used_blocks() == 0 and pool.free_blocks() == 8
        assert len(t.blocks) == 0

    def test_zero_token_table(self):
        pool = KVBlockPool(4, 16)
        t = pool.allocate(0)
        assert len(t.blocks) == 0 and pool.used_blocks() == 0

    def test_ensure_grows_incrementally(self):
        pool = KVBlockPool(8, 4)
        t = pool.allocate(3)
        assert len(t.blocks) == 1
        pool.ensure(t, 4)                   # exactly full: no new block
        assert len(t.blocks) == 1
        pool.ensure(t, 5)
        assert len(t.blocks) == 2
        pool.ensure(t, 5)                   # idempotent
        assert len(t.blocks) == 2

    def test_exhaustion_raises_table_unchanged(self):
        pool = KVBlockPool(2, 4)
        t = pool.allocate(8)                # both blocks
        t2 = pool.allocate(0)
        with pytest.raises(BlockPoolExhausted):
            pool.ensure(t2, 1)
        assert len(t2.blocks) == 0 and t2.tokens == 0
        pool.release(t)
        pool.ensure(t2, 1)                  # now it fits

    def test_fork_shares_and_cow_copies_boundary(self):
        pool = KVBlockPool(8, 4)
        parent = pool.allocate(6)           # 2 blocks, boundary half full
        assert pool.used_blocks() == 2
        child = pool.fork(parent)
        # zero pool cost: the child references the parent's blocks
        assert pool.used_blocks() == 2
        stats = pool.stats({1: parent, 2: child})
        assert stats["cow"] == 2
        # the child's first divergent token copies ONLY the boundary
        pool.ensure(child, 7)
        assert pool.used_blocks() == 3
        assert pool.cow_copies == 1
        assert child.blocks[0] is parent.blocks[0]      # still shared
        assert child.blocks[1] is not parent.blocks[1]  # copied
        # parent growing afterwards must also copy ITS boundary — the
        # child still references the original
        pool.release(child)
        assert pool.used_blocks() == 2

    def test_parent_growth_cows_when_child_references(self):
        pool = KVBlockPool(8, 4)
        parent = pool.allocate(6)
        child = pool.fork(parent)
        old_boundary = parent.blocks[1]
        pool.ensure(parent, 7)
        assert parent.blocks[1] is not old_boundary
        assert child.blocks[1] is old_boundary
        assert pool.cow_copies == 1

    def test_fork_prefix_share_is_trimmed(self):
        pool = KVBlockPool(8, 4)
        parent = pool.allocate(8)           # 2 full blocks
        child = pool.fork(parent, 4)        # share only the first
        assert len(child.blocks) == 1 and child.tokens == 4
        pool.ensure(child, 5)               # full boundary: plain grow
        assert pool.cow_copies == 0
        assert len(child.blocks) == 2

    def test_pinned_tables_outside_pool(self):
        pool = KVBlockPool(4, 4)
        pre = pool.pin(8)                   # 2 pinned blocks
        assert pool.used_blocks() == 0      # no pool cost
        assert pool.pinned_blocks() == 2
        assert pool.free_blocks() == 4
        child = pool.fork(pre)
        pool.ensure(child, 9)               # grows past the pin
        assert pool.used_blocks() == 1
        pool.release(pre)
        assert pool.pinned_blocks() == 2    # child still references
        pool.release(child)
        assert pool.pinned_blocks() == 0
        assert pool.used_blocks() == 0

    def test_pinned_boundary_write_adopts_pool_block(self):
        pool = KVBlockPool(4, 4)
        pre = pool.pin(6)                   # boundary half full
        child = pool.fork(pre)
        pool.ensure(child, 7)               # writes INTO the pinned block
        assert pool.cow_copies == 1
        assert child.blocks[1] is not pre.blocks[1]
        assert pool.used_blocks() == 1      # the adopted copy

    def test_utilization_true_block_occupancy(self):
        pool = KVBlockPool(8, 16)
        t = pool.allocate(24)               # 2 blocks = 32 capacity
        assert t.tokens == 24
        assert pool.utilization(24) == 24 / 32
        assert pool.utilization(0) == 0.0
        pool.release(t)
        assert pool.utilization(0) == 0.0   # empty pool: no capacity

    def test_utilization_counts_pinned_capacity(self):
        """Prefix-covered resident tokens live in pinned blocks: the
        capacity they divide by must include them, or any prefix-hit
        traffic saturates the gauge at 1.0."""
        pool = KVBlockPool(8, 4)
        pool.pin(8)                         # 2 pinned blocks
        t = pool.allocate(2)                # 1 allocated block
        # 10 resident tokens (8 prefix + 2 own) over 3 blocks of 4
        assert pool.utilization(10) == 10 / 12
        pool.release(t)

    def test_bad_construction(self):
        with pytest.raises(ValueError):
            KVBlockPool(0, 16)
        with pytest.raises(ValueError):
            KVBlockPool(8, 0)

    def test_bump_fast_path_semantics(self):
        """bump() is the incremental _sync_tables fast path: free when
        growth stays inside the current blocks, refused (False, table
        untouched) the moment a new block or a shared-boundary copy
        would be needed — ensure() then does the real work."""
        pool = KVBlockPool(8, 4)
        t = pool.allocate(2)                  # 1 block, 2 tokens
        assert pool.bump(t, 3)                # within the tail block
        assert t.tokens == 3 and len(t.blocks) == 1
        assert pool.bump(t, 2)                # shrink/no-op: True
        assert t.tokens == 3
        assert not pool.bump(t, 5)            # needs a second block
        assert t.tokens == 3 and pool.used_blocks() == 1
        pool.ensure(t, 5)
        assert len(t.blocks) == 2
        # shared partial boundary: growth must COW, bump refuses
        child = pool.fork(t, 5)
        assert not pool.bump(child, 6)
        before = pool.cow_copies
        pool.ensure(child, 6)
        assert pool.cow_copies == before + 1
        pool.release(t)
        pool.release(child)


class TestTenantSpecs:
    def test_full_grammar(self):
        specs = parse_tenant_specs(
            "gold:4:latency:0.5:0.05,free:1:best-effort:30,plain"
        )
        assert specs["gold"] == TenantSpec("gold", 4.0, "latency",
                                           0.5, 0.05)
        assert specs["free"].tenant_class == "best-effort"
        assert specs["free"].ttft_slo == 30.0
        assert specs["plain"].tenant_class == "standard"
        assert specs["plain"].weight == 1.0

    def test_errors(self):
        with pytest.raises(ValueError, match="class"):
            parse_tenant_specs("a:1:platinum")
        with pytest.raises(ValueError, match="weight"):
            parse_tenant_specs("a:0:latency")
        with pytest.raises(ValueError, match="numbers"):
            parse_tenant_specs("a:heavy:latency")
        with pytest.raises(ValueError, match="twice"):
            parse_tenant_specs("a:1,a:2")
        with pytest.raises(ValueError, match="empty name"):
            parse_tenant_specs(":1:latency")

    def test_class_rank_default(self):
        assert class_rank("latency") < class_rank("standard")
        assert class_rank("standard") < class_rank("best-effort")
        assert class_rank("nonsense") == CLASS_RANK["standard"]


class _StubEngine:
    """Just enough engine for the pure scheduling-order units."""

    def __init__(self):
        self.slots = {}
        self._slot_adapter_host = {}
        self.draft_model = None
        self.max_batch = 4
        self.max_len = 64


class TestAdmissionOrder:
    def _sched(self, tenants="", mode="continuous"):
        return Scheduler(_StubEngine(), tenants=tenants, mode=mode)

    def _pend(self, tenant, sched, seq, max_tokens=8, adapter=0):
        p = Pending([1, 2], max_tokens, tenant=tenant, adapter=adapter)
        sched._bind_tenant(p)
        p.seq = seq
        return p

    def test_class_rank_orders_admission(self):
        s = self._sched("gold:1:latency,bronze:1:best-effort")
        be = self._pend("bronze", s, 1)
        std = self._pend("", s, 2)
        gold = self._pend("gold", s, 3)
        s._ready = [be, std, gold]
        assert [p.tenant for p in s._admission_order()] == \
            ["gold", "", "bronze"]

    def test_weighted_fair_share_within_class(self):
        s = self._sched("heavy:4:standard,light:1:standard")
        # heavy admitted twice already: its vtime advanced by
        # 2 * 8/4 = 4; light once: 8/1 = 8 → heavy still goes first
        for _ in range(2):
            s._charge(self._pend("heavy", s, 0))
        s._charge(self._pend("light", s, 0))
        h = self._pend("heavy", s, 5)
        li = self._pend("light", s, 4)
        s._ready = [li, h]
        assert [p.tenant for p in s._admission_order()] == \
            ["heavy", "light"]
        # one more heavy admission tips the balance past light's 8
        for _ in range(3):
            s._charge(self._pend("heavy", s, 0))
        assert [p.tenant for p in s._admission_order()] == \
            ["light", "heavy"]

    def test_adapter_affinity_tiebreak(self):
        s = self._sched()
        s.engine.slots = {0: object()}
        s.engine._slot_adapter_host = {0: 2}
        # same tenant (same vtime), different adapters, FIFO says a
        # first — affinity with the live adapter 2 wins the tiebreak
        a = self._pend("", s, 1, adapter=1)
        b = self._pend("", s, 2, adapter=2)
        s._ready = [a, b]
        assert s._admission_order()[0] is b

    def test_fixed_mode_is_fifo(self):
        s = self._sched("gold:1:latency", mode="fixed")
        gold = self._pend("gold", s, 2)
        std = self._pend("", s, 1)
        s._ready = [gold, std]
        assert [p.seq for p in s._admission_order()] == [1, 2]

    def test_unknown_tenant_gets_default_class(self):
        s = self._sched("gold:1:latency")
        p = self._pend("mystery", s, 1)
        assert p.spec.tenant_class == "standard"
        assert p.spec.weight == 1.0
        anon = self._pend("", s, 2)
        assert anon.spec is DEFAULT_SPEC

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError, match="mode"):
            Scheduler(_StubEngine(), mode="sometimes")
