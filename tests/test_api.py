"""CR data-model tests: round-trips, state machine, CRD schema."""

import pytest

from instaslice_tpu.api import (
    AllocationDetails,
    AllocationStatus,
    PodRef,
    PreparedDetails,
    PreparedPart,
    TpuSlice,
    TpuSliceSpec,
    crd_manifest,
)
from instaslice_tpu.api.types import check_transition
from instaslice_tpu.topology import FirstFitPolicy, Occupancy, TorusGroup, parse_profile_name
from instaslice_tpu.topology.grid import get_generation


def make_allocation() -> AllocationDetails:
    g = TorusGroup.single_host("node-a", get_generation("v5e"))
    pl = FirstFitPolicy().choose(g, parse_profile_name("v5e-2x2"), Occupancy(g))
    return AllocationDetails.from_placement(
        pl, [PodRef("pu-1", "demo", "default", 0)], now=123.0
    )


class TestStateMachine:
    def test_legal_path(self):
        a = make_allocation()
        assert a.status == AllocationStatus.CREATING
        a.set_status(AllocationStatus.CREATED)
        a.set_status(AllocationStatus.UNGATED)
        a.set_status(AllocationStatus.DELETED)

    def test_illegal_transitions(self):
        with pytest.raises(ValueError):
            check_transition(AllocationStatus.UNGATED, AllocationStatus.CREATING)
        with pytest.raises(ValueError):
            check_transition(AllocationStatus.DELETED, AllocationStatus.CREATING)
        with pytest.raises(ValueError):
            check_transition(AllocationStatus.CREATING, AllocationStatus.UNGATED)

    def test_failure_and_retry(self):
        a = make_allocation()
        a.set_status(AllocationStatus.FAILED, "chip reservation failed")
        assert a.message == "chip reservation failed"
        a.set_status(AllocationStatus.CREATING)  # controller retries
        a.set_status(AllocationStatus.CREATED)


class TestRoundTrips:
    def test_allocation_roundtrip(self):
        a = make_allocation()
        d = a.to_dict()
        b = AllocationDetails.from_dict(d)
        assert b == a
        assert d["profile"] == "v5e-2x2"
        assert "node-a" in d["parts"]

    def test_prepared_roundtrip(self):
        p = PreparedDetails(
            slice_uuid="su-1",
            pod_uuid="pu-1",
            profile="v5e-2x2",
            box="0,0,0+2x2x1",
            parts={
                "node-a": PreparedPart(
                    node_name="node-a",
                    worker_id=0,
                    local_box="0,0,0+2x2x1",
                    chip_ids=[0, 1, 2, 3],
                    device_handle="fake-0",
                )
            },
        )
        assert PreparedDetails.from_dict(p.to_dict()) == p

    def test_tpuslice_manifest_roundtrip(self):
        ts = TpuSlice(
            name="node-a",
            namespace="instaslice-tpu-system",
            spec=TpuSliceSpec(
                generation="v5e",
                host_offset=(2, 0, 0),
                torus_group="g0",
                chips={"0": "/dev/accel0", "1": "/dev/accel1"},
                profiles=[{"name": "v5e-1x1", "chips": 1}],
                allocations={"pu-1": make_allocation()},
            ),
        )
        m = ts.to_manifest()
        assert m["apiVersion"] == "tpu.instaslice.dev/v1alpha1"
        assert m["kind"] == "TpuSlice"
        back = TpuSlice.from_manifest(m)
        assert back.spec == ts.spec
        assert back.name == "node-a"
        ng = back.spec.node_grid()
        assert ng.host_offset == (2, 0, 0)

    def test_dangling_prepared_convention(self):
        p = PreparedDetails.from_dict(
            {"sliceUUID": "s", "profile": "v5e-1x1", "box": "0,0,0+1x1x1"}
        )
        assert p.pod_uuid == ""  # dangling/adopted


class TestCrd:
    def test_crd_shape(self):
        crd = crd_manifest()
        assert crd["metadata"]["name"] == "tpuslices.tpu.instaslice.dev"
        v = crd["spec"]["versions"][0]
        assert v["storage"] is True
        schema = v["schema"]["openAPIV3Schema"]
        spec_props = schema["properties"]["spec"]["properties"]
        for field in ["generation", "hostOffset", "torusGroup", "chips",
                      "profiles", "allocations", "prepared"]:
            assert field in spec_props
        statuses = spec_props["allocations"]["additionalProperties"][
            "properties"]["status"]["enum"]
        assert set(statuses) == {s.value for s in AllocationStatus}

    def test_crd_serializes_to_yaml(self):
        import yaml

        text = yaml.safe_dump(crd_manifest())
        assert "tpuslices.tpu.instaslice.dev" in text
