"""Fake kube API tests: CRUD, optimistic concurrency, finalizers, watches,
and concurrent conflict-retry — the semantics every reconciler leans on."""

import threading

import pytest

from instaslice_tpu.kube import (
    AlreadyExists,
    Conflict,
    FakeKube,
    NotFound,
    update_with_retry,
)
from instaslice_tpu.kube.fake import merge_patch


def pod(name, ns="default", **meta):
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": ns, **meta},
        "spec": {},
        "status": {},
    }


class TestCrud:
    def test_create_get_list_delete(self):
        k = FakeKube()
        k.create("Pod", pod("a"))
        k.create("Pod", pod("b", ns="other"))
        assert k.get("Pod", "default", "a")["metadata"]["name"] == "a"
        assert len(k.list("Pod")) == 2
        assert len(k.list("Pod", namespace="default")) == 1
        k.delete("Pod", "default", "a")
        with pytest.raises(NotFound):
            k.get("Pod", "default", "a")

    def test_create_duplicate(self):
        k = FakeKube()
        k.create("Pod", pod("a"))
        with pytest.raises(AlreadyExists):
            k.create("Pod", pod("a"))

    def test_label_selector(self):
        k = FakeKube()
        k.create("Pod", pod("a", labels={"app": "x"}))
        k.create("Pod", pod("b", labels={"app": "y"}))
        assert len(k.list("Pod", label_selector={"app": "x"})) == 1

    def test_rv_assigned_and_monotonic(self):
        k = FakeKube()
        a = k.create("Pod", pod("a"))
        b = k.create("Pod", pod("b"))
        assert int(b["metadata"]["resourceVersion"]) > int(
            a["metadata"]["resourceVersion"]
        )


class TestOptimisticConcurrency:
    def test_stale_update_conflicts(self):
        k = FakeKube()
        k.create("Pod", pod("a"))
        v1 = k.get("Pod", "default", "a")
        v2 = k.get("Pod", "default", "a")
        v1["spec"]["x"] = 1
        k.update("Pod", v1)
        v2["spec"]["x"] = 2
        with pytest.raises(Conflict):
            k.update("Pod", v2)

    def test_patch_never_conflicts(self):
        k = FakeKube()
        k.create("Pod", pod("a"))
        k.patch("Pod", "default", "a", {"spec": {"x": 1}})
        k.patch("Pod", "default", "a", {"spec": {"y": 2}})
        got = k.get("Pod", "default", "a")
        assert got["spec"] == {"x": 1, "y": 2}

    def test_merge_patch_semantics(self):
        base = {"a": {"b": 1, "c": 2}, "l": [1, 2], "d": 3}
        out = merge_patch(base, {"a": {"b": None, "e": 9}, "l": [5]})
        assert out == {"a": {"c": 2, "e": 9}, "l": [5], "d": 3}

    def test_concurrent_update_with_retry(self):
        """16 threads increment one counter through conflict-retry; all
        increments must land (the reference's blind-update pattern loses
        these, SURVEY.md §7)."""
        k = FakeKube()
        k.create("Pod", pod("ctr"))
        k.patch("Pod", "default", "ctr", {"spec": {"n": 0}})
        N, T = 25, 16
        errs = []

        def worker():
            try:
                for _ in range(N):
                    def mut(obj):
                        obj["spec"]["n"] += 1
                        return obj
                    update_with_retry(k, "Pod", "default", "ctr", mut,
                                      attempts=50)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker) for _ in range(T)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert k.get("Pod", "default", "ctr")["spec"]["n"] == N * T

    def test_update_with_retry_abort(self):
        k = FakeKube()
        k.create("Pod", pod("a"))
        out = update_with_retry(k, "Pod", "default", "a", lambda o: None)
        assert out is None


class TestFinalizers:
    def test_delete_blocked_by_finalizer(self):
        k = FakeKube()
        k.create("Pod", pod("a", finalizers=["tpu.instaslice.dev/accelerator"]))
        k.delete("Pod", "default", "a")
        got = k.get("Pod", "default", "a")  # still there
        assert got["metadata"]["deletionTimestamp"]
        # removing the finalizer completes deletion
        got["metadata"]["finalizers"] = []
        k.update("Pod", got)
        with pytest.raises(NotFound):
            k.get("Pod", "default", "a")

    def test_delete_idempotent_while_finalized(self):
        k = FakeKube()
        k.create("Pod", pod("a", finalizers=["f"]))
        k.delete("Pod", "default", "a")
        ts1 = k.get("Pod", "default", "a")["metadata"]["deletionTimestamp"]
        k.delete("Pod", "default", "a")
        assert k.get("Pod", "default", "a")["metadata"]["deletionTimestamp"] == ts1


class TestWatch:
    def test_replay_and_live_events(self):
        k = FakeKube()
        k.create("Pod", pod("a"))
        events = []
        it = k.watch("Pod", timeout=0.5)
        t = threading.Thread(target=lambda: events.extend(
            e for e in it if e[0] != "BOOKMARK"))
        t.start()
        import time as _t

        _t.sleep(0.05)
        k.create("Pod", pod("b"))
        k.delete("Pod", "default", "b")
        t.join()
        kinds = [(e, o["metadata"]["name"]) for e, o in events]
        assert ("ADDED", "a") in kinds
        assert ("ADDED", "b") in kinds
        assert ("DELETED", "b") in kinds

    def test_namespace_filter(self):
        k = FakeKube()
        it = k.watch("Pod", namespace="ns1", timeout=0.3)
        k.create("Pod", pod("x", ns="ns1"))
        k.create("Pod", pod("y", ns="ns2"))
        names = [o["metadata"]["name"] for e, o in it if e != "BOOKMARK"]
        assert names == ["x"]

    def test_finalizer_release_emits_deleted(self):
        k = FakeKube()
        k.create("Pod", pod("a", finalizers=["f"]))
        it = k.watch("Pod", timeout=0.3)
        k.delete("Pod", "default", "a")
        obj = k.get("Pod", "default", "a")
        obj["metadata"]["finalizers"] = []
        k.update("Pod", obj)
        events = [e for e, _ in it if e != "BOOKMARK"]
        assert "DELETED" in events


class TestWatchResume:
    """Events emitted while no watch is established must be recoverable by
    resuming from the last seen resourceVersion — the informer contract
    that keeps the reconcile loops from losing wakeups (the reference
    relies on client-go for this)."""

    @staticmethod
    def _objects(stream):
        return [(e, o["metadata"]["name"]) for e, o in stream
                if e != "BOOKMARK"]

    def test_resume_replays_missed_events(self):
        k = FakeKube()
        k.create("Pod", pod("a"))
        seen = list(k.watch("Pod", timeout=0.05))
        assert seen[-1][0] == "BOOKMARK"  # burst ends with resume point
        last_rv = seen[-1][1]["metadata"]["resourceVersion"]
        # watch is down; events happen
        k.create("Pod", pod("b"))
        k.delete("Pod", "default", "b")
        missed = self._objects(
            k.watch("Pod", replay=False, timeout=0.05,
                    resource_version=last_rv)
        )
        assert ("ADDED", "b") in missed
        assert ("DELETED", "b") in missed
        assert ("ADDED", "a") not in missed  # older than the resume point

    def test_resume_from_zero_sees_everything(self):
        k = FakeKube()
        k.create("Pod", pod("a"))
        k.delete("Pod", "default", "a")
        events = self._objects(
            k.watch("Pod", replay=False, timeout=0.05, resource_version="0")
        )
        assert events == [("ADDED", "a"), ("DELETED", "a")]

    def test_truncated_log_falls_back_to_relist_plus_tail(self):
        k = FakeKube()
        k.HISTORY_MAX = 4
        k.create("Pod", pod("keeper"))
        for i in range(6):
            k.create("Pod", pod(f"t{i}"))
            k.delete("Pod", "default", f"t{i}")
        events = self._objects(
            k.watch("Pod", replay=False, timeout=0.05, resource_version="1")
        )
        # resume point fell off the log → relist of live objects plus the
        # retained tail (so recent DELETEDs still reach the consumer)
        assert events[0] == ("ADDED", "keeper")
        assert ("DELETED", "t5") in events

    def test_bookmark_advances_past_quiet_stream(self):
        k = FakeKube()
        k.create("Pod", pod("a"))
        k.create("ConfigMap", {"metadata": {"name": "x", "namespace": "d"}})
        # a watch on a kind with NO matching events still learns the head
        events = list(k.watch("ConfigMap", replay=False, timeout=0.05,
                              resource_version="2"))
        assert events[-1][0] == "BOOKMARK"
        head = int(events[-1][1]["metadata"]["resourceVersion"])
        # resuming from the bookmark replays nothing stale
        again = self._objects(
            k.watch("Pod", replay=False, timeout=0.05,
                    resource_version=str(head))
        )
        assert again == []

    def test_resync_relist_plus_resume_keeps_deletes(self):
        k = FakeKube()
        k.create("Pod", pod("a"))
        k.create("Pod", pod("b"))
        rv = k.get("Pod", "default", "b")["metadata"]["resourceVersion"]
        k.delete("Pod", "default", "b")  # deleted while watch is down
        events = self._objects(
            k.watch("Pod", replay=True, timeout=0.05, resource_version=rv)
        )
        # relist shows survivors; log replay still surfaces the deletion
        assert ("ADDED", "a") in events
        assert ("DELETED", "b") in events

    def test_deleted_event_rv_orders_after_updates(self):
        k = FakeKube()
        k.create("Pod", pod("a"))
        rv_live = k.get("Pod", "default", "a")["metadata"]["resourceVersion"]
        k.delete("Pod", "default", "a")
        events = self._objects(
            k.watch("Pod", replay=False, timeout=0.05,
                    resource_version=rv_live)
        )
        assert [e for e, _ in events] == ["DELETED"]


class TestNoopWrites:
    def test_noop_update_no_event_no_rv_bump(self):
        k = FakeKube()
        k.create("Pod", pod("a"))
        obj = k.get("Pod", "default", "a")
        rv = obj["metadata"]["resourceVersion"]
        it = k.watch("Pod", replay=False, timeout=0.2)
        out = k.update("Pod", obj)
        assert out["metadata"]["resourceVersion"] == rv
        out2 = k.patch("Pod", "default", "a", {"spec": {}})
        assert out2["metadata"]["resourceVersion"] == rv
        assert [e for e in it if e[0] != "BOOKMARK"] == []
