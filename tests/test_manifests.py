"""Deploy-plane validation: every YAML parses, the checked-in CRD matches
the in-code schema, RBAC covers the verbs the operator issues, and the
sample pods round-trip through the controller's gate/profile extraction.

The reference has no manifest tests at all (its e2e only waits for the
manager pod — SURVEY.md §4 tier 3); this tier catches the drift class the
reference's generated-vs-handwritten split invites.
"""

from __future__ import annotations

import glob
import os
import subprocess
import sys

import yaml

from instaslice_tpu import GATE_NAME, GROUP, PLURAL
from instaslice_tpu.api.crd import crd_manifest
from instaslice_tpu.controller.gates import (
    HANDOFF_ANNOTATION,
    extract_profile,
    is_pod_gated,
    pod_group,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_all(path):
    with open(path) as f:
        return [d for d in yaml.safe_load_all(f) if d is not None]


def all_yaml_files():
    out = []
    for sub in ("config", "samples"):
        out += glob.glob(os.path.join(REPO, sub, "**", "*.yaml"),
                         recursive=True)
    return sorted(out)


def iter_pods(doc):
    """Yield pod manifests from Pods, Lists, and workload templates."""
    kind = doc.get("kind")
    if kind == "Pod":
        yield doc
    elif kind == "List":
        for item in doc.get("items", []):
            yield from iter_pods(item)
    elif kind in ("Deployment", "DaemonSet", "StatefulSet", "Job"):
        tmpl = doc.get("spec", {}).get("template")
        if tmpl:
            yield {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": tmpl.get("metadata", {}),
                "spec": tmpl.get("spec", {}),
            }


class TestYamlParses:
    def test_all_files_parse(self):
        files = all_yaml_files()
        assert len(files) >= 12, files
        for path in files:
            docs = load_all(path)
            assert docs, f"{path} is empty"
            if os.path.basename(path) == "kustomization.yaml":
                continue  # kustomizations have no kind by design
            for d in docs:
                assert "kind" in d, f"{path}: doc without kind"


class TestCrdInSync:
    def test_checked_in_crd_matches_code(self):
        path = os.path.join(
            REPO, "config", "crd", "bases", f"{PLURAL}.{GROUP}.yaml"
        )
        with open(path) as f:
            on_disk = yaml.safe_load(f)
        assert on_disk == crd_manifest(), (
            "CRD yaml drifted from instaslice_tpu.api.crd — "
            "run python tools/gen_manifests.py"
        )

    def test_gen_manifests_check_mode(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "gen_manifests.py"),
             "--check"],
            capture_output=True,
        )
        assert r.returncode == 0, r.stderr.decode()


class TestRbacCoversClientVerbs:
    def test_role_covers_operator_surface(self):
        (role,) = load_all(os.path.join(REPO, "config", "rbac", "role.yaml"))
        rules = {}
        for rule in role["rules"]:
            for g in rule["apiGroups"]:
                for res in rule["resources"]:
                    rules.setdefault((g, res), set()).update(rule["verbs"])
        # controller: pod watch + gate removal (update), events
        assert {"get", "list", "watch", "update"} <= rules[("", "pods")]
        assert {"create"} <= rules[("", "events")]
        # agent: per-pod ConfigMap CRUD + node capacity patch
        assert {"create", "delete", "patch"} <= rules[("", "configmaps")]
        assert {"patch"} <= rules[("", "nodes/status")]
        # both: TpuSlice CRUD + status
        assert {"get", "list", "watch", "create", "update"} <= rules[
            (GROUP, PLURAL)
        ]
        assert {"patch"} <= rules[(GROUP, f"{PLURAL}/status")]


class TestSamplePods:
    def _sample_pods(self, name):
        pods = []
        for doc in load_all(os.path.join(REPO, "samples", name)):
            pods.extend(iter_pods(doc))
        return pods

    def test_all_sample_pods_are_gated_with_finalizer(self):
        for fname in ("test-pod.yaml", "tf-notebook.yaml", "vllm-tpu.yaml",
                      "multihost-4x4.yaml", "stress-binpack.yaml",
                      "reshard-preempt.yaml"):
            for pod in self._sample_pods(fname):
                gates = pod["spec"].get("schedulingGates", [])
                assert any(g["name"] == GATE_NAME for g in gates), (
                    fname, pod["metadata"].get("name"))
                fins = pod["metadata"].get("finalizers", [])
                assert GATE_NAME in fins, (fname, pod["metadata"].get("name"))

    def test_profiles_parse_through_controller_extraction(self):
        seen = set()
        for fname in ("test-pod.yaml", "vllm-tpu.yaml", "multihost-4x4.yaml",
                      "stress-binpack.yaml"):
            for pod in self._sample_pods(fname):
                prof = extract_profile(pod)
                assert prof is not None, (fname, pod["metadata"].get("name"))
                seen.add(prof.name)
        assert {"v5e-1x1", "v5e-2x1", "v5e-2x2", "v5e-4x4"} <= seen

    def test_gate_detection_on_samples(self):
        for pod in self._sample_pods("test-pod.yaml"):
            assert is_pod_gated(pod)

    def test_multihost_sample_declares_full_group(self):
        pods = self._sample_pods("multihost-4x4.yaml")
        groups = {}
        for p in pods:
            gid, size = pod_group(p)
            if gid:
                groups.setdefault((gid, size), []).append(
                    p["metadata"]["name"])
        assert groups, "no pod-group annotations found"
        for (gid, size), members in groups.items():
            assert len(members) == size, (gid, members)
        # envFrom ConfigMap name must match each pod's handoff name
        for p in pods:
            name = p["metadata"]["name"]
            refs = [
                e["configMapRef"]["name"]
                for c in p["spec"]["containers"]
                for e in c.get("envFrom", [])
            ]
            assert refs == [name], (name, refs)

    def test_deployment_sample_uses_stable_handoff_name(self):
        pods = self._sample_pods("vllm-tpu.yaml")
        assert pods
        for p in pods:
            ann = p["metadata"].get("annotations", {})
            handoff = ann.get(HANDOFF_ANNOTATION)
            assert handoff == "vllm-llama2-7b"
            refs = [
                e["configMapRef"]["name"]
                for c in p["spec"]["containers"]
                for e in c.get("envFrom", [])
            ]
            assert refs == [handoff]
            limits = p["spec"]["containers"][0]["resources"]["limits"]
            assert f"{GROUP}/{handoff}" in limits

    def test_per_pod_resource_matches_handoff_name(self):
        """Every bare sample Pod's limits carry tpu.instaslice.dev/<name>
        (the node-pinning resource the agent advertises)."""
        for fname in ("test-pod.yaml", "stress-binpack.yaml",
                      "reshard-preempt.yaml", "multihost-4x4.yaml"):
            for pod in self._sample_pods(fname):
                name = pod["metadata"]["name"]
                limits = pod["spec"]["containers"][0]["resources"]["limits"]
                assert f"{GROUP}/{name}" in limits, (fname, name)


class TestManagerManifests:
    def test_agent_daemonset_has_node_name_downward_api(self):
        docs = load_all(os.path.join(REPO, "config", "manager", "manager.yaml"))
        agents = [d for d in docs if d["kind"] == "DaemonSet"
                  and d["metadata"]["name"].endswith("agent")]
        assert len(agents) == 1
        (agent,) = agents
        ctr = agent["spec"]["template"]["spec"]["containers"][0]
        env = {e["name"]: e for e in ctr.get("env", [])}
        assert env["NODE_NAME"]["valueFrom"]["fieldRef"]["fieldPath"] == \
            "spec.nodeName"
        assert ctr["securityContext"]["privileged"] is True

    def test_deviceplugin_daemonset_mounts_kubelet_socket_dir(self):
        docs = load_all(os.path.join(REPO, "config", "manager", "manager.yaml"))
        dps = [d for d in docs if d["kind"] == "DaemonSet"
               and d["metadata"]["name"].endswith("deviceplugin")]
        assert len(dps) == 1
        (dp,) = dps
        spec = dp["spec"]["template"]["spec"]
        paths = [v.get("hostPath", {}).get("path") for v in spec["volumes"]]
        assert "/var/lib/kubelet/device-plugins" in paths

    def test_kustomizations_reference_existing_files(self):
        for kfile in glob.glob(
            os.path.join(REPO, "config", "**", "kustomization.yaml"),
            recursive=True,
        ):
            base = os.path.dirname(kfile)
            (k,) = load_all(kfile)
            for res in k.get("resources", []):
                target = os.path.normpath(os.path.join(base, res))
                assert os.path.exists(target), (kfile, res)
            for patch in k.get("patches", []):
                if "path" in patch:
                    target = os.path.normpath(
                        os.path.join(base, patch["path"])
                    )
                    assert os.path.exists(target), (kfile, patch)


class TestMetricsAuthn:
    """Reference parity: /metrics must sit behind kube-rbac-proxy
    (/root/reference/config/default/manager_auth_proxy_patch.yaml:12-33)."""

    def test_auth_proxy_patch_wires_sidecar_and_localhost_bind(self):
        docs = load_all(os.path.join(
            REPO, "config", "default", "manager_auth_proxy_patch.yaml"
        ))
        (patch,) = docs
        ctrs = {
            c["name"]: c
            for c in patch["spec"]["template"]["spec"]["containers"]
        }
        proxy = ctrs["kube-rbac-proxy"]
        assert any("--upstream=http://127.0.0.1:8080/" in a
                   for a in proxy["args"])
        assert any(p.get("name") == "https" for p in proxy["ports"])
        # the manager must retreat to localhost so the sidecar is the only
        # path to /metrics
        manager = ctrs["manager"]
        assert any("--metrics-bind-address=127.0.0.1:8080" in a
                   for a in manager["args"])

    def test_default_kustomization_applies_the_patch(self):
        (k,) = load_all(os.path.join(
            REPO, "config", "default", "kustomization.yaml"
        ))
        paths = [p.get("path", "") for p in k.get("patches", [])]
        assert "manager_auth_proxy_patch.yaml" in paths

    def test_rbac_grants_token_and_access_review(self):
        docs = load_all(os.path.join(
            REPO, "config", "rbac", "auth_proxy_role.yaml"
        ))
        (role,) = docs
        resources = {r for rule in role["rules"]
                     for r in rule.get("resources", [])}
        assert {"tokenreviews", "subjectaccessreviews"} <= resources

    def test_service_monitor_scrapes_https_with_token(self):
        (mon,) = load_all(os.path.join(
            REPO, "config", "prometheus", "monitor.yaml"
        ))
        (ep,) = mon["spec"]["endpoints"]
        assert ep["scheme"] == "https"
        assert "serviceaccount/token" in ep["bearerTokenFile"]
