"""Two-process DCN rendezvous smoke (SURVEY.md §7 risk #2).

Spawns two REAL OS processes, each with the handoff env the node agent
would publish for its worker of a two-host v5e-16 (4x4) placement, and
has them rendezvous through ``initialize_distributed`` →
``jax.distributed`` → one global psum. This covers the seam the
single-process dryrun cannot: cross-process coordinator bootstrap,
process_id assignment from ``TPU_WORKER_ID``, and a collective that
only sums correctly when BOTH processes' devices joined the mesh.
"""

import json
import os
import subprocess
import sys

from conftest import free_port

from instaslice_tpu.agent.handoff import slice_env
from instaslice_tpu.api.types import AllocationDetails, PodRef
from instaslice_tpu.topology.grid import (
    NodeGrid,
    TorusGroup,
    get_generation,
)
from instaslice_tpu.topology.placement import legal_placements
from instaslice_tpu.topology.profiles import parse_profile_name

LOCAL_DEVICES = 4  # virtual CPU devices per process ("chips" per host)


def _worker_envs():
    """Handoff env for BOTH workers of a real two-host 4x4 grant, via the
    real pipeline: placement engine → AllocationDetails → slice_env."""
    gen = get_generation("v5e")
    hosts = {
        "node-0": NodeGrid(gen, host_offset=(0, 0, 0), torus_group="g"),
        "node-1": NodeGrid(gen, host_offset=(2, 0, 0), torus_group="g"),
    }
    group = TorusGroup("g", gen, (4, 4, 1), hosts)
    placement = legal_placements(group, parse_profile_name("v5e-4x4"))[0]
    pods = [
        PodRef(
            pod_uuid=f"uid-{p.worker_id}",
            pod_name=f"worker-{p.worker_id}",
            namespace="default",
            worker_id=p.worker_id,
        )
        for p in placement.parts
    ]
    alloc = AllocationDetails.from_placement(placement, pods)
    return [
        slice_env(alloc, pod, placement.parts[i].node_name, "v5e")
        for i, pod in enumerate(pods)
    ]


class TestDcnRendezvous:
    def test_two_process_psum(self):
        envs = _worker_envs()
        assert len(envs) == 2
        port = free_port()
        procs = []
        for env in envs:
            child = dict(os.environ)
            child.update(env)
            # pod names resolve over the cluster's headless Service; in
            # this two-process test both workers are this host
            child["TPU_WORKER_HOSTNAMES"] = "127.0.0.1,127.0.0.1"
            child["TPUSLICE_SMOKE_PORT"] = str(port)
            child["TPUSLICE_SMOKE_FORCE_CPU"] = "1"
            child["TPUSLICE_SMOKE_CPU_DEVICES"] = str(LOCAL_DEVICES)
            child.pop("XLA_FLAGS", None)  # no forced 8-dev override
            # a single-chip TPU tunnel (if the session has one) cannot be
            # claimed by two processes at once — its interpreter hook
            # registers at startup and the second claim blocks forever;
            # these workers are CPU-only by design, so drop the trigger
            child.pop("PALLAS_AXON_POOL_IPS", None)
            child["JAX_PLATFORMS"] = "cpu"
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-m",
                     "instaslice_tpu.parallel.dcn_smoke"],
                    env=child,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                )
            )
        outs = []
        for p in procs:
            try:
                stdout, stderr = p.communicate(timeout=180)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise AssertionError(
                    "rendezvous hung: worker never completed"
                )
            assert p.returncode == 0, stderr.decode()[-800:]
            outs.append(json.loads(stdout.decode().strip().splitlines()[-1]))

        # every worker saw both processes and all devices
        expected_total = sum(
            (w + 1) * LOCAL_DEVICES for w in range(2)
        )  # 1*4 + 2*4 = 12
        for out in outs:
            assert out["num_workers"] == 2
            assert out["processes_seen"] == 2
            assert out["global_devices"] == 2 * LOCAL_DEVICES
            assert out["local_devices"] == LOCAL_DEVICES
            assert out["psum_total"] == expected_total
        assert sorted(o["worker_id"] for o in outs) == [0, 1]
