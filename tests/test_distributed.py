"""Two-process DCN rendezvous smoke (SURVEY.md §7 risk #2).

Spawns two REAL OS processes, each with the handoff env the node agent
would publish for its worker of a two-host v5e-16 (4x4) placement, and
has them rendezvous through ``initialize_distributed`` →
``jax.distributed`` → one global psum. This covers the seam the
single-process dryrun cannot: cross-process coordinator bootstrap,
process_id assignment from ``TPU_WORKER_ID``, and a collective that
only sums correctly when BOTH processes' devices joined the mesh.
"""

import json
import os
import subprocess
import sys

import jax
import pytest

from conftest import free_port

from instaslice_tpu.agent.handoff import slice_env
from instaslice_tpu.api.types import AllocationDetails, PodRef
from instaslice_tpu.topology.grid import (
    NodeGrid,
    TorusGroup,
    get_generation,
)
from instaslice_tpu.topology.placement import legal_placements
from instaslice_tpu.topology.profiles import parse_profile_name

LOCAL_DEVICES = 4  # virtual CPU devices per process ("chips" per host)

#: environment-bound (known set, not regressions): the two-process
#: tiers form a REAL multi-process mesh, and the jax 0.4.x CPU backend
#: refuses cross-process computations outright — every worker dies with
#: "Multiprocess computations aren't implemented on the CPU backend".
#: jax >= 0.5 (or real TPU hosts) runs them; marked explicitly so
#: tier output separates this known set from genuine regressions.
two_process_mesh = pytest.mark.skipif(
    tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5),
    reason="environment-bound: jax 0.4.x CPU backend cannot run a "
           "multi-process mesh (\"Multiprocess computations aren't "
           "implemented on the CPU backend\") — needs jax >= 0.5 or "
           "real TPU hosts",
)


def _worker_envs():
    """Handoff env for BOTH workers of a real two-host 4x4 grant, via the
    real pipeline: placement engine → AllocationDetails → slice_env."""
    gen = get_generation("v5e")
    hosts = {
        "node-0": NodeGrid(gen, host_offset=(0, 0, 0), torus_group="g"),
        "node-1": NodeGrid(gen, host_offset=(2, 0, 0), torus_group="g"),
    }
    group = TorusGroup("g", gen, (4, 4, 1), hosts)
    placement = legal_placements(group, parse_profile_name("v5e-4x4"))[0]
    pods = [
        PodRef(
            pod_uuid=f"uid-{p.worker_id}",
            pod_name=f"worker-{p.worker_id}",
            namespace="default",
            worker_id=p.worker_id,
        )
        for p in placement.parts
    ]
    alloc = AllocationDetails.from_placement(placement, pods)
    return [
        slice_env(alloc, pod, placement.parts[i].node_name, "v5e")
        for i, pod in enumerate(pods)
    ]


def _spawn_workers(module: str, extra_env=None, timeout=240):
    """Run ``module`` in one process per worker of the two-host grant;
    returns each worker's last-stdout-line JSON."""
    envs = _worker_envs()
    assert len(envs) == 2
    port = free_port()
    procs = []
    for env in envs:
        child = dict(os.environ)
        child.update(env)
        # pod names resolve over the cluster's headless Service; in
        # this two-process test both workers are this host
        child["TPU_WORKER_HOSTNAMES"] = "127.0.0.1,127.0.0.1"
        child["TPUSLICE_SMOKE_PORT"] = str(port)
        child["TPUSLICE_SMOKE_FORCE_CPU"] = "1"
        child["TPUSLICE_SMOKE_CPU_DEVICES"] = str(LOCAL_DEVICES)
        child.pop("XLA_FLAGS", None)  # no forced 8-dev override
        # a single-chip TPU tunnel (if the session has one) cannot be
        # claimed by two processes at once — its interpreter hook
        # registers at startup and the second claim blocks forever;
        # these workers are CPU-only by design, so drop the trigger
        child.pop("PALLAS_AXON_POOL_IPS", None)
        child["JAX_PLATFORMS"] = "cpu"
        child.update(extra_env or {})
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", module],
                env=child,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
        )
    outs = []
    for p in procs:
        try:
            stdout, stderr = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError("rendezvous hung: worker never completed")
        assert p.returncode == 0, stderr.decode()[-800:]
        outs.append(json.loads(stdout.decode().strip().splitlines()[-1]))
    return outs


class TestDcnRendezvous:
    @two_process_mesh
    def test_two_process_psum(self):
        outs = _spawn_workers("instaslice_tpu.parallel.dcn_smoke",
                              timeout=180)
        # every worker saw both processes and all devices
        expected_total = sum(
            (w + 1) * LOCAL_DEVICES for w in range(2)
        )  # 1*4 + 2*4 = 12
        for out in outs:
            assert out["num_workers"] == 2
            assert out["processes_seen"] == 2
            assert out["global_devices"] == 2 * LOCAL_DEVICES
            assert out["local_devices"] == LOCAL_DEVICES
            assert out["psum_total"] == expected_total
        assert sorted(o["worker_id"] for o in outs) == [0, 1]


class TestDcnServing:
    @two_process_mesh
    def test_two_process_tensor_parallel_decode(self):
        """The serving engine running SPMD over a DCN-spanning mesh:
        both workers execute the identical op stream and must produce
        identical tokens — equal to a single-process reference."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        import numpy as np

        from instaslice_tpu.models.lm import ModelConfig, TpuLM
        from instaslice_tpu.serving import ServingEngine

        outs = _spawn_workers("instaslice_tpu.serving.dcn_serve_smoke")
        assert all(o["processes_seen"] == 2 for o in outs)
        assert all(o["global_devices"] == 2 * LOCAL_DEVICES for o in outs)
        # both workers saw the same chain
        assert outs[0]["tokens"] == outs[1]["tokens"]
        assert len(outs[0]["tokens"]) == 8
        # …and it matches this process's single-mesh reference (same
        # seed, same config — 8 local CPU devices from conftest)
        cfg = ModelConfig(
            vocab_size=64, d_model=32, n_heads=2 * LOCAL_DEVICES,
            n_layers=2, d_ff=64, dtype=jnp.float32, remat=False,
        )
        mesh = Mesh(np.array(jax.devices()[:8]), ("model",))
        ref = ServingEngine(TpuLM(cfg), max_batch=2, max_len=64,
                            prefill_len=8, mesh=mesh)
        rid = ref.add_request([5, 9, 2, 7])
        want = ref.decode_block(8)[rid]
        assert outs[0]["tokens"] == want

    @two_process_mesh
    def test_two_process_oplog_driver_follower(self):
        """Dynamic traffic over the driver/follower op stream: worker 0
        drives ragged admissions + an external budget cut; worker 1
        replays the broadcast ops. Both engines must land in an
        identical state, equal to a single-process replay."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        import numpy as np

        from instaslice_tpu.models.lm import ModelConfig, TpuLM
        from instaslice_tpu.serving import ServingEngine
        from instaslice_tpu.serving.dcn_serve_smoke import (
            run_script,
            state_digest,
        )

        outs = _spawn_workers(
            "instaslice_tpu.serving.dcn_serve_smoke",
            extra_env={
                "TPUSLICE_SMOKE_MODE": "oplog",
                "TPUSLICE_OPLOG_PORT": str(free_port()),
            },
        )
        # …the driver's state equals this process's single-mesh replay
        cfg = ModelConfig(
            vocab_size=64, d_model=32, n_heads=2 * LOCAL_DEVICES,
            n_layers=2, d_ff=64, dtype=jnp.float32, remat=False,
        )
        mesh = Mesh(np.array(jax.devices()[:8]), ("model",))
        # the oplog smoke engines carry a self-draft (run_script
        # replays one speculative round); the replay must match
        ref_model = TpuLM(cfg)
        ref = ServingEngine(ref_model, max_batch=2, max_len=64,
                            prefill_len=8, mesh=mesh,
                            draft_model=ref_model, spec_k=3)
        run_script(ref)
        # followers drain `finished` (results are the driver's
        # business); compare the follower on live state only
        f_digest = dict(outs[1]["digest"], finished=[])
        d_digest = dict(outs[0]["digest"])
        assert f_digest == dict(d_digest, finished=[])
        assert outs[0]["digest"] == state_digest(ref)
        # the budget-cut request really kept exactly 4 tokens (a
        # literal, so a finish_slot regression can't hide in ref)
        assert len(outs[0]["digest"]["finished"][0][1]) == 4
        assert outs[0]["digest"]["finished"][0][2] == "max_new_tokens"


class TestServeCliMultiHost:
    @two_process_mesh
    def test_from_env_two_worker_serve(self):
        """The product path end-to-end: ``tpuslice-serve --from-env``
        in BOTH worker pods of a two-host grant. Worker 0 rendezvouses,
        builds the global mesh, drives; worker 1 follows. A completion
        against worker 0's HTTP port must come back greedy-valid."""
        import time as _time
        import urllib.request

        envs = _worker_envs()
        smoke_port, http_port, oplog_port = (
            free_port(), free_port(), free_port()
        )
        args = ["--from-env", "--port", str(http_port),
                "--oplog-port", str(oplog_port),
                "--d-model", "32", "--n-heads", "8", "--n-layers", "2",
                "--d-ff", "64", "--vocab-size", "64",
                "--max-batch", "2", "--max-len", "64",
                "--prefill-len", "8"]
        procs = []
        for env in envs:
            child = dict(os.environ)
            child.update(env)
            child["TPU_WORKER_HOSTNAMES"] = "127.0.0.1,127.0.0.1"
            child["TPUSLICE_COORDINATOR_PORT"] = str(smoke_port)
            child["XLA_FLAGS"] = (
                "--xla_force_host_platform_device_count=4"
            )
            child.pop("PALLAS_AXON_POOL_IPS", None)
            child["JAX_PLATFORMS"] = "cpu"
            procs.append(subprocess.Popen(
                [sys.executable, "-m",
                 "instaslice_tpu.serving.api_server"] + args,
                env=child,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            ))
        try:
            url = f"http://127.0.0.1:{http_port}"
            deadline = _time.monotonic() + 180
            up = False
            while _time.monotonic() < deadline:
                if any(p.poll() is not None for p in procs):
                    break                   # a worker died — fail below
                try:
                    urllib.request.urlopen(url + "/healthz", timeout=2)
                    up = True
                    break
                except OSError:
                    _time.sleep(1)
            if not up:
                errs = []
                for p in procs:
                    p.kill()
                    errs.append(p.communicate()[1].decode()[-400:])
                raise AssertionError(f"server never came up: {errs}")
            req = urllib.request.Request(
                url + "/v1/completions",
                data=json.dumps({"prompt": [5, 9, 2, 7],
                                 "max_tokens": 6}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=120) as r:
                out = json.loads(r.read())
            toks = out["choices"][0]["token_ids"]
            assert len(toks) == 6
            assert all(0 <= t < 64 for t in toks)
            with urllib.request.urlopen(
                url + "/v1/stats", timeout=30
            ) as r:
                stats = json.loads(r.read())
            assert stats["mesh"] == {"data": 1, "seq": 1, "model": 8}
        finally:
            for p in procs:
                p.kill()
                p.communicate()


class TestOplogHandshake:
    def test_stray_connector_rejected(self):
        """A port-scanner/prober connecting to the oplog port must not
        consume a follower slot or receive the op stream."""
        import socket as _socket
        import threading
        import time as _time

        import jax
        import jax.numpy as jnp

        from instaslice_tpu.models.lm import ModelConfig, TpuLM
        from instaslice_tpu.serving import ServingEngine
        from instaslice_tpu.serving.distributed import (
            DistributedEngine,
            run_follower,
        )

        cfg = ModelConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            dtype=jnp.float32, remat=False,
        )
        m = TpuLM(cfg)
        params = m.init(jax.random.key(0))
        driver_eng = ServingEngine(m, params, max_batch=2, max_len=64,
                                   prefill_len=8)
        follower_eng = ServingEngine(m, params, max_batch=2, max_len=64,
                                     prefill_len=8)
        port = free_port()
        stray_got = {}

        def stray():
            s = _socket.socket()
            deadline = _time.monotonic() + 30
            while True:
                try:
                    s.connect(("127.0.0.1", port))
                    break
                except OSError:
                    if _time.monotonic() > deadline:
                        return
                    _time.sleep(0.05)
            s.sendall(b"GET / HTTP/1.0\r\n\r\n")
            stray_got["data"] = s.recv(4096)   # b"" == closed on us
            s.close()

        t_stray = threading.Thread(target=stray, daemon=True)
        t_stray.start()

        def follower():
            _time.sleep(0.5)                  # let the stray go first
            run_follower(follower_eng, "127.0.0.1", port)

        t_follow = threading.Thread(target=follower, daemon=True)
        t_follow.start()
        deng = DistributedEngine(driver_eng, n_followers=1, port=port)
        deng.add_request([5, 9, 2, 7])
        deng.shutdown()
        t_follow.join(timeout=15)
        t_stray.join(timeout=15)
        assert not t_follow.is_alive()
        # the real follower replayed the op; the stray got nothing
        assert 0 in follower_eng.slots
        assert stray_got.get("data") == b""


class TestApiServerOverDistributedEngine:
    def test_scheduler_only_mutates_via_broadcast_ops(self):
        """ApiServer(DistributedEngine) with a same-process follower
        replica: after live HTTP traffic plus a broadcast eviction,
        the follower's replayed state must equal the driver's — any
        scheduler mutation that bypassed the broadcast surface would
        diverge the replicas (and, on real multi-host, deadlock)."""
        import json as _json
        import threading
        import time as _time
        import urllib.request

        import jax
        import jax.numpy as jnp

        from instaslice_tpu.models.lm import ModelConfig, TpuLM
        from instaslice_tpu.serving import ServingEngine
        from instaslice_tpu.serving.api_server import ApiServer
        from instaslice_tpu.serving.distributed import (
            DistributedEngine,
            run_follower,
        )

        cfg = ModelConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            dtype=jnp.float32, remat=False,
        )
        m = TpuLM(cfg)
        params = m.init(jax.random.key(0))
        driver_eng = ServingEngine(m, params, max_batch=2, max_len=64,
                                   prefill_len=8)
        follower_eng = ServingEngine(m, params, max_batch=2, max_len=64,
                                     prefill_len=8)
        port = free_port()
        follower = threading.Thread(
            target=run_follower,
            args=(follower_eng, "127.0.0.1", port),
            daemon=True,
        )
        follower.start()
        deng = DistributedEngine(driver_eng, n_followers=1, port=port)

        def post(url, payload, timeout=60):
            req = urllib.request.Request(
                f"{url}/v1/completions",
                data=_json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    return r.status, _json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, _json.loads(e.read().decode())

        with ApiServer(deng, request_timeout=20) as srv:
            code, out = post(srv.url, {"prompt": [5, 9, 2, 7],
                                       "max_tokens": 6})
            assert code == 200
            assert len(out["choices"][0]["token_ids"]) == 6
            code, _ = post(srv.url, {"prompt": [11, 3],
                                     "max_tokens": 4})
            assert code == 200
            # wait for the scheduler to go idle
            deadline = _time.monotonic() + 20
            while _time.monotonic() < deadline and driver_eng.slots:
                _time.sleep(0.05)
        # broadcast eviction path (what the scheduler's 503 sweep
        # calls): admit directly through the wrapper, then evict — the
        # follower must replay both
        rid = deng.add_request([9, 9])
        slot = next(s for s, r in driver_eng.slots.items()
                    if r.request_id == rid)
        deng.evict_slot(slot)
        deng.shutdown()
        follower.join(timeout=10)
        assert not follower.is_alive()
        # replicas agree on everything that feeds the compiled calls
        assert follower_eng.slots.keys() == driver_eng.slots.keys()
        for s in driver_eng.slots:
            assert (follower_eng.slots[s].generated
                    == driver_eng.slots[s].generated)
        assert (follower_eng.tokens_generated
                == driver_eng.tokens_generated)
