"""Pallas kernel tests (interpret mode on the CPU mesh — the same kernel
code lowers to Mosaic on real TPU; the driver's bench exercises that)."""

import jax
import jax.numpy as jnp
import pytest

from instaslice_tpu.ops.flash_attention import _xla_attention, flash_attention


def _qkv(B, S, H, hd, key=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(key), 3)
    return tuple(jax.random.normal(k, (B, S, H, hd), dtype) for k in ks)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_xla_reference(self, causal):
        q, k, v = _qkv(2, 256, 4, 64)
        out = flash_attention(q, k, v, causal=causal)
        ref = _xla_attention(q, k, v, causal)
        assert out.shape == ref.shape == (2, 256, 4, 64)
        assert float(jnp.max(jnp.abs(out - ref))) < 2e-5

    def test_multi_block_online_softmax(self):
        # several k-blocks per q-block exercises the running (m, l, acc)
        q, k, v = _qkv(1, 512, 2, 32, key=3)
        out = flash_attention(q, k, v, causal=True, block_q=128, block_k=64)
        ref = _xla_attention(q, k, v, True)
        assert float(jnp.max(jnp.abs(out - ref))) < 2e-5

    def test_untileable_shape_falls_back(self):
        # S=100 not divisible by any pow-2 block: must still be correct
        q, k, v = _qkv(2, 100, 2, 16, key=1)
        out = flash_attention(q, k, v, causal=True)
        ref = _xla_attention(q, k, v, True)
        assert float(jnp.max(jnp.abs(out - ref))) < 2e-5

    def test_bf16_inputs(self):
        q, k, v = (t.astype(jnp.bfloat16) for t in _qkv(1, 128, 2, 64))
        out = flash_attention(q, k, v, causal=True)
        ref = _xla_attention(q, k, v, True)
        assert out.dtype == jnp.bfloat16
        err = jnp.max(jnp.abs(out.astype(jnp.float32)
                              - ref.astype(jnp.float32)))
        assert float(err) < 0.05  # bf16 resolution

    @pytest.mark.parametrize("causal", [True, False])
    def test_grad_matches_xla_reference(self, causal):
        q, k, v = _qkv(1, 128, 2, 16, key=2)

        def loss(fn):
            return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

        g_flash = jax.grad(loss(
            lambda q, k, v: flash_attention(q, k, v, causal=causal)
        ), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss(
            lambda q, k, v: _xla_attention(q, k, v, causal)
        ), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            assert float(jnp.max(jnp.abs(a - b))) < 1e-4

    def test_grad_multi_block_uneven_blocks(self):
        # block_q != block_k exercises the dkv kernel's diagonal start
        # index and the dq kernel's partial-block masking together
        q, k, v = _qkv(1, 256, 2, 32, key=4)

        def loss(fn):
            return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

        g_flash = jax.grad(loss(
            lambda q, k, v: flash_attention(
                q, k, v, causal=True, block_q=64, block_k=32
            )
        ), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss(
            lambda q, k, v: _xla_attention(q, k, v, True)
        ), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            assert float(jnp.max(jnp.abs(a - b))) < 1e-4

    def test_grad_bf16(self):
        q, k, v = (t.astype(jnp.bfloat16) for t in _qkv(1, 128, 2, 32))

        def loss(fn):
            return lambda q, k, v: jnp.sum(
                fn(q, k, v).astype(jnp.float32) ** 2
            )

        g_flash = jax.grad(loss(
            lambda q, k, v: flash_attention(q, k, v, causal=True)
        ), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss(
            lambda q, k, v: _xla_attention(q, k, v, True)
        ), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            assert a.dtype == jnp.bfloat16
            err = jnp.max(jnp.abs(a.astype(jnp.float32)
                                  - b.astype(jnp.float32)))
            assert float(err) < 0.25  # bf16 grad resolution

    def test_causal_cropped_query_offset(self):
        # decode-style cross attention: q is the LAST S positions of a
        # kv_len sequence. The mask must be offset by kv_len - S — queries
        # aligned to the start would wrongly hide most keys.
        B, K, H, hd, S = 2, 64, 2, 16, 8
        qf, k, v = _qkv(B, K, H, hd, key=5)
        full = _xla_attention(qf, k, v, True)        # S == kv_len oracle
        out = flash_attention(qf[:, -S:], k, v, causal=True)
        assert float(jnp.max(jnp.abs(out - full[:, -S:]))) < 2e-5

    def test_model_flash_impl_matches_xla_impl(self):
        from instaslice_tpu.models.lm import ModelConfig, TpuLM

        kwargs = dict(
            vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            dtype=jnp.float32, remat=False,
        )
        toks = jax.random.randint(jax.random.key(0), (2, 64), 0, 64)
        m_xla = TpuLM(ModelConfig(attention_impl="xla", **kwargs))
        m_flash = TpuLM(ModelConfig(attention_impl="flash", **kwargs))
        params = m_xla.init(jax.random.key(1))
        a = m_xla.apply(params, toks)
        b = m_flash.apply(params, toks)
        assert float(jnp.max(jnp.abs(a - b))) < 1e-3


class TestBlockFitting:
    """_fit_block: degrade block size instead of abandoning the kernel
    (review finding: (256,512) defaults silently dropped S=384-style
    shapes to the O(S²)-HBM XLA path)."""

    def test_fit_block_halves_to_divisor(self):
        from instaslice_tpu.ops.flash_attention import _fit_block

        assert _fit_block(256, 384) == 128   # 384 = 3·128
        assert _fit_block(512, 384) == 384   # whole axis in one block
        assert _fit_block(256, 2048) == 256  # defaults untouched
        assert _fit_block(256, 100) == 100   # single whole-axis block
        assert _fit_block(256, 7) == 0       # nothing tiles → XLA

    def test_s384_stays_on_kernel_and_matches(self):
        q, k, v = _qkv(2, 384, 2, 32, key=3)
        out = flash_attention(q, k, v, causal=True)  # (256,512) prefs
        ref = _xla_attention(q, k, v, True)
        assert float(jnp.max(jnp.abs(out - ref))) < 2e-5
