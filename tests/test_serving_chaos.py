"""Serving-plane chaos tier: loadgen traffic through a live ApiServer
while a seeded FaultPlan injects chip failures (cache poison), dispatch
errors/delays, scheduler-round faults, and kube flakes into a
concurrently-churning control plane — plus a mid-run drain/undrain.

The contracts under test are the robustness story end to end:

- every HTTP request reaches a TERMINAL response (200/4xx/5xx) — zero
  hung requests (the loadgen "hung" outcome class stays 0);
- the metrics ledger reconciles: each request lands in EXACTLY one
  outcome counter, so the sum equals the requests sent;
- the engine recovers: once faults stop, the same server serves 200s;
- the fault-wrapped control plane converges (no wedged pods, no chip
  double-grants) despite injected API failures.

Seeded via CHAOS_SEED (printed on failure) like tests/test_chaos.py.
"""

import json
import os
import sys
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from instaslice_tpu.api.constants import (
    REASON_DRAIN_BEGIN,
    REASON_DRAIN_END,
    REASON_DRAINED,
    REASON_SHED,
)
from instaslice_tpu.faults import FaultPlan
from instaslice_tpu.obs.journal import get_journal, reset_journal
from instaslice_tpu.metrics.metrics import ServingMetrics
from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.serving import ServingEngine
from instaslice_tpu.serving import loadgen
from instaslice_tpu.serving.api_server import ApiServer

CHAOS_SEED = int(os.environ.get("CHAOS_SEED", "1234"))
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VOCAB = 64
OUTCOME_LABELS = ("ok", "error", "timeout", "rejected", "shed", "drained")


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(
        vocab_size=VOCAB, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


def post(url, payload, path="/v1/completions", method="POST", timeout=60):
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method=method,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode()), dict(e.headers)


def get(url, path, timeout=10):
    try:
        with urllib.request.urlopen(url + path, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def metrics_outcome_counts(metrics: ServingMetrics) -> dict:
    out = {}
    for label in OUTCOME_LABELS:
        v = metrics.registry.get_sample_value(
            "tpuslice_serve_requests_total", {"outcome": label}
        )
        if v:
            out[label] = int(v)
    return out


class TestServingChaos:
    def test_faults_everywhere_plus_midrun_drain(self, model):
        print(f"chaos params: CHAOS_SEED={CHAOS_SEED}")
        # fresh flight recorder: the journal ledger below reconciles
        # against THIS run's metrics, not whatever earlier tests emitted
        reset_journal()
        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8)
        # every serving site misbehaves, on a bounded budget (max_fires)
        # so the post-chaos recovery check is deterministic; the
        # at_calls entries guarantee the poison/recovery path runs at
        # EVERY seed (probability alone could whiff on a short run)
        plan = (
            FaultPlan(CHAOS_SEED)
            .site("engine.decode", probability=0.05,
                  kinds=("poison", "error", "delay"), max_fires=12,
                  at_calls={3, 9}, delay_s=0.02)
            .site("engine.prefill", probability=0.04,
                  kinds=("poison", "error"), max_fires=8)
            .site("scheduler.round", probability=0.005,
                  kinds=("error", "delay"), max_fires=10, delay_s=0.02)
        )
        # ... and so does the control plane's kube path, concurrently.
        # The plan starts with no sites and is ARMED after the cluster
        # is up: faults during __init__ hit the main thread (a real
        # process would crash-loop and restart), while faults against a
        # RUNNING cluster hit the reconcile loops — the case under test.
        cp_plan = FaultPlan(CHAOS_SEED + 1)
        from instaslice_tpu.sim import SimCluster

        metrics = ServingMetrics()
        sim = SimCluster(n_nodes=1, generation="v5e",
                         deletion_grace_seconds=0.1,
                         health_interval=0.1,
                         fault_plan=cp_plan).start()
        cp_plan.site("kube.request", probability=0.04,
                     kinds=("http-503", "conn-reset", "http-429"),
                     max_fires=60)
        cp_plan.site("kube.watch", probability=0.01,
                     kinds=("disconnect",), max_fires=20)
        cp_plan.site("device.reserve", probability=0.05,
                     kinds=("error",), max_fires=10)
        churn_stop = threading.Event()
        churned = []

        def churn():
            i = 0
            while not churn_stop.is_set():
                name = f"chaos-{i}"
                try:
                    sim.submit(name, "v5e-1x1")
                    churned.append(name)
                except Exception:
                    pass  # injected kube flake on the submit path
                if len(churned) >= 3 and i % 2:
                    victim = churned.pop(0)
                    try:
                        sim.delete_pod(victim)
                    except Exception:
                        pass
                i += 1
                churn_stop.wait(0.4)

        N_REQUESTS = 60
        try:
            with ApiServer(eng, block_size=4, metrics=metrics,
                           request_timeout=20, max_queue=10,
                           drain_budget=5.0, fault_plan=plan) as srv:
                # warm the compiled prefill/decode programs BEFORE the
                # clock starts: on a cold engine the first decode is a
                # multi-second jit compile, and a drain landing inside
                # it would evict the only admitted requests — testing
                # compile latency, not fault robustness. The warm-up
                # rides before the metrics snapshot below.
                for _ in range(3):  # a fault may fire mid-warm-up
                    code, out, _ = post(srv.url, {"prompt": [1, 2, 3],
                                                  "max_tokens": 2})
                    if code == 200:
                        break
                assert code == 200, out
                warm = metrics_outcome_counts(metrics)

                churner = threading.Thread(target=churn, daemon=True)
                churner.start()

                def mid_run_drain():
                    time.sleep(1.5)
                    code, body, _ = post(srv.url, {"budget": 0.5},
                                         path="/v1/drain")
                    assert code == 200 and body["draining"], body
                    code, _ = get(srv.url, "/readyz")
                    assert code == 503
                    time.sleep(1.5)
                    code, body, _ = post(srv.url, {}, path="/v1/drain",
                                         method="DELETE")
                    assert code == 200 and not body["draining"], body
                    code, _ = get(srv.url, "/readyz")
                    assert code == 200

                drainer = threading.Thread(target=mid_run_drain,
                                           daemon=True)
                drainer.start()
                report = loadgen.run(
                    srv.url, requests=N_REQUESTS, concurrency=8,
                    prompt_len=8, max_tokens=8, vocab=VOCAB,
                    stream=False, timeout=60, seed=CHAOS_SEED,
                )
                drainer.join(timeout=30)
                assert not drainer.is_alive(), "drain thread stuck"
                churn_stop.set()
                churner.join(timeout=10)

                print("loadgen:", json.dumps(report))
                print("faults:", json.dumps(plan.stats()))
                print("cp faults:", json.dumps(cp_plan.stats()))

                # 1. every request reached a terminal response
                assert report["outcomes"]["hung"] == 0, report
                assert sum(report["outcomes"].values()) == N_REQUESTS

                # 2. the metrics ledger reconciles: one outcome per
                # request, none double-counted, none lost (diffed
                # against the pre-run snapshot so the warm-up request
                # doesn't skew the ledger)
                counted = metrics_outcome_counts(metrics)
                print("metrics:", json.dumps(counted))
                delta = (sum(counted.values())
                         - sum(warm.values()))
                assert delta == N_REQUESTS, (warm, counted)

                # 3. faults actually fired (the tier tested something)
                assert sum(
                    s["fired"] for s in plan.stats().values()
                ) > 0, plan.stats()

                # 3b. the flight recorder reconciles with the metrics
                # ledger: one RequestShed journal event per shed outcome,
                # one RequestDrained per drained outcome — same
                # population, counted on two independent surfaces
                journal = get_journal()
                jcounts = journal.counts()
                print("journal:", json.dumps(jcounts))
                assert jcounts.get(REASON_SHED, 0) == \
                    counted.get("shed", 0) - warm.get("shed", 0), \
                    (jcounts, counted, warm)
                assert jcounts.get(REASON_DRAINED, 0) == \
                    counted.get("drained", 0) - warm.get("drained", 0), \
                    (jcounts, counted, warm)
                # exactly one drain cycle ran
                assert jcounts.get(REASON_DRAIN_BEGIN, 0) == 1, jcounts
                assert jcounts.get(REASON_DRAIN_END, 0) == 1, jcounts

                # 3c. under injected faults + churn, every allocation's
                # transition chain stays legal (stale-read tolerance:
                # set_status emits at decision time and a CR write can
                # lose the optimistic-concurrency race)
                sys.path.insert(0, os.path.join(REPO, "tools"))
                import validate_events

                chain_errors = validate_events.check_chains(
                    [e.to_dict() for e in journal.events()],
                    strict=False,
                )
                assert chain_errors == [], chain_errors

                # 3d. the journal is live-queryable on the serving plane
                code, out = get(
                    srv.url, f"/v1/debug/events?reason={REASON_DRAIN_BEGIN}"
                )
                assert code == 200, out
                assert [e["reason"] for e in out["events"]] == \
                    [REASON_DRAIN_BEGIN], out

                # 4. recovery: faults off, the SAME server serves 200s
                eng.fault_hook = None
                srv.scheduler.fault_hook = None
                for _ in range(3):
                    code, out, _ = post(srv.url, {
                        "prompt": [5, 9, 2, 7], "max_tokens": 4,
                    })
                    assert code == 200, out
                    assert len(out["choices"][0]["token_ids"]) == 4

                # 5. the fault-injected control plane didn't wedge:
                # chips never double-granted, pods settle
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    claimed = [
                        c for r in sim.backends["node-0"]
                        .list_reservations() for c in r.chip_ids
                    ]
                    assert len(claimed) == len(set(claimed)), claimed
                    phases = {p: sim.pod_phase(p) for p in churned}
                    if all(ph in ("Running", "Pending", "Gone")
                           for ph in phases.values()):
                        break
                    time.sleep(0.2)
                bad = {p: ph for p, ph in phases.items()
                       if ph not in ("Running", "Pending", "Gone")}
                assert not bad, f"pods wedged under kube faults: {bad}"
        finally:
            churn_stop.set()
            sim.stop()

    def test_drain_lifecycle_deterministic(self, model):
        """No faults: SIGTERM-equivalent drain semantics alone.
        readyz flips, in-flight finishes inside the budget, a queued
        request sheds 503, past-budget slots evict with 503, undrain
        restores service."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8)
        with ApiServer(eng, block_size=4, request_timeout=30) as srv:
            code, _ = get(srv.url, "/readyz")
            assert code == 200

            # occupy the slot with a long request
            results = {}

            def long_request():
                results["long"] = post(srv.url, {
                    "prompt": [1, 2, 3], "max_tokens": 48,
                })

            t = threading.Thread(target=long_request, daemon=True)
            t.start()
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and not eng.slots:
                time.sleep(0.01)
            assert eng.slots, "long request never admitted"

            # drain with a zero budget: the in-flight slot is evicted
            # with a clean 503, new admissions 503 immediately
            code, body, headers = post(srv.url, {"budget": 0.0},
                                       path="/v1/drain")
            assert code == 200, body
            code, _ = get(srv.url, "/readyz")
            assert code == 503
            code, out, headers = post(srv.url, {
                "prompt": [4, 5], "max_tokens": 4,
            })
            assert code == 503, out
            assert "Retry-After" in headers
            t.join(timeout=20)
            assert not t.is_alive(), "evicted request hung"
            code, out, _ = results["long"]
            assert code == 503, out
            assert srv.scheduler.drained.wait(10), "drain never quiesced"

            # undrain: service restored, same engine
            code, body, _ = post(srv.url, {}, path="/v1/drain",
                                 method="DELETE")
            assert code == 200 and not body["draining"]
            code, _ = get(srv.url, "/readyz")
            assert code == 200
            code, out, _ = post(srv.url, {
                "prompt": [5, 9, 2, 7], "max_tokens": 4,
            })
            assert code == 200, out

    def test_shutdown_latency(self, model):
        """Every serving/control loop paces on a stop event, never a
        bare time.sleep — so teardown returns within a small bound
        instead of waiting out somebody's nap. Guards the slicelint
        ``sleep-in-loop`` conversions at the behavioral level."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        srv = ApiServer(eng, block_size=4, request_timeout=30).start()
        code, out, _ = post(srv.url, {"prompt": [1, 2, 3],
                                      "max_tokens": 2})
        assert code == 200, out
        t0 = time.monotonic()
        srv.stop()
        dt_srv = time.monotonic() - t0
        assert dt_srv < 3.0, (
            f"ApiServer.stop() took {dt_srv:.2f}s — a loop is pacing "
            "on time.sleep instead of the stop event"
        )

        from instaslice_tpu.sim import SimCluster

        sim = SimCluster(n_nodes=1, generation="v5e",
                         deletion_grace_seconds=0.1,
                         health_interval=0.1).start()
        try:
            sim.submit("shutdown-latency-pod", "v5e-1x1")
            assert sim.wait_phase("shutdown-latency-pod", "Running",
                                  timeout=20)
        finally:
            t0 = time.monotonic()
            sim.stop()
            dt_sim = time.monotonic() - t0
        assert dt_sim < 3.0, (
            f"SimCluster.stop() took {dt_sim:.2f}s — a reconcile/agent "
            "loop is pacing on time.sleep instead of the stop event"
        )

    def test_bounded_queue_sheds_with_429(self, model):
        """Past the admission bound, requests get an immediate 429 +
        Retry-After instead of queueing into a timeout."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8)
        with ApiServer(eng, block_size=4, request_timeout=30,
                       max_queue=1) as srv:
            results = []
            lock = threading.Lock()

            def fire(max_tokens):
                r = post(srv.url, {"prompt": [1, 2, 3],
                                   "max_tokens": max_tokens})
                with lock:
                    results.append(r)

            # one decoding (occupies the slot), one parked head-of-line
            t1 = threading.Thread(target=fire, args=(48,), daemon=True)
            t1.start()
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and not eng.slots:
                time.sleep(0.01)
            t2 = threading.Thread(target=fire, args=(4,), daemon=True)
            t2.start()
            deadline = time.monotonic() + 10
            while (time.monotonic() < deadline
                   and srv.scheduler.queue.qsize() == 0
                   and srv.scheduler._head is None):
                time.sleep(0.01)
            # the bound is hit: this one must shed NOW
            code, out, headers = post(srv.url, {"prompt": [7, 8],
                                                "max_tokens": 4})
            assert code == 429, out
            assert "Retry-After" in headers
            t1.join(timeout=30)
            t2.join(timeout=30)
            codes = sorted(r[0] for r in results)
            assert codes == [200, 200], results

    def test_client_percentiles_reconcile_with_server_histograms(
        self, model
    ):
        """Satellite contract (docs/OBSERVABILITY.md): loadgen's
        client-side TTFT / per-token percentiles must reconcile with
        the server-side profiler histograms — same request population
        (counts match exactly) and consistent magnitudes (client-
        observed times sit at or above the server-measured ones, by no
        more than scheduling/delivery slack)."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8)
        metrics = ServingMetrics()
        with ApiServer(eng, block_size=4, metrics=metrics,
                       request_timeout=60) as srv:
            # warm the compiled programs, then snapshot the histograms
            # so the diff below covers exactly the loadgen population
            code, out, _ = post(srv.url, {"prompt": [1, 2, 3],
                                          "max_tokens": 2})
            assert code == 200, out

            def sample(name, labels=None):
                return metrics.registry.get_sample_value(
                    name, labels or {}
                ) or 0.0

            warm = {
                n: sample(n) for n in (
                    "tpuslice_serve_ttft_seconds_count",
                    "tpuslice_serve_ttft_seconds_sum",
                    "tpuslice_serve_tpot_seconds_count",
                    "tpuslice_serve_tpot_seconds_sum",
                    "tpuslice_serve_request_seconds_count",
                    "tpuslice_serve_request_seconds_sum",
                )
            }
            N = 24
            report = loadgen.run(
                srv.url, requests=N, concurrency=4, prompt_len=8,
                max_tokens=8, vocab=VOCAB, stream=True, timeout=60,
                seed=CHAOS_SEED,
            )
            print("loadgen:", json.dumps(report))
            assert report["outcomes"]["hung"] == 0, report
            assert report["ok"] == N, report

            # counts reconcile exactly: one TTFT / TPOT / latency
            # observation per successful request, none double-counted
            ttft_n = sample("tpuslice_serve_ttft_seconds_count") - \
                warm["tpuslice_serve_ttft_seconds_count"]
            tpot_n = sample("tpuslice_serve_tpot_seconds_count") - \
                warm["tpuslice_serve_tpot_seconds_count"]
            req_n = sample("tpuslice_serve_request_seconds_count") - \
                warm["tpuslice_serve_request_seconds_count"]
            assert ttft_n == N, (ttft_n, N)
            assert tpot_n == N, (tpot_n, N)
            assert req_n == N, (req_n, N)

            # magnitudes reconcile: the server measures queue-entry →
            # first sampled token; the client measures send → first
            # chunk RECEIVED — strictly later on the wall clock, by
            # delivery latency only (generous slack: one decode round
            # + HTTP overhead)
            ttft_mean = (
                sample("tpuslice_serve_ttft_seconds_sum")
                - warm["tpuslice_serve_ttft_seconds_sum"]
            ) / ttft_n
            assert ttft_mean <= report["ttft_mean"] + 0.25, (
                ttft_mean, report["ttft_mean"])
            assert report["ttft_mean"] <= ttft_mean + 2.0, (
                ttft_mean, report["ttft_mean"])

            tpot_mean = (
                sample("tpuslice_serve_tpot_seconds_sum")
                - warm["tpuslice_serve_tpot_seconds_sum"]
            ) / tpot_n
            assert tpot_mean >= 0.0
            # client TPOT includes delivery; same order of magnitude
            assert tpot_mean <= report["tpot_p99"] + 0.25, (
                tpot_mean, report)

            req_mean = (
                sample("tpuslice_serve_request_seconds_sum")
                - warm["tpuslice_serve_request_seconds_sum"]
            ) / req_n
            assert abs(req_mean - report["mean_latency"]) <= \
                0.5 + 0.5 * report["mean_latency"], (
                    req_mean, report["mean_latency"])

            # the per-round profiler populated alongside: step times
            # in both phases, occupancy/KV gauges exported. Prefill
            # observations count DISPATCH CHAINS, and batched
            # admission (r10) admits a whole burst through one — so
            # the floor is bursts, not requests
            assert sample("tpuslice_serve_step_seconds_count",
                          {"phase": "prefill"}) >= 1
            assert sample("tpuslice_serve_step_seconds_count",
                          {"phase": "decode"}) >= 1
            assert sample("tpuslice_serve_phase_seconds_total",
                          {"phase": "decode"}) > 0
            from instaslice_tpu.metrics.metrics import render

            text = render(metrics)
            assert "tpuslice_serve_batch_occupancy" in text
            assert "tpuslice_serve_kv_cache_utilization" in text

    def test_scheduler_survives_injected_round_faults(self, model):
        """Errors raised INSIDE the scheduler loop (not decode) never
        kill the serving thread."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        plan = FaultPlan(CHAOS_SEED).site(
            "scheduler.round", at_calls={1, 2, 3, 5, 8},
            kinds=("error",),
        )
        with ApiServer(eng, block_size=4, request_timeout=30,
                       fault_plan=plan) as srv:
            for _ in range(3):
                code, out, _ = post(srv.url, {
                    "prompt": [5, 9, 2, 7], "max_tokens": 4,
                })
                assert code == 200, out
            assert srv.scheduler.is_alive()
            assert plan.stats()["scheduler.round"]["fired"] >= 3


class TestPreemptLedgerChaos:
    def test_preempt_ledger_reconciles_under_faults(self, model):
        """Tenanted serving under fault injection: preemption/resume
        must keep the three ledgers aligned — scheduler counters,
        journal RequestPreempted/RequestResumed events, and the
        preemptions/resumes metrics — while every request still
        reaches a terminal response (the shed/preempt extension of the
        outcome-reconciliation contract)."""
        from instaslice_tpu.api.constants import (
            REASON_PREEMPTED,
            REASON_RESUMED,
        )

        print(f"chaos params: CHAOS_SEED={CHAOS_SEED}")
        reset_journal()
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, kv_block_size=8)
        plan = (
            FaultPlan(CHAOS_SEED)
            .site("engine.decode", probability=0.03,
                  kinds=("error", "delay"), max_fires=6, delay_s=0.02)
            .site("engine.prefill", probability=0.03,
                  kinds=("error",), max_fires=4)
        )
        metrics = ServingMetrics()
        N = 30
        with ApiServer(eng, block_size=4, metrics=metrics,
                       request_timeout=60, fault_plan=plan,
                       tenants=("gold:2:latency:1.0,"
                                "bronze:1:best-effort"),
                       preempt_margin=0.05) as srv:
            for _ in range(3):  # warm through possible injected faults
                code, out, _ = post(srv.url, {"prompt": [1, 2, 3],
                                              "max_tokens": 2})
                if code == 200:
                    break
            assert code == 200, out
            report = loadgen.run(
                srv.url, requests=N, concurrency=6, prompt_len=8,
                max_tokens=24, vocab=VOCAB, stream=False, timeout=60,
                seed=CHAOS_SEED, jitter=0.7,
                tenants="gold:2:latency:1.0,bronze:1:best-effort",
            )
            print("loadgen:", json.dumps(
                {k: report[k] for k in ("ok", "errors", "outcomes")}
            ))
            sched = srv.scheduler
            stats = sched.stats()
            print("sched:", json.dumps({
                k: stats[k] for k in ("preempted", "resumed",
                                      "parked_shed", "parked")
            }))
            # every request terminal, none hung
            assert report["outcomes"]["hung"] == 0, report
            assert sum(report["outcomes"].values()) == N

            # three-way ledger: scheduler counters == journal events
            # == engine totals; metrics agree when prometheus exists
            jc = get_journal().counts()
            assert jc.get(REASON_PREEMPTED, 0) == stats["preempted"]
            assert jc.get(REASON_RESUMED, 0) == stats["resumed"]
            assert eng.preempted_total == stats["preempted"]
            assert eng.resumed_total == stats["resumed"]
            if metrics.registry is not None:
                got = metrics.registry.get_sample_value(
                    "tpuslice_serve_preemptions_total"
                ) or 0.0
                assert int(got) == stats["preempted"]
                got = metrics.registry.get_sample_value(
                    "tpuslice_serve_resumes_total"
                ) or 0.0
                assert int(got) == stats["resumed"]
            # parked state fully accounted: every preemption either
            # resumed, was shed (clean 503), or is still parked (none,
            # since the run quiesced)
            assert stats["preempted"] == (
                stats["resumed"] + stats["parked_shed"]
                + stats["parked"]
            )
            # the kv block pool is fully reconciled after the run:
            # once everything is terminal, every used block belongs
            # to the radix prefix cache (completions legitimately
            # cache their KV — PR 11) and no request pins a tree path
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and (
                eng.slots or eng.parked
            ):
                time.sleep(0.05)
            assert eng.kv.used_blocks() == eng.radix.pool_blocks(), \
                eng.kv.stats()
            assert not eng._radix_locks
            # and the cache is fully reclaimable — dropping it leaves
            # a truly empty pool (the pre-radix invariant, restorable)
            eng.radix.reclaim(10 ** 9)
            assert eng.kv.used_blocks() == 0, eng.kv.stats()

            # recovery: faults off, the same server serves 200s
            eng.fault_hook = None
            srv.scheduler.fault_hook = None
            code, out, _ = post(srv.url, {"prompt": [5, 9, 2, 7],
                                          "max_tokens": 4})
            assert code == 200, out
