"""Defragmentation tier: fragmentation metrics (`topology/frag.py`),
the FragAwarePolicy, per-policy no-fit memo keying in the indexed
placement path, the NoCapacity fragmentation snapshot, the repacker
(`controller/defrag.py`) end to end in the sim — including the
mid-migration chaos rollback — and the describe-pod rendering of
migration epochs (docs/SCALING.md "Fragmentation-aware placement &
the repacker")."""

from __future__ import annotations

import os
import sys
import time

import pytest

from instaslice_tpu.api.constants import (
    REASON_NO_CAPACITY,
    REASON_REPACK_DONE,
    REASON_REPACK_MIGRATING,
    REPACK_OPTOUT_ANNOTATION,
)
from instaslice_tpu.obs.journal import get_journal, reset_journal
from instaslice_tpu.topology.frag import (
    frag_metrics,
    free_fit_boxes,
    snapshot_line,
    weighted_free_capacity,
)
from instaslice_tpu.topology.grid import NodeGrid, TorusGroup, get_generation
from instaslice_tpu.topology.placement import Box, Occupancy
from instaslice_tpu.topology.policy import get_policy, policy_names
from instaslice_tpu.topology.profiles import parse_profile_name

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import validate_events  # noqa: E402


@pytest.fixture(autouse=True)
def fresh_journal():
    reset_journal()
    yield
    reset_journal()


def two_host_group() -> TorusGroup:
    """Two v5e hosts side by side: one 4x4 torus, four 2x2 quads."""
    gen = get_generation("v5e")
    hb = gen.host_bounds
    hosts = {
        f"node-{i}": NodeGrid(gen, host_offset=(i * hb[0], 0, 0))
        for i in range(2)
    }
    return TorusGroup("g", gen, (4, 4, 1), hosts)


def carve_survivors(c, fillers):
    """Delete all but one (first-seen) filler per 2x2-aligned quad;
    returns the surviving pod names."""
    keep = {}
    doomed = []
    for _aid, a in sorted(c.allocations().items()):
        box = Box.from_key(a["box"])
        quad = (a.get("torusGroup", ""),
                box.anchor[0] // 2 * 2, box.anchor[1] // 2 * 2)
        name = a["pods"][0]["podName"]
        if name not in fillers:
            continue
        if quad in keep:
            doomed.append(name)
        else:
            keep[quad] = name
    for name in doomed:
        c.delete_pod(name)
    for name in doomed:
        assert c.wait_gone(name, timeout=30), name
    return sorted(keep.values())


# ========================================================== frag metrics


class TestFragMetrics:
    def test_empty_group_is_unfragmented(self):
        g = two_host_group()
        m = frag_metrics(g, Occupancy(g))
        assert m.free_chips == 16
        assert m.largest_free_box == "v5e-4x4"
        assert m.stranded_free_chips == 0
        assert m.fit_counts["v5e-2x2"] == 4

    def test_one_survivor_per_quad_blocks_2x2_and_strands(self):
        g = two_host_group()
        occ = Occupancy(g)
        for q in [(0, 0), (2, 0), (0, 2), (2, 2)]:
            occ.occupy(Box((q[0], q[1], 0), (1, 1, 1)))
        m = frag_metrics(g, occ)
        assert m.free_chips == 12
        assert m.fit_counts["v5e-2x2"] == 0
        # the fragmentation signature: plenty free, big boxes gone
        assert m.largest_free_chips < 12
        assert m.stranded_free_chips > 0
        assert 0 < m.stranded_fraction < 1
        line = snapshot_line(m)
        assert "12/16 chips free" in line
        assert "largest free box" in line
        assert "stranded" in line

    def test_snapshot_line_exhausted_and_fully_fragmented(self):
        g = two_host_group()
        occ = Occupancy(g)
        for c in [(x, y, 0) for x in range(4) for y in range(4)]:
            occ.occupy(Box(c, (1, 1, 1)))
        assert "exhausted" in snapshot_line(frag_metrics(g, occ))

    def test_weighted_capacity_prices_big_boxes_higher(self):
        g = two_host_group()
        boxes = free_fit_boxes(g, Occupancy(g))
        whole = weighted_free_capacity(boxes)
        # destroying a quad costs more weighted capacity than one cell
        quad_hit = weighted_free_capacity(
            boxes, excluding=Box((0, 0, 0), (2, 2, 1))
        )
        cell_hit = weighted_free_capacity(
            boxes, excluding=Box((0, 0, 0), (1, 1, 1))
        )
        assert whole > cell_hit > quad_hit


# ======================================================= frag-aware policy


class TestFragAwarePolicy:
    def test_registered_and_helpful_error(self):
        assert "frag-aware" in policy_names()
        assert get_policy("frag-aware").name == "frag-aware"
        with pytest.raises(KeyError) as ei:
            get_policy("no-such-policy")
        msg = str(ei.value)
        for name in policy_names():
            assert name in msg
        assert "TPUSLICE_PLACEMENT_POLICY" in msg

    def test_consolidates_into_broken_quad(self):
        """A 1x1 must land in the quad that already lost its 2x2 —
        preserving every other quad's 2x2 fit."""
        g = two_host_group()
        occ = Occupancy(g)
        occ.occupy(Box((1, 1, 0), (1, 1, 1)))  # breaks quad (0,0)
        pl = get_policy("frag-aware").choose(
            g, parse_profile_name("v5e-1x1"), occ
        )
        assert pl is not None
        ax, ay, _ = pl.box.anchor
        assert (ax // 2 * 2, ay // 2 * 2) == (0, 0), pl.box.key()

    def test_preserves_largest_box_for_2x1(self):
        g = two_host_group()
        occ = Occupancy(g)
        occ.occupy(Box((0, 0, 0), (1, 1, 1)))
        pl = get_policy("frag-aware").choose(
            g, parse_profile_name("v5e-2x1"), occ
        )
        assert pl is not None
        occ.occupy(pl.box)
        # after the placement, three full quads must survive
        assert frag_metrics(g, occ).fit_counts["v5e-2x2"] == 3


# ==================================== indexed placement + no-fit memo


class TestNoFitMemoPerPolicy:
    def _synced_sim(self):
        from instaslice_tpu.sim import SimCluster

        return SimCluster(
            n_nodes=1, generation="v5e", policy="best-fit",
            deletion_grace_seconds=0.2, health_interval=0,
        )

    def _wait_group(self, ctl, gid="node-0", timeout=10.0):
        from instaslice_tpu.controller.reconciler import INDEX_SLICE_GROUP

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if ctl._cache_ready() and any(
                m.status.processed
                for m in ctl._slices_inf.by_index(
                    INDEX_SLICE_GROUP, gid, transformed=True
                )
            ):
                return
            time.sleep(0.02)  # slicelint: disable=sleep-in-loop
        raise AssertionError("informer never served the node CR")

    def test_policies_exercised_and_memo_keyed_per_policy(self):
        with self._synced_sim() as c:
            ctl = c.controller
            self._wait_group(ctl)
            fits = parse_profile_name("v5e-2x2")
            too_big = parse_profile_name("v5e-4x4")  # host is 2x4

            # BestFit through the indexed path: places
            with ctl._placement_lock:
                p1 = ctl._place_indexed(fits, frozenset())
            assert p1 is not None

            # no-fit memo lands keyed by (gid, profile, policy name)
            with ctl._placement_lock:
                assert ctl._place_indexed(too_big, frozenset()) is None
            assert ("node-0", "v5e-4x4", "best-fit") in ctl._no_fit

            # swap to PackedFit: the stale best-fit memo must NOT be
            # consulted — _try_group runs again under the new key
            calls = []
            orig = ctl._try_group

            def spy(*a, **kw):
                calls.append(1)
                return orig(*a, **kw)

            ctl._try_group = spy
            ctl.policy = get_policy("packed-fit")
            with ctl._placement_lock:
                assert ctl._place_indexed(too_big, frozenset()) is None
            assert calls, "policy swap did not invalidate the no-fit memo"
            assert ("node-0", "v5e-4x4", "packed-fit") in ctl._no_fit
            assert ("node-0", "v5e-4x4", "best-fit") in ctl._no_fit

            # and with an unchanged group + same policy, the memo DOES
            # short-circuit (no _try_group call)
            calls.clear()
            with ctl._placement_lock:
                assert ctl._place_indexed(too_big, frozenset()) is None
            assert not calls

            # PackedFit through the indexed path: corner placement
            with ctl._placement_lock:
                p2 = ctl._place_indexed(fits, frozenset())
            assert p2 is not None
            assert p2.box.anchor == (0, 0, 0)


# =========================================== NoCapacity frag snapshot


class TestNoCapacityFragSnapshot:
    def test_event_message_names_largest_free_box(self):
        from instaslice_tpu.sim import SimCluster

        with SimCluster(
            n_nodes=1, generation="v5e", policy="first-fit",
            deletion_grace_seconds=0.2, health_interval=0,
        ) as c:
            fillers = [f"f-{i}" for i in range(8)]
            for n in fillers:
                c.submit(n, profile="v5e-1x1")
            for n in fillers:
                assert c.wait_phase(n, "Running", timeout=30), n
            # carve: free 6 of 8 chips but keep both 2x2 areas broken
            survivors = carve_survivors(c, fillers)
            assert len(survivors) == 2
            # wait for the teardowns to reach the CONTROLLER'S OWN
            # VIEW (informer cache), not just the CR store: the
            # NoCapacity snapshot — emitted once per wait — computes
            # occupancy from the cache, and submitting while it still
            # holds stale allocations races a "1/8 chips free" message
            # into the one event this test reads
            from instaslice_tpu.controller.reconciler import (
                INDEX_SLICE_GROUP,
            )

            def informer_occupied():
                allocs = {}
                for ts in c.controller._slices_inf.by_index(
                    INDEX_SLICE_GROUP, "node-0", transformed=True
                ):
                    for aid, a in ts.spec.allocations.items():
                        if a.status.value != "deleted":
                            allocs[aid] = a
                return sum(
                    Box.from_key(a.box).chip_count
                    for a in allocs.values()
                )

            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                occupied = informer_occupied()
                if occupied == 2:
                    break
                time.sleep(0.02)  # slicelint: disable=sleep-in-loop
            assert occupied == 2, occupied
            c.submit("blocked", profile="v5e-2x2")
            deadline = time.monotonic() + 10
            evs = []
            while time.monotonic() < deadline and not evs:
                evs = get_journal().events(reason=REASON_NO_CAPACITY)
                time.sleep(0.02)  # slicelint: disable=sleep-in-loop
            assert evs, "NoCapacity never emitted"
            msg = evs[0].message
            # the snapshot's exact chip count races the informer's
            # application of the final teardown events (pre-existing
            # flake: the once-per-wait event can capture 5/8 or 1/8 on
            # a loaded box) — the CONTRACT under test is that the
            # message carries a per-group fragmentation snapshot, not
            # which reconcile tick it sampled
            assert "/8 chips free" in msg, msg
            assert "largest free box" in msg, msg


# ================================================================ repacker


class TestRepacker:
    def _fragmented_sim(self, **kw):
        from instaslice_tpu.sim import SimCluster

        defaults = dict(
            n_nodes=2, generation="v5e", nodes_per_group=2,
            policy="frag-aware", repack=True, repack_interval=0.1,
            repack_cooldown=0.4, deletion_grace_seconds=0.2,
            health_interval=0,
        )
        defaults.update(kw)
        return SimCluster(**defaults)

    def _fill_and_carve(self, c, annotations=None):
        fillers = [f"fill-{i}" for i in range(16)]
        for n in fillers:
            c.submit(n, profile="v5e-1x1", annotations=annotations)
        for n in fillers:
            assert c.wait_phase(n, "Running", timeout=30), n
        return carve_survivors(c, set(fillers))

    def test_stranded_2x2_recovered_by_migration(self):
        with self._fragmented_sim() as c:
            survivors = self._fill_and_carve(c)
            assert len(survivors) == 4  # one per quad: every 2x2 blocked
            c.submit("big-0", profile="v5e-2x2")
            c.submit("big-1", profile="v5e-2x2")
            assert c.wait_phase("big-0", "Running", timeout=30)
            assert c.wait_phase("big-1", "Running", timeout=30)
            assert c.repacker.migrations_done >= 2
            # survivors are still Running (migrated, not evicted)
            for n in survivors:
                assert c.pod_phase(n) == "Running", n
            # no double allocation anywhere
            boxes = [
                Box.from_key(a["box"])
                for a in c.allocations().values()
                if a["status"] != "deleted"
            ]
            for i, a in enumerate(boxes):
                for b in boxes[i + 1:]:
                    assert not a.overlaps(b), (a.key(), b.key())
            # every epoch — original grants AND migration epochs — is a
            # legal chain under the strict events-check validator
            errs = validate_events.check_chains(
                [e.to_dict() for e in get_journal().events()],
                strict=True,
            )
            assert errs == []
            done = get_journal().events(reason=REASON_REPACK_DONE)
            assert done
            # migration epochs are trace-correlated: the RepackDone
            # trace id matches the fresh epoch's transition events
            tid = done[0].trace_id
            assert tid
            assert any(
                e.trace_id == tid
                for e in get_journal().events(reason="SliceUngated")
            )

    def test_optout_annotation_pins_slices(self):
        with self._fragmented_sim() as c:
            survivors = self._fill_and_carve(
                c, annotations={REPACK_OPTOUT_ANNOTATION: "true"}
            )
            assert len(survivors) == 4
            c.submit("big-0", profile="v5e-2x2")
            # give the repacker ~15 ticks: it must refuse to move
            # opted-out slices, so the pod stays Pending
            assert not c.wait_phase("big-0", "Running", timeout=1.5)
            assert c.repacker.migrations_done == 0
            assert c.repacker.plans == 0
            for n in survivors:
                assert c.pod_phase(n) == "Running", n

    def test_chaos_realize_failure_mid_migration_rolls_back(self):
        with self._fragmented_sim() as c:
            self._fill_and_carve(c)
            # every node's NEXT chip reservation fails: the first
            # migration's destination realize dies mid-flight
            for node in list(c.backends):
                c.backends[node].inject_failures("reserve", 1)
            c.submit("big-0", profile="v5e-2x2")
            assert c.wait_phase("big-0", "Running", timeout=45)
            # rollback happened (FAILED epoch) and nothing leaked:
            # device reservations match the CRs' prepared records
            for node, backend in c.backends.items():
                ts = c.kube.get("TpuSlice", c.namespace, node)
                prepared = set(ts["spec"].get("prepared", {}))
                reserved = {
                    r.slice_uuid for r in backend.list_reservations()
                }
                assert prepared == reserved, (node, prepared, reserved)
            boxes = [
                Box.from_key(a["box"])
                for a in c.allocations().values()
                if a["status"] != "deleted"
            ]
            for i, a in enumerate(boxes):
                for b in boxes[i + 1:]:
                    assert not a.overlaps(b), (a.key(), b.key())
            errs = validate_events.check_chains(
                [e.to_dict() for e in get_journal().events()],
                strict=True,
            )
            assert errs == []


# ===================================================== describe rendering


class TestDescribeMigration:
    def test_migrated_pod_timeline_shows_repack_chain(self):
        from instaslice_tpu.cli.tpuslicectl import (
            describe_pod,
            render_describe,
        )
        from instaslice_tpu.sim import SimCluster

        with SimCluster(
            n_nodes=2, generation="v5e", nodes_per_group=2,
            policy="frag-aware", repack=True, repack_interval=0.1,
            repack_cooldown=0.4, deletion_grace_seconds=0.2,
            health_interval=0,
        ) as c:
            fillers = [f"fill-{i}" for i in range(16)]
            for n in fillers:
                c.submit(n, profile="v5e-1x1")
            for n in fillers:
                assert c.wait_phase(n, "Running", timeout=30), n
            carve_survivors(c, set(fillers))
            c.submit("big-0", profile="v5e-2x2")
            assert c.wait_phase("big-0", "Running", timeout=30)
            moved = {
                e.object_ref.rpartition("/")[2]
                for e in get_journal().events(
                    reason=REASON_REPACK_MIGRATING
                )
            }
            assert moved
            name = sorted(moved)[0]
            text = render_describe(describe_pod(c.kube, name))
            # the repack reason chain is visible and marked distinctly
            assert "RepackMigrating" in text
            assert "RepackDone" in text
            assert "⟳" in text
            # the migration epoch's creating transition is stamped
            assert "(repack)" in text


# ==================================================== runtime selection


class TestPolicyRuntimeSelection:
    @staticmethod
    def _detach(runner):
        from instaslice_tpu.obs import journal as obs_journal

        obs_journal.detach_metrics(runner._event_metrics)

    def test_env_var_selects_policy_on_runner(self, monkeypatch):
        from instaslice_tpu.controller.runner import ControllerRunner
        from instaslice_tpu.kube import FakeKube

        monkeypatch.setenv("TPUSLICE_PLACEMENT_POLICY", "frag-aware")
        runner = ControllerRunner(FakeKube())
        self._detach(runner)
        assert runner.controller.policy.name == "frag-aware"

    def test_explicit_policy_beats_env(self, monkeypatch):
        from instaslice_tpu.controller.runner import ControllerRunner
        from instaslice_tpu.kube import FakeKube

        monkeypatch.setenv("TPUSLICE_PLACEMENT_POLICY", "frag-aware")
        runner = ControllerRunner(FakeKube(), policy="packed-fit")
        self._detach(runner)
        assert runner.controller.policy.name == "packed-fit"

    def test_unknown_env_policy_raises_with_catalog(self, monkeypatch):
        from instaslice_tpu.controller.runner import ControllerRunner
        from instaslice_tpu.kube import FakeKube

        monkeypatch.setenv("TPUSLICE_PLACEMENT_POLICY", "bogus")
        with pytest.raises(KeyError) as ei:
            ControllerRunner(FakeKube())
        assert "frag-aware" in str(ei.value)

    def test_controller_main_flags(self):
        from instaslice_tpu.cli.controller_main import build_parser

        args = build_parser().parse_args(
            ["--repack", "--repack-interval", "2",
             "--policy", "frag-aware"]
        )
        assert args.repack
        assert args.repack_interval == 2.0
        assert args.policy == "frag-aware"
        # default: policy defers to env resolution in the runner
        assert build_parser().parse_args([]).policy is None


# ====================================================== proactive repack


class TestProactiveRepack:
    """ROADMAP item 1 headroom: the repacker also plans when a group's
    stranded-capacity fraction exceeds TPUSLICE_REPACK_FRAG_THRESHOLD —
    no starved pod required."""

    def _sim(self, **kw):
        from instaslice_tpu.sim import SimCluster

        defaults = dict(
            n_nodes=2, generation="v5e", nodes_per_group=2,
            policy="frag-aware", repack=True, repack_interval=0.1,
            repack_cooldown=0.4, deletion_grace_seconds=0.2,
            health_interval=0,
        )
        defaults.update(kw)
        return SimCluster(**defaults)

    def _fragment_unblocked(self, c):
        """Free quad (0,0) entirely; keep ONE survivor in each other
        quad: 13/16 chips free, 2x2 fits exactly once, every larger
        box blocked — stranded capacity with NO pending pod."""
        fillers = [f"fill-{i}" for i in range(16)]
        for n in fillers:
            c.submit(n, profile="v5e-1x1")
        for n in fillers:
            assert c.wait_phase(n, "Running", timeout=30), n
        pod_quad = {}
        for a in c.allocations().values():
            if a.get("status") == "deleted":
                continue
            box = Box.from_key(a["box"])
            quad = (box.anchor[0] // 2 * 2, box.anchor[1] // 2 * 2)
            for p in a.get("pods", []):
                pod_quad[p["podName"]] = quad
        by_quad = {}
        for n in fillers:
            by_quad.setdefault(pod_quad[n], []).append(n)
        doomed = list(by_quad.pop((0, 0)))          # whole quad free
        for quad, names in sorted(by_quad.items()):
            doomed.extend(sorted(names)[1:])        # one survivor each
        for n in doomed:
            c.delete_pod(n)
        for n in doomed:
            assert c.wait_gone(n, timeout=30), n
        return [sorted(v)[0] for v in by_quad.values()]

    def test_threshold_triggers_consolidation_without_pending_pod(self):
        with self._sim(repack_frag_threshold=0.3) as c:
            survivors = self._fragment_unblocked(c)
            # no pod is starving — any plan from here is proactive
            assert not c.controller.pending_requests()
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and \
                    c.repacker.migrations_done < 1:
                time.sleep(0.05)  # slicelint: disable=sleep-in-loop
            assert c.repacker.proactive_plans >= 1
            assert c.repacker.migrations_done >= 1
            from instaslice_tpu.api.constants import (
                REASON_REPACK_PLANNED,
            )

            planned = get_journal().events(reason=REASON_REPACK_PLANNED)
            assert any(
                e.object_ref.startswith("group/") for e in planned
            ), [e.object_ref for e in planned]
            assert any("proactive" in e.message for e in planned)
            # the consolidation is real: a 2x4 grants promptly now
            c.submit("big", profile="v5e-2x4")
            assert c.wait_phase("big", "Running", timeout=20)
            for n in survivors:
                assert c.pod_phase(n) == "Running", n
            errs = validate_events.check_chains(
                [e.to_dict() for e in get_journal().events()],
                strict=True,
            )
            assert errs == []

    def test_threshold_off_by_default_stays_reactive_only(self):
        from instaslice_tpu.controller.defrag import Repacker

        r = Repacker(controller=None)
        assert r.frag_threshold == 0.0
        with pytest.raises(ValueError, match="frag_threshold"):
            Repacker(controller=None, frag_threshold=1.5)

    def test_env_var_enables(self, monkeypatch):
        from instaslice_tpu.controller.defrag import Repacker

        monkeypatch.setenv("TPUSLICE_REPACK_FRAG_THRESHOLD", "0.25")
        r = Repacker(controller=None)
        assert r.frag_threshold == 0.25

    def test_controller_main_flag(self):
        from instaslice_tpu.cli.controller_main import build_parser

        args = build_parser().parse_args(
            ["--repack", "--repack-frag-threshold", "0.4"]
        )
        assert args.repack_frag_threshold == 0.4

    def test_record_vanishing_mid_migration_finishes_failed(self):
        """Regression for the `_advance` record-vanished path: a pod
        force-deleted mid-migration erases the allocation under the
        repacker — the migration must finish failed (journaled, slot
        and destination reservation released), never spin or re-grant
        a dead pod."""
        from instaslice_tpu.api import PodRef
        from instaslice_tpu.api.constants import REASON_REPACK_FAILED
        from instaslice_tpu.controller.defrag import Migration
        from instaslice_tpu.topology.placement import Box

        with self._sim() as c:
            c.submit("seed-pod", profile="v5e-1x1")
            assert c.wait_phase("seed-pod", "Running", timeout=30)
            rep = c.repacker
            rep.stop()  # drive ticks by hand
            mig = Migration(
                alloc_id="ghost-alloc", group_id="sim-torus-0",
                profile="v5e-1x1", old_box="0,0,0+1x1x1",
                dest_box="1,0,0+1x1x1", target_box="0,0,0+2x2x1",
                pending_profile="v5e-2x2",
                pods=[PodRef(pod_uuid="uid-gone", pod_name="gone",
                             namespace="default")],
                trace_id="t-vanish", started=time.monotonic(),
                phase="realizing",
            )
            rep._active[mig.alloc_id] = mig
            with c.controller._placement_lock:
                c.controller._inflight[mig.alloc_id] = (
                    Box.from_key(mig.dest_box), frozenset({"node-0"}),
                    mig.group_id,
                )
            failed_before = rep.migrations_failed
            rep.run_once()
            assert rep.migrations_failed == failed_before + 1
            assert mig.alloc_id not in rep._active
            with c.controller._placement_lock:
                assert mig.alloc_id not in c.controller._inflight
            evs = get_journal().events(reason=REASON_REPACK_FAILED)
            assert any("vanished" in e.message for e in evs), (
                [e.message for e in evs]
            )
            # the unrelated granted pod is untouched
            assert c.pod_phase("seed-pod") == "Running"
