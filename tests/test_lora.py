"""LoRA adapter fine-tuning (``models/lora.py``): zero-init equivalence,
adapter-only training, sharded merge under tp, and the QLoRA path over
an int8 base. Runs on the virtual 8-device CPU mesh from conftest.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.models.lora import (
    LoraConfig,
    init_lora,
    lora_specs,
    make_lora_train_step,
    merge_lora,
)
from instaslice_tpu.models.quant import quantize_params
from instaslice_tpu.models.train import loss_fn


def tiny(**kw):
    return ModelConfig(
        vocab_size=128, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        dtype=jnp.float32, remat=False, **kw,
    )


def mesh2():
    devs = jax.devices()[:2]
    return Mesh(np.array(devs).reshape(1, 1, 2), ("data", "seq", "model"))


class TestLoraInit:
    def test_zero_b_merge_is_identity(self):
        """B = 0 ⇒ merged weights equal the base exactly — a fresh LoRA
        model IS the base model."""
        cfg = tiny()
        lcfg = LoraConfig(rank=4)
        params = TpuLM(cfg).init(jax.random.key(0))
        lora = init_lora(jax.random.key(1), cfg, lcfg)
        merged = merge_lora(params, lora, cfg, lcfg)
        for t in lcfg.targets:
            np.testing.assert_array_equal(
                np.asarray(merged["blocks"][t]),
                np.asarray(params["blocks"][t]),
            )
        # untargeted leaves are the same objects, not copies
        assert merged["blocks"]["wo"] is params["blocks"]["wo"]

    def test_bad_target_rejected(self):
        with pytest.raises(ValueError, match="unsupported"):
            LoraConfig(targets=("router",))
        with pytest.raises(ValueError, match="rank"):
            LoraConfig(rank=0)

    def test_moe_model_rejects_mlp_targets(self):
        cfg = tiny(n_experts=4)
        with pytest.raises(ValueError, match="not adaptable"):
            init_lora(jax.random.key(0), cfg,
                      LoraConfig(targets=("w_in",)))
        # attention targets remain fine on MoE models
        init_lora(jax.random.key(0), cfg, LoraConfig(targets=("wq",)))

    def test_b_spec_follows_base_output_axis(self):
        cfg = tiny()
        specs = lora_specs(cfg, LoraConfig(targets=("wq", "wo", "w_in")))
        assert specs["blocks"]["wq"]["b"] == P(None, None, "model")
        assert specs["blocks"]["w_in"]["b"] == P(None, None, "model")
        # wo's base spec is P("model", None): output dim unsharded
        assert specs["blocks"]["wo"]["b"] == P(None, None, None)


class TestLoraTrain:
    def test_first_loss_is_base_loss_then_decreases(self):
        cfg = tiny()
        lcfg = LoraConfig(rank=4)
        model = TpuLM(cfg)
        params = model.init(jax.random.key(0))
        mesh = mesh2()
        toks = jax.random.randint(jax.random.key(1), (2, 32), 0, 128)

        base_loss = float(loss_fn(model, params, toks))
        init_fn, step_fn = make_lora_train_step(
            model, mesh, params, lcfg, learning_rate=3e-3,
        )
        state = init_fn(jax.random.key(2))
        state, first = step_fn(state, toks)
        # the step's loss is computed BEFORE the update, with B=0
        np.testing.assert_allclose(float(first), base_loss, rtol=1e-5)
        for _ in range(5):
            state, loss = step_fn(state, toks)
        assert float(loss) < base_loss

    def test_only_adapters_train(self):
        """The train state holds adapters only — and after steps, B has
        actually moved off zero (gradients reach it through the
        merge)."""
        cfg = tiny()
        lcfg = LoraConfig(rank=4)
        model = TpuLM(cfg)
        params = model.init(jax.random.key(0))
        init_fn, step_fn = make_lora_train_step(
            model, mesh2(), params, lcfg, learning_rate=3e-3,
        )
        state = init_fn(jax.random.key(2))
        leaves = jax.tree.leaves(state.params)
        n_adapter = sum(l.size for l in leaves)
        n_base = sum(l.size for l in jax.tree.leaves(params))
        assert n_adapter < n_base / 5      # the PEFT point
        toks = jax.random.randint(jax.random.key(1), (2, 32), 0, 128)
        state, _ = step_fn(state, toks)
        state, _ = step_fn(state, toks)
        b = state.params["blocks"]["wq"]["b"]
        assert float(jnp.abs(b).max()) > 0.0

    def test_qlora_int8_base(self):
        """QuantizedTensor base leaves dequantize inside the merge: the
        int8 base trains adapters with finite decreasing loss."""
        cfg = tiny()
        lcfg = LoraConfig(rank=4)
        model = TpuLM(cfg)
        qparams = quantize_params(model.init(jax.random.key(0)))
        init_fn, step_fn = make_lora_train_step(
            model, mesh2(), qparams, lcfg, learning_rate=3e-3,
        )
        state = init_fn(jax.random.key(2))
        toks = jax.random.randint(jax.random.key(1), (2, 32), 0, 128)
        state, first = step_fn(state, toks)
        for _ in range(5):
            state, loss = step_fn(state, toks)
        assert np.isfinite(float(loss))
        assert float(loss) < float(first)

    def test_merged_adapter_serves_like_plain_params(self):
        """merge_lora output is a plain params tree: the unmodified
        forward accepts it — the single-adapter serving path."""
        cfg = tiny()
        lcfg = LoraConfig(rank=4)
        model = TpuLM(cfg)
        params = model.init(jax.random.key(0))
        lora = init_lora(jax.random.key(1), cfg, lcfg)
        # make the adapter nonzero so the test is not the identity case
        lora["blocks"]["wq"]["b"] = (
            jnp.ones_like(lora["blocks"]["wq"]["b"]) * 0.01
        )
        merged = merge_lora(params, lora, cfg, lcfg)
        toks = jax.random.randint(jax.random.key(2), (2, 16), 0, 128)
        out = model.apply(merged, toks)
        base = model.apply(params, toks)
        assert bool(jnp.isfinite(out).all())
        assert float(jnp.abs(out - base).max()) > 0.0


class TestLoraServing:
    def test_serve_with_merged_adapter(self, tmp_path):
        """tpuslice-serve --lora: a trained adapter checkpoint merges
        into the weights at startup (rank/targets read from the tree),
        and the engine's weights provably differ from the base by the
        adapter delta."""
        from instaslice_tpu.models.checkpoint import TrainCheckpointer
        from instaslice_tpu.serving.api_server import (
            build_engine,
            build_parser,
        )

        cfg = ModelConfig(
            vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.bfloat16, remat=False,
        )
        model = TpuLM(cfg)
        mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1),
                    ("data", "seq", "model"))
        lcfg = LoraConfig(rank=4)
        # the serving base is the DEFAULT init (seed 0) — what
        # build_engine materializes without --checkpoint
        base = model.init(jax.random.key(0))
        init_fn, step_fn = make_lora_train_step(
            model, mesh, base, lcfg, learning_rate=1e-2,
        )
        state = init_fn(jax.random.key(2))
        toks = jax.random.randint(jax.random.key(1), (2, 16), 0, 64)
        for _ in range(3):
            state, _ = step_fn(state, toks)
        with TrainCheckpointer(str(tmp_path)) as ckpt:
            assert ckpt.save(state)

        cfg_args = ["--d-model", "32", "--n-heads", "2", "--n-layers",
                    "2", "--d-ff", "64", "--vocab-size", "64",
                    "--max-len", "64", "--prefill-len", "8"]
        eng = build_engine(build_parser().parse_args(
            cfg_args + ["--lora", str(tmp_path)]
        ))
        want = merge_lora(base, state.params, cfg, lcfg)
        got = jnp.asarray(eng.params["blocks"]["wq"], jnp.float32)
        np.testing.assert_allclose(
            got, np.asarray(want["blocks"]["wq"], np.float32),
            rtol=1e-3,
        )
        # and it actually serves
        rid = eng.add_request([3, 1, 4])
        assert len(eng.decode_block(4)[rid]) == 4

    def test_serve_rejects_non_adapter_checkpoint(self, tmp_path):
        """--lora pointed at a FULL model checkpoint must refuse, not
        merge garbage."""
        from instaslice_tpu.models.checkpoint import TrainCheckpointer
        from instaslice_tpu.models.train import make_train_step
        from instaslice_tpu.serving.api_server import (
            build_engine,
            build_parser,
        )

        cfg = ModelConfig(
            vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.bfloat16, remat=False,
        )
        mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1),
                    ("data", "seq", "model"))
        init_fn, _ = make_train_step(TpuLM(cfg), mesh)
        with TrainCheckpointer(str(tmp_path)) as ckpt:
            assert ckpt.save(init_fn(jax.random.key(0)))
        cfg_args = ["--d-model", "32", "--n-heads", "2", "--n-layers",
                    "2", "--d-ff", "64", "--vocab-size", "64",
                    "--max-len", "64", "--prefill-len", "8"]
        with pytest.raises(SystemExit, match="adapter"):
            build_engine(build_parser().parse_args(
                cfg_args + ["--lora", str(tmp_path)]
            ))


class TestMultiLoraServing:
    def _mk(self, **kw):
        cfg = ModelConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, remat=False,
        )
        return cfg, TpuLM(cfg)

    def _adapter(self, cfg, key, scale=0.05):
        lcfg = LoraConfig(rank=4)
        ad = init_lora(jax.random.key(key), cfg, lcfg)
        for t in lcfg.targets:
            ad["blocks"][t]["b"] = (
                jax.random.normal(jax.random.key(key + 50),
                                  ad["blocks"][t]["b"].shape) * scale
            )
        return lcfg, ad

    def test_batched_adapters_match_per_adapter_merged_engines(self):
        """THE multi-LoRA contract: three requests on three adapters
        (base, ad1, ad2) decode in ONE batched engine, and each stream
        is token-identical to a dedicated engine serving that adapter
        merged into the weights."""
        from instaslice_tpu.serving import ServingEngine

        cfg, model = self._mk()
        params = model.init(jax.random.key(0))
        lcfg1, ad1 = self._adapter(cfg, 1, scale=0.4)
        lcfg2, ad2 = self._adapter(cfg, 2, scale=1.0)
        prompt = [5, 9, 3, 7]

        eng = ServingEngine(model, params, max_batch=4, max_len=32,
                            prefill_len=8,
                            lora_adapters=[ad1, ad2])
        rids = {
            a: eng.add_request(prompt, adapter=a) for a in (0, 1, 2)
        }
        got = eng.decode_block(6)

        for a, (lc, ad) in ((0, (None, None)), (1, (lcfg1, ad1)),
                            (2, (lcfg2, ad2))):
            p = params if ad is None else merge_lora(params, ad, cfg, lc)
            ref = ServingEngine(model, p, max_batch=4, max_len=32,
                                prefill_len=8)
            rr = ref.add_request(prompt)
            want = ref.decode_block(6)[rr]
            assert got[rids[a]] == want, (
                f"adapter {a}: batched {got[rids[a]]} != merged {want}"
            )
        # distinct adapters must actually produce distinct streams
        # (otherwise the test proves nothing)
        assert len({tuple(v) for v in got.values()}) >= 2

    def test_adapter_out_of_range_rejected(self):
        from instaslice_tpu.serving import ServingEngine

        cfg, model = self._mk()
        _, ad = self._adapter(cfg, 1)
        eng = ServingEngine(model, model.init(jax.random.key(0)),
                            max_batch=2, max_len=32, prefill_len=8,
                            lora_adapters=[ad])
        with pytest.raises(ValueError, match="out of range"):
            eng.add_request([1, 2], adapter=2)
        # no adapters configured: only 0 is legal
        eng2 = ServingEngine(model, model.init(jax.random.key(0)),
                             max_batch=2, max_len=32, prefill_len=8)
        with pytest.raises(ValueError, match="out of range"):
            eng2.add_request([1, 2], adapter=1)

    def test_lora_plus_spec_decode_rejected(self):
        from instaslice_tpu.serving import ServingEngine

        cfg, model = self._mk()
        _, ad = self._adapter(cfg, 1)
        with pytest.raises(ValueError, match="speculative"):
            ServingEngine(model, model.init(jax.random.key(0)),
                          max_batch=2, max_len=32, prefill_len=8,
                          lora_adapters=[ad], draft_model=model)

    def test_mismatched_ranks_rejected_at_stack(self):
        from instaslice_tpu.models.lora import stack_adapters

        cfg, _ = self._mk()
        _, a1 = self._adapter(cfg, 1)
        a2 = init_lora(jax.random.key(9), cfg, LoraConfig(rank=8))
        with pytest.raises(ValueError, match="rank"):
            stack_adapters([a1, a2], cfg)

    def test_build_engine_multi_lora(self, tmp_path):
        """Two --lora dirs: the server engine keeps the BASE weights
        and registers both adapters by dir basename (1-based engine
        ids); one --lora dir still merges (no runtime adapters)."""
        from instaslice_tpu.models.checkpoint import TrainCheckpointer
        from instaslice_tpu.serving.api_server import (
            build_engine,
            build_parser,
        )

        cfg = ModelConfig(
            vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.bfloat16, remat=False,
        )
        model = TpuLM(cfg)
        mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1),
                    ("data", "seq", "model"))
        base = model.init(jax.random.key(0))
        for sub in ("billing", "support"):
            init_fn, step_fn = make_lora_train_step(
                model, mesh, base, LoraConfig(rank=4),
                learning_rate=1e-2,
            )
            state = init_fn(jax.random.key(hash(sub) % 100))
            state, _ = step_fn(
                state,
                jax.random.randint(jax.random.key(1), (2, 16), 0, 64),
            )
            with TrainCheckpointer(str(tmp_path / sub)) as ckpt:
                assert ckpt.save(state)

        cfg_args = ["--d-model", "32", "--n-heads", "2", "--n-layers",
                    "2", "--d-ff", "64", "--vocab-size", "64",
                    "--max-len", "64", "--prefill-len", "8"]
        eng = build_engine(build_parser().parse_args(
            cfg_args + ["--lora", str(tmp_path / "billing"),
                        "--lora", str(tmp_path / "support")]
        ))
        assert eng.n_adapters == 2
        assert eng.adapter_names == {"billing": 1, "support": 2}
        # base weights untouched (runtime adapters, not a merge)
        np.testing.assert_array_equal(
            np.asarray(eng.params["blocks"]["wq"], np.float32),
            np.asarray(base["blocks"]["wq"], np.float32),
        )
        r0 = eng.add_request([3, 1, 4])
        r1 = eng.add_request([3, 1, 4], adapter=1)
        r2 = eng.add_request([3, 1, 4], adapter=2)
        out = eng.decode_block(4)
        assert all(len(v) == 4 for v in out.values())
        assert set(out) == {r0, r1, r2}
