"""LoRA adapter fine-tuning (``models/lora.py``): zero-init equivalence,
adapter-only training, sharded merge under tp, and the QLoRA path over
an int8 base. Runs on the virtual 8-device CPU mesh from conftest.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.models.lora import (
    LoraConfig,
    init_lora,
    lora_specs,
    make_lora_train_step,
    merge_lora,
)
from instaslice_tpu.models.quant import quantize_params
from instaslice_tpu.models.train import loss_fn


def tiny(**kw):
    return ModelConfig(
        vocab_size=128, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        dtype=jnp.float32, remat=False, **kw,
    )


def mesh2():
    devs = jax.devices()[:2]
    return Mesh(np.array(devs).reshape(1, 1, 2), ("data", "seq", "model"))


class TestLoraInit:
    def test_zero_b_merge_is_identity(self):
        """B = 0 ⇒ merged weights equal the base exactly — a fresh LoRA
        model IS the base model."""
        cfg = tiny()
        lcfg = LoraConfig(rank=4)
        params = TpuLM(cfg).init(jax.random.key(0))
        lora = init_lora(jax.random.key(1), cfg, lcfg)
        merged = merge_lora(params, lora, cfg, lcfg)
        for t in lcfg.targets:
            np.testing.assert_array_equal(
                np.asarray(merged["blocks"][t]),
                np.asarray(params["blocks"][t]),
            )
        # untargeted leaves are the same objects, not copies
        assert merged["blocks"]["wo"] is params["blocks"]["wo"]

    def test_bad_target_rejected(self):
        with pytest.raises(ValueError, match="unsupported"):
            LoraConfig(targets=("router",))
        with pytest.raises(ValueError, match="rank"):
            LoraConfig(rank=0)

    def test_moe_model_rejects_mlp_targets(self):
        cfg = tiny(n_experts=4)
        with pytest.raises(ValueError, match="not adaptable"):
            init_lora(jax.random.key(0), cfg,
                      LoraConfig(targets=("w_in",)))
        # attention targets remain fine on MoE models
        init_lora(jax.random.key(0), cfg, LoraConfig(targets=("wq",)))

    def test_b_spec_follows_base_output_axis(self):
        cfg = tiny()
        specs = lora_specs(cfg, LoraConfig(targets=("wq", "wo", "w_in")))
        assert specs["blocks"]["wq"]["b"] == P(None, None, "model")
        assert specs["blocks"]["w_in"]["b"] == P(None, None, "model")
        # wo's base spec is P("model", None): output dim unsharded
        assert specs["blocks"]["wo"]["b"] == P(None, None, None)


class TestLoraTrain:
    def test_first_loss_is_base_loss_then_decreases(self):
        cfg = tiny()
        lcfg = LoraConfig(rank=4)
        model = TpuLM(cfg)
        params = model.init(jax.random.key(0))
        mesh = mesh2()
        toks = jax.random.randint(jax.random.key(1), (2, 32), 0, 128)

        base_loss = float(loss_fn(model, params, toks))
        init_fn, step_fn = make_lora_train_step(
            model, mesh, params, lcfg, learning_rate=3e-3,
        )
        state = init_fn(jax.random.key(2))
        state, first = step_fn(state, toks)
        # the step's loss is computed BEFORE the update, with B=0
        np.testing.assert_allclose(float(first), base_loss, rtol=1e-5)
        for _ in range(5):
            state, loss = step_fn(state, toks)
        assert float(loss) < base_loss

    def test_only_adapters_train(self):
        """The train state holds adapters only — and after steps, B has
        actually moved off zero (gradients reach it through the
        merge)."""
        cfg = tiny()
        lcfg = LoraConfig(rank=4)
        model = TpuLM(cfg)
        params = model.init(jax.random.key(0))
        init_fn, step_fn = make_lora_train_step(
            model, mesh2(), params, lcfg, learning_rate=3e-3,
        )
        state = init_fn(jax.random.key(2))
        leaves = jax.tree.leaves(state.params)
        n_adapter = sum(l.size for l in leaves)
        n_base = sum(l.size for l in jax.tree.leaves(params))
        assert n_adapter < n_base / 5      # the PEFT point
        toks = jax.random.randint(jax.random.key(1), (2, 32), 0, 128)
        state, _ = step_fn(state, toks)
        state, _ = step_fn(state, toks)
        b = state.params["blocks"]["wq"]["b"]
        assert float(jnp.abs(b).max()) > 0.0

    def test_qlora_int8_base(self):
        """QuantizedTensor base leaves dequantize inside the merge: the
        int8 base trains adapters with finite decreasing loss."""
        cfg = tiny()
        lcfg = LoraConfig(rank=4)
        model = TpuLM(cfg)
        qparams = quantize_params(model.init(jax.random.key(0)))
        init_fn, step_fn = make_lora_train_step(
            model, mesh2(), qparams, lcfg, learning_rate=3e-3,
        )
        state = init_fn(jax.random.key(2))
        toks = jax.random.randint(jax.random.key(1), (2, 32), 0, 128)
        state, first = step_fn(state, toks)
        for _ in range(5):
            state, loss = step_fn(state, toks)
        assert np.isfinite(float(loss))
        assert float(loss) < float(first)

    def test_merged_adapter_serves_like_plain_params(self):
        """merge_lora output is a plain params tree: the unmodified
        forward accepts it — the single-adapter serving path."""
        cfg = tiny()
        lcfg = LoraConfig(rank=4)
        model = TpuLM(cfg)
        params = model.init(jax.random.key(0))
        lora = init_lora(jax.random.key(1), cfg, lcfg)
        # make the adapter nonzero so the test is not the identity case
        lora["blocks"]["wq"]["b"] = (
            jnp.ones_like(lora["blocks"]["wq"]["b"]) * 0.01
        )
        merged = merge_lora(params, lora, cfg, lcfg)
        toks = jax.random.randint(jax.random.key(2), (2, 16), 0, 128)
        out = model.apply(merged, toks)
        base = model.apply(params, toks)
        assert bool(jnp.isfinite(out).all())
        assert float(jnp.abs(out - base).max()) > 0.0


class TestLoraServing:
    def test_serve_with_merged_adapter(self, tmp_path):
        """tpuslice-serve --lora: a trained adapter checkpoint merges
        into the weights at startup (rank/targets read from the tree),
        and the engine's weights provably differ from the base by the
        adapter delta."""
        from instaslice_tpu.models.checkpoint import TrainCheckpointer
        from instaslice_tpu.serving.api_server import (
            build_engine,
            build_parser,
        )

        cfg = ModelConfig(
            vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.bfloat16, remat=False,
        )
        model = TpuLM(cfg)
        mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1),
                    ("data", "seq", "model"))
        lcfg = LoraConfig(rank=4)
        # the serving base is the DEFAULT init (seed 0) — what
        # build_engine materializes without --checkpoint
        base = model.init(jax.random.key(0))
        init_fn, step_fn = make_lora_train_step(
            model, mesh, base, lcfg, learning_rate=1e-2,
        )
        state = init_fn(jax.random.key(2))
        toks = jax.random.randint(jax.random.key(1), (2, 16), 0, 64)
        for _ in range(3):
            state, _ = step_fn(state, toks)
        with TrainCheckpointer(str(tmp_path)) as ckpt:
            assert ckpt.save(state)

        cfg_args = ["--d-model", "32", "--n-heads", "2", "--n-layers",
                    "2", "--d-ff", "64", "--vocab-size", "64",
                    "--max-len", "64", "--prefill-len", "8"]
        eng = build_engine(build_parser().parse_args(
            cfg_args + ["--lora", str(tmp_path)]
        ))
        want = merge_lora(base, state.params, cfg, lcfg)
        got = jnp.asarray(eng.params["blocks"]["wq"], jnp.float32)
        np.testing.assert_allclose(
            got, np.asarray(want["blocks"]["wq"], np.float32),
            rtol=1e-3,
        )
        # and it actually serves
        rid = eng.add_request([3, 1, 4])
        assert len(eng.decode_block(4)[rid]) == 4

    def test_serve_rejects_non_adapter_checkpoint(self, tmp_path):
        """--lora pointed at a FULL model checkpoint must refuse, not
        merge garbage."""
        from instaslice_tpu.models.checkpoint import TrainCheckpointer
        from instaslice_tpu.models.train import make_train_step
        from instaslice_tpu.serving.api_server import (
            build_engine,
            build_parser,
        )

        cfg = ModelConfig(
            vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.bfloat16, remat=False,
        )
        mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1),
                    ("data", "seq", "model"))
        init_fn, _ = make_train_step(TpuLM(cfg), mesh)
        with TrainCheckpointer(str(tmp_path)) as ckpt:
            assert ckpt.save(init_fn(jax.random.key(0)))
        cfg_args = ["--d-model", "32", "--n-heads", "2", "--n-layers",
                    "2", "--d-ff", "64", "--vocab-size", "64",
                    "--max-len", "64", "--prefill-len", "8"]
        with pytest.raises(SystemExit, match="adapter"):
            build_engine(build_parser().parse_args(
                cfg_args + ["--lora", str(tmp_path)]
            ))
