"""LoRA adapter fine-tuning (``models/lora.py``): zero-init equivalence,
adapter-only training, sharded merge under tp, and the QLoRA path over
an int8 base. Runs on the virtual 8-device CPU mesh from conftest.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.models.lora import (
    LoraConfig,
    init_lora,
    lora_specs,
    make_lora_train_step,
    merge_lora,
)
from instaslice_tpu.models.quant import quantize_params
from instaslice_tpu.models.train import loss_fn


def tiny(**kw):
    return ModelConfig(
        vocab_size=128, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        dtype=jnp.float32, remat=False, **kw,
    )


def mesh2():
    devs = jax.devices()[:2]
    return Mesh(np.array(devs).reshape(1, 1, 2), ("data", "seq", "model"))


class TestLoraInit:
    def test_zero_b_merge_is_identity(self):
        """B = 0 ⇒ merged weights equal the base exactly — a fresh LoRA
        model IS the base model."""
        cfg = tiny()
        lcfg = LoraConfig(rank=4)
        params = TpuLM(cfg).init(jax.random.key(0))
        lora = init_lora(jax.random.key(1), cfg, lcfg)
        merged = merge_lora(params, lora, cfg, lcfg)
        for t in lcfg.targets:
            np.testing.assert_array_equal(
                np.asarray(merged["blocks"][t]),
                np.asarray(params["blocks"][t]),
            )
        # untargeted leaves are the same objects, not copies
        assert merged["blocks"]["wo"] is params["blocks"]["wo"]

    def test_bad_target_rejected(self):
        with pytest.raises(ValueError, match="unsupported"):
            LoraConfig(targets=("router",))
        with pytest.raises(ValueError, match="rank"):
            LoraConfig(rank=0)

    def test_moe_model_rejects_mlp_targets(self):
        cfg = tiny(n_experts=4)
        with pytest.raises(ValueError, match="not adaptable"):
            init_lora(jax.random.key(0), cfg,
                      LoraConfig(targets=("w_in",)))
        # attention targets remain fine on MoE models
        init_lora(jax.random.key(0), cfg, LoraConfig(targets=("wq",)))

    def test_b_spec_follows_base_output_axis(self):
        cfg = tiny()
        specs = lora_specs(cfg, LoraConfig(targets=("wq", "wo", "w_in")))
        assert specs["blocks"]["wq"]["b"] == P(None, None, "model")
        assert specs["blocks"]["w_in"]["b"] == P(None, None, "model")
        # wo's base spec is P("model", None): output dim unsharded
        assert specs["blocks"]["wo"]["b"] == P(None, None, None)


class TestLoraTrain:
    def test_first_loss_is_base_loss_then_decreases(self):
        cfg = tiny()
        lcfg = LoraConfig(rank=4)
        model = TpuLM(cfg)
        params = model.init(jax.random.key(0))
        mesh = mesh2()
        toks = jax.random.randint(jax.random.key(1), (2, 32), 0, 128)

        base_loss = float(loss_fn(model, params, toks))
        init_fn, step_fn = make_lora_train_step(
            model, mesh, params, lcfg, learning_rate=3e-3,
        )
        state = init_fn(jax.random.key(2))
        state, first = step_fn(state, toks)
        # the step's loss is computed BEFORE the update, with B=0
        np.testing.assert_allclose(float(first), base_loss, rtol=1e-5)
        for _ in range(5):
            state, loss = step_fn(state, toks)
        assert float(loss) < base_loss

    def test_only_adapters_train(self):
        """The train state holds adapters only — and after steps, B has
        actually moved off zero (gradients reach it through the
        merge)."""
        cfg = tiny()
        lcfg = LoraConfig(rank=4)
        model = TpuLM(cfg)
        params = model.init(jax.random.key(0))
        init_fn, step_fn = make_lora_train_step(
            model, mesh2(), params, lcfg, learning_rate=3e-3,
        )
        state = init_fn(jax.random.key(2))
        leaves = jax.tree.leaves(state.params)
        n_adapter = sum(l.size for l in leaves)
        n_base = sum(l.size for l in jax.tree.leaves(params))
        assert n_adapter < n_base / 5      # the PEFT point
        toks = jax.random.randint(jax.random.key(1), (2, 32), 0, 128)
        state, _ = step_fn(state, toks)
        state, _ = step_fn(state, toks)
        b = state.params["blocks"]["wq"]["b"]
        assert float(jnp.abs(b).max()) > 0.0

    def test_qlora_int8_base(self):
        """QuantizedTensor base leaves dequantize inside the merge: the
        int8 base trains adapters with finite decreasing loss."""
        cfg = tiny()
        lcfg = LoraConfig(rank=4)
        model = TpuLM(cfg)
        qparams = quantize_params(model.init(jax.random.key(0)))
        init_fn, step_fn = make_lora_train_step(
            model, mesh2(), qparams, lcfg, learning_rate=3e-3,
        )
        state = init_fn(jax.random.key(2))
        toks = jax.random.randint(jax.random.key(1), (2, 32), 0, 128)
        state, first = step_fn(state, toks)
        for _ in range(5):
            state, loss = step_fn(state, toks)
        assert np.isfinite(float(loss))
        assert float(loss) < float(first)

    def test_merged_adapter_serves_like_plain_params(self):
        """merge_lora output is a plain params tree: the unmodified
        forward accepts it — the single-adapter serving path."""
        cfg = tiny()
        lcfg = LoraConfig(rank=4)
        model = TpuLM(cfg)
        params = model.init(jax.random.key(0))
        lora = init_lora(jax.random.key(1), cfg, lcfg)
        # make the adapter nonzero so the test is not the identity case
        lora["blocks"]["wq"]["b"] = (
            jnp.ones_like(lora["blocks"]["wq"]["b"]) * 0.01
        )
        merged = merge_lora(params, lora, cfg, lcfg)
        toks = jax.random.randint(jax.random.key(2), (2, 16), 0, 128)
        out = model.apply(merged, toks)
        base = model.apply(params, toks)
        assert bool(jnp.isfinite(out).all())
        assert float(jnp.abs(out - base).max()) > 0.0
