"""OpenAI-style serving front-end (serving/api_server.py).

The native replacement for the reference's "point vLLM at the slice"
sample: real HTTP, continuous batching through the scheduler thread,
per-request budgets, and the speculative path — all must produce the
same greedy chains the oracle does.
"""

import json
import threading
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.serving import ServingEngine
from instaslice_tpu.serving.api_server import ApiServer


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


def greedy_reference(model, params, prompt, n_new):
    toks = list(prompt)
    out = []
    for _ in range(n_new):
        logits = model.apply(params, jnp.asarray(toks, jnp.int32)[None])
        t = int(jnp.argmax(logits[0, -1]))
        out.append(t)
        toks.append(t)
    return out


def post(url, payload, timeout=120):
    req = urllib.request.Request(
        f"{url}/v1/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


class TestApiServer:
    def test_completion_matches_oracle(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        with ApiServer(eng) as srv:
            code, out = post(srv.url, {"prompt": [5, 9, 2, 7],
                                       "max_tokens": 6})
            assert code == 200
            choice = out["choices"][0]
            assert choice["token_ids"] == greedy_reference(
                m, params, [5, 9, 2, 7], 6
            )
            assert choice["finish_reason"] == "max_new_tokens"
            assert out["usage"]["completion_tokens"] == 6

    def test_more_requests_than_slots(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8)
        with ApiServer(eng) as srv:
            results = {}

            def ask(i):
                prompt = [i + 1, i + 2, i + 3]
                results[i] = (prompt, post(
                    srv.url, {"prompt": prompt, "max_tokens": 4}
                ))

            threads = [threading.Thread(target=ask, args=(i,))
                       for i in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            for i, (prompt, (code, out)) in results.items():
                assert code == 200, out
                assert out["choices"][0]["token_ids"] == greedy_reference(
                    m, params, prompt, 4
                ), i

    def test_speculative_backend(self, model):
        from instaslice_tpu.models.quant import quantize_params

        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, draft_model=m,
                            draft_params=quantize_params(params),
                            spec_k=3)
        with ApiServer(eng) as srv:
            code, out = post(srv.url, {"prompt": [9, 3, 1],
                                       "max_tokens": 8})
            assert code == 200
            got = out["choices"][0]["token_ids"]
            assert got == greedy_reference(m, params, [9, 3, 1], 8)

    def test_streaming_matches_oracle(self, model):
        import http.client

        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        prompt = [5, 9, 2, 7]
        want = greedy_reference(m, params, prompt, 12)
        with ApiServer(eng, block_size=4) as srv:
            host, port = srv.url.replace("http://", "").split(":")
            conn = http.client.HTTPConnection(host, int(port), timeout=120)
            conn.request(
                "POST", "/v1/completions",
                body=json.dumps({"prompt": prompt, "max_tokens": 12,
                                 "stream": True}),
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            assert resp.status == 200
            assert resp.getheader("Content-Type") == "text/event-stream"
            events = []
            buf = b""
            while b"data: [DONE]" not in buf:
                chunk = resp.read1(65536)
                assert chunk, "stream ended without [DONE]"
                buf += chunk
            for line in buf.decode().splitlines():
                if line.startswith("data: ") and line != "data: [DONE]":
                    events.append(json.loads(line[len("data: "):]))
            conn.close()
        got = [t for e in events for t in e["choices"][0]["token_ids"]]
        assert got == want
        # multiple incremental chunks (block_size 4 < 12 tokens)
        assert len(events) >= 3
        final = events[-1]
        assert final["choices"][0]["finish_reason"] == "max_new_tokens"
        assert final["usage"]["completion_tokens"] == 12

    def test_stop_sequences_over_http(self, model):
        from test_serving import first_match

        m, params = model
        oracle = greedy_reference(m, params, [5, 9, 2, 7], 12)
        stop = oracle[3:5]
        cut = first_match(oracle, stop)
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        with ApiServer(eng, block_size=4) as srv:
            code, out = post(srv.url, {"prompt": [5, 9, 2, 7],
                                       "max_tokens": 12, "stop": stop})
            assert code == 200
            choice = out["choices"][0]
            assert choice["token_ids"] == oracle[:cut]
            assert choice["finish_reason"] == "stop"
            # malformed stop → 400
            code, out = post(srv.url, {"prompt": [1, 2],
                                       "max_tokens": 4, "stop": [[]]})
            assert code == 400

    def test_streaming_with_stop_never_over_delivers(self, model):
        import http.client

        from test_serving import first_match

        m, params = model
        oracle = greedy_reference(m, params, [5, 9, 2, 7], 12)
        stop = oracle[3:5]
        cut = first_match(oracle, stop)
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        with ApiServer(eng, block_size=4) as srv:
            host, port = srv.url.replace("http://", "").split(":")
            conn = http.client.HTTPConnection(host, int(port), timeout=120)
            conn.request(
                "POST", "/v1/completions",
                body=json.dumps({"prompt": [5, 9, 2, 7], "max_tokens": 12,
                                 "stream": True, "stop": stop}),
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            buf = b""
            while b"data: [DONE]" not in buf:
                chunk = resp.read1(65536)
                assert chunk, "stream ended without [DONE]"
                buf += chunk
            conn.close()
        events = [json.loads(l[6:]) for l in buf.decode().splitlines()
                  if l.startswith("data: ") and l != "data: [DONE]"]
        got = [t for e in events for t in e["choices"][0]["token_ids"]]
        # the stop-window holdback must prevent streaming tokens that a
        # later match would truncate: exactly the pre-stop tokens arrive
        assert got == oracle[:cut]
        assert events[-1]["choices"][0]["finish_reason"] == "stop"
        assert events[-1]["usage"]["completion_tokens"] == cut

    def test_n_choices_over_http(self, model):
        m, params = model
        oracle = greedy_reference(m, params, [5, 9, 2, 7], 5)
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8)
        with ApiServer(eng) as srv:
            code, out = post(srv.url, {"prompt": [5, 9, 2, 7],
                                       "max_tokens": 5, "n": 3})
            assert code == 200
            assert [c["index"] for c in out["choices"]] == [0, 1, 2]
            for c in out["choices"]:
                assert c["token_ids"] == oracle     # greedy: identical
            assert out["usage"]["completion_tokens"] == 15
            # n beyond the slot count is rejected up front
            code, out = post(srv.url, {"prompt": [1, 2],
                                       "max_tokens": 2, "n": 5})
            assert code == 400
            assert "slot count" in out["error"]

    def test_n_streaming_all_choices_complete(self, model):
        import http.client

        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8)
        with ApiServer(eng, block_size=4) as srv:
            host, port = srv.url.replace("http://", "").split(":")
            conn = http.client.HTTPConnection(host, int(port), timeout=120)
            conn.request(
                "POST", "/v1/completions",
                body=json.dumps({"prompt": [5, 9, 2, 7], "max_tokens": 6,
                                 "stream": True, "n": 2}),
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            buf = b""
            while b"data: [DONE]" not in buf:
                chunk = resp.read1(65536)
                assert chunk, "stream ended without [DONE]"
                buf += chunk
            conn.close()
        events = [json.loads(l[6:]) for l in buf.decode().splitlines()
                  if l.startswith("data: ") and l != "data: [DONE]"]
        per_index = {0: [], 1: []}
        finals = set()
        for e in events:
            c = e["choices"][0]
            if c["finish_reason"] is None:
                per_index[c["index"]].extend(c["token_ids"])
            else:
                finals.add(c["index"])
        assert finals == {0, 1}
        assert len(per_index[0]) == 6 and len(per_index[1]) == 6

    def test_logprobs_over_http(self, model):
        import math

        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        with ApiServer(eng) as srv:
            code, out = post(srv.url, {"prompt": [5, 9, 2, 7],
                                       "max_tokens": 6,
                                       "logprobs": True})
            assert code == 200
            choice = out["choices"][0]
            assert len(choice["logprobs"]) == len(choice["token_ids"])
            assert all(
                isinstance(x, float) and x <= 0.0 and math.isfinite(x)
                for x in choice["logprobs"]
            )
            # not requested → not in the response
            code, out = post(srv.url, {"prompt": [5, 9, 2, 7],
                                       "max_tokens": 4})
            assert "logprobs" not in out["choices"][0]

    def test_streaming_logprobs_one_per_token(self, model):
        import http.client

        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        with ApiServer(eng, block_size=4) as srv:
            host, port = srv.url.replace("http://", "").split(":")
            conn = http.client.HTTPConnection(host, int(port), timeout=120)
            conn.request(
                "POST", "/v1/completions",
                body=json.dumps({"prompt": [5, 9, 2, 7], "max_tokens": 10,
                                 "stream": True, "logprobs": True}),
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            buf = b""
            while b"data: [DONE]" not in buf:
                chunk = resp.read1(65536)
                assert chunk
                buf += chunk
            conn.close()
        events = [json.loads(l[6:]) for l in buf.decode().splitlines()
                  if l.startswith("data: ") and l != "data: [DONE]"]
        toks = [t for e in events for t in e["choices"][0]["token_ids"]]
        lps = [x for e in events
               for x in e["choices"][0].get("logprobs", [])]
        assert len(toks) == 10
        assert len(lps) == 10

    def test_budget_cut_rewrites_stop_reason(self, model):
        """A stop match beyond the request budget is evidence the client
        never sees — the delivered reason must be max_new_tokens (the
        spec_step path can overshoot budgets by up to k+1 tokens)."""
        from instaslice_tpu.serving.api_server import _Pending, _Scheduler
        from instaslice_tpu.serving.engine import GenerationResult

        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        sched = _Scheduler(eng)            # not started: direct _deliver
        p = _Pending([1, 2], max_tokens=2)
        p.rid_index[7] = 0
        sched._by_rid[7] = p
        sched._budget[7] = 2
        eng.finished.append(
            GenerationResult(7, [1, 2], [5, 6, 8], "stop")
        )
        sched._deliver()
        assert p.done.is_set()
        assert p.result.tokens == [5, 6]
        assert p.result.finished_reason == "max_new_tokens"

    def test_streaming_disconnect_evicts_slot(self, model):
        import http.client
        import time as _time

        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8)
        with ApiServer(eng, block_size=4) as srv:
            host, port = srv.url.replace("http://", "").split(":")
            conn = http.client.HTTPConnection(host, int(port), timeout=60)
            conn.request(
                "POST", "/v1/completions",
                body=json.dumps({"prompt": [5, 9], "max_tokens": 50,
                                 "stream": True}),
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            resp.read1(64)                 # first chunk arrives…
            conn.close()                   # …then the client vanishes
            deadline = _time.monotonic() + 15
            while _time.monotonic() < deadline:
                with urllib.request.urlopen(
                    f"{srv.url}/v1/stats", timeout=10
                ) as r:
                    if json.loads(r.read())["live_slots"] == 0:
                        break
                _time.sleep(0.05)
            else:
                assert False, "disconnected stream still holds its slot"

    def test_timed_out_request_evicted_frees_slot(self, model):
        import time as _time

        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8)
        # 0.15 s HTTP deadline << the time 40 decode tokens take on the
        # one slot, so the client 503s while the request still decodes
        with ApiServer(eng, request_timeout=0.15) as srv:
            code, out = post(srv.url, {"prompt": [5, 9, 2, 7],
                                       "max_tokens": 40}, timeout=30)
            assert code == 503
            # the scheduler must evict the abandoned slot, not decode it
            # to its 40-token budget
            deadline = _time.monotonic() + 10
            while _time.monotonic() < deadline:
                with urllib.request.urlopen(
                    f"{srv.url}/v1/stats", timeout=10
                ) as r:
                    if json.loads(r.read())["live_slots"] == 0:
                        break
                _time.sleep(0.05)
            else:
                assert False, "timed-out request still occupies its slot"
            # the freed slot serves the next request normally — retried,
            # because the 0.15 s deadline applies server-wide and a
            # fresh block size can cost one more compile
            for _ in range(40):
                code, out = post(srv.url, {"prompt": [5, 9, 2, 7],
                                           "max_tokens": 4}, timeout=60)
                if code == 200:
                    break
                _time.sleep(0.25)
            assert code == 200
            assert out["choices"][0]["token_ids"] == greedy_reference(
                m, params, [5, 9, 2, 7], 4
            )

    def test_prefix_registration_route(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        prefix = list(range(1, 9))                    # one chunk
        prompt = prefix + [40, 41]
        want = greedy_reference(m, params, prompt, 6)
        with ApiServer(eng) as srv:
            req = urllib.request.Request(
                f"{srv.url}/v1/prefixes",
                data=json.dumps({"tokens": prefix}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=60) as r:
                assert r.status == 200
                assert json.loads(r.read())["registered"] == len(prefix)
            code, out = post(srv.url, {"prompt": prompt, "max_tokens": 6})
            assert code == 200
            assert out["choices"][0]["token_ids"] == want
            with urllib.request.urlopen(
                f"{srv.url}/v1/stats", timeout=30
            ) as r:
                stats = json.loads(r.read())
            assert stats["prefixes"] == 1
            assert stats["prefix_hits"] == 1
            assert stats["prefix_tokens_saved"] == len(prefix)
            # invalid: not a chunk multiple → 400 with the engine error
            req = urllib.request.Request(
                f"{srv.url}/v1/prefixes",
                data=json.dumps({"tokens": [1, 2, 3]}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            try:
                urllib.request.urlopen(req, timeout=60)
                assert False, "expected 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
                assert "multiple of prefill_len" in (
                    json.loads(e.read())["error"]
                )
            # DELETE frees the stripe; a second DELETE 404s
            req = urllib.request.Request(
                f"{srv.url}/v1/prefixes",
                data=json.dumps({"tokens": prefix}).encode(),
                headers={"Content-Type": "application/json"},
                method="DELETE",
            )
            with urllib.request.urlopen(req, timeout=60) as r:
                assert r.status == 200
                assert json.loads(r.read())["dropped"] == len(prefix)
            req = urllib.request.Request(
                f"{srv.url}/v1/prefixes",
                data=json.dumps({"tokens": prefix}).encode(),
                headers={"Content-Type": "application/json"},
                method="DELETE",
            )
            try:
                urllib.request.urlopen(req, timeout=60)
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404

    def test_bad_requests(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=16,
                            prefill_len=8)
        with ApiServer(eng) as srv:
            code, out = post(srv.url, {"prompt": "not tokens"})
            assert code == 400 and "token ids" in out["error"]
            code, out = post(srv.url, {"prompt": [1], "max_tokens": 0})
            assert code == 400
            # prompt longer than the cache: engine rejection surfaces
            code, out = post(srv.url, {"prompt": [1] * 40,
                                       "max_tokens": 2})
            assert code == 400 and "max_len" in out["error"]

    def test_loadgen_sync_and_stream(self, model):
        """The load generator against a live server: all requests
        succeed, latency/TTFT fields populated, token accounting
        consistent with the per-request budget."""
        from instaslice_tpu.serving.loadgen import run

        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8)
        with ApiServer(eng, block_size=4) as srv:
            out = run(srv.url, requests=6, concurrency=3, prompt_len=6,
                      max_tokens=5, vocab=64, stream=False, timeout=120)
            assert out["ok"] == 6 and out["errors"] == 0
            assert out["value"] > 0
            assert out["client_tokens_per_sec"] > 0
            s = run(srv.url, requests=4, concurrency=2, prompt_len=6,
                    max_tokens=5, vocab=64, stream=True, timeout=120)
            assert s["ok"] == 4 and s["errors"] == 0
            assert 0 < s["ttft_p50"] <= s["p95_latency"]

    def test_loadgen_sweep_cli(self, model, capsys):
        from instaslice_tpu.serving.loadgen import main as lg_main

        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8)
        with ApiServer(eng, block_size=4) as srv:
            rc = lg_main(["--url", srv.url, "--requests", "4",
                          "--sweep", "1,2", "--prompt-len", "6",
                          "--max-tokens", "4", "--vocab", "64"])
        out = json.loads(capsys.readouterr().out.strip())
        assert rc == 0
        assert out["metric"] == "serve_capacity_sweep"
        assert [l["concurrency"] for l in out["levels"]] == [1, 2]
        assert all(l["ok"] == 4 for l in out["levels"])
        assert out["best_concurrency"] in (1, 2)

    def test_models_route_lists_adapters(self, model):
        """Multi-LoRA servers list each adapter as a model entry
        (parent = the base id, adapter flag set) — how OpenAI-ecosystem
        clients discover what they can put in the adapter field."""
        from instaslice_tpu.models.lora import LoraConfig, init_lora

        m, params = model
        ads = [init_lora(jax.random.key(i), m.cfg, LoraConfig(rank=2))
               for i in (1, 2)]
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, lora_adapters=ads,
                            lora_names=["billing", "support"])
        with ApiServer(eng) as srv:
            with urllib.request.urlopen(
                f"{srv.url}/v1/models", timeout=30
            ) as r:
                out = json.loads(r.read())
            ids = [e["id"] for e in out["data"]]
            assert ids[0].startswith("tpuslice-lm-")
            assert set(ids[1:]) == {"billing", "support"}
            assert all(e["adapter"] and e["parent"] == ids[0]
                       for e in out["data"][1:])
            # retrieve-model works for an adapter id too
            with urllib.request.urlopen(
                f"{srv.url}/v1/models/billing", timeout=30
            ) as r:
                one = json.loads(r.read())
            assert one["id"] == "billing" and one["adapter"] is True

    def test_loadgen_multi_lora_round_robin(self, model):
        """--adapters: requests round-robin across named adapters (and
        the base via the empty name) over real HTTP — the multi-LoRA
        serving path under client load; unknown names surface as
        errors, not silent base-model traffic."""
        from instaslice_tpu.models.lora import LoraConfig, init_lora
        from instaslice_tpu.serving.loadgen import run

        m, params = model
        ads = [init_lora(jax.random.key(i), m.cfg, LoraConfig(rank=2))
               for i in (1, 2)]
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8, lora_adapters=ads,
                            lora_names=["billing", "support"])
        with ApiServer(eng, block_size=4) as srv:
            out = run(srv.url, requests=6, concurrency=2, prompt_len=6,
                      max_tokens=4, vocab=64, stream=False,
                      timeout=120,
                      adapters=["", "billing", "support"])
            assert out["ok"] == 6 and out["errors"] == 0
            assert out["adapters"] == ["", "billing", "support"]
            bad = run(srv.url, requests=2, concurrency=1, prompt_len=6,
                      max_tokens=4, vocab=64, stream=False,
                      timeout=120, adapters=["nonexistent"])
            assert bad["errors"] == 2
            assert "unknown adapter" in bad["first_error"]

    def test_models_route(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        with ApiServer(eng) as srv:
            with urllib.request.urlopen(
                f"{srv.url}/v1/models", timeout=30
            ) as r:
                out = json.loads(r.read())
            [entry] = out["data"]
            assert entry["object"] == "model"
            assert entry["max_model_len"] == 64
            assert entry["config"]["d_model"] == 32
            assert entry["owned_by"] == "tpuslice"
            # retrieve-model route returns the single object / 404
            with urllib.request.urlopen(
                f"{srv.url}/v1/models/{entry['id']}", timeout=30
            ) as r:
                got = json.loads(r.read())
            assert got["id"] == entry["id"]
            assert got["object"] == "model"
            try:
                urllib.request.urlopen(
                    f"{srv.url}/v1/models/nope", timeout=30
                )
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404

    def test_health_and_stats(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=32,
                            prefill_len=8)
        with ApiServer(eng) as srv:
            with urllib.request.urlopen(f"{srv.url}/healthz",
                                        timeout=10) as r:
                assert r.status == 200
            with urllib.request.urlopen(f"{srv.url}/v1/stats",
                                        timeout=10) as r:
                stats = json.loads(r.read().decode())
            assert stats["max_batch"] == 2
            assert stats["speculative"] is False


class TestBuildEngineCli:
    """The tpuslice-serve wiring: --from-env builds the TP mesh from the
    handoff env, --quantize serves int8, --checkpoint restores params."""

    def test_from_env_quantized(self, monkeypatch):
        from instaslice_tpu.serving.api_server import (
            build_engine,
            build_parser,
        )

        # a 4-chip single-host grant's env (what the agent publishes)
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "")
        monkeypatch.setenv("TPU_CHIPS_PER_HOST_BOUNDS", "2,2,1")
        monkeypatch.setenv("TPU_HOST_BOUNDS", "1,1,1")
        args = build_parser().parse_args([
            "--d-model", "32", "--n-heads", "4", "--n-layers", "2",
            "--d-ff", "64", "--vocab-size", "64", "--max-len", "64",
            "--prefill-len", "8", "--max-batch", "2", "--quantize",
            "--from-env",
        ])
        eng = build_engine(args)
        assert eng.mesh is not None
        assert eng.mesh.shape["model"] >= 1
        assert eng.cache["k"].dtype == jnp.int8       # kv_quant on
        rid = eng.add_request([3, 1, 4])
        assert len(eng.decode_block(4)[rid]) == 4

    def test_quantize_bits_4(self):
        """--quantize-bits 4 builds a packed-int4 engine; bad widths
        are an argparse error, not a runtime crash."""
        import pytest

        from instaslice_tpu.models.quant import Int4Tensor
        from instaslice_tpu.serving.api_server import (
            build_engine,
            build_parser,
        )

        args = build_parser().parse_args([
            "--d-model", "32", "--n-heads", "4", "--n-layers", "2",
            "--d-ff", "64", "--vocab-size", "64", "--max-len", "64",
            "--prefill-len", "8", "--max-batch", "2",
            "--quantize", "--quantize-bits", "4",
        ])
        eng = build_engine(args)
        assert isinstance(eng.params["blocks"]["wq"], Int4Tensor)
        assert eng.cache["k"].dtype == jnp.int8
        rid = eng.add_request([3, 1, 4])
        assert len(eng.decode_block(4)[rid]) == 4
        with pytest.raises(SystemExit):
            build_parser().parse_args(["--quantize-bits", "5"])

    def test_quantize_bits_implies_quantize(self):
        """An explicit non-default --quantize-bits without --quantize
        means the operator wants quantization — honor it rather than
        silently serving bf16 (which OOMs the 13B-on-one-chip recipe
        at load instead of at the flag)."""
        from instaslice_tpu.models.quant import Int4Tensor
        from instaslice_tpu.serving.api_server import (
            build_engine,
            build_parser,
        )

        args = build_parser().parse_args([
            "--d-model", "32", "--n-heads", "4", "--n-layers", "2",
            "--d-ff", "64", "--vocab-size", "64", "--max-len", "64",
            "--prefill-len", "8", "--max-batch", "2",
            "--quantize-bits", "4",
        ])
        eng = build_engine(args)
        assert isinstance(eng.params["blocks"]["wq"], Int4Tensor)
        assert eng.cache["k"].dtype == jnp.int8

    def test_checkpoint_restore(self, tmp_path):
        import numpy as np

        from instaslice_tpu.models.checkpoint import TrainCheckpointer
        from instaslice_tpu.models.lm import ModelConfig, TpuLM
        from instaslice_tpu.models.train import make_train_step
        from instaslice_tpu.serving.api_server import (
            build_engine,
            build_parser,
        )
        from jax.sharding import Mesh

        cfg_args = ["--d-model", "32", "--n-heads", "2", "--n-layers",
                    "2", "--d-ff", "64", "--vocab-size", "64",
                    "--max-len", "64", "--prefill-len", "8"]
        # train one step and checkpoint it
        m = TpuLM(ModelConfig(
            vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            max_seq_len=64, dtype=jnp.bfloat16, remat=False,
        ))
        mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1),
                    ("data", "seq", "model"))
        init_fn, step_fn = make_train_step(m, mesh)
        state = init_fn(jax.random.key(0))
        state, _ = step_fn(state, jnp.zeros((2, 16), jnp.int32))
        with TrainCheckpointer(str(tmp_path)) as ckpt:
            assert ckpt.save(state)
        args = build_parser().parse_args(
            cfg_args + ["--checkpoint", str(tmp_path)]
        )
        eng = build_engine(args)
        # restored params, not the fresh init: compare a weight
        got = jnp.asarray(eng.params["blocks"]["wq"])
        want = jnp.asarray(state.params["blocks"]["wq"])
        assert jnp.allclose(
            got.astype(jnp.float32), want.astype(jnp.float32)
        )


class TestGrantToServe:
    """The whole story in one test: operator grants a slice → agent
    publishes the handoff env → tpuslice-serve (a REAL subprocess) joins
    with --from-env, builds the mesh from that env, and serves a
    completion over HTTP. This is what samples/native-serve.yaml does in
    a cluster."""

    def test_granted_env_serves_completions(self, tmp_path):
        import os
        import subprocess
        import sys
        import time
        from pathlib import Path

        from conftest import free_port, wait_until
        from instaslice_tpu.sim import SimCluster

        with SimCluster(n_nodes=1, generation="v5e",
                        deletion_grace_seconds=0.2) as c:
            c.submit("serve-pod", profile="v5e-2x2")
            assert c.wait_phase("serve-pod", "Running", timeout=30)
            cm = c.configmap("serve-pod")
            handoff = dict(cm["data"])

        port = free_port()
        env = {
            "PATH": "/usr/bin:/bin",
            "PYTHONPATH": str(Path(__file__).resolve().parent.parent),
            # CPU-only child (and we must not touch a single-claim TPU
            # tunnel from a second process); 8 virtual devices so the
            # 4-chip grant's mesh has devices to cap from
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            **handoff,
        }
        code = (
            "import jax;"
            "jax.config.update('jax_platforms','cpu');"
            # jax < 0.5 has no jax_num_cpu_devices; XLA_FLAGS covers it
            "\ntry: jax.config.update('jax_num_cpu_devices',8)\n"
            "except AttributeError: pass\n"
            "from instaslice_tpu.serving.api_server import main;"
            f"main(['--host','127.0.0.1','--port','{port}',"
            "'--d-model','32','--n-heads','4','--n-layers','2',"
            "'--d-ff','64','--vocab-size','64','--max-len','64',"
            "'--prefill-len','8','--max-batch','2','--from-env'])"
        )
        log = open(tmp_path / "serve.log", "w+")
        proc = subprocess.Popen(
            [sys.executable, "-c", code], env=env,
            stdout=log, stderr=subprocess.STDOUT,
        )
        try:
            def ready():
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=1
                    ) as r:
                        return r.status == 200
                except Exception:
                    return False

            wait_until(
                ready, 90, "server ready",
                lambda: Path(log.name).read_text()[-800:],
            )
            code_, out = post(f"http://127.0.0.1:{port}",
                              {"prompt": [5, 9, 2, 7], "max_tokens": 4})
            assert code_ == 200, out
            assert len(out["choices"][0]["token_ids"]) == 4
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/stats", timeout=5
            ) as r:
                stats = json.loads(r.read().decode())
            assert stats["tokens_generated"] >= 4
            # the mesh really came from the 2x2 grant's handoff env:
            # 4 chips, all on the model axis
            assert stats["mesh"] == {"data": 1, "seq": 1, "model": 4}
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
            log.close()


class TestServingMetrics:
    def test_counters_track_requests(self, model):
        from instaslice_tpu.metrics.metrics import ServingMetrics

        m, params = model
        metrics = ServingMetrics()
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        with ApiServer(eng, metrics=metrics) as srv:
            code, _ = post(srv.url, {"prompt": [5, 9, 2], "max_tokens": 3})
            assert code == 200
            code, _ = post(srv.url, {"prompt": [1] * 80, "max_tokens": 2})
            assert code == 400           # too long → rejected
        if metrics.registry is None:
            pytest.skip("prometheus_client unavailable")
        from prometheus_client import generate_latest

        body = generate_latest(metrics.registry).decode()
        assert 'tpuslice_serve_requests_total{outcome="ok"} 1.0' in body
        assert ('tpuslice_serve_requests_total{outcome="rejected"} 1.0'
                in body)
        assert "tpuslice_serve_tokens_total 3.0" in body
        assert "tpuslice_serve_request_seconds_bucket" in body


class TestSamplingConfig:
    def test_mismatched_request_sampling_rejected(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=32,
                            prefill_len=8)
        with ApiServer(eng) as srv:
            code, out = post(srv.url, {"prompt": [1, 2],
                                       "max_tokens": 2,
                                       "temperature": 0.9})
            assert code == 400 and "engine-level" in out["error"]
            # matching values pass through
            code, out = post(srv.url, {"prompt": [1, 2], "max_tokens": 2,
                                       "temperature": 0.0, "top_p": 1.0})
            assert code == 200

    def test_sampled_server(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=32,
                            prefill_len=8, temperature=0.9, top_k=4)
        with ApiServer(eng) as srv:
            code, out = post(srv.url, {"prompt": [5, 9], "max_tokens": 4,
                                       "temperature": 0.9})
            assert code == 200
            assert len(out["choices"][0]["token_ids"]) == 4


class TestEngineRecovery:
    """Donated cache buffers are consumed even by a FAILING jitted call;
    the scheduler must reset the engine and keep serving instead of
    spinning on 'Array has been deleted' forever."""

    def test_decode_failure_recovers_and_serves_again(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        # decode_block_start is THE dispatch point both the overlap
        # and the sync path go through (decode_block = start + finish)
        real = eng.decode_block_start
        calls = {"n": 0}

        def flaky(n):
            calls["n"] += 1
            if calls["n"] == 1:
                # consume the donated cache WITHOUT rebinding — exactly
                # what a jitted call that raises mid-flight leaves
                # behind — then raise
                jax.jit(lambda c: c, donate_argnums=(0,))(eng.cache)
                assert eng.cache_poisoned()
                raise RuntimeError("RESOURCE_EXHAUSTED: injected")
            return real(n)

        eng.decode_block_start = flaky
        with ApiServer(eng) as srv:
            code, out = post(srv.url, {"prompt": [5, 9, 2], "max_tokens": 6})
            assert code == 500
            assert "engine recovered" in out["error"]
            # the server survived: a fresh request decodes normally and
            # matches the oracle (zeroed cache, same params)
            code, out = post(srv.url, {"prompt": [5, 9, 2, 7],
                                       "max_tokens": 6})
            assert code == 200
            assert out["choices"][0]["token_ids"] == greedy_reference(
                m, params, [5, 9, 2, 7], 6
            )

    def test_healthy_cache_host_error_does_not_nuke_slots(self, model):
        """Recovery is gated on actual poisoning: a host-side bug that
        raises with the cache intact must not kill live requests."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        real = eng.decode_block_start
        calls = {"n": 0}

        def flaky(n):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("host-side bug, cache untouched")
            return real(n)

        eng.decode_block_start = flaky
        with ApiServer(eng) as srv:
            code, out = post(srv.url, {"prompt": [5, 9, 2, 7],
                                       "max_tokens": 6})
            # the request survives the transient error and completes
            assert code == 200
            assert out["choices"][0]["token_ids"] == greedy_reference(
                m, params, [5, 9, 2, 7], 6
            )

    def test_admission_poisoning_recovers(self, model):
        """A prefill failure that consumed the donated cache must also
        recover — admission, not just decode, goes through donating
        jits. (A lone request rides _admit_one, so the injected fault
        is its 500; only multi-request bursts get the per-request
        retry after recovery.)"""
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        real = eng.add_request_n
        calls = {"n": 0}

        def flaky(prompt, n, stop=None, adapter=0):
            calls["n"] += 1
            if calls["n"] == 1:
                jax.jit(lambda c: c, donate_argnums=(0,))(eng.cache)
                raise RuntimeError("RESOURCE_EXHAUSTED: injected")
            return real(prompt, n, stop=stop, adapter=adapter)

        eng.add_request_n = flaky
        with ApiServer(eng) as srv:
            code, out = post(srv.url, {"prompt": [5, 9], "max_tokens": 4})
            assert code == 500          # server fault, not client 400
            code, out = post(srv.url, {"prompt": [5, 9, 2, 7],
                                       "max_tokens": 6})
            assert code == 200
            assert out["choices"][0]["token_ids"] == greedy_reference(
                m, params, [5, 9, 2, 7], 6
            )

    def test_engine_recover_reports_lost_rids_and_keeps_prefixes(
        self, model
    ):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        eng.register_prefix([3, 1, 4, 1, 5, 9, 2, 6])
        rid = eng.add_request([3, 1, 4, 1, 5, 9, 2, 6, 7])
        assert eng.prefix_hits == 1
        lost = eng.recover()
        assert lost == [rid]
        assert not eng.slots
        # prefix stripes are independent copies — they survive recovery
        # and keep accelerating admissions
        eng.add_request([3, 1, 4, 1, 5, 9, 2, 6, 8])
        assert eng.prefix_hits == 2
        # decode still works on the rebuilt cache
        for _ in range(4):
            eng.step()
        assert len(eng.slots[next(iter(eng.slots))].generated) >= 4
