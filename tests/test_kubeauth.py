"""GKE-grade auth for the real client + watch-expiry recovery.

The reference inherits exec-credential-plugin auth, rotating service-
account tokens, and 410-Gone watch recovery from client-go / controller-
runtime (/root/reference/go.mod:7,60). These tests prove the hand-rolled
client has the same behaviors: a fake exec plugin binary, an expiring-
token server, a bounded watch window, and the Manager synthesizing
DELETED events from a relist diff after a watch gap.
"""

import json
import os
import stat
import sys
import threading
import time

import pytest

from instaslice_tpu.kube import FakeKube
from instaslice_tpu.kube.client import ApiError, ResourceVersionExpired
from instaslice_tpu.kube.httptest import FakeApiServer
from instaslice_tpu.kube.real import RealKubeClient
from instaslice_tpu.utils.reconcile import Manager


def pod(name, ns="default"):
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": ns},
        "spec": {},
        "status": {},
    }


@pytest.fixture
def served():
    store = FakeKube()
    with FakeApiServer(store) as srv:
        yield srv, store


class TestTokenRefresh:
    def test_token_file_reread_on_401(self, served, tmp_path):
        srv, store = served
        accepted = {"tok": "v1"}
        srv.handler.token_validator = lambda t: t == accepted["tok"]
        tok_file = tmp_path / "token"
        tok_file.write_text("v1")
        c = RealKubeClient(srv.url, token_file=str(tok_file))
        c.create("Pod", pod("a"))

        # rotate: kubelet refreshes the projected token file; the old
        # token stops working. The client must re-read and retry.
        accepted["tok"] = "v2"
        tok_file.write_text("v2")
        assert c.get("Pod", "default", "a")["metadata"]["name"] == "a"

    def test_static_token_does_not_retry(self, served):
        srv, _ = served
        srv.handler.token_validator = lambda t: t == "good"
        c = RealKubeClient(srv.url, token="bad")
        with pytest.raises(ApiError) as ei:
            c.list("Pod", namespace="default")
        assert ei.value.code == 401


class TestExecPlugin:
    def _write_plugin(self, tmp_path, body: str) -> str:
        path = tmp_path / "fake-gke-auth-plugin.py"
        path.write_text("#!" + sys.executable + "\n" + body)
        path.chmod(path.stat().st_mode | stat.S_IEXEC)
        return str(path)

    def test_exec_credential_token(self, served, tmp_path):
        srv, _ = served
        srv.handler.token_validator = lambda t: t == "exec-tok"
        plugin = self._write_plugin(tmp_path, (
            "import json, os, sys\n"
            # plugins receive the request context via KUBERNETES_EXEC_INFO
            "info = json.loads(os.environ['KUBERNETES_EXEC_INFO'])\n"
            "assert info['kind'] == 'ExecCredential'\n"
            "json.dump({'apiVersion': info['apiVersion'],\n"
            "           'kind': 'ExecCredential',\n"
            "           'status': {'token': 'exec-tok'}}, sys.stdout)\n"
        ))
        c = RealKubeClient(
            srv.url,
            exec_config={
                "apiVersion": "client.authentication.k8s.io/v1",
                "command": sys.executable,
                "args": [plugin],
                "env": [{"name": "X_TEST", "value": "1"}],
            },
        )
        c.create("Pod", pod("a"))
        assert len(c.list("Pod", namespace="default")) == 1

    def test_exec_credential_rerun_on_401(self, served, tmp_path):
        """A cached exec token that the server starts rejecting (rotation)
        must trigger one plugin re-run and a transparent retry."""
        srv, _ = served
        # only the SECOND run's token is acceptable: the first request
        # gets 401 with et-1, re-runs the plugin, succeeds with et-2
        srv.handler.token_validator = lambda t: t == "et-2"
        count_file = tmp_path / "runs"
        plugin = self._write_plugin(tmp_path, (
            "import json, sys\n"
            f"p = {str(count_file)!r}\n"
            "try: n = int(open(p).read())\n"
            "except Exception: n = 0\n"
            "open(p, 'w').write(str(n + 1))\n"
            "json.dump({'kind': 'ExecCredential',\n"
            "           'status': {'token': 'et-%d' % (n + 1)}},\n"
            "          sys.stdout)\n"
        ))
        c = RealKubeClient(
            srv.url,
            exec_config={"command": sys.executable, "args": [plugin]},
        )
        c.create("Pod", pod("a"))
        assert int(count_file.read_text()) == 2
        # cached et-2 is reused — no third run
        c.get("Pod", "default", "a")
        assert int(count_file.read_text()) == 2

    def test_exec_plugin_failure_is_api_error(self, served, tmp_path):
        srv, _ = served
        plugin = self._write_plugin(
            tmp_path, "import sys; sys.exit(3)\n"
        )
        c = RealKubeClient(
            srv.url,
            exec_config={"command": sys.executable, "args": [plugin]},
        )
        with pytest.raises(ApiError, match="exec credential plugin"):
            c.list("Pod", namespace="default")


class TestWatchExpiry:
    def test_stale_rv_raises_resource_version_expired(self, served):
        srv, store = served
        store.create("Pod", pod("a"))
        srv.handler.min_watch_rv = 10_000
        c = RealKubeClient(srv.url)
        with pytest.raises(ResourceVersionExpired):
            list(c.watch("Pod", namespace="default", replay=False,
                         timeout=1.0, resource_version="1"))

    def test_fresh_list_then_watch_unaffected(self, served):
        srv, store = served
        store.create("Pod", pod("a"))
        srv.handler.min_watch_rv = 0  # everything current is fine
        c = RealKubeClient(srv.url)
        burst = list(c.watch("Pod", namespace="default", timeout=0.5))
        names = [o["metadata"].get("name") for e, o in burst
                 if e != "BOOKMARK"]
        assert "a" in names


class _ScriptedClient:
    """Watch script: burst {a,b} → gap (410) → relist shows only {a}.

    Models a real API server across a watch outage during which pod b was
    deleted: the deletion event fell out of the bounded window, so only a
    relist diff can reveal it.
    """

    preferred_watch_timeout = 0.05

    def __init__(self):
        self.calls = []
        self.a = pod("a")
        self.b = pod("b")

    def watch(self, kind, namespace=None, replay=True, timeout=None,
              resource_version=None):
        n = len(self.calls)
        self.calls.append((replay, resource_version))
        if n == 0:
            yield ("ADDED", self.a)
            yield ("ADDED", self.b)
            yield ("BOOKMARK", {"metadata": {"resourceVersion": "5"}})
        elif n == 1:
            raise ResourceVersionExpired("window passed")
        elif n == 2:
            # post-410 relist: b is gone and no DELETED event exists
            yield ("ADDED", self.a)
            yield ("BOOKMARK", {"metadata": {"resourceVersion": "9"}})
        else:
            yield ("BOOKMARK", {"metadata": {"resourceVersion": "9"}})
            time.sleep(0.02)


class TestManagerRelistDiff:
    def test_deleted_synthesized_after_410_gap(self):
        client = _ScriptedClient()
        seen = []
        lock = threading.Lock()

        def mapper(event, obj):
            with lock:
                seen.append((event, obj["metadata"]["name"]))
            return []

        mgr = Manager(
            "t", client, reconcile=lambda key: None,
            watches=[("Pod", None, mapper)],
            resync_period=300.0, error_backoff=0.01,
        )
        mgr.start()
        try:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                with lock:
                    if ("DELETED", "b") in seen:
                        break
                time.sleep(0.02)
            with lock:
                assert ("DELETED", "b") in seen, seen
                assert ("ADDED", "a") in seen
            # the post-410 establishment dropped the stale rv and relisted
            replay, rv = client.calls[2]
            assert replay is True
            assert rv is None
        finally:
            mgr.stop()

    def test_no_false_deletes_on_clean_resync(self):
        # same-store relist must NOT fire DELETED for objects still there
        client = _ScriptedClient()
        # rewrite script: every establishment is a full relist of {a}
        def watch(kind, namespace=None, replay=True, timeout=None,
                  resource_version=None):
            if replay:
                yield ("ADDED", client.a)
            yield ("BOOKMARK", {"metadata": {"resourceVersion": "3"}})
            time.sleep(0.02)
        client.watch = watch
        seen = []
        mgr = Manager(
            "t", client, reconcile=lambda key: None,
            watches=[("Pod", None,
                      lambda e, o: seen.append((e, o["metadata"]["name"]))
                      or [])],
            resync_period=0.05, error_backoff=0.01,
        )
        mgr.start()
        time.sleep(0.5)
        mgr.stop()
        assert ("DELETED", "a") not in seen


class TestTempCertCleanup:
    def test_kubeconfig_cert_tempfiles_deleted_on_close(self, tmp_path):
        import base64
        import yaml

        blob = base64.b64encode(b"not-a-real-pem").decode()
        cfg = {
            "current-context": "c",
            "contexts": [{"name": "c",
                          "context": {"cluster": "cl", "user": "u"}}],
            "clusters": [{"name": "cl", "cluster": {
                "server": "http://127.0.0.1:1",
                "certificate-authority-data": blob,
            }}],
            "users": [{"name": "u", "user": {
                "client-certificate-data": blob,
                "client-key-data": blob,
                "token": "t",
            }}],
        }
        path = tmp_path / "kubeconfig"
        path.write_text(yaml.safe_dump(cfg))
        c = RealKubeClient.from_kubeconfig(str(path))
        temps = list(c._temp_files)
        assert len(temps) == 3
        assert all(os.path.exists(p) for p in temps)
        c.close()
        assert not any(os.path.exists(p) for p in temps)
        c.close()  # idempotent
