"""slicecheck: the whole-program guarded-by + dispatch-hygiene gate.

Mirrors test_slicelint.py's contract: the seeded corpus under
``tests/check_fixtures/`` must flag with exact per-rule counts, the
clean and suppressed fixtures must pass, the CLI must exit 1 on
findings and 0 on clean — and the actual gate: the repo itself
(``instaslice_tpu`` + ``tools``) must be slicecheck-clean, with at
least a dozen real ``guarded_by`` declarations under verification.
"""

import json
import os
import subprocess
import sys
from collections import Counter

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXDIR = os.path.join(REPO, "tests", "check_fixtures")
SLICECHECK = os.path.join(REPO, "tools", "slicecheck.py")

sys.path.insert(0, os.path.join(REPO, "tools"))
import slicecheck  # noqa: E402
import slicelint  # noqa: E402


class TestSeededFixtures:
    @pytest.fixture(scope="class")
    def findings(self):
        return slicecheck.check_paths([FIXDIR])

    def test_every_rule_fires(self, findings):
        fired = {f.rule for f in findings}
        assert fired == set(slicecheck.RULES), (
            "rules that never fired on the seeded corpus: "
            f"{set(slicecheck.RULES) - fired}"
        )

    def test_exact_counts(self, findings):
        by_rule = Counter(f.rule for f in findings)
        assert by_rule == {
            "guarded-field": 2,       # lock-free write + lock-free read
            "undeclared-shared": 1,   # shared_log, no declaration
            "guard-unknown-lock": 1,  # fixture.ghost has no factory
            "unbalanced-pair": 1,     # raise between allocate/release
            "host-sync-in-loop": 3,   # .item + device_get + float(sum)
            "nonstatic-shape-arg": 1, # attend_len traced, not static
            "unbudgeted-jit": 2,      # _rogue + the unbound program
            "dead-reason": 1,         # REASON_DEAD
        }, dict(by_rule)

    def test_findings_carry_location(self, findings):
        for f in findings:
            assert f.path.startswith("tests/check_fixtures"), f.path
            assert f.line > 0 and f.col > 0
            assert f.rule in str(f) and f.path in str(f)

    def test_clean_and_suppressed_contribute_nothing(self, findings):
        flagged_files = {os.path.basename(f.path) for f in findings}
        assert "clean_module.py" not in flagged_files
        assert "suppressed.py" not in flagged_files
        assert "emitter.py" not in flagged_files


class TestCleanAndSuppressed:
    def test_clean_module_passes(self):
        assert slicecheck.check_paths(
            [os.path.join(FIXDIR, "clean_module.py")]
        ) == []

    def test_suppressed_module_passes(self):
        assert slicecheck.check_paths(
            [os.path.join(FIXDIR, "suppressed.py")]
        ) == []

    def test_suppression_is_per_rule(self, tmp_path):
        # a disable for one rule must not blanket-suppress another on
        # the same line (same grammar rule slicelint pins)
        p = tmp_path / "one.py"
        p.write_text(
            "from instaslice_tpu.utils.guards import guarded_by\n"
            "from instaslice_tpu.utils.lockcheck import named_lock\n"
            "class C:\n"
            '    f: guarded_by("tmp.lock")\n'
            "    def __init__(self):\n"
            '        self._lock = named_lock("tmp.lock")\n'
            "        self.f = 0\n"
            "    def bad(self):\n"
            "        self.f += 1  # slicecheck: disable=dead-reason\n"
        )
        found = slicecheck.check_paths([str(p)])
        assert [f.rule for f in found] == ["guarded-field"]

    def test_slicelint_grammar_does_not_leak_across_tools(self, tmp_path):
        # a slicelint: disable= comment must NOT silence slicecheck —
        # the two gates use distinct tags so one cannot mask the other
        p = tmp_path / "two.py"
        p.write_text(
            "from instaslice_tpu.utils.guards import guarded_by\n"
            "from instaslice_tpu.utils.lockcheck import named_lock\n"
            "class C:\n"
            '    f: guarded_by("tmp.lock2")\n'
            "    def __init__(self):\n"
            '        self._lock = named_lock("tmp.lock2")\n'
            "        self.f = 0\n"
            "    def bad(self):\n"
            "        self.f += 1  # slicelint: disable=guarded-field\n"
        )
        found = slicecheck.check_paths([str(p)])
        assert [f.rule for f in found] == ["guarded-field"]


class TestRepoGate:
    def test_repo_is_clean(self):
        findings = slicecheck.check_paths([
            os.path.join(REPO, "instaslice_tpu"),
            os.path.join(REPO, "tools"),
        ])
        assert findings == [], "\n" + "\n".join(str(f) for f in findings)

    def test_repo_declares_a_real_guard_surface(self):
        # the annotation pass is the point: the analyzed program must
        # carry a dozen-plus guarded_by declarations tied to factory-
        # registered lock names, spread across multiple subsystems
        checker = slicecheck.build_checker([
            os.path.join(REPO, "instaslice_tpu"),
        ])
        gmap = checker.guard_map()
        guarded = [
            (cls, fld)
            for cls, fields in gmap.items()
            for fld, d in fields.items()
            if d["lock"] is not None
        ]
        assert len(guarded) >= 12, guarded
        files = {cls.split(":")[0] for cls, _ in guarded}
        assert len(files) >= 5, files


class TestCli:
    def test_exit_nonzero_on_fixture_corpus(self):
        proc = subprocess.run(
            [sys.executable, SLICECHECK, FIXDIR],
            capture_output=True, text=True,
        )
        assert proc.returncode == 1
        assert "12 finding(s)" in proc.stderr
        assert "guarded-field" in proc.stdout

    def test_exit_zero_on_clean(self):
        proc = subprocess.run(
            [sys.executable, SLICECHECK,
             os.path.join(FIXDIR, "clean_module.py")],
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_list_rules(self):
        proc = subprocess.run(
            [sys.executable, SLICECHECK, "--list-rules"],
            capture_output=True, text=True,
        )
        assert proc.returncode == 0
        for rule in slicecheck.RULES:
            assert rule in proc.stdout

    def test_dump_guards_is_json(self):
        proc = subprocess.run(
            [sys.executable, SLICECHECK, "--dump-guards", FIXDIR],
            capture_output=True, text=True,
        )
        assert proc.returncode == 0
        gmap = json.loads(proc.stdout)
        racy = gmap["tests/check_fixtures/racy_class.py:RacyCounter"]
        assert racy["hits"]["lock"] == "fixture.racy"
        assert racy["noted"]["lock"] is None
        assert racy["noted"]["reason"]


class TestGuardsRuntime:
    def test_guards_of_reads_string_annotations(self):
        # PEP 563 leaves class-body declarations as source text; the
        # runtime view must still recover them for /v1/debug surfaces
        from instaslice_tpu.kube.informer import Informer
        from instaslice_tpu.utils.guards import guards_of

        g = guards_of(Informer)
        assert g["_store"]["lock"] == "kube.informer"
        assert g["_handlers"]["lock"] is None
        assert g["_handlers"]["reason"]

    def test_requires_is_introspectable(self):
        from instaslice_tpu.controller.reconciler import Controller
        from instaslice_tpu.utils.guards import requirement_of

        assert "controller.placement" in requirement_of(
            Controller._occupancy
        )
        assert requirement_of(lambda: None) == frozenset()

    def test_reads_racy_mode_validated(self):
        from instaslice_tpu.utils.guards import guarded_by

        assert guarded_by("x", reads="racy").reads == "racy"
        with pytest.raises(ValueError):
            guarded_by("x", reads="sometimes")


class TestDocDrift:
    def test_every_rule_documented(self):
        # the rule catalog in docs/STATIC_ANALYSIS.md must track BOTH
        # tools — a new rule lands with its documentation
        doc = open(os.path.join(REPO, "docs", "STATIC_ANALYSIS.md")).read()
        for rule in slicecheck.RULES:
            assert rule in doc, f"slicecheck rule {rule} missing from docs"
        for rule in slicelint.RULES:
            assert rule in doc, f"slicelint rule {rule} missing from docs"
