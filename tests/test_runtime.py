"""Process-runner tier: leader election, probes, and the full operator
stack (controller + agent) running through RealKubeClient over the
HTTP-served fake API — every wire hop a production deployment makes,
minus the kubelet."""

import threading
import time

import pytest

from instaslice_tpu import GATE_NAME
from instaslice_tpu.agent.runner import AgentRunner
from instaslice_tpu.controller.runner import ControllerRunner
from instaslice_tpu.device import FakeTpuBackend
from instaslice_tpu.kube import FakeKube
from instaslice_tpu.kube.httptest import FakeApiServer
from instaslice_tpu.kube.real import RealKubeClient
from instaslice_tpu.utils.election import LeaderElector
from instaslice_tpu.utils.probes import ProbeServer


class TestLeaderElection:
    def test_single_winner(self):
        k = FakeKube()
        a = LeaderElector(k, "ns", "lease", "a", lease_seconds=5)
        b = LeaderElector(k, "ns", "lease", "b", lease_seconds=5)
        assert a.acquire()
        stop = threading.Event()
        got_b = []
        t = threading.Thread(
            target=lambda: got_b.append(b.acquire(stop)), daemon=True
        )
        t.start()
        time.sleep(0.3)
        assert not got_b  # b waits while a holds
        stop.set()
        t.join(timeout=5)
        assert got_b == [False]

    def test_expired_lease_taken_over(self):
        k = FakeKube()
        a = LeaderElector(k, "ns", "lease", "a", lease_seconds=0.2)
        assert a.acquire()
        time.sleep(0.4)  # a never renews → expires
        b = LeaderElector(k, "ns", "lease", "b", lease_seconds=5,
                          retry_seconds=0.05)
        assert b.acquire()
        lease = k.get("Lease", "ns", "lease")
        assert lease["spec"]["holderIdentity"] == "b"
        assert lease["spec"]["leaseTransitions"] == 1

    def test_release_hands_over_immediately(self):
        k = FakeKube()
        a = LeaderElector(k, "ns", "lease", "a", lease_seconds=30)
        assert a.acquire()
        a.release()
        b = LeaderElector(k, "ns", "lease", "b", lease_seconds=30,
                          retry_seconds=0.05)
        assert b.acquire()  # would block 30s if release hadn't cleared

    def test_renew_loss_calls_on_lost(self):
        k = FakeKube()
        a = LeaderElector(k, "ns", "lease", "a", lease_seconds=0.3)
        assert a.acquire()
        lost = threading.Event()
        a.start_renewing(on_lost=lost.set)
        # usurp the lease: bump holder + renewTime far into the future
        lease = k.get("Lease", "ns", "lease")
        lease["spec"]["holderIdentity"] = "usurper"
        lease["spec"]["renewTime"] = time.time() + 1000
        k.update("Lease", lease)
        assert lost.wait(5)


class TestProbes:
    def test_healthz_and_readyz(self):
        import urllib.request

        ready = {"ok": False}
        srv = ProbeServer("127.0.0.1:0",
                          ready_check=lambda: ready["ok"]).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            assert urllib.request.urlopen(base + "/healthz").status == 200
            try:
                urllib.request.urlopen(base + "/readyz")
                assert False, "expected 503"
            except urllib.error.HTTPError as e:
                assert e.code == 503
            ready["ok"] = True
            assert urllib.request.urlopen(base + "/readyz").status == 200
        finally:
            srv.stop()


@pytest.fixture
def http_cluster():
    """Store + HTTP API + runners wired exactly like production."""
    store = FakeKube()
    store.create("Node", {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": "node-0", "namespace": ""},
        "status": {"capacity": {}, "allocatable": {}},
    })
    srv = FakeApiServer(store).start()
    controller = ControllerRunner(
        RealKubeClient(srv.url),
        deletion_grace_seconds=0.3,
        metrics_bind_address=":0",
        health_probe_bind_address="127.0.0.1:0",
        leader_elect=True,
    )
    agent = AgentRunner(
        RealKubeClient(srv.url),
        FakeTpuBackend(generation="v5e"),
        node_name="node-0",
        metrics_bind_address=":0",
        health_probe_bind_address="127.0.0.1:0",
    )
    threads = [
        threading.Thread(target=controller.run, daemon=True),
        threading.Thread(target=agent.run, daemon=True),
    ]
    for t in threads:
        t.start()
    yield store, srv
    controller.stop()
    agent.stop()
    for t in threads:
        t.join(timeout=10)
    srv.stop()


class TestFullStackOverHttp:
    def test_grant_lifecycle_through_real_wire(self, http_cluster):
        store, srv = http_cluster
        user = RealKubeClient(srv.url)
        user.create("Pod", {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {
                "name": "demo", "namespace": "default",
                "uid": "uid-demo",
                "annotations": {"tpu.instaslice.dev/profile": "v5e-2x2"},
            },
            "spec": {
                "schedulingGates": [{"name": GATE_NAME}],
                "containers": [{
                    "name": "m",
                    "resources": {
                        "limits": {"tpu.instaslice.dev/demo": "1"}
                    },
                }],
            },
            "status": {"phase": "Pending"},
        })
        # controller + agent converge: pod ungated, ConfigMap written,
        # node capacity patched — all through real HTTP
        deadline = time.monotonic() + 30
        ungated = False
        while time.monotonic() < deadline and not ungated:
            pod = user.get("Pod", "default", "demo")
            ungated = pod["spec"].get("schedulingGates") == []
            time.sleep(0.1)
        assert ungated, pod
        cm = user.get("ConfigMap", "default", "demo")
        assert cm["data"]["TPU_CHIPS_PER_HOST_BOUNDS"] == "2,2,1"
        node = user.get("Node", "", "node-0")
        assert node["status"]["capacity"]["tpu.instaslice.dev/demo"] == "1"
        # teardown through the same wire
        user.delete("Pod", "default", "demo")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                user.get("Pod", "default", "demo")
            except Exception:
                break
            time.sleep(0.1)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            allocs = {
                k: v
                for m in store.list("TpuSlice")
                for k, v in m["spec"].get("allocations", {}).items()
            }
            if not allocs:
                break
            time.sleep(0.1)
        assert allocs == {}


class TestMetricsBind:
    def test_split_bind_parses_host(self):
        from instaslice_tpu.controller.runner import _split_bind

        assert _split_bind(":8080") == ("", 8080)
        assert _split_bind("127.0.0.1:9090") == ("127.0.0.1", 9090)
        assert _split_bind("bogus") == ("", 0)

    def test_metrics_server_honors_localhost_bind(self):
        """The kube-rbac-proxy patch depends on a REAL 127.0.0.1 bind —
        an 0.0.0.0 listener would bypass the auth proxy entirely."""
        import socket
        import urllib.request

        from instaslice_tpu.metrics.metrics import (
            OperatorMetrics,
            start_metrics_server,
        )

        m = OperatorMetrics()
        if m.registry is None:
            pytest.skip("prometheus_client unavailable")
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        assert start_metrics_server(m, port, host="127.0.0.1")
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ).read()
        assert b"tpuslice" in body


class TestElectionFencing:
    """The handover race VERDICT flagged: a deposed leader's in-flight
    update_with_retry must not land after the new leader acts."""

    def _lease(self, kube, holder, renew_offset=0.0):
        from instaslice_tpu.utils.timeutil import rfc3339_now

        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": "tpuslice-controller-leader",
                         "namespace": "ns"},
            "spec": {"holderIdentity": holder,
                     "leaseDurationSeconds": 1,
                     "renewTime": rfc3339_now(),
                     "leaseTransitions": 0},
        }

    def test_fenced_write_raises_after_deposition(self):
        import pytest as _pytest

        from instaslice_tpu.kube import FakeKube
        from instaslice_tpu.kube.client import Fenced, update_with_retry
        from instaslice_tpu.utils.election import LeaderElector

        kube = FakeKube()
        a = LeaderElector(kube, "ns", "tpuslice-controller-leader", "A",
                          lease_seconds=1.0, retry_seconds=0.05)
        assert a.acquire()
        kube.create("Pod", {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "p", "namespace": "ns"}, "spec": {},
        })
        fence = a.is_leader.is_set

        def mut(obj):
            obj["spec"]["writer"] = "A"
            return obj

        # while leader: writes land
        update_with_retry(kube, "Pod", "ns", "p", mut, fence=fence)
        assert kube.get("Pod", "ns", "p")["spec"]["writer"] == "A"

        # deposed (what on_lost/renew-expiry does): writes refuse
        a.is_leader.clear()
        with _pytest.raises(Fenced):
            update_with_retry(kube, "Pod", "ns", "p", mut, fence=fence)

    def test_fence_rechecked_between_conflict_retries(self):
        """Deposition landing DURING the conflict-retry loop must stop
        the loop — this is the exact in-flight window of the race."""
        import pytest as _pytest

        from instaslice_tpu.kube import FakeKube
        from instaslice_tpu.kube.client import Fenced, update_with_retry

        kube = FakeKube()
        kube.create("Pod", {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "p", "namespace": "ns"}, "spec": {},
        })
        state = {"leader": True, "attempts": 0}

        def mut(obj):
            state["attempts"] += 1
            # new leader writes between our read and our update → our
            # update conflicts; deposition lands at the same time
            fresh = kube.get("Pod", "ns", "p")
            fresh["spec"]["writer"] = "B"
            kube.update("Pod", fresh)
            state["leader"] = False
            obj["spec"]["writer"] = "A-stale"
            return obj

        with _pytest.raises(Fenced):
            update_with_retry(
                kube, "Pod", "ns", "p", mut,
                fence=lambda: state["leader"],
            )
        assert state["attempts"] == 1  # no second attempt after deposition
        assert kube.get("Pod", "ns", "p")["spec"]["writer"] == "B"

    def test_handover_old_leader_steps_down_new_leader_writes(self):
        """Full handover: A expires, B acquires, A's renew loop reports
        lost, A's fence closes, B's writes proceed."""
        import time as _time

        from instaslice_tpu.kube import FakeKube
        from instaslice_tpu.utils.election import LeaderElector

        kube = FakeKube()
        a = LeaderElector(kube, "ns", "lease", "A",
                          lease_seconds=0.3, retry_seconds=0.02)
        b = LeaderElector(kube, "ns", "lease", "B",
                          lease_seconds=0.3, retry_seconds=0.02)
        assert a.acquire()
        lost = threading.Event()
        # stop A's renewals entirely (simulates a wedged process): the
        # lease expires, B takes it, A's loop reports loss
        a._stop.set()
        _time.sleep(0.4)
        assert b.acquire()
        b.start_renewing(on_lost=lambda: None)  # B must keep holding
        try:
            a._stop.clear()
            a.start_renewing(on_lost=lost.set)
            assert lost.wait(3.0), "old leader never noticed deposition"
            assert not a.is_leader.is_set()
            assert b.is_leader.is_set()
            lease = kube.get("Lease", "ns", "lease")
            assert lease["spec"]["holderIdentity"] == "B"
        finally:
            a._stop.set()
            b.release()


class TestAgentMainArgs:
    def test_backend_choices_reject_bad_kind(self):
        from instaslice_tpu.cli.agent_main import build_parser

        p = build_parser()
        with pytest.raises(SystemExit):
            p.parse_args(["--node-name", "n0", "--backend", "sysfs"])
        for kind in ("auto", "fake", "native", "cloudtpu"):
            assert p.parse_args(
                ["--node-name", "n0", "--backend", kind]
            ).backend == kind
