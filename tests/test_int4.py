"""Group-wise packed int4 weights (models/quant.py: Int4Tensor).

The capacity tier below int8: correctness bars are (1) pack/unpack is
a lossless round-trip of the int values, (2) dequantization error is
group-bounded, (3) the quantized model's full and cache forwards agree
(the serving invariant), (4) the engine serves an int4 model end to
end including TP-sharded, and (5) the int4 tree really is ~4× smaller
than bf16.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.models.quant import (
    Int4Tensor,
    quantize_params,
    quantize_tensor_int4,
)
from instaslice_tpu.serving import ServingEngine


class TestInt4Tensor:
    def test_pack_unpack_roundtrip_exact(self):
        """Every int in [-7, 7] survives pack→unpack bit-exactly, at
        every position parity."""
        w = jax.random.normal(jax.random.key(0), (64, 32), jnp.float32)
        qt = quantize_tensor_int4(w, group=32)
        u = qt._unpack()
        # reconstruct the reference quantized ints the same way the
        # quantizer did
        wg = w.astype(jnp.float32).reshape(2, 32, 32)
        amax = jnp.max(jnp.abs(wg), axis=1, keepdims=True)
        scale = jnp.maximum(amax, 1e-8) / 7.0
        ref = jnp.clip(jnp.round(wg / scale), -7, 7).astype(jnp.int32)
        np.testing.assert_array_equal(u, ref.reshape(64, 32))
        assert qt.p.dtype == jnp.uint8
        assert qt.p.shape == (32, 32)            # packed axis halved
        assert qt.s.shape == (2, 32)             # one scale per group

    def test_dequantize_error_group_bounded(self):
        w = jax.random.normal(jax.random.key(1), (256, 64), jnp.float32)
        qt = quantize_tensor_int4(w, group=128)
        err = jnp.abs(qt.dequantize(jnp.float32) - w)
        # per-group scale: error <= scale/2 per element
        wg = jnp.abs(w).reshape(2, 128, 64)
        bound = jnp.max(wg, axis=1, keepdims=True) / 7.0 / 2.0
        assert bool(jnp.all(err.reshape(2, 128, 64) <= bound + 1e-6))

    def test_embed_layout_last_axis(self):
        """The (vocab, d) table packs along d (reduce -1)."""
        from instaslice_tpu.models.quant import embed_lookup

        w = jax.random.normal(jax.random.key(2), (64, 32), jnp.float32)
        qt = quantize_tensor_int4(w, reduce_axis=-1, group=16)
        assert qt.p.shape == (64, 16)
        assert qt.s.shape == (64, 2)
        toks = jnp.array([[3, 9], [61, 0]])
        got = embed_lookup(qt, toks)
        want = qt.dequantize(jnp.float32)[toks]
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_pytree_roundtrip(self):
        qt = quantize_tensor_int4(jnp.ones((32, 8)), group=16)
        leaves, treedef = jax.tree.flatten(qt)
        assert len(leaves) == 2
        back = jax.tree.unflatten(treedef, leaves)
        assert isinstance(back, Int4Tensor)
        assert back.group == 16 and back.pack_axis == -2

    def test_odd_contraction_rejected(self):
        with pytest.raises(ValueError, match="even"):
            quantize_tensor_int4(jnp.ones((33, 8)), group=33)


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


class TestInt4Model:
    def test_quantize_params_bits4(self, model):
        _, params = model
        qp = quantize_params(params, bits=4, group=16)
        assert isinstance(qp["blocks"]["wq"], Int4Tensor)
        assert isinstance(qp["embed"], Int4Tensor)
        # norms stay full precision; idempotent
        assert not isinstance(qp["blocks"]["ln1"]["scale"], Int4Tensor)
        qp2 = quantize_params(qp, bits=4)
        assert qp2["blocks"]["wq"] is qp["blocks"]["wq"]

    def test_tree_is_4x_smaller_than_fp32_over_8x(self, model):
        """The capacity claim: packed int4 ≈ 1/8 the fp32 bytes (1/4
        of bf16), scales amortized away at group 16+."""
        _, params = model
        qp = quantize_params(params, bits=4, group=16)

        def nbytes(t):
            return sum(x.size * x.dtype.itemsize
                       for x in jax.tree.leaves(t))

        ratio = nbytes(qp) / nbytes(params)        # params are fp32
        assert ratio < 0.22, ratio                  # 1/8 + scale slack

    def test_logits_close_to_full_precision(self, model):
        m, params = model
        toks = jax.random.randint(jax.random.key(3), (2, 16), 0, 64)
        full = m.apply(params, toks)
        q4 = m.apply(quantize_params(params, bits=4, group=16), toks)
        rel = float(jnp.linalg.norm(q4 - full) / jnp.linalg.norm(full))
        # int4 is lossy and a tiny random d=32 model is its worst case
        # (no outlier structure for the group scales to exploit, logit
        # norm near zero); measured ~0.17 here vs int8's ~0.012 — the
        # bound catches packing/scale bugs (which blow past 1.0), not
        # quantization noise
        assert rel < 0.3, rel

    def test_cache_path_matches_full_forward(self, model):
        """The serving invariant under int4: same weights, two code
        paths, same logits."""
        m, params = model
        qp = quantize_params(params, bits=4, group=16)
        toks = jax.random.randint(jax.random.key(4), (2, 12), 0, 64)
        full = m.apply(qp, toks)
        cache = m.init_cache(2, 32)
        lengths = jnp.zeros(2, jnp.int32)
        lg, cache = m.apply_with_cache(qp, toks[:, :5], cache, lengths)
        assert float(jnp.abs(lg - full[:, :5]).max()) < 1e-4
        lengths = lengths + 5
        for t in range(5, 12):
            lg, cache = m.apply_with_cache(
                qp, toks[:, t:t + 1], cache, lengths
            )
            assert float(jnp.abs(lg[:, 0] - full[:, t]).max()) < 1e-4
            lengths = lengths + 1


class TestInt4Serving:
    def test_engine_serves_int4(self, model):
        m, params = model
        qp = quantize_params(params, bits=4, group=16)
        eng = ServingEngine(m, qp, max_batch=2, max_len=64,
                            prefill_len=8, kv_quant=True)
        rid = eng.add_request([5, 9, 2, 7])
        out = eng.decode_block(6)[rid]
        assert len(out) == 6 and all(0 <= t < 64 for t in out)

    def test_engine_tp_int4(self, model):
        """TP-sharded int4: the packed/group axis is masked from the
        spec, the output-channel shards still split."""
        from jax.sharding import Mesh

        m, params = model
        qp = quantize_params(params, bits=4, group=16)
        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("model",))
        eng = ServingEngine(m, qp, max_batch=2, max_len=64,
                            prefill_len=8, mesh=mesh)
        wq = eng.params["blocks"]["wq"]
        shard = next(iter(wq.p.addressable_shards))
        assert shard.data.shape[-1] == wq.p.shape[-1] // 2
        rid = eng.add_request([5, 9, 2, 7])
        out = eng.decode_block(6)[rid]
        assert len(out) == 6 and all(0 <= t < 64 for t in out)
