"""slicelint: the seeded-violation fixtures must flag, the clean fixture
must pass, suppressions must hold, and — the actual gate — the repo
itself must be clean (this test IS ``make lint`` inside the fast tier).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXDIR = os.path.join(REPO, "tests", "lint_fixtures")
SLICELINT = os.path.join(REPO, "tools", "slicelint.py")

sys.path.insert(0, os.path.join(REPO, "tools"))
import slicelint  # noqa: E402


def lint(name):
    return slicelint.lint_file(os.path.join(FIXDIR, name))


class TestSeededViolations:
    @pytest.fixture(scope="class")
    def findings(self):
        return lint("seeded_violations.py")

    def test_every_rule_fires(self, findings):
        fired = {f.rule for f in findings}
        assert fired == set(slicelint.RULES), (
            f"rules that never fired on the seeded fixture: "
            f"{set(slicelint.RULES) - fired}"
        )

    def test_expected_counts(self, findings):
        by_rule = {}
        for f in findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        assert by_rule == {
            "raw-http": 3,        # incl. the from-import alias
            "name-literal": 3,
            "broad-except": 3,    # incl. report only in a nested lambda
            "sleep-in-loop": 2,   # incl. the from-import alias
            "span-leak": 1,
            "mutable-default": 2,
            "raw-lock": 6,        # call + from-import alias + 2 bare
                                  # (uncalled) factory references;
                                  # annotations stay exempt
            "event-reason-literal": 2,  # journal.emit + emit_pod_event
        }, by_rule

    def test_findings_carry_location(self, findings):
        for f in findings:
            assert f.path.endswith("seeded_violations.py")
            assert f.line > 0 and f.col > 0
            assert f.rule in str(f) and f.path in str(f)


class TestCleanAndSuppressed:
    def test_clean_module_passes(self):
        assert lint("clean_module.py") == []

    def test_suppressions_honored(self):
        assert lint("suppressed.py") == []

    def test_suppression_is_per_rule(self, tmp_path):
        # a disable for one rule must not blanket-suppress another on
        # the same line
        p = tmp_path / "one.py"
        p.write_text(
            "import threading\n"
            "x = threading.Lock()  # slicelint: disable=broad-except\n"
        )
        found = slicelint.lint_file(str(p))
        assert [f.rule for f in found] == ["raw-lock"]

    def test_docstring_names_not_flagged(self, tmp_path):
        p = tmp_path / "doc.py"
        p.write_text('"""mentions tpu.instaslice.dev/profile in prose"""\n')
        assert slicelint.lint_file(str(p)) == []

    def test_span_leak_scoped_to_tracer_receivers(self, tmp_path):
        # re.Match.span() (any non-tracer receiver) is not a tracer span;
        # every tracer-shaped receiver must still be policed
        p = tmp_path / "spans.py"
        p.write_text(
            "def f(m, tracer, get_tracer, self):\n"
            "    ok = m.span()\n"
            "    bad1 = tracer.span('x')\n"
            "    bad2 = get_tracer().span('x')\n"
            "    bad3 = self.tracer.span('x')\n"
        )
        found = slicelint.lint_file(str(p))
        assert [f.rule for f in found] == ["span-leak"] * 3
        assert [f.line for f in found] == [3, 4, 5]

    def test_broad_except_ignores_nested_defs(self, tmp_path):
        # a raise inside a nested def runs later (if ever) — it cannot
        # discharge the handler's report-or-reraise duty; a direct
        # log call still does
        p = tmp_path / "nested.py"
        p.write_text(
            "def f(fn, cbs, log):\n"
            "    try:\n"
            "        return fn()\n"
            "    except Exception:\n"
            "        def later():\n"
            "            raise\n"
            "        cbs.append(later)\n"
            "    try:\n"
            "        return fn()\n"
            "    except Exception:\n"
            "        log.exception('ctx')\n"
        )
        found = slicelint.lint_file(str(p))
        assert [(f.rule, f.line) for f in found] == [("broad-except", 4)]


class TestRepoGate:
    def test_repo_is_clean(self):
        findings = slicelint.lint_paths([
            os.path.join(REPO, "instaslice_tpu"),
            os.path.join(REPO, "tools"),
        ])
        assert findings == [], "\n" + "\n".join(str(f) for f in findings)


class TestCli:
    def test_exit_nonzero_on_fixture(self):
        proc = subprocess.run(
            [sys.executable, SLICELINT,
             os.path.join(FIXDIR, "seeded_violations.py")],
            capture_output=True, text=True,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "raw-lock" in proc.stdout

    def test_exit_zero_on_clean(self):
        proc = subprocess.run(
            [sys.executable, SLICELINT,
             os.path.join(FIXDIR, "clean_module.py")],
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_list_rules(self):
        proc = subprocess.run(
            [sys.executable, SLICELINT, "--list-rules"],
            capture_output=True, text=True,
        )
        assert proc.returncode == 0
        for rule in slicelint.RULES:
            assert rule in proc.stdout
