"""slicelint test fixture: every violation suppressed inline.

Also carries a file-level suppression for mutable-default.
"""
# slicelint: disable-file=mutable-default

import threading
import time


def justified_catch_all(fn):
    try:
        return fn()
    except Exception:  # slicelint: disable=broad-except
        return None


def justified_sleep(stop):
    while not stop.is_set():
        time.sleep(0.5)  # slicelint: disable=sleep-in-loop


def justified_raw_lock():
    return threading.Lock()  # slicelint: disable=raw-lock


def file_level_suppressed(items=[]):
    return items
