"""slicelint test fixture: one (or more) seeded violations per rule.

NEVER imported — parsed by tests/test_slicelint.py, which asserts every
rule below fires at the marked line. This directory is deliberately
outside the ``make lint`` roots (instaslice_tpu + tools).
"""

import threading
import time
import urllib.request
from threading import Lock as _AliasedLock
from time import sleep as _aliased_sleep
from urllib.request import urlopen as _aliased_urlopen


def raw_http_violation(url):
    req = urllib.request.Request(url)          # raw-http
    return urllib.request.urlopen(req)         # raw-http


def raw_http_via_from_import(url):
    return _aliased_urlopen(url)               # raw-http (aliased)


def name_literal_violation(pod):
    ann = pod.get("annotations", {})
    profile = ann.get("tpu.instaslice.dev/profile")      # name-literal
    limit = pod.get("limits", {}).get("google.com/tpu")  # name-literal
    gate = "org.instaslice/accelarator"                  # name-literal
    return profile, limit, gate


def broad_except_violation(fn):
    try:
        return fn()
    except Exception:                          # broad-except
        return None


def bare_except_violation(fn):
    try:
        return fn()
    except:  # noqa: E722                      # broad-except (bare)
        return None


def broad_except_nested_report_violation(fn, callbacks):
    try:
        return fn()
    except Exception:                          # broad-except (log only
        # inside a nested lambda — deferred, maybe never run — cannot
        # satisfy the handler's report-or-reraise duty)
        callbacks.append(lambda: print("later"))
        return None


def sleep_in_loop_violation(stop):
    while not stop.is_set():
        time.sleep(0.5)                        # sleep-in-loop


def sleep_in_loop_via_from_import(stop):
    while not stop.is_set():
        _aliased_sleep(0.5)                    # sleep-in-loop (aliased)


def span_leak_violation(tracer):
    span = tracer.span("orphan")               # span-leak
    return span


def mutable_default_violation(items=[], index={}):   # mutable-default x2
    items.append(1)
    return items, index


def raw_lock_violation():
    lock = threading.Lock()                    # raw-lock
    cond = threading.Condition()               # raw-lock
    rlock = threading.RLock()                  # raw-lock
    return lock, cond, rlock


def raw_lock_via_from_import():
    return _AliasedLock()                      # raw-lock (aliased)


def raw_lock_bare_reference():
    # uncalled factory references manufacture raw locks at a distance
    make = threading.Lock                      # raw-lock (bare ref)
    pool = list(map(_AliasedLock, range(2)))   # raw-lock (bare aliased)
    return make, pool


def raw_lock_annotation_ok(lock: threading.Lock) -> threading.RLock:
    # naming the type is NOT making a lock: no finding here
    held: threading.Condition = lock
    return held


def event_reason_literal_violation(journal, client):
    journal.emit("controller", reason="MadeUpReason")   # event-reason-literal
    emit_pod_event(                            # event-reason-literal
        client, "ns", "pod", reason="AlsoMadeUp", message="x",
        component="controller",
    )
