"""slicelint test fixture: a module every rule must pass.

Mentions like ``tpu.instaslice.dev`` in prose (docstrings) are fine —
the name-literal rule only polices behavioral string literals.
"""

import logging
import re
import time

from instaslice_tpu.api.constants import PROFILE_ANNOTATION
from instaslice_tpu.utils.lockcheck import named_lock

log = logging.getLogger("lint-fixture")

_lock = named_lock("fixture.clean")


def profile_of(pod: dict):
    return (pod.get("metadata", {}).get("annotations") or {}).get(
        PROFILE_ANNOTATION
    )


def guarded(fn):
    try:
        return fn()
    except ValueError:
        return None
    except Exception:
        log.exception("fixture op failed")
        raise


def paced_loop(stop_event):
    while not stop_event.is_set():
        stop_event.wait(0.5)


def traced(tracer):
    with tracer.span("fixture.op") as sp:
        return sp


def one_shot_nap():
    time.sleep(0.01)  # not in a loop: allowed


def regex_span(pattern, text):
    m = re.match(pattern, text)
    # span-leak polices tracer spans; re.Match.span() is unrelated
    return m.span() if m else None
