"""Fused decode-attention kernel (ops/flash_decode.py) — opt-in.

Correctness bars: (1) kernel partials + local merge reproduce the
joint-softmax oracle over (prefix ‖ local) at per-row lengths,
including empty prefixes; (2) the layer index picks the right layer's
cache; (3) the opt-in gate default-off keeps the measured XLA path.
The in-situ perf verdict (kernel LOSES once the head-major layout let
XLA fuse the dequant reads) is recorded in docs/PERF.md.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from instaslice_tpu.ops.flash_decode import (
    decode_kernel_enabled,
    merge_local,
    quant_decode_attention,
)


def _mk_cache(L, B, Hkv, S, hd, seed=0):
    kk, kv = jax.random.split(jax.random.key(seed))
    k3 = jax.random.randint(kk, (L, B, Hkv, S, hd), -127, 128, jnp.int8)
    v3 = jax.random.randint(kv, (L, B, Hkv, S, hd), -127, 128, jnp.int8)
    ks3 = jax.random.uniform(kk, (L, B, Hkv, S), jnp.float32, 0.01, 0.1)
    vs3 = jax.random.uniform(kv, (L, B, Hkv, S), jnp.float32, 0.01, 0.1)
    return k3, ks3, v3, vs3


def _oracle(q4, k3, ks3, v3, vs3, lengths, li, lg_l, v_local):
    """Joint softmax over (dequantized prefix ‖ local entry), fp32."""
    sm = q4.shape[-1] ** -0.5
    k = k3[li].astype(jnp.float32) * ks3[li][..., None]
    v = v3[li].astype(jnp.float32) * vs3[li][..., None]
    s = jnp.einsum("bkgd,bksd->bkgs", q4.astype(jnp.float32) * sm, k)
    S = s.shape[-1]
    mask = jnp.arange(S)[None, None, None] < lengths[:, None, None, None]
    s = jnp.where(mask, s, -1e30)
    joint = jnp.concatenate([s, lg_l[..., None]], axis=-1)
    p = jax.nn.softmax(joint, axis=-1)
    return (jnp.einsum("bkgs,bksd->bkgd", p[..., :S], v)
            + p[..., S:] * v_local[:, :, None, :].astype(jnp.float32))


@pytest.mark.parametrize("li", [0, 2])
def test_matches_joint_softmax_oracle(li):
    L, B, Hkv, S, hd, G = 3, 4, 2, 256, 16, 2
    q4 = jax.random.normal(jax.random.key(1), (B, Hkv, G, hd))
    k3, ks3, v3, vs3 = _mk_cache(L, B, Hkv, S, hd)
    # staggered lengths, including an EMPTY prefix (row 0)
    lengths = jnp.array([0, 5, 100, 256], jnp.int32)
    k_loc = jax.random.normal(jax.random.key(2), (B, Hkv, hd))
    v_loc = jax.random.normal(jax.random.key(3), (B, Hkv, hd))
    sm = hd ** -0.5
    lg_l = jnp.einsum("bkgd,bkd->bkg", q4 * sm, k_loc)

    o, m, l = quant_decode_attention(
        q4, k3, ks3, v3, vs3, lengths, jnp.int32(li), S
    )
    got = merge_local(o, m, l, lg_l, v_loc)
    want = _oracle(q4, k3, ks3, v3, vs3, lengths, li, lg_l, v_loc)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_prefix_bound_reads_only_s_attn():
    """s_attn bounds the attended prefix: entries beyond it must not
    influence the result even when lengths would admit them."""
    L, B, Hkv, S, hd = 1, 2, 2, 512, 16
    q4 = jax.random.normal(jax.random.key(4), (B, Hkv, 2, hd))
    k3, ks3, v3, vs3 = _mk_cache(L, B, Hkv, S, hd, seed=5)
    lengths = jnp.array([200, 256], jnp.int32)
    out_full = quant_decode_attention(
        q4, k3, ks3, v3, vs3, lengths, jnp.int32(0), 512)
    out_bound = quant_decode_attention(
        q4, k3, ks3, v3, vs3, lengths, jnp.int32(0), 256)
    # lengths <= 256, so bounding to 256 changes nothing
    for a, b in zip(out_full, out_bound):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)


def test_opt_in_gate_defaults_off(monkeypatch):
    monkeypatch.delenv("TPUSLICE_DECODE_KERNEL", raising=False)
    assert decode_kernel_enabled() is False
    monkeypatch.setenv("TPUSLICE_DECODE_KERNEL", "1")
    assert decode_kernel_enabled() is True
