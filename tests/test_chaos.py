"""Chaos/fuzz test: random concurrent operations against the simulated
cluster, then invariant checks.

SURVEY.md §5 "Race detection": the reference runs `go test` without -race
and leaves its controller↔daemonset seam untested under concurrency. This
tier hammers the full state machine with randomized submissions,
deletions, device-failure injection, and chip failures/heals, then
asserts the system converged to a consistent state: no chip double-grant,
no leaked reservations, every surviving pod either Running or Pending,
and a clean sweep after deleting everything.
"""

import os
import random
import time

import pytest

from instaslice_tpu.controller.gates import RESTART_ON_FAILURE_ANNOTATION
from instaslice_tpu.sim import SimCluster

PROFILES = ["v5e-1x1", "v5e-2x1", "v5e-2x2"]
# Parametrized via env so `make chaos` sweeps seeds and a red run is
# reproducible with CHAOS_SEED=<printed seed>.
SEED = int(os.environ.get("CHAOS_SEED", "1234"))
DURATION_S = float(os.environ.get("CHAOS_DURATION", "8.0"))


@pytest.fixture(autouse=True)
def _print_chaos_params():
    # pytest surfaces captured stdout only for FAILING tests, so this
    # line is exactly the repro recipe a red chaos run needs
    print(f"chaos params: CHAOS_SEED={SEED} CHAOS_DURATION={DURATION_S}")
    yield


def _no_double_grant(cluster):
    for node, backend in cluster.backends.items():
        claimed = [c for r in backend.list_reservations()
                   for c in r.chip_ids]
        assert len(claimed) == len(set(claimed)), (
            f"{node}: chip double-granted: {sorted(claimed)}"
        )


@pytest.mark.slow
class TestChaos:
    def test_randomized_ops_converge(self):
        rng = random.Random(SEED)
        c = SimCluster(n_nodes=2, generation="v5e", shared_torus=True,
                       deletion_grace_seconds=0.1,
                       health_interval=0.1).start()
        try:
            live = []
            n = 0
            deadline = time.monotonic() + DURATION_S
            while time.monotonic() < deadline:
                op = rng.random()
                if op < 0.45:
                    name = f"c{n}"
                    n += 1
                    ann = (
                        {RESTART_ON_FAILURE_ANNOTATION: "true"}
                        if rng.random() < 0.3 else None
                    )
                    c.submit(name, rng.choice(PROFILES), annotations=ann)
                    live.append(name)
                elif op < 0.70 and live:
                    victim = live.pop(rng.randrange(len(live)))
                    try:
                        c.delete_pod(victim)
                    except Exception:
                        pass
                elif op < 0.80:
                    node = rng.choice(list(c.backends))
                    c.backends[node].inject_failures(
                        rng.choice(["reserve", "release"]), 1
                    )
                elif op < 0.90:
                    node = rng.choice(list(c.backends))
                    chip = rng.randrange(8)
                    c.backends[node].fail_chip(chip)
                else:
                    for b in c.backends.values():
                        for chip in range(8):
                            b.heal_chip(chip)
                _no_double_grant(c)
                time.sleep(rng.uniform(0.0, 0.05))

            # heal everything and let the dust settle: every surviving pod
            # must converge to Running or stay Pending (capacity), never
            # wedge in a half-granted state. "Settled" = the phase map is
            # unchanged across consecutive polls; then we ASSERT on it.
            for b in c.backends.values():
                for chip in range(8):
                    b.heal_chip(chip)
            deadline = time.monotonic() + 20
            prev, stable = None, 0
            phases = {}
            while time.monotonic() < deadline:
                _no_double_grant(c)
                phases = {p: c.pod_phase(p) for p in live}
                stable = stable + 1 if phases == prev else 0
                prev = phases
                if stable >= 5 and not any(
                    ph == "Pending" for ph in phases.values()
                ):
                    break
                time.sleep(0.2)
            bad = {p: ph for p, ph in phases.items()
                   if ph not in ("Running", "Pending", "Gone")}
            assert not bad, f"pods wedged mid-grant after settle: {bad}"

            # drain: delete everything, expect full cleanup
            for name in live:
                try:
                    c.delete_pod(name)
                except Exception:
                    pass
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                leftover = sum(
                    len(b.list_reservations())
                    for b in c.backends.values()
                )
                if not c.allocations() and leftover == 0:
                    break
                time.sleep(0.2)
            assert c.allocations() == {}, c.allocations()
            for node, b in c.backends.items():
                assert b.list_reservations() == [], node
        finally:
            c.stop()

    def test_randomized_ops_converge_cloudtpu(self):
        """The same hammering through the Cloud TPU queued-resources
        wire path (real HTTP to per-node mock APIs): randomized
        submissions, deletions, and injected FAILED provisioning. The
        cloud is the durable registry, so the invariants read IT — no
        chip double-reserved server-side, full drain leaves no queued
        resources behind."""
        rng = random.Random(SEED + 1)
        c = SimCluster(n_nodes=2, generation="v5e", shared_torus=True,
                       deletion_grace_seconds=0.1,
                       health_interval=0.1,
                       backend="cloudtpu").start()
        try:
            live = []
            n = 0
            deadline = time.monotonic() + DURATION_S
            while time.monotonic() < deadline:
                op = rng.random()
                if op < 0.5:
                    name = f"q{n}"
                    n += 1
                    c.submit(name, rng.choice(PROFILES))
                    live.append(name)
                elif op < 0.75 and live:
                    victim = live.pop(rng.randrange(len(live)))
                    try:
                        c.delete_pod(victim)
                    except Exception:
                        pass
                else:
                    node = rng.choice(list(c.mock_servers))
                    c.mock_servers[node].fail_next_create(1)
                _no_double_grant(c)
                time.sleep(rng.uniform(0.0, 0.05))

            deadline = time.monotonic() + 25
            prev, stable = None, 0
            phases = {}
            while time.monotonic() < deadline:
                _no_double_grant(c)
                phases = {p: c.pod_phase(p) for p in live}
                stable = stable + 1 if phases == prev else 0
                prev = phases
                if stable >= 5 and not any(
                    ph == "Pending" for ph in phases.values()
                ):
                    break
                time.sleep(0.2)
            bad = {p: ph for p, ph in phases.items()
                   if ph not in ("Running", "Pending", "Gone")}
            assert not bad, f"pods wedged mid-grant after settle: {bad}"

            for name in live:
                try:
                    c.delete_pod(name)
                except Exception:
                    pass
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                leftover = sum(
                    len(b.list_reservations())
                    for b in c.backends.values()
                )
                if not c.allocations() and leftover == 0:
                    break
                time.sleep(0.2)
            assert c.allocations() == {}, c.allocations()
            for node, b in c.backends.items():
                assert b.list_reservations() == [], node
        finally:
            c.stop()
