"""Serving engine tests: KV-cache decode correctness against the full
forward, slot-based continuous batching, eos/max-len lifecycle."""

import jax
import jax.numpy as jnp
import pytest

from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.serving import ServingEngine


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


def greedy_reference(model, params, prompt, n_new):
    """Re-run the FULL forward for every generated token (O(n²) oracle)."""
    toks = list(prompt)
    out = []
    for _ in range(n_new):
        logits = model.apply(params, jnp.asarray(toks, jnp.int32)[None])
        t = int(jnp.argmax(logits[0, -1]))
        out.append(t)
        toks.append(t)
    return out


class TestCacheDecodeCorrectness:
    def test_incremental_matches_full_forward(self, model):
        m, params = model
        toks = jax.random.randint(jax.random.key(1), (2, 12), 0, 64)
        full = m.apply(params, toks)
        cache = m.init_cache(2, 32)
        lengths = jnp.zeros(2, jnp.int32)
        lg, cache = m.apply_with_cache(params, toks[:, :5], cache, lengths)
        assert float(jnp.abs(lg - full[:, :5]).max()) < 1e-4
        lengths = lengths + 5
        for t in range(5, 12):
            lg, cache = m.apply_with_cache(
                params, toks[:, t:t + 1], cache, lengths
            )
            assert float(jnp.abs(lg[:, 0] - full[:, t]).max()) < 1e-4
            lengths = lengths + 1


class TestEngine:
    def test_greedy_generation_matches_oracle(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16)
        prompt = [5, 9, 2, 7]
        [res] = eng.generate([prompt], max_new_tokens=8)
        assert res.tokens == greedy_reference(m, params, prompt, 8)

    def test_continuous_batching_ragged_prompts(self, model):
        """Prompts of different lengths share the rectangular batch; each
        must match its solo oracle."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=16)
        prompts = [[3], [1, 2, 3, 4, 5, 6, 7], [9, 8], [4, 4, 4, 4]]
        results = eng.generate(prompts, max_new_tokens=6)
        assert len(results) == 4
        for p, r in zip(prompts, results):
            assert r.tokens == greedy_reference(m, params, p, 6), p

    def test_more_prompts_than_slots(self, model):
        """Continuous batching: 5 prompts through 2 slots."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        prompts = [[i + 1, i + 2] for i in range(5)]
        results = eng.generate(prompts, max_new_tokens=4)
        assert len(results) == 5
        for p, r in zip(prompts, results):
            assert r.tokens == greedy_reference(m, params, p, 4), p

    def test_eos_frees_slot(self, model):
        m, params = model
        prompt = [5, 9, 2, 7]
        eos = greedy_reference(m, params, prompt, 3)[2]
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8, eos_id=eos)
        [res] = eng.generate([prompt], max_new_tokens=10)
        assert res.finished_reason == "eos"
        assert res.tokens[-1] == eos and len(res.tokens) <= 3
        assert eng.free_slots() == 1

    def test_chunked_prefill_matches_oracle(self, model):
        """A prompt 4× prefill_len is admitted (chunked) and generates
        exactly what the full-forward oracle does."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=4)
        prompt = list(jax.random.randint(
            jax.random.key(7), (16,), 1, 64
        ))
        prompt = [int(t) for t in prompt]
        assert len(prompt) == 4 * eng.prefill_len
        [res] = eng.generate([prompt], max_new_tokens=6)
        assert res.tokens == greedy_reference(m, params, prompt, 6)

    def test_chunked_prefill_partial_last_chunk(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=4)
        for n in (5, 7, 8, 9):
            prompt = [(i % 63) + 1 for i in range(n)]
            [res] = eng.generate([prompt], max_new_tokens=4)
            assert res.tokens == greedy_reference(m, params, prompt, 4), n

    def test_prompt_exceeding_cache_rejected(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=16,
                            prefill_len=4)
        with pytest.raises(ValueError, match="max_len"):
            eng.add_request([1] * 16)

    def test_generate_tolerates_preexisting_slots(self, model):
        """A slot admitted via add_request() before generate() must not
        crash the budget enforcement, and its result must stay harvestable
        by its owner instead of being discarded."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=24,
                            prefill_len=8)
        foreign = eng.add_request([3, 1, 4])
        results = eng.generate([[2, 7]], max_new_tokens=4)
        assert len(results) == 1
        assert results[0].tokens == greedy_reference(m, params, [2, 7], 4)
        # the foreign request was NOT budget-killed or discarded: it is
        # still live (generate returns once its own requests finish) with
        # its progress intact, or finished on its own terms
        live = [s for s in eng.slots.values() if s.request_id == foreign]
        done = [r for r in eng.finished if r.request_id == foreign]
        assert live or done, (eng.finished, eng.slots)
        if live:
            assert len(live[0].generated) >= 4  # kept decoding alongside

    def test_throughput_positive(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=128,
                            prefill_len=8)
        assert eng.throughput(n_steps=5) > 0


class TestBlockDecode:
    """decode_block: the on-device lax.scan decode loop must be
    token-identical to the per-step path (same greedy argmax chain)."""

    def test_block_matches_stepwise(self, model):
        m, params = model
        prompt = [5, 9, 2, 7]
        eng_a = ServingEngine(m, params, max_batch=2, max_len=64,
                              prefill_len=16)
        eng_b = ServingEngine(m, params, max_batch=2, max_len=64,
                              prefill_len=16)
        rid_a = eng_a.add_request(prompt)
        rid_b = eng_b.add_request(prompt)
        step_toks = []
        for _ in range(6):
            step_toks.append(eng_a.step()[rid_a])
        block = eng_b.decode_block(6)[rid_b]
        assert block == step_toks
        assert block[:3] == greedy_reference(m, params, prompt, 7)[1:4]

    def test_block_eos_truncates_and_finishes(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8)
        rid = eng.add_request([5, 9, 2, 7])
        first_tok = next(iter(eng.slots.values())).generated[0]
        # the tiny fixture model greedily repeats its last token, so every
        # block token equals add_request's sample; arm eos AFTER admission
        # (the engine reads it per block) to hit mid-block truncation
        # deterministically: the block's first token must cut it
        eng.eos_id = first_tok
        out = eng.decode_block(5)[rid]
        assert out == [first_tok]                   # truncated at eos
        assert not eng.slots                        # slot freed
        assert eng.finished[-1].finished_reason == "eos"

    def test_block_overrun_rejected(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=16,
                            prefill_len=8)
        eng.add_request([1, 2, 3, 4])
        with pytest.raises(ValueError, match="overrun"):
            eng.decode_block(64)


class TestTensorParallelServing:
    """mesh= engine: weights + KV cache sharded over the 'model' axis;
    tokens must match the single-device engine exactly (same programs,
    different layout)."""

    def _mesh(self, n):
        import numpy as np
        from jax.sharding import Mesh

        return Mesh(np.array(jax.devices()[:n]).reshape(n), ("model",))

    def test_tp_matches_single_device_tokens(self, model):
        m, params = model
        mesh = self._mesh(2)  # n_heads=2 shards over 2 devices
        eng_tp = ServingEngine(m, params, max_batch=2, max_len=64,
                               prefill_len=16, mesh=mesh)
        prompt = [5, 9, 2, 7]
        rid = eng_tp.add_request(prompt)
        got = eng_tp.decode_block(6)[rid]
        assert got == greedy_reference(m, params, prompt, 7)[1:7]

    def test_tp_4dev_generate(self):
        import numpy as np
        from jax.sharding import Mesh

        cfg = ModelConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            dtype=jnp.float32, remat=False,
        )
        m = TpuLM(cfg)
        params = m.init(jax.random.key(0))
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("model",))
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, mesh=mesh)
        [res] = eng.generate([[3, 1, 4, 1, 5]], max_new_tokens=6)
        assert res.tokens == greedy_reference(m, params, [3, 1, 4, 1, 5], 6)

    def test_tp_params_actually_sharded(self, model):
        m, params = model
        mesh = self._mesh(2)
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16, mesh=mesh)
        wq = eng.params["blocks"]["wq"]
        shard = next(iter(wq.addressable_shards))
        assert shard.data.shape[-1] == wq.shape[-1] // 2  # heads split
        kc = eng.cache["k"]
        kshard = next(iter(kc.addressable_shards))
        # head-major cache: heads at axis 2
        assert kshard.data.shape[2] == kc.shape[2] // 2   # cache H split

    def test_tp_rejects_mesh_without_model_axis(self, model):
        import numpy as np
        from jax.sharding import Mesh

        m, params = model
        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("data",))
        with pytest.raises(ValueError, match="model"):
            ServingEngine(m, params, mesh=mesh)

    def test_tp_rejects_indivisible_heads(self, model):
        m, params = model  # n_heads=2
        mesh = self._mesh(4)
        with pytest.raises(ValueError, match="divisible"):
            ServingEngine(m, params, mesh=mesh)


class TestSpeculativeDecoding:
    """Greedy speculative decoding: draft k, verify in one target pass,
    emit the agreeing prefix + the target's own token. The hard
    property: token-IDENTICAL to plain greedy decode for any draft."""

    def _draft(self):
        cfg = ModelConfig(
            vocab_size=64, d_model=16, n_heads=2, n_layers=1, d_ff=32,
            dtype=jnp.float32, remat=False,
        )
        m = TpuLM(cfg)
        return m, m.init(jax.random.key(7))

    def test_lossless_vs_plain_greedy(self, model):
        m, params = model
        dm, dp = self._draft()
        plain = ServingEngine(m, params, max_batch=2, max_len=64,
                              prefill_len=8)
        rref = plain.add_request([5, 9, 2, 7])
        ref = plain.decode_block(12)[rref]
        spec = ServingEngine(m, params, max_batch=2, max_len=64,
                             prefill_len=8, draft_model=dm,
                             draft_params=dp, spec_k=4)
        rid = spec.add_request([5, 9, 2, 7])
        got = []
        while len(got) < 12:
            got.extend(spec.spec_step()[rid])
        assert got[:12] == ref

    def test_self_draft_accepts_k_plus_one(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8, draft_model=m,
                            draft_params=params, spec_k=4)
        rid = eng.add_request([5, 9, 2, 7])
        assert len(eng.spec_step()[rid]) == 5   # all k accepted + bonus

    def test_quantized_self_draft_lossless(self, model):
        """The classic deployment: the draft is the target's own int8
        quantization — high acceptance, still token-identical output."""
        from instaslice_tpu.models.quant import quantize_params

        m, params = model
        plain = ServingEngine(m, params, max_batch=1, max_len=64,
                              prefill_len=8)
        rref = plain.add_request([9, 3, 1])
        ref = plain.decode_block(12)[rref]
        spec = ServingEngine(m, params, max_batch=1, max_len=64,
                             prefill_len=8, draft_model=m,
                             draft_params=quantize_params(params),
                             spec_k=4)
        rid = spec.add_request([9, 3, 1])
        got = []
        while len(got) < 12:
            got.extend(spec.spec_step()[rid])
        assert got[:12] == ref

    def test_multi_slot_ragged_acceptance(self, model):
        """Slots at different depths with different acceptance counts
        must each stay on their own greedy chain."""
        m, params = model
        dm, dp = self._draft()
        plain = ServingEngine(m, params, max_batch=2, max_len=64,
                              prefill_len=8)
        ra = plain.add_request([5, 9, 2, 7])
        rb = plain.add_request([11, 4])
        ref = {r: toks for r, toks in (
            (ra, []), (rb, []),
        )}
        for _ in range(10):
            for r, t in plain.step().items():
                ref[r].append(t)
        spec = ServingEngine(m, params, max_batch=2, max_len=64,
                             prefill_len=8, draft_model=dm,
                             draft_params=dp, spec_k=3)
        sa = spec.add_request([5, 9, 2, 7])
        sb = spec.add_request([11, 4])
        got = {sa: [], sb: []}
        while len(got[sa]) < 10 or len(got[sb]) < 10:
            for r, seq in spec.spec_step().items():
                got[r].extend(seq)
        assert got[sa][:10] == ref[ra]
        assert got[sb][:10] == ref[rb]

    def test_requires_draft(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=32,
                            prefill_len=8)
        with pytest.raises(RuntimeError, match="draft_model"):
            eng.spec_step()
        # temperature > 0 + draft is now ALLOWED (rejection sampling —
        # tests/test_spec_decode.py pins distribution identity); what
        # stays rejected is a nonsensical spec_k
        with pytest.raises(ValueError, match="spec_k"):
            ServingEngine(m, params, draft_model=m,
                          draft_params=params, spec_k=0)

    def test_k_shrinks_near_cache_end_and_drains(self, model):
        """Near max_len, k shrinks (down to a plain greedy step) so the
        slot drains to its max_len finish through spec_step alone — and
        the tokens still match the plain engine's chain."""
        m, params = model
        prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10]
        plain = ServingEngine(m, params, max_batch=1, max_len=16,
                              prefill_len=8)
        rp = plain.add_request(prompt)
        ref = [plain.slots[0].generated[0]]
        while plain.slots:
            ref.extend(plain.step().values())
        spec = ServingEngine(m, params, max_batch=1, max_len=16,
                             prefill_len=8, draft_model=m,
                             draft_params=params, spec_k=8)
        spec.add_request(prompt)
        got = [spec.slots[0].generated[0]]
        for _ in range(32):
            if not spec.slots:
                break
            for seq in spec.spec_step().values():
                got.extend(seq)
        assert not spec.slots, "slot never drained to max_len"
        assert spec.finished[-1].finished_reason == "max_len"
        assert got == ref

    def test_mixed_step_and_spec_keeps_draft_cache_whole(self, model):
        """Plain step()/decode_block() on a draft-enabled engine must
        teacher-force the draft cache, so a later spec_step still
        proposes from a complete prefix (self-draft: full acceptance
        proves no holes)."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8, draft_model=m,
                            draft_params=params, spec_k=3)
        rid = eng.add_request([5, 9, 2, 7])
        eng.step()
        eng.decode_block(4)
        out = eng.spec_step()[rid]
        assert len(out) == 4     # k accepted + bonus: cache had no holes
        plain = ServingEngine(m, params, max_batch=1, max_len=64,
                              prefill_len=8)
        rp = plain.add_request([5, 9, 2, 7])
        ref = plain.decode_block(10)[rp]
        assert eng.slots[0].generated[1:] == ref[:len(
            eng.slots[0].generated) - 1]


class TestBoundedAttentionWindow:
    """decode_block buckets the attended cache window to the live
    prefix (decode HBM traffic is dominated by the cache stream); the
    tokens must be bit-identical to full-window attention."""

    def test_bucketed_matches_full_window(self, model):
        m, params = model
        # max_len 512 with shallow slots → bucket 256 < 512 (the sliced
        # path); the default test engines (max_len 64) never slice
        full = ServingEngine(m, params, max_batch=2, max_len=512,
                             prefill_len=8)
        sliced = ServingEngine(m, params, max_batch=2, max_len=512,
                               prefill_len=8)
        rf = full.add_request([5, 9, 2, 7])
        rs = sliced.add_request([5, 9, 2, 7])
        # force the full-window variant by monkey-free means: call the
        # jitted impl directly with attend_len=0
        import jax.numpy as jnp

        full.cache, full.last_token, full.lengths, _, toks, _ = (
            full._decode_block(
                full.params, full.cache, full.last_token, full.lengths,
                jax.random.key(0), jnp.float32(1e-6),
                jnp.zeros((2, 1), jnp.bool_), jnp.float32(1.0),
                full.slot_adapter,
                n_steps=10, greedy=True, attend_len=0,
            )
        )
        ref = [int(t) for t in jax.device_get(toks)[:, 0]]
        # spy that the sliced engine REALLY buckets (this exact plumbing
        # once silently no-opped — the window must not regress to dead
        # code that trivially equals the full path)
        seen = {}
        orig = sliced._decode_block

        def spy(*a, **kw):
            seen.update(kw)
            return orig(*a, **kw)

        sliced._decode_block = spy
        got = sliced.decode_block(10)[rs]        # bucketed internally
        assert seen.get("attend_len") == 256, seen
        assert got == ref

    def test_quant_cache_bucketed(self, model):
        m, params = model
        a = ServingEngine(m, params, max_batch=1, max_len=512,
                          prefill_len=8, kv_quant=True)
        b = ServingEngine(m, params, max_batch=1, max_len=64,
                          prefill_len=8, kv_quant=True)
        ra, rb = a.add_request([9, 3, 1]), b.add_request([9, 3, 1])
        assert a.decode_block(8)[ra] == b.decode_block(8)[rb]


def first_match(seq, sub):
    """Earliest start index of ``sub`` in ``seq`` (test oracle for stop
    semantics; also imported by test_api_server)."""
    for i in range(len(seq) - len(sub) + 1):
        if seq[i:i + len(sub)] == sub:
            return i
    raise AssertionError("stop not in oracle")


class TestParallelSampling:
    def test_greedy_forks_match_single_chain(self, model):
        """n=3 greedy: the forked KV stripes must attend exactly like
        the prefilled original — every fork reproduces the oracle."""
        m, params = model
        oracle = greedy_reference(m, params, [5, 9, 2, 7], 6)
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=16)
        rids = eng.add_request_n([5, 9, 2, 7], 3)
        assert len(rids) == 3 and len(eng.slots) == 3
        eng.decode_block(5)
        for req in eng.slots.values():
            assert req.generated == oracle

    @pytest.mark.xfail(
        strict=False,
        reason="environment-bound (known set, not a regression): under "
               "jax 0.4.x CPU this fixture model's next-token "
               "distribution degenerates to ~one-hot, so even "
               "temperature-2 Gumbel noise cannot make the forks "
               "diverge over a 6-token horizon",
    )
    def test_sampled_forks_diverge(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=16, temperature=2.0, seed=7)
        eng.add_request_n([5, 9, 2, 7], 4)
        eng.decode_block(6)
        chains = [tuple(r.generated) for r in eng.slots.values()]
        # independent Gumbel noise per row: at temperature 2 over a
        # 64-token vocab, four identical chains would mean the forks
        # share their randomness (the bug this test pins)
        assert len(set(chains)) > 1

    def test_capacity_all_or_nothing(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16)
        eng.add_request([1, 2])
        with pytest.raises(RuntimeError, match="free slots"):
            eng.add_request_n([5, 9], 2)
        assert len(eng.slots) == 1             # nothing admitted

    def test_forks_with_prefix_cache(self, model):
        m, params = model
        prefix = list(range(1, 17))
        prompt = prefix + [40, 41]
        oracle = greedy_reference(m, params, prompt, 5)
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=16)
        eng.register_prefix(prefix)
        eng.add_request_n(prompt, 2)
        assert eng.prefix_hits == 1            # prefilled once, forked
        eng.decode_block(4)
        for req in eng.slots.values():
            assert req.generated == oracle


class TestLogprobs:
    def oracle_logprobs(self, model, params, prompt, tokens):
        """log p(token_i | prompt + tokens[:i]) from the full forward."""
        out = []
        ctx = list(prompt)
        for t in tokens:
            logits = model.apply(params, jnp.asarray(ctx, jnp.int32)[None])
            lp = jax.nn.log_softmax(logits[0, -1])
            out.append(float(lp[t]))
            ctx.append(t)
        return out

    def test_block_decode_logprobs_match_full_forward(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16)
        [res] = eng.generate([[5, 9, 2, 7]], max_new_tokens=8,
                             block_size=4)
        assert len(res.logprobs) == len(res.tokens) == 8
        want = self.oracle_logprobs(m, params, [5, 9, 2, 7], res.tokens)
        assert res.logprobs == pytest.approx(want, abs=1e-3)

    def test_stepwise_and_block_logprobs_agree(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=16)
        eng.add_request([5, 9, 2, 7])
        for _ in range(5):
            eng.step()
        req = next(iter(eng.slots.values()))
        step_lps = list(req.logprobs)
        eng2 = ServingEngine(m, params, max_batch=1, max_len=64,
                             prefill_len=16)
        eng2.add_request([5, 9, 2, 7])
        eng2.decode_block(5)
        req2 = next(iter(eng2.slots.values()))
        assert req2.generated == req.generated
        assert req2.logprobs == pytest.approx(step_lps, abs=1e-3)

    def test_spec_step_logprobs_match_plain(self, model):
        m, params = model
        plain = ServingEngine(m, params, max_batch=1, max_len=64,
                              prefill_len=16)
        plain.add_request([5, 9, 2, 7])
        for _ in range(6):
            plain.step()
        spec = ServingEngine(m, params, max_batch=1, max_len=64,
                             prefill_len=16, draft_model=m,
                             draft_params=params, spec_k=3)
        spec.add_request([5, 9, 2, 7])
        while len(next(iter(spec.slots.values())).generated) < 7:
            spec.spec_step()
        p_req = next(iter(plain.slots.values()))
        s_req = next(iter(spec.slots.values()))
        n = len(p_req.generated)
        assert s_req.generated[:n] == p_req.generated
        assert s_req.logprobs[:n] == pytest.approx(
            p_req.logprobs, abs=1e-3
        )

    @pytest.mark.xfail(
        strict=False,
        reason="environment-bound (known set, not a regression): under "
               "jax 0.4.x CPU this fixture model's unfiltered "
               "log_softmax saturates to ~0 (the distribution is "
               "effectively one-hot), so the greedy-path 'real "
               "logprobs' assertion cannot distinguish filtered from "
               "unfiltered",
    )
    def test_sampled_logprobs_are_post_filter(self, model):
        """top_k=1 at temperature 1.0 leaves exactly one candidate, so
        the logprob under the SAMPLED-FROM (filtered) distribution is 0
        — while the greedy path reports the unfiltered log_softmax.
        Catches computing lp before filter_logits (or dropping the
        temperature divide)."""
        m, params = model
        sampled = ServingEngine(m, params, max_batch=1, max_len=64,
                                prefill_len=16, temperature=1.0,
                                top_k=1)
        sampled.add_request([5, 9, 2, 7])
        sampled.decode_block(5)
        s_req = next(iter(sampled.slots.values()))
        assert s_req.logprobs == pytest.approx([0.0] * 6, abs=1e-5)
        greedy = ServingEngine(m, params, max_batch=1, max_len=64,
                               prefill_len=16)
        greedy.add_request([5, 9, 2, 7])
        greedy.decode_block(5)
        g_req = next(iter(greedy.slots.values()))
        # same tokens (top_k=1 == argmax), different (real) logprobs
        assert g_req.generated == s_req.generated
        assert any(x < -1e-4 for x in g_req.logprobs)

    def test_logprobs_lockstep_with_stop_truncation(self, model):
        m, params = model
        oracle = greedy_reference(m, params, [5, 9, 2, 7], 12)
        stop = oracle[3:5]
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16)
        [res] = eng.generate([[5, 9, 2, 7]], max_new_tokens=12,
                             block_size=4, stop=stop)
        assert res.finished_reason == "stop"
        assert len(res.logprobs) == len(res.tokens)


class TestStopSequences:
    first_match = staticmethod(first_match)

    @pytest.mark.parametrize("k", [0, 3, 4])
    def test_stop_truncates_at_earliest_match(self, model, k):
        """Stop = oracle[k:k+2]: generation must end at the EARLIEST
        occurrence of that pair (the greedy chain may repeat, so the
        earliest match can precede k), stop excluded, reason "stop" —
        matches spanning decode-block boundaries included
        (block_size=4)."""
        m, params = model
        oracle = greedy_reference(m, params, [5, 9, 2, 7], 12)
        stop = oracle[k:k + 2]
        cut = self.first_match(oracle, stop)
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16)
        [res] = eng.generate([[5, 9, 2, 7]], max_new_tokens=12,
                             block_size=4, stop=stop)
        assert res.tokens == oracle[:cut]
        assert res.finished_reason == "stop"

    def test_multiple_stop_sequences_earliest_wins(self, model):
        m, params = model
        oracle = greedy_reference(m, params, [5, 9, 2, 7], 12)
        stops = [oracle[6:8], oracle[2:4]]
        cut = min(self.first_match(oracle, s) for s in stops)
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16)
        [res] = eng.generate(
            [[5, 9, 2, 7]], max_new_tokens=12, block_size=4,
            stop=stops,
        )
        assert res.tokens == oracle[:cut]
        assert res.finished_reason == "stop"

    def test_no_match_runs_to_budget(self, model):
        m, params = model
        oracle = greedy_reference(m, params, [5, 9, 2, 7], 8)
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16)
        # 63 is outside the greedy chain for this seed; never matches
        assert 63 not in oracle
        [res] = eng.generate([[5, 9, 2, 7]], max_new_tokens=8,
                             block_size=4, stop=[[63]])
        assert res.tokens == oracle
        assert res.finished_reason == "max_new_tokens"

    def test_malformed_stop_rejected(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16)
        with pytest.raises(ValueError, match="stop"):
            eng.add_request([1, 2], stop=[[]])
        with pytest.raises(ValueError, match="stop"):
            eng.add_request([1, 2], stop=["x"])


class TestPrefixCaching:
    PREFIX = list(range(1, 17))            # 16 = one prefill_len chunk

    def test_hit_matches_cold_prefill_exactly(self, model):
        m, params = model
        prompt = self.PREFIX + [40, 41, 42]
        cold = ServingEngine(m, params, max_batch=2, max_len=64,
                             prefill_len=16)
        [want] = cold.generate([prompt], max_new_tokens=8)
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16)
        eng.register_prefix(self.PREFIX)
        [got] = eng.generate([prompt], max_new_tokens=8)
        assert got.tokens == want.tokens
        assert eng.prefix_hits == 1
        assert eng.prefix_tokens_saved == len(self.PREFIX)

    def test_longest_of_multiple_prefixes_wins(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16)
        long = self.PREFIX + list(range(17, 33))       # 32 tokens
        eng.register_prefix(self.PREFIX)
        eng.register_prefix(long)
        eng.add_request(long + [7])
        assert eng.prefix_tokens_saved == len(long)

    def test_exact_equal_prompt_is_not_a_hit(self, model):
        # strict-prefix rule: the remainder chunk's logits seed the
        # first sampled token, so prompt == prefix must prefill
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16)
        eng.register_prefix(self.PREFIX)
        eng.add_request(list(self.PREFIX))
        assert eng.prefix_hits == 0

    def test_non_chunk_multiple_rejected(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16)
        with pytest.raises(ValueError, match="multiple of prefill_len"):
            eng.register_prefix([1, 2, 3])

    def test_unusable_prefix_rejected(self, model):
        # a 64-token prefix in a 64-slot cache can never be hit (the
        # strictly-longer prompt's remainder chunk cannot fit) — it must
        # be rejected, not pin an unusable stripe
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16)
        with pytest.raises(ValueError, match="remainder chunk"):
            eng.register_prefix(list(range(64)))
        eng.register_prefix(list(range(48)))       # 48 + 16 == 64: fits

    def test_register_needs_free_slot_and_leaves_slots_free(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=16)
        eng.register_prefix(self.PREFIX)
        assert eng.free_slots() == 1
        eng.add_request([1, 2])                       # occupies the slot
        with pytest.raises(RuntimeError, match="free slot"):
            eng.register_prefix(list(range(17, 33)))

    def test_prefix_cap_enforced(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16, max_prefixes=1)
        eng.register_prefix(self.PREFIX)
        with pytest.raises(RuntimeError, match="prefix cache full"):
            eng.register_prefix(list(range(17, 33)))
        eng.drop_prefix(self.PREFIX)
        eng.register_prefix(list(range(17, 33)))      # room again

    def test_drop_prefix_frees_and_misses(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16)
        eng.register_prefix(self.PREFIX)
        assert eng.drop_prefix(self.PREFIX)
        assert not eng.drop_prefix(self.PREFIX)
        eng.add_request(self.PREFIX + [7])
        assert eng.prefix_hits == 0

    def test_quantized_cache_prefix_hit(self, model):
        m, params = model
        prompt = self.PREFIX + [9, 8]
        cold = ServingEngine(m, params, max_batch=2, max_len=64,
                             prefill_len=16, kv_quant=True)
        [want] = cold.generate([prompt], max_new_tokens=6)
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16, kv_quant=True)
        eng.register_prefix(self.PREFIX)
        [got] = eng.generate([prompt], max_new_tokens=6)
        assert got.tokens == want.tokens
        assert eng.prefix_hits == 1

    def test_tp_mesh_prefix_hit(self, model):
        import numpy as np
        from jax.sharding import Mesh

        m, params = model
        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("model",))
        prompt = self.PREFIX + [3, 4, 5]
        cold = ServingEngine(m, params, max_batch=2, max_len=64,
                             prefill_len=16, mesh=mesh)
        [want] = cold.generate([prompt], max_new_tokens=6)
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16, mesh=mesh)
        eng.register_prefix(self.PREFIX)
        [got] = eng.generate([prompt], max_new_tokens=6)
        assert got.tokens == want.tokens
        assert eng.prefix_hits == 1

    def test_speculative_draft_prefix_hit(self, model):
        m, params = model
        prompt = self.PREFIX + [11, 12]
        cold = ServingEngine(m, params, max_batch=2, max_len=64,
                             prefill_len=16, draft_model=m,
                             draft_params=params, spec_k=3)
        cold.add_request(prompt)
        for _ in range(4):
            cold.spec_step()
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16, draft_model=m,
                            draft_params=params, spec_k=3)
        eng.register_prefix(self.PREFIX)
        eng.add_request(prompt)
        for _ in range(4):
            eng.spec_step()
        want = next(iter(cold.slots.values())).generated
        got = next(iter(eng.slots.values())).generated
        assert got == want
        assert eng.prefix_hits == 1


class TestFeatureMatrixCorner:
    def test_quant_prefix_fork_stop_together(self, model):
        """The whole feature set in ONE engine: int8 KV cache, a
        registered prefix, a 3-way fork whose prompt hits it, and a
        stop sequence — output must equal the same engine's plain
        single-request run."""
        m, params = model
        prefix = list(range(1, 17))
        prompt = prefix + [40, 41]
        plain = ServingEngine(m, params, max_batch=4, max_len=64,
                              prefill_len=16, kv_quant=True)
        [want] = plain.generate([prompt], max_new_tokens=10)
        stop = want.tokens[4:6]
        [want_stopped] = ServingEngine(
            m, params, max_batch=4, max_len=64, prefill_len=16,
            kv_quant=True,
        ).generate([prompt], max_new_tokens=10, stop=stop)
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=16, kv_quant=True)
        eng.register_prefix(prefix)
        rids = eng.add_request_n(prompt, 3, stop=stop)
        assert eng.prefix_hits == 1
        while eng.slots and all(
            len(r.generated) < 10 for r in eng.slots.values()
        ):
            eng.decode_block(4)
        done = {r.request_id: r for r in eng.finished}
        # the stop match sits at generated index 4, well inside the
        # decode loop: every fork MUST have finished via stop — a
        # conditional here would pass vacuously on the exact
        # quant/prefix/fork chain-perturbation this test exists for
        assert set(rids) <= set(done)
        for rid in rids:
            r = done[rid]
            assert r.tokens == want_stopped.tokens
            assert r.finished_reason == want_stopped.finished_reason


class TestSpecThroughput:
    def test_refills_drained_slots(self, model):
        """Steady-state methodology: slots that hit max_len mid-run are
        refilled, so the rate never measures an empty engine."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=32,
                            prefill_len=8, draft_model=m,
                            draft_params=params, spec_k=3)
        # max_len 32 drains a slot every ~7 rounds of k+1 tokens; 20
        # rounds forces several refills
        tput, per_round = eng.spec_throughput(rounds=20)
        assert tput > 0
        # draft == target: full acceptance, k+1 per live-slot round
        assert per_round == pytest.approx(4.0, abs=0.5)
        # several generations drained AND were replaced (refill ran):
        # more finished results than the batch could hold at once
        assert len(eng.finished) > eng.max_batch

    def test_requires_draft(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=32,
                            prefill_len=8)
        with pytest.raises(RuntimeError, match="draft_model"):
            eng.spec_throughput()


class TestRandomizedOps:
    """Property test: random interleavings of the engine's public ops
    (admit / fork / block / step / external finish / evict / prefix
    register) must keep every live slot's chain exactly the greedy
    oracle continuation of its prompt — the invariant every feature
    added this round (forks, prefixes, stops, eviction) must preserve."""

    @pytest.mark.parametrize("seed,spec", [
        (1234, False), (99, False), (2026, False),
        # speculative engines: mixed spec_step/decode_block/step
        # interleavings exercise the draft-cache catch-up machinery,
        # and losslessness says the chains must STILL match the plain
        # solo oracle
        (1234, True), (7, True),
    ])
    def test_random_interleavings_match_oracle(self, model, seed, spec):
        import random

        m, params = model
        rng = random.Random(seed)
        prompts = ([5, 9, 2, 7], [11, 3], list(range(1, 9)) + [40],
                   [6, 6, 1])
        # oracle = a SOLO single-slot engine per prompt (slot isolation
        # is the property under test: the shared engine's interleaved
        # chains must equal the undisturbed solo chains); one spot-check
        # against the O(n²) full-forward reference anchors the oracle
        chains = {}
        for p in prompts:
            solo = ServingEngine(m, params, max_batch=1, max_len=48,
                                 prefill_len=8)
            # generate() runs the chain to the cache edge (the same
            # bound the shared engine hits), so every interleaved
            # chain is a prefix of the solo chain
            [res] = solo.generate([list(p)], max_new_tokens=solo.max_len)
            chains[tuple(p)] = res.tokens
        assert chains[(5, 9, 2, 7)][:6] == greedy_reference(
            m, params, [5, 9, 2, 7], 6
        )

        def oracle(prompt, k):
            return chains[tuple(prompt)][:k]

        eng = ServingEngine(m, params, max_batch=4, max_len=48,
                            prefill_len=8,
                            draft_model=m if spec else None,
                            draft_params=params if spec else None,
                            spec_k=3)
        eng.register_prefix(list(range(1, 9)))       # one shared prefix
        rid_prompt = {}
        ok_ops = 0
        ops = ("add", "fork", "block", "step", "finish", "evict")
        if spec:
            ops += ("spec", "spec")                  # weight spec rounds
        for step_no in range(60):
            op = rng.choice(ops)
            try:
                if op == "add":
                    p = rng.choice(prompts)
                    rid_prompt[eng.add_request(list(p))] = p
                elif op == "fork":
                    p = rng.choice(prompts)
                    for rid in eng.add_request_n(list(p), 2):
                        rid_prompt[rid] = p
                elif op == "block":
                    eng.decode_block(rng.randint(1, 6))
                elif op == "step":
                    eng.step()
                elif op == "spec":
                    eng.spec_step()
                elif op == "finish" and eng.slots:
                    slot = rng.choice(list(eng.slots))
                    eng.finish_slot(slot, n_keep=rng.randint(1, 3))
                elif op == "evict" and eng.slots:
                    eng.evict_slot(rng.choice(list(eng.slots)))
            except (RuntimeError, ValueError):
                continue                       # full batch / cache edge
            ok_ops += 1
            # invariant: every live chain is the oracle continuation
            for req in eng.slots.values():
                p = rid_prompt[req.request_id]
                want = oracle(p, len(req.generated))
                assert req.generated == want, (
                    f"step {step_no}: slot chain diverged for {p}"
                )
                assert len(req.logprobs) == len(req.generated)
        # the property must not be vacuous: most ops succeed and work
        # actually flowed through the shared engine
        assert ok_ops >= 30, f"only {ok_ops}/60 ops succeeded"
        assert eng.finished or eng.slots
        # finished results too (external cuts keep oracle prefixes)
        for r in eng.finished:
            p = rid_prompt[r.request_id]
            assert r.tokens == oracle(p, len(r.tokens))
            assert len(r.logprobs) == len(r.tokens)


class TestSamplingFilters:
    """top-k / nucleus sampling: the filter math, and that BOTH sample
    paths (host _sample and the on-device block scan) apply it."""

    def test_filter_logits_top_k(self):
        from instaslice_tpu.serving.sampling import filter_logits

        logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0, 4.0]])
        out = filter_logits(logits, top_k=2)
        kept = [i for i in range(5) if float(out[0, i]) > -1e8]
        assert kept == [1, 4]                     # the two largest

    def test_filter_logits_top_p(self):
        from instaslice_tpu.serving.sampling import filter_logits

        # probs ≈ [0.64, 0.24, 0.09, 0.03]: top_p=0.7 keeps the first
        # two (0.64 < 0.7, crossing token kept)
        logits = jnp.log(jnp.asarray([[0.64, 0.24, 0.09, 0.03]]))
        out = filter_logits(logits, top_p=0.7)
        kept = [i for i in range(4) if float(out[0, i]) > -1e8]
        assert kept == [0, 1]

    def test_filter_degenerate_top_p_keeps_argmax(self):
        from instaslice_tpu.serving.sampling import filter_logits

        logits = jnp.asarray([[1.0, 5.0, 3.0]])
        out = filter_logits(logits, top_p=1e-9)
        kept = [i for i in range(3) if float(out[0, i]) > -1e8]
        assert kept == [1]            # greedy, never uniform garbage

    def test_engine_validates_sampling_ranges(self, model):
        m, params = model
        with pytest.raises(ValueError, match="top_p"):
            ServingEngine(m, params, top_p=0.0)
        with pytest.raises(ValueError, match="top_k"):
            ServingEngine(m, params, top_k=-1)

    def test_filter_noop_defaults(self):
        from instaslice_tpu.serving.sampling import filter_logits

        logits = jax.random.normal(jax.random.key(0), (2, 16))
        out = filter_logits(logits)
        assert jnp.allclose(out, logits)

    def test_top_k_one_equals_greedy(self, model):
        """temperature > 0 with top_k=1 must reproduce the greedy chain
        on BOTH paths — the filter leaves a single candidate."""
        m, params = model
        greedy = ServingEngine(m, params, max_batch=1, max_len=64,
                               prefill_len=8)
        rg = greedy.add_request([5, 9, 2, 7])
        ref = greedy.decode_block(8)[rg]
        sampled = ServingEngine(m, params, max_batch=1, max_len=64,
                                prefill_len=8, temperature=0.8, top_k=1)
        rs = sampled.add_request([5, 9, 2, 7])
        assert sampled.decode_block(8)[rs] == ref      # block path
        stepped = ServingEngine(m, params, max_batch=1, max_len=64,
                                prefill_len=8, temperature=0.8, top_k=1)
        rt = stepped.add_request([5, 9, 2, 7])
        got = [stepped.step()[rt] for _ in range(8)]
        assert got == ref                              # host path

    def test_sampled_tokens_within_top_k(self, model):
        """With top_k=3, every sampled token must be among the 3 most
        likely next tokens of the oracle at that position."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8, temperature=1.0, top_k=3)
        prompt = [5, 9, 2, 7]
        rid = eng.add_request(prompt)
        chain = [next(iter(eng.slots.values())).generated[0]]
        chain += eng.decode_block(6)[rid]
        toks = list(prompt)
        for t in chain:
            logits = m.apply(params, jnp.asarray(toks, jnp.int32)[None])
            top3 = set(
                int(i) for i in
                jnp.argsort(logits[0, -1])[::-1][:3]
            )
            assert t in top3, (t, top3)
            toks.append(t)
