"""Serving engine tests: KV-cache decode correctness against the full
forward, slot-based continuous batching, eos/max-len lifecycle."""

import jax
import jax.numpy as jnp
import pytest

from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.serving import ServingEngine


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


def greedy_reference(model, params, prompt, n_new):
    """Re-run the FULL forward for every generated token (O(n²) oracle)."""
    toks = list(prompt)
    out = []
    for _ in range(n_new):
        logits = model.apply(params, jnp.asarray(toks, jnp.int32)[None])
        t = int(jnp.argmax(logits[0, -1]))
        out.append(t)
        toks.append(t)
    return out


class TestCacheDecodeCorrectness:
    def test_incremental_matches_full_forward(self, model):
        m, params = model
        toks = jax.random.randint(jax.random.key(1), (2, 12), 0, 64)
        full = m.apply(params, toks)
        cache = m.init_cache(2, 32)
        lengths = jnp.zeros(2, jnp.int32)
        lg, cache = m.apply_with_cache(params, toks[:, :5], cache, lengths)
        assert float(jnp.abs(lg - full[:, :5]).max()) < 1e-4
        lengths = lengths + 5
        for t in range(5, 12):
            lg, cache = m.apply_with_cache(
                params, toks[:, t:t + 1], cache, lengths
            )
            assert float(jnp.abs(lg[:, 0] - full[:, t]).max()) < 1e-4
            lengths = lengths + 1


class TestEngine:
    def test_greedy_generation_matches_oracle(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=16)
        prompt = [5, 9, 2, 7]
        [res] = eng.generate([prompt], max_new_tokens=8)
        assert res.tokens == greedy_reference(m, params, prompt, 8)

    def test_continuous_batching_ragged_prompts(self, model):
        """Prompts of different lengths share the rectangular batch; each
        must match its solo oracle."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=16)
        prompts = [[3], [1, 2, 3, 4, 5, 6, 7], [9, 8], [4, 4, 4, 4]]
        results = eng.generate(prompts, max_new_tokens=6)
        assert len(results) == 4
        for p, r in zip(prompts, results):
            assert r.tokens == greedy_reference(m, params, p, 6), p

    def test_more_prompts_than_slots(self, model):
        """Continuous batching: 5 prompts through 2 slots."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        prompts = [[i + 1, i + 2] for i in range(5)]
        results = eng.generate(prompts, max_new_tokens=4)
        assert len(results) == 5
        for p, r in zip(prompts, results):
            assert r.tokens == greedy_reference(m, params, p, 4), p

    def test_eos_frees_slot(self, model):
        m, params = model
        prompt = [5, 9, 2, 7]
        eos = greedy_reference(m, params, prompt, 3)[2]
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8, eos_id=eos)
        [res] = eng.generate([prompt], max_new_tokens=10)
        assert res.finished_reason == "eos"
        assert res.tokens[-1] == eos and len(res.tokens) <= 3
        assert eng.free_slots() == 1

    def test_prompt_too_long_rejected(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, prefill_len=4)
        with pytest.raises(ValueError, match="prefill_len"):
            eng.add_request([1] * 5)

    def test_throughput_positive(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=128,
                            prefill_len=8)
        assert eng.throughput(n_steps=5) > 0
