"""Cross-product regression net for the 2026-07-31 decode rewrite.

The attention path changed twice in one day (read-only cache with a
joint prefix‖local softmax; head-major cache layout), each change
validated piecewise by the serving/window/gqa suites. This module pins
the combined semantics directly at the model level, across the full
feature cross-product, against the full-forward oracle — including the
mixed-depth + rollback case the engine only exercises implicitly:

rows sit at DIFFERENT depths (the rectangular-batch invariant), reached
here by prefilling uniformly and then rolling rows back to staggered
lengths — exactly speculative decoding's rejection semantics: a cache
position beyond ``lengths`` must be invisible AND overwritable.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from instaslice_tpu.models.lm import ModelConfig, TpuLM


@pytest.mark.parametrize("kv_quant", [False, True])
@pytest.mark.parametrize("window", [0, 5])
@pytest.mark.parametrize("n_kv", [0, 2])
def test_mixed_depth_decode_matches_full_forward(kv_quant, window, n_kv):
    cfg = ModelConfig(
        vocab_size=64, d_model=32, n_heads=4, n_kv_heads=n_kv,
        n_layers=2, d_ff=64, window=window, max_seq_len=32,
        dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    params = m.init(jax.random.key(0))
    B, S = 3, 10
    seqs = jax.random.randint(jax.random.key(1), (B, S), 0, 64)
    full = m.apply(params, seqs)                     # (B, S, V)

    cache = m.init_cache(B, 24, quant=kv_quant)
    lg, cache = m.apply_with_cache(
        params, seqs[:, :6], cache, jnp.zeros(B, jnp.int32)
    )
    # the prefill chunk itself must match the oracle at every position
    tol = 0.05 if kv_quant else 1e-4
    rel = np.linalg.norm(np.asarray(lg - full[:, :6])) / np.linalg.norm(
        np.asarray(full[:, :6])
    )
    assert rel < tol, rel

    # roll rows back to staggered depths (spec-decode rejection): the
    # discarded positions still hold stale K/V — they must be invisible
    depths = jnp.array([4, 2, 6], jnp.int32)
    for step in range(3):
        lens = depths + step
        tok = jnp.take_along_axis(seqs, lens[:, None], axis=1)
        lg, cache = m.apply_with_cache(params, tok, cache, lens)
        for r in range(B):
            pos = int(lens[r])
            got = np.asarray(lg[r, 0])
            want = np.asarray(full[r, pos])
            rel = np.linalg.norm(got - want) / np.linalg.norm(want)
            assert rel < tol, (kv_quant, window, n_kv, step, r, rel)


@pytest.mark.parametrize("kv_quant", [False, True])
def test_attend_len_bucket_is_bit_identical(kv_quant):
    """The engine's attend_len bucketing claim: bounding the attended
    prefix must not change a single logit (rows' lengths all fit the
    bucket)."""
    cfg = ModelConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    params = m.init(jax.random.key(2))
    B = 2
    seqs = jax.random.randint(jax.random.key(3), (B, 8), 0, 64)
    caches = []
    for attend in (0, 16):           # 0 = whole buffer
        cache = m.init_cache(B, 48, quant=kv_quant)
        _, cache = m.apply_with_cache(
            params, seqs, cache, jnp.zeros(B, jnp.int32)
        )
        lg, cache = m.apply_with_cache(
            params, seqs[:, :1], cache,
            jnp.full((B,), 8, jnp.int32), attend_len=attend,
        )
        caches.append(np.asarray(lg))
    np.testing.assert_array_equal(caches[0], caches[1])
