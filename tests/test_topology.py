"""Topology core tests: grids, profiles, placement, policies.

Covers the property obligations from SURVEY.md §7 layer 1: no overlap,
ICI contiguity (axis-aligned boxes only), alignment, and the BASELINE
bin-packing stress mix on a v5e-16 mesh.
"""

import random

import pytest

from instaslice_tpu.topology import (
    BestFitPolicy,
    Box,
    FirstFitPolicy,
    GENERATIONS,
    NodeGrid,
    Occupancy,
    TorusGroup,
    get_policy,
    legal_placements,
    parse_profile_name,
    profile_catalog,
)
from instaslice_tpu.topology.grid import (
    coord_to_id,
    get_generation,
    id_to_coord,
    iter_coords,
)
from instaslice_tpu.topology.placement import find_placements, legal_anchors
from instaslice_tpu.topology.profiles import parse_shape


def v5e_single(node="node-a"):
    return TorusGroup.single_host(node, get_generation("v5e"))


def v5e_16(prefix="node"):
    """Two v5e hosts forming a 4x4 mesh (the v5e-16 machine shape)."""
    gen = get_generation("v5e")
    hosts = {
        f"{prefix}-0": NodeGrid(gen, host_offset=(0, 0, 0), torus_group="g"),
        f"{prefix}-1": NodeGrid(gen, host_offset=(2, 0, 0), torus_group="g"),
    }
    return TorusGroup("g", gen, (4, 4, 1), hosts)


class TestGrid:
    def test_generations_present(self):
        assert {"v4", "v5e", "v5p", "v6e"} <= set(GENERATIONS)
        assert GENERATIONS["v5e"].chips_per_host == 8
        assert GENERATIONS["v4"].chips_per_host == 4

    def test_coord_id_roundtrip(self):
        bounds = (2, 4, 1)
        ids = set()
        for c in iter_coords(bounds):
            i = coord_to_id(c, bounds)
            assert id_to_coord(i, bounds) == c
            ids.add(i)
        assert ids == set(range(8))

    def test_single_host_group(self):
        g = v5e_single()
        assert g.chip_count == 8
        assert g.host_at((1, 3, 0)) == "node-a"
        assert g.host_at((2, 0, 0)) is None

    def test_multi_host_group(self):
        g = v5e_16()
        assert g.chip_count == 16
        assert g.host_at((1, 1, 0)) == "node-0"
        assert g.host_at((3, 1, 0)) == "node-1"
        assert g.host_grid_shape() == (2, 1, 1)

    def test_misaligned_host_offset_rejected(self):
        gen = get_generation("v5e")
        with pytest.raises(ValueError):
            TorusGroup(
                "g", gen, (4, 4, 1),
                {"n": NodeGrid(gen, host_offset=(1, 0, 0))},
            )


class TestProfiles:
    def test_parse_and_render(self):
        p = parse_profile_name("v5e-2x2")
        assert p.shape == (2, 2, 1)
        assert p.name == "v5e-2x2"
        assert p.chip_count == 4
        p3 = parse_profile_name("v4-2x2x2")
        assert p3.shape == (2, 2, 2)
        assert p3.chip_count == 8

    def test_parse_rejects_garbage(self):
        for bad in ["", "v5e", "v5e-", "v5e-2x", "v9z-2x2", "v5e-3x2", "mig-1g.5gb"]:
            with pytest.raises((ValueError, KeyError)):
                parse_profile_name(bad)

    def test_catalog_v5e(self):
        names = {p.name for p in profile_catalog("v5e")}
        for want in ["v5e-1x1", "v5e-2x1", "v5e-2x2", "v5e-4x2", "v5e-4x4",
                     "v5e-8x4", "v5e-8x8", "v5e-16x16"]:
            assert want in names, f"{want} missing from {sorted(names)}"

    def test_catalog_capped(self):
        cat = profile_catalog("v5e", max_chips=8)
        assert all(p.chip_count <= 8 for p in cat)
        assert any(p.chip_count == 8 for p in cat)

    def test_hosts_needed(self):
        assert parse_profile_name("v5e-2x2").hosts_needed() == 1
        assert parse_profile_name("v5e-4x4").hosts_needed() == 2
        assert parse_profile_name("v5e-8x8").hosts_needed() == 8

    def test_attributes(self):
        a = parse_profile_name("v5e-2x2").attributes()
        assert a["chips"] == 4 and a["hosts"] == 1 and a["hbmGiB"] == 64

    def test_orientations_raises_on_fully_invalid_shape(self):
        # No permutation of (3,1,1) is a power-of-two shape; the scan must
        # refuse rather than echo the invalid shape back into placement.
        from instaslice_tpu.topology.profiles import orientations

        gen = get_generation("v5e")
        with pytest.raises(ValueError):
            orientations(gen, (3, 1, 1))

    def test_orientations_multi_host_fixed(self):
        from instaslice_tpu.topology.profiles import orientations

        gen = get_generation("v5e")
        # 4x4 exceeds the 2x4 host bounds in every permutation but is a
        # legal multi-host shape: orientation-fixed single result.
        assert orientations(gen, (4, 4, 1)) == [(4, 4, 1)]

    def test_parse_shape(self):
        assert parse_shape("v5e", "2x2").name == "v5e-2x2"


class TestPlacement:
    def test_anchors_aligned(self):
        anchors = legal_anchors((4, 4, 1), (2, 2, 1))
        assert anchors == [(0, 0, 0), (2, 0, 0), (0, 2, 0), (2, 2, 0)]

    def test_1x1_fills_host(self):
        g = v5e_single()
        pls = legal_placements(g, parse_profile_name("v5e-1x1"))
        assert len(pls) == 8

    def test_2x2_on_host(self):
        g = v5e_single()
        pls = legal_placements(g, parse_profile_name("v5e-2x2"))
        assert len(pls) == 2  # bounds 2x4: anchors y in {0, 2}
        for p in pls:
            assert len(p.parts) == 1 and p.parts[0].node_name == "node-a"

    def test_2x1_orientations(self):
        g = v5e_single()
        pls = legal_placements(g, parse_profile_name("v5e-2x1"))
        # (2,1): 4 anchors; (1,2): 2x2 anchor grid = 4 → 8 total
        assert len(pls) == 8

    def test_multi_host_4x4(self):
        g = v5e_16()
        pls = legal_placements(g, parse_profile_name("v5e-4x4"))
        assert len(pls) == 1
        p = pls[0]
        assert p.box.chip_count == 16
        assert [pt.node_name for pt in p.parts] == ["node-0", "node-1"]
        assert [pt.worker_id for pt in p.parts] == [0, 1]
        hb = g.generation.host_bounds
        for pt in p.parts:
            assert pt.local_box.shape == (2, 4, 1)
            assert pt.local_chip_ids(hb) == list(range(8))

    def test_sparse_group_skips_missing_host(self):
        gen = get_generation("v5e")
        # 4x4 bounds but only one host present → no 4x4 placement.
        g = TorusGroup(
            "g", gen, (4, 4, 1),
            {"n0": NodeGrid(gen, host_offset=(0, 0, 0))},
        )
        assert legal_placements(g, parse_profile_name("v5e-4x4")) == []
        # but sub-host profiles still place on the live host
        assert len(legal_placements(g, parse_profile_name("v5e-2x2"))) == 2

    def test_occupancy_overlap_rejected(self):
        g = v5e_single()
        occ = Occupancy(g)
        occ.occupy(Box((0, 0, 0), (2, 2, 1)), owner="a")
        with pytest.raises(ValueError):
            occ.occupy(Box((0, 1, 0), (1, 1, 1)), owner="b")
        occ.release(Box((0, 0, 0), (2, 2, 1)), owner="a")
        occ.occupy(Box((0, 1, 0), (1, 1, 1)), owner="b")

    def test_occupancy_out_of_bounds(self):
        occ = Occupancy(v5e_single())
        with pytest.raises(ValueError):
            occ.occupy(Box((0, 3, 0), (2, 2, 1)))

    def test_box_key_roundtrip(self):
        b = Box((2, 0, 0), (2, 2, 1))
        assert Box.from_key(b.key()) == b


class TestPolicies:
    def test_first_fit_fills_then_exhausts(self):
        g = v5e_single()
        occ = Occupancy(g)
        pol = FirstFitPolicy()
        prof = parse_profile_name("v5e-1x1")
        got = []
        for i in range(8):
            pl = pol.choose(g, prof, occ)
            assert pl is not None
            occ.occupy(pl.box, owner=str(i))
            got.append(pl.box.anchor)
        assert len(set(got)) == 8
        assert pol.choose(g, prof, occ) is None

    def test_tail_placement_not_rejected(self):
        """Reference bug: `<` vs `<=` made the full-size profile
        unplaceable (instaslice_controller.go:351,360,370). The full-host
        profile must place on an empty host."""
        g = v5e_single()
        pl = FirstFitPolicy().choose(
            g, parse_shape("v5e", "4x2"), Occupancy(g)
        )
        assert pl is not None and pl.box.chip_count == 8

    def test_best_fit_preserves_big_slots(self):
        g = v5e_16()
        occ = Occupancy(g)
        bf = BestFitPolicy()
        # Place a 2x2; best-fit should leave at least one more 2x2 and as
        # many 2x1s as possible intact.
        pl = bf.choose(g, parse_profile_name("v5e-2x2"), occ)
        assert pl is not None
        occ.occupy(pl.box)
        pl2 = bf.choose(g, parse_profile_name("v5e-2x2"), occ)
        assert pl2 is not None
        occ.occupy(pl2.box)
        # Two more 2x2s must still fit on a 4x4 with two taken.
        pl3 = bf.choose(g, parse_profile_name("v5e-2x2"), occ)
        assert pl3 is not None

    def test_registry(self):
        assert get_policy("first-fit").name == "first-fit"
        with pytest.raises(KeyError):
            get_policy("nope")

    def test_left_to_right_and_right_to_left_opposite_ends(self):
        """The reference's two stub policies, real here: consecutive 1x1
        grants grow from opposite ends of the same mesh."""
        g = v5e_16()
        occ = Occupancy(g)
        ltr = get_policy("left-to-right")
        rtl = get_policy("right-to-left")
        prof = parse_profile_name("v5e-1x1")
        a = ltr.choose(g, prof, occ)
        occ.occupy(a.box)
        b = rtl.choose(g, prof, occ)
        occ.occupy(b.box)
        assert a.box.anchor == (0, 0, 0)
        assert b.box.anchor[0] + b.box.shape[0] == g.bounds[0]
        # churn to full: ltr grants stay in the low-x half, rtl grants in
        # the high-x half, converging on the middle (occupancy already
        # forbids overlap; the POLICY property is the directionality)
        mid = g.bounds[0] // 2
        for _ in range(7):
            pa = ltr.choose(g, prof, occ)
            occ.occupy(pa.box)
            pb = rtl.choose(g, prof, occ)
            occ.occupy(pb.box)
            assert pa.box.anchor[0] < mid or occ.free_chips() < 2
            assert (
                pb.box.anchor[0] + pb.box.shape[0] > mid
                or occ.free_chips() < 2
            )
        assert occ.free_chips() == 0

    def test_stress_mix_8_pods_v5e16(self):
        """BASELINE bin-packing stress: 8 concurrent pods, mixed profiles
        on one v5e-16 mesh (16 chips): 1x 2x2 + 3x 2x1 + 4x 1x1 = 14 chips
        must all place with zero overlap."""
        g = v5e_16()
        occ = Occupancy(g)
        pol = BestFitPolicy()
        mix = (["v5e-2x2"] + ["v5e-2x1"] * 3 + ["v5e-1x1"] * 4)
        boxes = []
        for i, name in enumerate(mix):
            pl = pol.choose(g, parse_profile_name(name), occ)
            assert pl is not None, f"pod {i} ({name}) unplaceable"
            occ.occupy(pl.box, owner=str(i))
            boxes.append(pl.box)
        for i in range(len(boxes)):
            for j in range(i + 1, len(boxes)):
                assert not boxes[i].overlaps(boxes[j])

    def test_property_random_alloc_free(self):
        """Random alloc/free churn: no overlap ever, all placements
        aligned, occupancy returns to empty."""
        rng = random.Random(1234)
        g = v5e_16()
        occ = Occupancy(g)
        live = {}
        names = ["v5e-1x1", "v5e-2x1", "v5e-2x2", "v5e-4x2"]
        pol = FirstFitPolicy()
        for step in range(300):
            if live and (rng.random() < 0.4 or occ.free_chips() == 0):
                k = rng.choice(list(live))
                occ.release(live.pop(k), owner=k)
            else:
                prof = parse_profile_name(rng.choice(names))
                pl = pol.choose(g, prof, occ)
                if pl is None:
                    continue
                for b in live.values():
                    assert not b.overlaps(pl.box)
                for i in range(3):
                    assert pl.box.anchor[i] % pl.box.shape[i] == 0
                k = f"o{step}"
                occ.occupy(pl.box, owner=k)
                live[k] = pl.box
        for k in list(live):
            occ.release(live.pop(k), owner=k)
        assert occ.free_chips() == g.chip_count


class TestReviewRegressions:
    """Fixes from the first code review."""

    def test_parse_canonicalizes_spellings(self):
        from instaslice_tpu.topology import profile_catalog
        a = parse_profile_name("v5e-1x4")
        b = parse_profile_name("v5e-4x1")
        assert a == b
        names = {p.name for p in profile_catalog("v5e")}
        assert a.name in names

    def test_duplicate_host_offsets_rejected(self):
        gen = get_generation("v5e")
        with pytest.raises(ValueError, match="both claim"):
            TorusGroup(
                "g", gen, (2, 4, 1),
                {"a": NodeGrid(gen, host_offset=(0, 0, 0)),
                 "b": NodeGrid(gen, host_offset=(0, 0, 0))},
            )

    def test_non_multiple_bounds_rejected(self):
        gen = get_generation("v5e")
        with pytest.raises(ValueError, match="whole multiple"):
            TorusGroup(
                "g", gen, (3, 4, 1),
                {"a": NodeGrid(gen, host_offset=(0, 0, 0))},
            )

    def test_release_mismatched_box_refused(self):
        g = v5e_single()
        occ = Occupancy(g)
        a = Box((0, 0, 0), (2, 2, 1))
        b = Box((0, 2, 0), (2, 2, 1))
        occ.occupy(a, owner="a")
        occ.occupy(b, owner="b")
        with pytest.raises(ValueError, match="mismatched"):
            occ.release(b, owner="a")
        occ.release(a, owner="a")
        occ.release(b, owner="b")
        assert occ.free_chips() == 8

    def test_release_unknown_owner_refused(self):
        g = v5e_single()
        occ = Occupancy(g)
        a = Box((0, 0, 0), (2, 2, 1))
        occ.occupy(a, owner="a")
        with pytest.raises(ValueError, match="holds no box"):
            occ.release(a, owner="b")
        occ.release(a, owner="a")

    def test_mixed_generation_group_rejected(self):
        with pytest.raises(ValueError, match="but group is"):
            TorusGroup(
                "g", get_generation("v5e"), (2, 4, 1),
                {"n": NodeGrid(get_generation("v4"))},
            )
