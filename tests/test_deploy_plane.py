"""Deploy-plane validation as a test: the `make test-deploy` logic
(render config/default, apply the rendered tree over HTTP to the fake
API server, cross-check references, lint the build plane) must stay
green. Reference anchor: /root/reference/test/e2e/e2e_test.go:84-118 —
the half of its e2e that needs no cluster."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_deploy_plane_validates():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "test_deploy.py")],
        capture_output=True, timeout=120,
    )
    out = proc.stdout.decode()
    assert proc.returncode == 0, out[-2000:]
    assert "FAIL" not in out, out[-2000:]
    assert "OK: 0 failures" in out
