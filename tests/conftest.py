"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; all sharding-aware tests run
against ``--xla_force_host_platform_device_count=8`` CPU devices, and the
driver separately dry-run-compiles the multi-chip path via
``__graft_entry__.dryrun_multichip``. Must run before the first jax import,
hence module-level in conftest.
"""

import os

# Force, don't setdefault: the session may export JAX_PLATFORMS=axon (one
# real chip via tunnel) — tests must still run on the virtual 8-CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Pytest plugins (jaxtyping) import jax before this conftest runs, so the
# env vars above are snapshotted too late for jax.config — set it directly.
import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices option; the XLA_FLAGS env var
    # set above (before any jax import) is the only path there
    pass


# ------------------------------------------------------------ fast/slow
# The jax-workload and multi-process tiers dominate the suite's wall
# clock (the full run is ~19 min serial); the control-plane modules run
# in ~2 min. `make test` runs the fast tier (-m "not slow"),
# `make test-all` everything. Whole modules are marked here, by name,
# so a new test in a slow module cannot silently join the fast tier.

SLOW_MODULES = {
    "test_serving",       # jax engine: prefill/decode/spec compiles
    "test_api_server",    # HTTP server over the jax engine
    "test_workload",      # train-step / remat / ring-attention compiles
    "test_distributed",   # 2-process DCN rendezvous + oplog smokes
    "test_process_e2e",   # real OS processes: mains + election
    "test_checkpoint",    # orbax save/restore round-trips
    "test_pipeline",      # GPipe stage compiles over the CPU mesh
    "test_ops",           # pallas kernel (interpret mode) sweeps
    "test_bench_tpu",     # chained-timing harness units
    "test_quant",         # int8 quantization sweeps
    "test_gqa",           # GQA attention compiles across the stack
    "test_window",        # sliding-window attention + banded cache reads
    "test_sampling_extras",  # repetition-penalty / min-p sampling compiles
    "test_data",          # mmap dataset + training-input pipelines
    "test_tpulock",       # cross-process holder spawn/kill round-trips
    "test_lora",          # adapter train-step compiles
    "test_quant_matmul",  # pallas w8a16 kernel (interpret mode) sweeps
    "test_int4",          # packed int4 quantization + engine compiles
    "test_decode_equivalence",  # decode-vs-oracle cross-product compiles
    "test_flash_decode",  # fused decode-attention kernel (interpret)
    "test_serving_chaos",  # fault-injected serving + drain under load
    "test_serving_sched",  # SLO scheduler + preempt/resume engine paths
    "test_engine_hotpath",  # batched prefill / fast-path / overlap compiles
    "test_radix",         # radix prefix cache over the jax engine
    "test_spec_decode",   # rejection-sampling spec decode compiles
    "test_router",        # fleet router + live migration over jax engines
}


def pytest_collection_modifyitems(config, items):
    import pytest

    for item in items:
        if item.module.__name__ in SLOW_MODULES:
            item.add_marker(pytest.mark.slow)


# ------------------------------------------------------------ watchdog
# The chaos/serving tiers run under `timeout -k`: a hung test used to
# die SILENTLY when the outer kill landed (no stacks, no culprit). Arm
# faulthandler for the whole session so a session still alive at the
# deadline dumps every thread's stack to stderr — the outer `timeout
# -k` stays the killer (exit=False: a healthy-but-long run, e.g. `make
# test-all` at ~19 min, must never be shot by its own diagnostics;
# repeat=True keeps dumping so the LAST stacks before the outer kill
# show the actual hang). PYTEST_FAULTHANDLER_SESSION_TIMEOUT tunes the
# deadline (0 disables); the Makefile chaos target sets it just below
# its own `timeout -k` budget.


def pytest_configure(config):
    import faulthandler

    timeout = float(os.environ.get(
        "PYTEST_FAULTHANDLER_SESSION_TIMEOUT", "840"
    ))
    if timeout > 0:
        faulthandler.dump_traceback_later(timeout, repeat=True,
                                          exit=False)


def pytest_unconfigure(config):
    import faulthandler

    faulthandler.cancel_dump_traceback_later()


# ------------------------------------------------------------ lockcheck
# With TPUSLICE_LOCKCHECK=1 every named lock records its per-thread
# acquisition order (instaslice_tpu/utils/lockcheck.py); any ABBA cycle
# observed anywhere in the session — even on a benign interleaving —
# fails the run here. `make chaos` armed this way IS the race detector
# (docs/STATIC_ANALYSIS.md). test_lockcheck.py's deliberate cycles are
# reset by its own fixtures, so only cycles from REAL project locks
# survive to this hook.


def pytest_sessionfinish(session, exitstatus):
    from instaslice_tpu.utils import lockcheck

    if not lockcheck.armed():
        return
    rep = lockcheck.report()
    print(
        f"\nlockcheck: {len(rep['edges'])} order edge(s), "
        f"{len(rep['cycles'])} cycle(s), "
        f"{len(rep['longHolds'])} long hold(s)"
    )
    if rep["cycles"] or rep["longHolds"]:
        import json

        print(json.dumps(
            {"cycles": rep["cycles"], "longHolds": rep["longHolds"]},
            indent=2,
        ))
    if rep["cycles"]:
        session.exitstatus = 3


# --------------------------------------------------------------- helpers
# Shared across process-spawning tests (promoted here so fixes reach all
# copies — review finding r3).


def free_port() -> int:
    """An OS-assigned free TCP port on localhost."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_until(pred, timeout: float, what: str, diag=None) -> None:
    """Poll ``pred`` until true or raise with ``what`` (plus ``diag()``'s
    output, when given — e.g. subprocess log tails)."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    extra = f"\n{diag()}" if diag else ""
    raise AssertionError(f"timed out waiting for {what}{extra}")
