"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; all sharding-aware tests run
against ``--xla_force_host_platform_device_count=8`` CPU devices, and the
driver separately dry-run-compiles the multi-chip path via
``__graft_entry__.dryrun_multichip``. Must run before the first jax import,
hence module-level in conftest.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
