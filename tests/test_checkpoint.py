"""Checkpoint/resume tests: sharded save/restore on the virtual 8-CPU
mesh, bit-identical training continuation after a simulated crash."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from instaslice_tpu.models.checkpoint import (
    TrainCheckpointer,
    abstract_train_state,
)
from instaslice_tpu.models.lm import ModelConfig
from instaslice_tpu.models.train import make_train_step
from instaslice_tpu.models.lm import TpuLM


@pytest.fixture(scope="module")
def mesh():
    devs = np.array(jax.devices()[:8]).reshape(2, 1, 4)
    return Mesh(devs, ("data", "seq", "model"))


@pytest.fixture(scope="module")
def setup(mesh):
    cfg = ModelConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        dtype=jnp.float32, remat=False,
    )
    model = TpuLM(cfg)
    init_fn, step_fn = make_train_step(model, mesh)
    tokens = jax.random.randint(jax.random.key(7), (4, 16), 0, 64)
    return init_fn, step_fn, tokens


class TestCheckpointResume:
    def test_fresh_dir_restores_none(self, tmp_path, setup):
        init_fn, _, _ = setup
        with TrainCheckpointer(str(tmp_path)) as ckpt:
            assert ckpt.latest_step() is None
            assert ckpt.restore(abstract_train_state(init_fn)) is None

    def test_resume_is_bit_identical(self, tmp_path, setup):
        init_fn, step_fn, tokens = setup
        # uninterrupted: 4 steps
        state = init_fn(jax.random.key(0))
        losses = []
        for _ in range(4):
            state, loss = step_fn(state, tokens)
            losses.append(float(loss))
        ref_params = state.params

        # interrupted: 2 steps, save, "crash", restore, 2 more
        state2 = init_fn(jax.random.key(0))
        for _ in range(2):
            state2, _ = step_fn(state2, tokens)
        with TrainCheckpointer(str(tmp_path)) as ckpt:
            assert ckpt.save(state2)
        del state2

        with TrainCheckpointer(str(tmp_path)) as ckpt:
            assert ckpt.latest_step() == 2
            restored = ckpt.restore(abstract_train_state(init_fn))
        assert int(restored.step) == 2
        losses2 = []
        for _ in range(2):
            restored, loss = step_fn(restored, tokens)
            losses2.append(float(loss))
        assert losses2 == losses[2:]
        for a, b in zip(
            jax.tree.leaves(ref_params), jax.tree.leaves(restored.params)
        ):
            assert jnp.array_equal(a, b)

    def test_restore_preserves_shardings(self, tmp_path, setup):
        init_fn, step_fn, tokens = setup
        state = init_fn(jax.random.key(0))
        state, _ = step_fn(state, tokens)
        with TrainCheckpointer(str(tmp_path)) as ckpt:
            ckpt.save(state)
            restored = ckpt.restore(abstract_train_state(init_fn))
        for orig, rest in zip(
            jax.tree.leaves(state.params), jax.tree.leaves(restored.params)
        ):
            assert orig.sharding == rest.sharding, (
                orig.sharding, rest.sharding
            )

    def test_max_to_keep_prunes(self, tmp_path, setup):
        init_fn, step_fn, tokens = setup
        state = init_fn(jax.random.key(0))
        with TrainCheckpointer(str(tmp_path), max_to_keep=2) as ckpt:
            for _ in range(4):
                state, _ = step_fn(state, tokens)
                ckpt.save(state)
            assert ckpt.latest_step() == 4
            steps = ckpt._mgr.all_steps()
        assert sorted(steps) == [3, 4]
