"""Fleet telemetry plane (instaslice_tpu/obs/telemetry.py): exposition
parsing, trace stitching + the caused-by demand→supply link, chip-hours
accounting, the multi-window burn-rate monitor, journal sink rotation,
the router/probe debug-surface parity, and the bench-trend gate.

The full-wire version (2 jax replicas behind the router, loadgen,
exact three-way reconciliation) is ``make telemetry-smoke``
(tools/telemetry_smoke.py); these tests pin the component contracts
it composes."""

import json
import os
import urllib.error
import urllib.request

import pytest

from instaslice_tpu.api.constants import (
    CAUSED_BY_ANNOTATION,
    REASON_ADMITTED,
    REASON_SLICE_DELETED,
    REASON_SLICE_UNGATED,
    REASON_SLO_BURN_CLEARED,
    REASON_SLO_BURN_HIGH,
)
from instaslice_tpu.obs import journal as journal_mod
from instaslice_tpu.obs.journal import Journal
from instaslice_tpu.obs.telemetry import (
    BurnRateMonitor,
    ChipHoursAccountant,
    FleetAggregator,
    TelemetryServer,
    TraceStitcher,
    metric_by_label,
    metric_sum,
    parse_exposition,
    span_component,
)
from instaslice_tpu.utils.trace import (
    Tracer,
    debug_trace_payload,
    get_tracer,
    new_trace_id,
)

EXPOSITION = """\
# HELP tpuslice_serve_requests_total served requests
# TYPE tpuslice_serve_requests_total counter
tpuslice_serve_requests_total{outcome="ok"} 7.0
tpuslice_serve_requests_total{outcome="shed"} 2.0
tpuslice_serve_tokens_total 321.0
tpuslice_serve_tokens_created 1.7e9
tpuslice_serve_class_ttft_seconds_count{tenant_class="latency"} 4.0
tpuslice_serve_class_ttft_seconds_count{tenant_class="standard"} 3.0
tpuslice_serve_slo_missed_total{slo="ttft",tenant_class="latency"} 1.0
tpuslice_serve_slo_missed_total{slo="tpot",tenant_class="latency"} 9.0
garbage line that must be skipped
tpuslice_weird{label="quo\\"te"} 1.0
"""


class TestExposition:
    def test_parse_and_sum(self):
        s = parse_exposition(EXPOSITION)
        assert metric_sum(s, "tpuslice_serve_requests_total") == 9.0
        assert metric_sum(s, "tpuslice_serve_requests_total",
                          outcome="ok") == 7.0
        assert metric_sum(s, "tpuslice_serve_tokens_total") == 321.0
        # exact-name lookups: the _created companion series the
        # prometheus client emits must never pollute a rollup
        assert metric_sum(s, "tpuslice_serve_tokens") == 0.0

    def test_by_label_with_match(self):
        s = parse_exposition(EXPOSITION)
        assert metric_by_label(
            s, "tpuslice_serve_class_ttft_seconds_count", "tenant_class"
        ) == {"latency": 4.0, "standard": 3.0}
        # the slo="ttft" filter is what keeps tpot misses out of the
        # TTFT attainment rollup
        assert metric_by_label(
            s, "tpuslice_serve_slo_missed_total", "tenant_class",
            slo="ttft",
        ) == {"latency": 1.0}

    def test_escaped_label_value(self):
        s = parse_exposition(EXPOSITION)
        assert ("tpuslice_weird", frozenset({("label", 'quo"te')})) in s


class TestSpanComponent:
    @pytest.mark.parametrize("name,comp", [
        ("controller.allocate", "controller"),
        ("repacker.migrate", "controller"),
        ("device.reserve", "agent"),
        ("agent.realize", "agent"),
        ("engine.decode", "serve"),
        ("serve.request", "serve"),
        ("router.route", "router"),
        ("telemetry.scrape", "telemetry"),
    ])
    def test_taxonomy(self, name, comp):
        assert span_component(name) == comp


class TestTraceStitcher:
    def test_dedupe_across_sources(self):
        st = TraceStitcher()
        span = {"name": "serve.request", "traceId": "t", "spanId": "a",
                "start": 1.0}
        st.add_span(span)
        assert st.ingest_debug_payload({"recent": [dict(span)]}) == 1
        assert len(st.spans("t")) == 1

    def test_caused_by_from_span_and_event(self):
        st = TraceStitcher()
        st.add_span({"name": "controller.allocate", "traceId": "g1",
                     "spanId": "s", "start": 2.0,
                     "attrs": {"caused_by": "serve-tid"}})
        st.add_event({"reason": REASON_ADMITTED, "traceId": "g2",
                      "attrs": {"caused_by": "serve-tid"}})
        assert st.caused_by("g1") == "serve-tid"
        assert st.links_into("serve-tid") == ["g1", "g2"]

    def test_timeline_merges_linked_grant(self):
        st = TraceStitcher()
        st.add_span({"name": "router.route", "traceId": "t",
                     "spanId": "r", "start": 0.0})
        st.add_span({"name": "serve.request", "traceId": "t",
                     "spanId": "s", "start": 1.0})
        st.add_span({"name": "controller.allocate", "traceId": "g",
                     "spanId": "c", "start": 2.0,
                     "attrs": {"caused_by": "t"}})
        tl = st.timeline("t")
        assert tl["spanCount"] == 3
        assert tl["components"] == ["controller", "router", "serve"]
        assert [x["traceId"] for x in tl["linked"]] == ["g"]
        # the trace's own spans come back in start order
        assert [s["spanId"] for s in tl["spans"]] == ["r", "s"]

    def test_orphans_cross_source(self, tmp_path):
        st = TraceStitcher()
        child = {"name": "a.b", "traceId": "t", "spanId": "c",
                 "parentId": "p", "start": 1.0}
        f1 = tmp_path / "one.jsonl"
        f1.write_text(json.dumps(child) + "\n")
        assert st.ingest_file(str(f1)) == 1
        assert len(st.orphans()) == 1
        # the parent arriving from ANOTHER file resolves the orphan —
        # the property tools/validate_trace.py --fleet exists for
        f2 = tmp_path / "two.jsonl"
        f2.write_text(json.dumps(
            {"name": "a.root", "traceId": "t", "spanId": "p",
             "start": 0.0}
        ) + "\n")
        st.ingest_file(str(f2))
        assert st.orphans() == []

    def test_ingest_file_tolerates_garbage(self, tmp_path):
        f = tmp_path / "bad.jsonl"
        f.write_text('not json\n{"name": "x.y", "traceId": "t", '
                     '"spanId": "s", "start": 1}\n')
        st = TraceStitcher()
        assert st.ingest_file(str(f)) == 1
        assert st.ingest_file(str(tmp_path / "missing.jsonl")) == 0


class TestChipHours:
    def test_open_close_and_live_accrual(self):
        ch = ChipHoursAccountant(clock=lambda: 100.0)
        ch.add_event({"reason": REASON_SLICE_UNGATED,
                      "objectRef": "alloc/a", "ts": 10.0,
                      "attrs": {"chips": 4}})
        ch.add_event({"reason": REASON_SLICE_UNGATED,
                      "objectRef": "alloc/b", "ts": 20.0,
                      "attrs": {"chips": 8}})
        assert ch.chips_live() == 12
        # live allocations accrue to "now"
        assert ch.chip_seconds(30.0) == pytest.approx(4 * 20 + 8 * 10)
        ch.add_event({"reason": REASON_SLICE_DELETED,
                      "objectRef": "alloc/a", "ts": 30.0})
        assert ch.chips_live() == 8
        # a's interval is closed at 80 chip-seconds forever
        assert ch.chip_seconds(40.0) == pytest.approx(80 + 8 * 20)

    def test_ignores_non_alloc_and_chipless(self):
        ch = ChipHoursAccountant(clock=lambda: 0.0)
        ch.add_event({"reason": REASON_SLICE_UNGATED,
                      "objectRef": "pod/x", "ts": 1.0,
                      "attrs": {"chips": 4}})
        ch.add_event({"reason": REASON_SLICE_UNGATED,
                      "objectRef": "alloc/x", "ts": 1.0,
                      "attrs": {"chips": "junk"}})
        ch.add_event({"reason": REASON_SLICE_DELETED,
                      "objectRef": "alloc/never-opened", "ts": 2.0})
        assert ch.chip_seconds(10.0) == 0.0


class TestBurnRateMonitor:
    def make(self, clk, windows=((10.0, 60.0, 2.0),), target=0.9):
        j = Journal(clock=lambda: clk[0])
        mon = BurnRateMonitor(target=target, windows=windows,
                              clock=lambda: clk[0], journal=j)
        return mon, j

    def test_fire_needs_both_windows_and_clear(self):
        clk = [1000.0]
        mon, j = self.make(clk)
        mon.observe("latency", 0, 100)
        clk[0] += 30
        mon.observe("latency", 30, 200)   # 30% errors -> burn 3 >= 2
        out = mon.evaluate()
        assert out["latency"]["burning"]
        assert out["latency"]["fired"] == ["10s/1m"]
        assert j.counts()[REASON_SLO_BURN_HIGH] == 1
        # no new misses: the windows slide clean -> cleared once
        clk[0] += 30
        mon.observe("latency", 30, 300)
        out = mon.evaluate()
        assert not out["latency"]["burning"]
        assert j.counts()[REASON_SLO_BURN_CLEARED] == 1
        # steady state journals nothing more
        clk[0] += 30
        mon.observe("latency", 30, 400)
        mon.evaluate()
        assert j.counts()[REASON_SLO_BURN_CLEARED] == 1

    def test_single_sample_cannot_fire(self):
        clk = [0.0]
        mon, j = self.make(clk)
        mon.observe("latency", 50, 50)
        out = mon.evaluate()
        assert not out["latency"]["burning"]
        assert REASON_SLO_BURN_HIGH not in j.counts()

    def test_short_window_alone_does_not_fire(self):
        # a burst that burns the short window but not the long one must
        # stay quiet — that is the whole point of multiwindow pairs
        clk = [0.0]
        mon, _ = self.make(clk, windows=((10.0, 1000.0, 2.0),))
        mon.observe("latency", 0, 1000)
        clk[0] += 990
        mon.observe("latency", 0, 2000)
        clk[0] += 10
        mon.observe("latency", 30, 2100)  # short burn 3, long burn ~0.3
        out = mon.evaluate()
        assert not out["latency"]["burning"]
        rates = out["latency"]["rates"]
        assert rates["10s"] >= 2.0 > rates["1000s"]

    def test_target_validation(self):
        with pytest.raises(ValueError):
            BurnRateMonitor(target=1.0)


class TestJournalRotation:
    def emit_n(self, j, n):
        for i in range(n):
            j.emit("test", reason=REASON_SLICE_UNGATED,
                   object_ref=f"alloc/{i}", message="x" * 64)

    def test_rotates_and_keeps_n(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        j = Journal(event_file=path, max_mb=0.0005, keep=2)  # ~512 B
        self.emit_n(j, 40)
        j.close()
        assert os.path.exists(path + ".1")
        assert os.path.exists(path + ".2")
        assert not os.path.exists(path + ".3")  # keep=2 bounds it
        # every surviving file is valid JSONL and the ring kept all 40
        for p in (path, path + ".1", path + ".2"):
            with open(p) as f:
                for line in f:
                    assert json.loads(line)["reason"] \
                        == REASON_SLICE_UNGATED
        assert j.counts()[REASON_SLICE_UNGATED] == 40

    def test_unbounded_by_default(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        j = Journal(event_file=path)
        self.emit_n(j, 40)
        j.close()
        assert not os.path.exists(path + ".1")
        with open(path) as f:
            assert len(f.readlines()) == 40

    def test_rotation_failure_degrades_to_ring_only(self, tmp_path,
                                                    monkeypatch):
        path = str(tmp_path / "events.jsonl")
        j = Journal(event_file=path, max_mb=0.0005, keep=2)

        def boom(*a, **k):
            raise OSError("disk broke")

        monkeypatch.setattr(journal_mod.os, "replace", boom)
        self.emit_n(j, 40)
        # the sink is gone but the ring keeps recording — the same
        # degradation contract as an unwritable TPUSLICE_EVENT_FILE
        assert j._file is None
        assert j.counts()[REASON_SLICE_UNGATED] == 40
        j.close()

    def test_env_knobs(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPUSLICE_EVENT_FILE_MAX_MB", "2")
        monkeypatch.setenv("TPUSLICE_EVENT_FILE_KEEP", "5")
        j = Journal(event_file=str(tmp_path / "e.jsonl"))
        assert j._max_bytes == 2 * 1024 * 1024
        assert j._keep == 5
        j.close()
        monkeypatch.setenv("TPUSLICE_EVENT_FILE_MAX_MB", "junk")
        j = Journal(event_file=str(tmp_path / "e2.jsonl"))
        assert j._max_bytes == 0
        j.close()


class TestDebugTracePayload:
    def test_shapes_and_errors(self):
        t = Tracer(capacity=64)
        with t.span("serve.request") as sp:
            pass
        tid = sp.trace_id
        out = debug_trace_payload({"trace_id": [tid]}, tracer=t)
        assert out["traceId"] == tid and out["spans"]
        out = debug_trace_payload({"n": ["5"]}, tracer=t)
        assert set(out) == {"summary", "slowest", "recent"}
        with pytest.raises(ValueError):
            debug_trace_payload({"n": ["0"]}, tracer=t)
        with pytest.raises(LookupError):
            debug_trace_payload({"trace_id": ["absent"]}, tracer=t)


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read())


class TestRouterDebugParity:
    def test_router_serves_metrics_trace_events(self):
        from instaslice_tpu.serving.router import Router

        router = Router(replicas=(), poll_interval=0.1)
        router.start()
        try:
            with get_tracer().span("router.route"):
                pass
            _, trace = _get(router.url + "/v1/debug/trace?n=50")
            assert {"summary", "slowest", "recent"} <= set(trace)
            _, events = _get(router.url + "/v1/debug/events?n=10")
            assert "events" in events
            with urllib.request.urlopen(router.url + "/metrics",
                                        timeout=5) as r:
                body = r.read().decode()
                assert r.headers["Content-Type"].startswith(
                    "text/plain"
                )
            assert parse_exposition(body) is not None
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(router.url + "/v1/debug/trace?n=0")
            assert ei.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(router.url + "/v1/debug/trace?trace_id=absent")
            assert ei.value.code == 404
        finally:
            router.stop()

    def test_probe_server_serves_debug_surface(self):
        from instaslice_tpu.utils.probes import ProbeServer

        p = ProbeServer("127.0.0.1:0").start()
        try:
            port = p._srv.server_address[1]
            base = f"http://127.0.0.1:{port}"
            with get_tracer().span("controller.allocate"):
                pass
            _, trace = _get(base + "/v1/debug/trace?n=50")
            assert {"summary", "slowest", "recent"} <= set(trace)
            _, events = _get(base + "/v1/debug/events?n=10")
            assert "events" in events
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(base + "/v1/debug/trace?trace_id=absent")
            assert ei.value.code == 404
        finally:
            p.stop()


class TestAggregatorOffline:
    """The aggregator over files only — no HTTP, pinned clock."""

    def make_agg(self, tmp_path, clk, spans=(), events=()):
        tf = tmp_path / "trace.jsonl"
        tf.write_text("".join(json.dumps(s) + "\n" for s in spans))
        ef = tmp_path / "events.jsonl"
        ef.write_text("".join(json.dumps(e) + "\n" for e in events))
        return FleetAggregator(
            trace_files=(str(tf),), event_files=(str(ef),),
            clock=lambda: clk[0], journal=Journal(),
        )

    def test_poll_rolls_up_files(self, tmp_path):
        clk = [100.0]
        agg = self.make_agg(
            tmp_path, clk,
            spans=[{"name": "serve.request", "traceId": "t",
                    "spanId": "s", "start": 1.0}],
            events=[
                {"seq": 1, "ts": 10.0, "component": "agent",
                 "reason": REASON_SLICE_UNGATED,
                 "objectRef": "alloc/a", "attrs": {"chips": 4}},
                {"seq": 2, "ts": 60.0, "component": "agent",
                 "reason": REASON_SLICE_DELETED,
                 "objectRef": "alloc/a"},
            ],
        )
        fleet = agg.poll()
        assert fleet["traces"] == 1
        assert fleet["chip_hours"]["chip_seconds"] \
            == pytest.approx(200.0)
        assert fleet["chip_hours"]["chips_live"] == 0
        # event dedup: a second poll re-reads the same file without
        # double-counting the interval
        clk[0] += 10
        fleet = agg.poll()
        assert fleet["polls"] == 2
        assert fleet["chip_hours"]["chip_seconds"] \
            == pytest.approx(200.0)

    def test_http_plane(self, tmp_path):
        clk = [100.0]
        agg = self.make_agg(tmp_path, clk, spans=[
            {"name": "serve.request", "traceId": "t", "spanId": "s",
             "start": 1.0},
        ])
        tel = TelemetryServer(agg).start()
        try:
            # not ready until the first poll lands
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(tel.url + "/readyz")
            assert ei.value.code == 503
            agg.poll()
            assert _get(tel.url + "/readyz")[0] == 200
            _, fleet = _get(tel.url + "/v1/fleet")
            assert fleet["polls"] == 1
            _, tl = _get(tel.url + "/v1/fleet/trace?trace_id=t")
            assert tl["spanCount"] == 1
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(tel.url + "/v1/fleet/trace")
            assert ei.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(tel.url + "/v1/fleet/trace?trace_id=zzz")
            assert ei.value.code == 404
            with urllib.request.urlopen(tel.url + "/metrics",
                                        timeout=5) as r:
                s = parse_exposition(r.read().decode())
            assert any(n == "tpuslice_fleet_tokens_total"
                       for n, _ in s) or s == {}  # noop-metrics env
        finally:
            tel.stop()

    def test_dead_endpoints_are_counted_not_raised(self, tmp_path):
        clk = [0.0]
        agg = FleetAggregator(
            router_url="http://127.0.0.1:1",
            replica_urls=("http://127.0.0.1:1",),
            clock=lambda: clk[0], journal=Journal(),
            http_timeout=0.2,
        )
        fleet = agg.poll()
        assert fleet["scrapes"]["error"] > 0
        assert fleet["replicas"]["http://127.0.0.1:1"]["ok"] is False


class TestStitchedGrantE2E:
    """router→serve demand trace + a capacity-blocked pod's grant
    trace, linked through the caused-by annotation at admission: one
    timeline, >= 3 components. (The full-wire version with real jax
    replicas is ``make telemetry-smoke``.)"""

    def test_capacity_blocked_grant_stitches(self):
        from instaslice_tpu.sim import SimCluster

        tid = new_trace_id()
        tracer = get_tracer()
        # the demand side: a routed serving request under ONE trace id
        with tracer.span("router.route", trace_id=tid):
            with tracer.span("serve.request"):
                pass

        with SimCluster(n_nodes=1, deletion_grace_seconds=0.2) as c:
            # a v5e node is 2x4 = 8 chips: two 2x2 fillers exhaust it
            c.submit("filler-a", "v5e-2x2")
            c.submit("filler-b", "v5e-2x2")
            assert c.wait_phase("filler-a", "Running", timeout=30)
            assert c.wait_phase("filler-b", "Running", timeout=30)
            c.submit("blocked", "v5e-1x1",
                     annotations={CAUSED_BY_ANNOTATION: tid})
            assert not c.wait_phase("blocked", "Running", timeout=1.0), \
                "pod ran with the node full — not capacity-blocked"
            c.delete_pod("filler-a")
            assert c.wait_gone("filler-a", timeout=30)
            assert c.wait_phase("blocked", "Running", timeout=30)

        st = TraceStitcher()
        st.ingest_debug_payload(
            debug_trace_payload({"n": ["2048"]}, tracer=tracer)
        )
        from instaslice_tpu.obs.journal import debug_events_payload

        for ev in debug_events_payload({"n": ["2000"]})["events"]:
            st.add_event(ev)

        grants = st.links_into(tid)
        assert grants, "no grant trace linked via caused-by"
        tl = st.timeline(tid)
        assert len(tl["components"]) >= 3, tl["components"]
        assert {"router", "serve", "controller"} <= set(
            tl["components"]
        )
        # the grant trace's allocate span carries the stamp itself
        grant_spans = st.spans(grants[0])
        alloc = [s for s in grant_spans
                 if s["name"] == "controller.allocate"]
        assert alloc and alloc[0]["attrs"]["caused_by"] == tid

    def test_malformed_caused_by_is_dropped(self):
        from instaslice_tpu.sim import SimCluster

        bad = "zz;DROP TABLE|" + "x" * 80
        with SimCluster(n_nodes=1, deletion_grace_seconds=0.2) as c:
            c.submit("sneaky", "v5e-1x1",
                     annotations={CAUSED_BY_ANNOTATION: bad})
            assert c.wait_phase("sneaky", "Running", timeout=30)

        st = TraceStitcher()
        st.ingest_debug_payload(
            debug_trace_payload({"n": ["2048"]}, tracer=get_tracer())
        )
        assert st.links_into(bad) == []


class TestBenchTrend:
    def _load(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench_trend",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            )), "tools", "bench_trend.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_headline_shapes(self):
        bt = self._load()
        assert bt.headline({"metric": "m", "value": 2, "unit": "u"}) \
            == ("m", 2.0, "u")
        assert bt.headline(
            {"parsed": {"metric": "m", "value": 3, "unit": "u"}}
        ) == ("m", 3.0, "u")
        assert bt.headline(
            {"tail": 'noise\n{"metric": "m", "value": 4, '
                     '"unit": "u"}\n'}
        ) == ("m", 4.0, "u")
        assert bt.headline(
            {"metric": "grants", "scale": {"grants_per_sec": 5}}
        ) == ("grants", 5.0, "grants/sec")
        assert bt.headline({"tail": "garbage only"}) is None

    def _write(self, root, name, value, unit="toks/s"):
        with open(os.path.join(root, name), "w") as f:
            json.dump({"metric": "m", "value": value, "unit": unit}, f)

    def test_regression_gate_direction(self, tmp_path):
        bt = self._load()
        root = str(tmp_path)
        self._write(root, "BENCH_SERVING_r01.json", 100)
        self._write(root, "BENCH_SERVING_r02.json", 80)  # -20%: regress
        self._write(root, "BENCH_LAT_r01.json", 1.0, unit="seconds")
        self._write(root, "BENCH_LAT_r02.json", 0.5, unit="seconds")
        tiers = bt.load_records(root)
        regs = bt.check_regressions(tiers, 0.10)
        assert [r["tier"] for r in regs] == ["SERVING"]
        # lower-is-better: 0.5s after 1.0s is a WIN, not a regression;
        # and within threshold passes
        self._write(root, "BENCH_SERVING_r03.json", 95)
        assert bt.check_regressions(bt.load_records(root), 0.10) == []
        assert bt.main(["--dir", root]) == 0
        self._write(root, "BENCH_LAT_r03.json", 2.0, unit="seconds")
        assert bt.main(["--dir", root, "--json"]) == 2

    def test_unparsable_records_skipped_never_fatal(self, tmp_path):
        bt = self._load()
        root = str(tmp_path)
        self._write(root, "BENCH_r01.json", 100)
        (tmp_path / "BENCH_r02.json").write_text("{truncated")
        tiers = bt.load_records(root)
        assert tiers["GRANT"][1]["value"] is None
        assert bt.check_regressions(tiers, 0.10) == []
        assert bt.main(["--dir", root]) == 0

    def test_repo_history_parses(self):
        # the real record set must keep parsing — history stays
        # readable even where it is ragged
        bt = self._load()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__
        )))
        tiers = bt.load_records(repo)
        assert tiers, "no BENCH records found in the repo root"
        parseable = [e for es in tiers.values() for e in es
                     if e["value"] is not None]
        assert len(parseable) >= 10


class TestValidateTraceFleet:
    def _run(self, args):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "validate_trace",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            )), "tools", "validate_trace.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.main(args)

    def _span(self, name, tid, sid, parent=""):
        s = {"name": name, "traceId": tid, "spanId": sid,
             "start": 1.0, "durationMs": 1.0}
        if parent:
            s["parentId"] = parent
        return s

    def test_cross_file_parent_passes_only_with_fleet(self, tmp_path,
                                                      capsys):
        f1 = tmp_path / "serve.jsonl"
        f1.write_text(json.dumps(
            self._span("serve.request", "t", "child", parent="root")
        ) + "\n")
        f2 = tmp_path / "router.jsonl"
        f2.write_text(json.dumps(
            self._span("router.route", "t", "root")
        ) + "\n")
        # single-file view: a genuine orphan
        assert self._run([str(f1)]) == 1
        capsys.readouterr()
        # fleet view: the parent lives in the router's file
        assert self._run([str(f1), str(f2), "--fleet"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["fleet"]["orphans"] == 0
        assert out["fleet"]["files"] == 2

    def test_fleet_still_fails_on_true_orphan(self, tmp_path, capsys):
        f1 = tmp_path / "a.jsonl"
        f1.write_text(json.dumps(
            self._span("serve.request", "t", "child", parent="gone")
        ) + "\n")
        f2 = tmp_path / "b.jsonl"
        f2.write_text(json.dumps(
            self._span("router.route", "t2", "root")
        ) + "\n")
        assert self._run([str(f1), str(f2), "--fleet"]) == 1
        out = json.loads(capsys.readouterr().out)
        assert out["fleet"]["orphans"] == 1

    def test_multiple_files_require_fleet(self, tmp_path):
        f1 = tmp_path / "a.jsonl"
        f1.write_text("")
        with pytest.raises(SystemExit):
            self._run([str(f1), str(f1)])
