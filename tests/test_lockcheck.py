"""lockcheck: the runtime lock-order detector.

The centerpiece is the deliberate ABBA deadlock: two threads acquiring
two named locks in opposite orders on a *benign* interleaving (no actual
deadlock occurs) — the detector must still report the cycle, because the
hazard is the ordering, not the unlucky schedule. This is exactly what
arming ``TPUSLICE_LOCKCHECK=1`` buys the chaos tier.
"""

import threading
import time

import pytest

from instaslice_tpu.utils import lockcheck as lc


@pytest.fixture(autouse=True)
def armed_lockcheck():
    """Arm + isolate per test; restore whatever the session had (under
    ``make chaos`` with TPUSLICE_LOCKCHECK=1 the env arms the session —
    these tests must not disarm it behind the chaos tier's back).

    The session's pre-existing findings are stashed before the reset and
    merged back after: in an armed full-suite run, a REAL project-lock
    cycle recorded before this module must still reach the conftest
    session gate — these tests' deliberate cycles are what gets
    discarded, not the session's."""
    was_armed = lc.armed()
    stash = lc.snapshot()
    lc.reset()
    lc.arm()
    yield
    lc.reset()
    lc.restore(stash)
    # RESTORE, don't just conditionally disarm: TestDisarmed tests
    # disarm in their bodies, and leaving the session disarmed would
    # silently defeat the TPUSLICE_LOCKCHECK session gate for every
    # test that runs after this module
    if was_armed:
        lc.arm()
    else:
        lc.disarm()


def _run_threads(*fns, timeout=10.0):
    threads = [threading.Thread(target=fn, daemon=True) for fn in fns]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
        assert not t.is_alive(), "test thread wedged"


class TestOrderGraph:
    def test_abba_cycle_reported(self):
        """Opposite-order acquisition across two threads: a reported
        cycle A -> B -> A even though the interleaving never deadlocks
        (the second thread backs off via a timed acquire)."""
        a, b = lc.named_lock("fixture.A"), lc.named_lock("fixture.B")
        ready = threading.Event()

        def t1():
            with a:
                ready.set()
                time.sleep(0.05)
                with b:
                    pass

        def t2():
            ready.wait(5)
            with b:
                time.sleep(0.1)
                if a.acquire(timeout=0.02):   # backs off: no deadlock
                    a.release()

        _run_threads(t1, t2)
        rep = lc.report()
        assert rep["cycles"], rep
        chain = rep["cycles"][0]["chain"]
        assert chain[0] == chain[-1]
        assert set(chain) == {"fixture.A", "fixture.B"}
        assert len(rep["cycles"][0]["threads"]) == 2
        with pytest.raises(lc.LockOrderError) as ei:
            lc.assert_clean()
        assert ei.value.report["cycles"]

    def test_consistent_order_is_clean(self):
        a, b = lc.named_lock("fixture.A"), lc.named_lock("fixture.B")

        def worker():
            for _ in range(5):
                with a:
                    with b:
                        pass

        _run_threads(worker, worker)
        rep = lc.report()
        assert not rep["cycles"], rep
        assert {
            (e["held"], e["acquired"]) for e in rep["edges"]
        } == {("fixture.A", "fixture.B")}
        lc.assert_clean()

    def test_three_lock_cycle(self):
        """Cycles longer than two: A->B, B->C, C->A."""
        locks = {n: lc.named_lock(f"fixture.{n}") for n in "ABC"}

        def pair(first, second):
            with locks[first]:
                got = locks[second].acquire(timeout=0.01)
                if got:
                    locks[second].release()

        # sequential, single thread: ordering edges are recorded from
        # the acquisition pattern alone
        pair("A", "B")
        pair("B", "C")
        pair("C", "A")
        rep = lc.report()
        assert rep["cycles"], rep
        assert len(rep["cycles"][0]["chain"]) == 4  # closed A..A

    def test_rlock_reentry_records_no_edge(self):
        r = lc.named_rlock("fixture.R")
        with r:
            with r:
                pass
        rep = lc.report()
        assert rep["edges"] == []
        assert rep["cycles"] == []

    def test_self_deadlock_on_plain_lock_reported(self):
        lock = lc.named_lock("fixture.self")
        assert lock.acquire()
        assert not lock.acquire(timeout=0.01)
        lock.release()
        rep = lc.report()
        assert {"chain": ["fixture.self", "fixture.self"],
                "threads": [threading.current_thread().name]} in rep["cycles"]


class TestConditionSemantics:
    def test_wait_suspends_the_held_entry(self):
        """While a thread waits on a condition, the lock is RELEASED;
        locks acquired by other threads meanwhile must not fabricate an
        ordering edge cv -> other."""
        cv = lc.named_condition("fixture.cv")
        other = lc.named_lock("fixture.other")
        woke = threading.Event()

        def waiter():
            with cv:
                cv.wait(timeout=5)
                woke.set()

        def toucher():
            time.sleep(0.05)
            with other:
                pass
            with cv:
                cv.notify_all()

        _run_threads(waiter, toucher)
        assert woke.is_set()
        edges = {
            (e["held"], e["acquired"]) for e in lc.report()["edges"]
        }
        assert ("fixture.cv", "fixture.other") not in edges

    def test_explicit_acquire_release_instrumented(self):
        cv = lc.named_condition("fixture.cv2")
        inner = lc.named_lock("fixture.inner")
        cv.acquire()
        with inner:
            pass
        cv.release()
        edges = {
            (e["held"], e["acquired"]) for e in lc.report()["edges"]
        }
        assert ("fixture.cv2", "fixture.inner") in edges

    def test_notify_wakes_waiter(self):
        cv = lc.named_condition("fixture.cv3")
        got = []

        def waiter():
            with cv:
                cv.wait(timeout=5)
                got.append(1)

        def notifier():
            time.sleep(0.05)
            with cv:
                cv.notify_all()

        _run_threads(waiter, notifier)
        assert got == [1]


class TestHoldTimes:
    def test_holds_recorded(self):
        lock = lc.named_lock("fixture.hold")
        with lock:
            time.sleep(0.02)
        holds = lc.report()["holds"]["fixture.hold"]
        assert holds["count"] == 1
        assert holds["maxSeconds"] >= 0.02
        assert holds["totalSeconds"] >= 0.02

    def test_long_hold_incident(self, monkeypatch):
        monkeypatch.setattr(lc, "HOLD_WARN_SECONDS", 0.01)
        lock = lc.named_lock("fixture.slow")
        with lock:
            time.sleep(0.03)
        incidents = lc.report()["longHolds"]
        assert any(i["name"] == "fixture.slow" for i in incidents)


class TestSnapshotRestore:
    def test_session_cycles_survive_a_reset_cycle(self):
        """What the autouse fixture does on behalf of an armed session:
        real findings stashed before reset() come back via restore()."""
        a, b = lc.named_lock("fixture.SA"), lc.named_lock("fixture.SB")
        with a:
            with b:
                pass
        with b:
            if a.acquire(timeout=0.01):
                a.release()
        assert lc.report()["cycles"]
        stash = lc.snapshot()
        lc.reset()
        assert not lc.report()["cycles"]
        # an unrelated edge recorded between reset and restore survives
        with a:
            with b:
                pass
        lc.restore(stash)
        rep = lc.report()
        assert any(
            set(c["chain"]) == {"fixture.SA", "fixture.SB"}
            for c in rep["cycles"]
        )
        merged = {
            (e["held"], e["acquired"]): e["count"] for e in rep["edges"]
        }
        assert merged[("fixture.SA", "fixture.SB")] == 2  # 1 + restored 1


class TestDisarmed:
    def test_disarmed_records_nothing(self):
        lc.disarm()
        a, b = lc.named_lock("fixture.A"), lc.named_lock("fixture.B")
        with a:
            with b:
                pass
        with b:
            if a.acquire(timeout=0.01):
                a.release()
        rep = lc.report()
        assert rep["edges"] == [] and rep["cycles"] == []
        assert rep["holds"] == {}

    def test_disarm_mid_hold_leaves_no_stale_entry(self):
        """Disarming between an acquire and its release must still pop
        the per-thread held entry — a leftover would fabricate a
        self-deadlock ``N -> N`` on the next armed acquire of the same
        lock, plus phantom ordering edges from a lock not actually
        held."""
        lock = lc.named_lock("fixture.midhold")
        other = lc.named_lock("fixture.midhold-other")
        lock.acquire()          # armed: entry pushed
        lc.disarm()
        lock.release()          # disarmed: entry must STILL pop
        lc.arm()
        with lock:              # no false self-deadlock
            with other:         # no edge beyond the real one
                pass
        rep = lc.report()
        assert rep["cycles"] == []
        assert {
            (e["held"], e["acquired"]) for e in rep["edges"]
        } == {("fixture.midhold", "fixture.midhold-other")}

    def test_factory_semantics_survive_disarm(self):
        lc.disarm()
        lock = lc.named_lock("fixture.sem")
        assert lock.acquire()
        assert not lock.acquire(timeout=0.01)   # plain-lock semantics
        lock.release()
        assert not lock.locked()
        r = lc.named_rlock("fixture.rsem")
        with r:
            with r:
                assert r.locked()


class TestLiveView:
    """`lc.live()` and the `/v1/debug/locks` surface it feeds."""

    def test_live_shows_held_stack_then_empties(self):
        a = lc.named_lock("fixture.live-a")
        b = lc.named_lock("fixture.live-b")
        holding = threading.Event()
        done = threading.Event()

        def holder():
            with a:
                with b:
                    holding.set()
                    assert done.wait(10.0)

        t = threading.Thread(target=holder, name="live-holder", daemon=True)
        t.start()
        assert holding.wait(10.0)
        try:
            snap = lc.live()
            assert snap["armed"] is True
            mine = [
                th for th in snap["threads"] if th["thread"] == "live-holder"
            ]
            assert len(mine) == 1
            held = mine[0]["held"]
            assert [h["name"] for h in held] == [
                "fixture.live-a", "fixture.live-b",
            ]
            assert held[0]["heldSeconds"] >= 0.0
            # depth is per-lock reentrancy, not stack position
            assert held[0]["depth"] == 1 and held[1]["depth"] == 1
        finally:
            done.set()
            t.join(timeout=10.0)
        assert not t.is_alive()
        after = lc.live()
        assert all(
            th["thread"] != "live-holder" or th["held"] == []
            for th in after["threads"]
        )

    def test_debug_payload_merges_report_and_live(self):
        with lc.named_lock("fixture.payload"):
            payload = lc.debug_locks_payload()
        # report() keys stay present alongside the live view
        assert "cycles" in payload and "edges" in payload
        assert "holds" in payload and "armed" in payload
        assert isinstance(payload["live"], list)
        names = {
            h["name"] for th in payload["live"] for h in th["held"]
        }
        assert "fixture.payload" in names  # snapshot taken while held
        after = {
            h["name"]
            for th in lc.debug_locks_payload()["live"]
            for h in th["held"]
        }
        assert "fixture.payload" not in after

    def test_probe_server_serves_locks_endpoint(self):
        import json
        import urllib.request

        from instaslice_tpu.utils.probes import ProbeServer

        srv = ProbeServer("127.0.0.1:0")
        srv.start()
        try:
            gate = threading.Event()
            done = threading.Event()

            def holder():
                with lc.named_lock("fixture.http-held"):
                    gate.set()
                    assert done.wait(10.0)

            t = threading.Thread(
                target=holder, name="http-holder", daemon=True
            )
            t.start()
            assert gate.wait(10.0)
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/v1/debug/locks", timeout=10
                ) as resp:
                    assert resp.status == 200
                    payload = json.loads(resp.read())
            finally:
                done.set()
                t.join(timeout=10.0)
            held = {
                h["name"]
                for th in payload["live"]
                if th["thread"] == "http-holder"
                for h in th["held"]
            }
            assert held == {"fixture.http-held"}
            assert "edges" in payload
        finally:
            srv.stop()

    def test_ctl_describe_locks_renders_live_state(self, capsys):
        from instaslice_tpu.cli.tpuslicectl import main

        from instaslice_tpu.utils.probes import ProbeServer

        srv = ProbeServer("127.0.0.1:0")
        srv.start()
        try:
            gate = threading.Event()
            done = threading.Event()

            def holder():
                with lc.named_lock("fixture.ctl-held"):
                    gate.set()
                    assert done.wait(10.0)

            t = threading.Thread(
                target=holder, name="ctl-holder", daemon=True
            )
            t.start()
            assert gate.wait(10.0)
            try:
                rc = main([
                    "describe", "locks",
                    "--url", f"http://127.0.0.1:{srv.port}",
                ])
            finally:
                done.set()
                t.join(timeout=10.0)
            out = capsys.readouterr().out
            assert rc == 0
            assert "ctl-holder" in out
            assert "fixture.ctl-held" in out
        finally:
            srv.stop()

    def test_ctl_describe_locks_requires_url(self):
        from instaslice_tpu.cli.tpuslicectl import main

        assert main(["describe", "locks"]) == 2
