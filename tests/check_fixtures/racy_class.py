"""Seeded guarded-by violations for tests/test_slicecheck.py.

One class, three distinct defects:

- ``hits`` is declared ``guarded_by("fixture.racy")`` but touched
  lock-free in ``_loop`` (write) and ``snapshot`` (read): exactly TWO
  ``guarded-field`` findings.
- ``shared_log`` is written from the worker thread and drained from a
  public method with no declaration at all: ONE ``undeclared-shared``.
- ``ghost`` names a lock no factory registers: ONE
  ``guard-unknown-lock``.

``noted`` shows the escape hatch: an ``unguarded(reason)`` declaration
keeps a deliberately racy field out of the report.
"""

from __future__ import annotations

import threading

from instaslice_tpu.utils.guards import guarded_by, unguarded
from instaslice_tpu.utils.lockcheck import named_lock


class RacyCounter:
    hits: guarded_by("fixture.racy")
    ghost: guarded_by("fixture.ghost")
    noted: unguarded("fixture: deliberately racy counter")

    def __init__(self) -> None:
        self._lock = named_lock("fixture.racy")
        self.hits = 0
        self.ghost = 0
        self.noted = 0
        self.shared_log = []
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def _loop(self) -> None:
        while True:
            self.hits += 1          # guarded-field: write, no lock
            self.shared_log.append(1)
            self.noted += 1         # declared unguarded: no finding

    def bump(self) -> None:
        with self._lock:
            self.hits += 1          # correct: no finding

    def snapshot(self) -> int:
        return self.hits            # guarded-field: read, no lock

    def drain(self) -> list:
        with self._lock:
            # the lock is held, but shared_log carries NO declaration:
            # undeclared-shared (reachable from _loop + external)
            return list(self.shared_log)
