"""A correctly-disciplined concurrent class: every access to the
guarded field is under its named lock (including via the
receiver-typed ``with self._lock:`` match, since ``_lock`` is an
attribute name shared with ``racy_class.py``'s different lock). Zero
findings."""

from __future__ import annotations

import threading

from instaslice_tpu.utils.guards import guarded_by
from instaslice_tpu.utils.lockcheck import named_lock


class CleanCounter:
    clean_hits: guarded_by("fixture.clean")

    def __init__(self) -> None:
        self._lock = named_lock("fixture.clean")
        self.clean_hits = 0
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def _loop(self) -> None:
        with self._lock:
            self.clean_hits += 1

    def snapshot(self) -> int:
        with self._lock:
            return self.clean_hits
