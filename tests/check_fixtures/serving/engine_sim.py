"""Seeded compile-budget violations (engine module: the path carries
``serving/engine``) for tests/test_slicecheck.py.

``compile_budget`` declares the bounded program set; ``_decode`` is
accounted, ``_rogue`` is a jit attribute the budget never mentions, and
``extra`` is a jit program not even bound to a ``self._X`` slot — TWO
``unbudgeted-jit`` findings.
"""

from __future__ import annotations

import jax


def compile_budget():
    return {"decode": 1, "prefill": 1}


class MiniEngine:
    def __init__(self, fns) -> None:
        self._decode = jax.jit(fns.decode)      # accounted: no finding
        self._rogue = jax.jit(fns.rogue)        # unbudgeted-jit
        extra = jax.jit(fns.extra)              # unbudgeted-jit
        self._extra = extra
