"""Emit sites keeping ``reasons.py`` constants alive (imports alone do
not count as liveness — the reference must appear in executable code)."""

from tests.check_fixtures.reasons import (
    FIXTURE_TRANSITIONS,
    REASON_USED,
)


def emit_fixture_event(journal) -> tuple:
    journal.emit("fixture", reason=REASON_USED)
    return FIXTURE_TRANSITIONS
