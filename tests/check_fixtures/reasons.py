"""Seeded reason-catalog rot for tests/test_slicecheck.py.

This file IS the corpus's catalog (it assigns ``EVENT_REASONS``).
``REASON_USED`` is emitted by ``emitter.py``; ``REASON_IN_CONTAINER``
is live because the container it sits in is referenced elsewhere;
``REASON_DEAD`` has no emit site anywhere in the corpus — exactly ONE
``dead-reason`` finding.
"""

REASON_USED = "FixtureUsed"
REASON_DEAD = "FixtureDead"
REASON_IN_CONTAINER = "FixtureContained"

FIXTURE_TRANSITIONS = (REASON_IN_CONTAINER,)

EVENT_REASONS = {REASON_USED, REASON_DEAD, REASON_IN_CONTAINER}
