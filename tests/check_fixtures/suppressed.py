# slicecheck: disable-file=guard-unknown-lock
"""The same defect shapes as the seeded fixtures, every one carrying a
justified suppression: line-level ``disable=`` for the lock-free
access, file-level ``disable-file=`` (header line above) for the
unregistered lock name. Zero findings — and the suppressions are
rule-scoped, which ``test_suppression_is_per_rule`` pins."""

from __future__ import annotations

from instaslice_tpu.utils.guards import guarded_by
from instaslice_tpu.utils.lockcheck import named_lock


class SuppressedCounter:
    sup_hits: guarded_by("fixture.sup")
    sup_ghost: guarded_by("fixture.phantom")

    def __init__(self) -> None:
        self._lock = named_lock("fixture.sup")
        self.sup_hits = 0
        self.sup_ghost = 0

    def bump(self) -> None:
        # justified: fixture exercises the line-level escape hatch
        self.sup_hits += 1  # slicecheck: disable=guarded-field
