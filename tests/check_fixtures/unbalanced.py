"""Seeded paired-resource leak for tests/test_slicecheck.py.

``leaky_import`` opens (``allocate``) and closes (``release``) on the
same receiver but raises between the two with the close not in a
``finally``: exactly ONE ``unbalanced-pair`` finding. The other two
functions are the legal shapes — close in ``finally``, and a raise
inside the open's own failure handler (nothing was allocated, nothing
can leak).
"""

from __future__ import annotations


def leaky_import(pool, blob):
    table = pool.allocate(4)
    if not blob:
        # unbalanced-pair: this exit skips pool.release(table)
        raise ValueError("bad blob")
    pool.release(table)
    return table


def balanced_import(pool, blob):
    table = pool.allocate(4)
    try:
        if not blob:
            raise ValueError("bad blob")
        return table
    finally:
        pool.release(table)


def open_failure_is_not_a_leak(pool):
    try:
        table = pool.allocate(4)
    except MemoryError:
        # allocate itself failed — there is no table to release
        raise RuntimeError("pool exhausted") from None
    pool.release(table)
