"""Seeded dispatch-hygiene violations (hot-path module: lives under
``models/``) for tests/test_slicecheck.py.

- ``drive`` syncs the host three times per iteration: ``.item()``,
  ``jax.device_get`` and ``float(jnp.sum(...))`` — THREE
  ``host-sync-in-loop`` findings.
- ``attend_fast`` jits a function whose ``attend_len`` parameter is
  shape-bearing but not static: ONE ``nonstatic-shape-arg``.
  ``attend_static`` shows the fix and must NOT be flagged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def decode_step(state):
    return state, jnp.argmax(state)


step = jax.jit(decode_step)


def attend(x, attend_len):
    return x[:attend_len]


attend_fast = jax.jit(attend)                             # flagged
attend_static = jax.jit(attend, static_argnames=("attend_len",))


def drive(state, n):
    outs = []
    for _ in range(n):
        state, tok = step(state)
        outs.append(tok.item())           # host-sync-in-loop
        mirror = jax.device_get(state)    # host-sync-in-loop
        total = float(jnp.sum(state))     # host-sync-in-loop
        del mirror, total
    return outs
