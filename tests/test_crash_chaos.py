"""Crash-consistent lifecycle tier (docs/RECOVERY.md): process-kill
chaos, restart reconciliation, and the self-healing watchdogs.

Fast units cover the CrashPlan grammar, the agent orphan sweep, the
loadgen ``stream-truncated`` outcome, and ``validate_events
--epochs``. The ``smoke`` tests (the ``make chaos-crash-smoke`` gate
inside ``make test``) kill one controller, one agent, and one serving
replica mid-lifecycle under load and assert the recovery invariants:
every pod granted, zero double-allocations, zero orphaned device
slices after quiesce, zero hung requests, event chains legal across
restart epochs. The kill-loop (``make chaos`` crash arm) sweeps every
control-plane crash point per seed.
"""

import os
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import validate_events  # noqa: E402

from instaslice_tpu.api.types import slice_uuid_for
from instaslice_tpu.faults import (
    CrashPlan,
    InjectedCrash,
    maybe_crash,
    set_crash_plan,
)
from instaslice_tpu.obs.journal import get_journal, reset_journal
from instaslice_tpu.topology.placement import Box

CHAOS_SEED = int(os.environ.get("CHAOS_SEED", "1234"))

#: every control-plane crash site the kill-loop sweeps (site, nth)
CONTROL_SITES = [
    ("controller.write_allocation", 1),
    ("controller.write_allocation", 2),
    ("controller.ungate", 1),
    ("agent.realize", 1),
    ("agent.teardown", 1),
]


@pytest.fixture(autouse=True)
def _clean_crash_plan():
    set_crash_plan(None)
    reset_journal()
    yield
    set_crash_plan(None)
    reset_journal()


def _sim(**kw):
    from instaslice_tpu.sim import SimCluster

    defaults = dict(
        n_nodes=2, generation="v5e", nodes_per_group=2,
        deletion_grace_seconds=0.2, health_interval=0,
    )
    defaults.update(kw)
    return SimCluster(**defaults)


# ----------------------------------------------------------- invariants


def assert_no_overlaps(c):
    """Zero double-allocation: per torus group, every pair of live
    allocation boxes is disjoint."""
    by_group = {}
    for a in c.allocations().values():
        if a["status"] == "deleted":
            continue
        by_group.setdefault(a.get("torusGroup", ""), []).append(
            Box.from_key(a["box"])
        )
    for gid, boxes in by_group.items():
        for i, x in enumerate(boxes):
            for y in boxes[i + 1:]:
                assert not x.overlaps(y), (
                    f"double allocation in {gid}: {x.key()} overlaps "
                    f"{y.key()}"
                )


def assert_no_orphans(c):
    """Zero orphaned device slices: every reservation on every backend
    maps to an allocation some CR epoch still claims."""
    for node, backend in c.backends.items():
        ts = c.kube.get("TpuSlice", c.namespace, node)
        allocs = set(ts["spec"].get("allocations", {}))
        claimed = {
            suid
            for aid in allocs
            for suid in (slice_uuid_for(aid),
                         slice_uuid_for(aid, multihost=True))
        }
        for r in backend.list_reservations():
            assert r.slice_uuid in claimed, (
                f"{node}: orphaned device slice {r.slice_uuid} "
                f"(claimed: {sorted(claimed)})"
            )


def assert_epochs_legal(extra=""):
    errs = validate_events.check_epochs(
        [e.to_dict() for e in get_journal().events()]
    )
    assert not errs, f"{extra}{errs}"


def settle(c, pods, timeout=45.0):
    for name in pods:
        assert c.wait_phase(name, "Running", timeout=timeout), (
            name, c.pod_phase(name),
            {e.reason: True for e in get_journal().events()},
        )
    # Running precedes the created→ungated STATUS edge (gates drop
    # first; the sim binds immediately): wait for every live record to
    # converge to ungated before asserting on the journal chains
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        live = [a["status"] for a in c.allocations().values()]
        if all(s in ("ungated", "deleted") for s in live):
            return
        time.sleep(0.05)
    raise AssertionError(
        f"allocations never converged: "
        f"{[(k, a['status']) for k, a in c.allocations().items()]}"
    )


# ------------------------------------------------------------ CrashPlan


class TestCrashPlan:
    def test_env_grammar(self):
        plan = CrashPlan.from_env("a.b, c.d:3")
        assert plan.sites == {"a.b": 1, "c.d": 3}
        assert CrashPlan.from_env("") is None
        assert CrashPlan.from_env("   ") is None

    def test_nth_call_fires_once(self):
        plan = CrashPlan().arm("s", 2)
        plan.check("s")  # call 1: no fire
        with pytest.raises(InjectedCrash):
            plan.check("s")
        # a crashed component does not keep crashing: later calls
        # (the restarted instance) pass through
        for _ in range(5):
            plan.check("s")
        assert plan.stats()["s"] == {"calls": 7, "fired": 2}

    def test_malformed_env_fails_clear(self):
        with pytest.raises(ValueError, match="TPUSLICE_CRASH_AT"):
            CrashPlan.from_env("agent.realize:2nd")

    def test_rearm_counts_from_arming(self):
        """A kill-loop re-arming a hot site must fire again even when
        the site's call count already passed nth."""
        plan = CrashPlan().arm("s", 1)
        with pytest.raises(InjectedCrash):
            plan.check("s")
        for _ in range(5):
            plan.check("s")  # fired already: passes through
        plan.arm("s", 2)     # re-arm: nth counts from here
        plan.check("s")
        with pytest.raises(InjectedCrash):
            plan.check("s")

    def test_maybe_crash_noop_without_plan(self):
        set_crash_plan(None)
        maybe_crash("anything.at.all")  # must not raise

    def test_maybe_crash_consults_process_plan(self):
        set_crash_plan(CrashPlan().arm("x.y", 1))
        with pytest.raises(InjectedCrash):
            maybe_crash("x.y")
        maybe_crash("x.y")  # fired already

    def test_injected_crash_passes_except_exception(self):
        # the whole design: keep-alive guards must NOT absorb a crash
        plan = CrashPlan().arm("s", 1)
        with pytest.raises(InjectedCrash):
            try:
                plan.check("s")
            except Exception:  # slicelint: disable=broad-except
                pytest.fail("InjectedCrash was absorbed by "
                            "`except Exception`")


# ----------------------------------------------------- agent boot sweep


class TestOrphanSweep:
    def test_unclaimed_reservation_reaped(self):
        """Device has it, no CR epoch claims it → released + journaled
        OrphanReaped, never adopted as dangling. The FIRST boot
        (fresh CR, no history) deliberately adopts — a missing CR may
        mean an operator deleted it under live workloads — and the
        refresh sweep on the next boot reaps what no epoch claims."""
        c = _sim(n_nodes=1)
        # a crashed agent's leftover: reserved on the device, nothing
        # in the CR (the sim hasn't even started)
        c.backends["node-0"].reserve("sl-dead-alloc", [0])
        c.start()
        try:
            time.sleep(0.3)
            # first boot (create path): adopted as dangling, NOT reaped
            held = [r.slice_uuid
                    for r in c.backends["node-0"].list_reservations()]
            assert held == ["sl-dead-alloc"]
            # second boot (refresh path): the CR's epochs are the
            # truth now — nothing claims the handle, so it is reaped
            c.restart_agent("node-0")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if not c.backends["node-0"].list_reservations():
                    break
                time.sleep(0.05)
            assert c.backends["node-0"].list_reservations() == []
            ts = c.kube.get("TpuSlice", c.namespace, "node-0")
            assert "sl-dead-alloc" not in ts["spec"].get("prepared", {})
            reaped = [e for e in get_journal().events()
                      if e.reason == "OrphanReaped"]
            assert reaped and "sl-dead-alloc" in reaped[0].message
        finally:
            c.stop()

    def test_foreign_reservation_still_adopted(self):
        """Non-instaslice handles keep the reference's adopt-as-
        dangling behavior: counted occupied, never released."""
        c = _sim(n_nodes=1)
        c.backends["node-0"].reserve("preexisting-job", [0, 1])
        c.start()
        try:
            time.sleep(0.5)
            assert [r.slice_uuid
                    for r in c.backends["node-0"].list_reservations()
                    ] == ["preexisting-job"]
            ts = c.kube.get("TpuSlice", c.namespace, "node-0")
            prep = ts["spec"]["prepared"]["preexisting-job"]
            assert prep["podUUID"] == ""
        finally:
            c.stop()

    def test_claimed_reservation_not_reaped_on_restart(self):
        """A granted pod's reservation survives an agent restart: the
        sweep only reaps handles no epoch claims."""
        c = _sim(n_nodes=1).start()
        try:
            c.submit("keep", "v5e-1x1")
            settle(c, ["keep"])
            before = [r.slice_uuid
                      for r in c.backends["node-0"].list_reservations()]
            c.restart_agent("node-0")
            time.sleep(0.5)
            after = [r.slice_uuid
                     for r in c.backends["node-0"].list_reservations()]
            assert before == after
            assert c.pod_phase("keep") == "Running"
        finally:
            c.stop()


# ------------------------------------------------- loadgen classification


class TestStreamTruncated:
    def test_classify(self):
        from instaslice_tpu.serving.loadgen import OUTCOMES, _classify

        assert "stream-truncated" in OUTCOMES
        # mid-stream disconnect AFTER tokens: its own class
        assert _classify("ConnectionResetError: peer", None, 5) \
            == "stream-truncated"
        assert _classify("stream ended without [DONE]", 200, 3) \
            == "stream-truncated"
        # a router-relayed replica death is a truncation too
        assert _classify("replica stream died: reset", 200, 3) \
            == "stream-truncated"
        # a CLEAN in-band terminal error after tokens is not: the
        # server was alive and said so (engine recovery, etc.)
        assert _classify("request lost to engine recovery", 200, 3) \
            == "transport-error"
        # dead on arrival stays transport-error
        assert _classify("ConnectionResetError: peer", None, 0) \
            == "transport-error"
        # terminal statuses and hangs are unchanged by token count
        assert _classify(None, 200, 7) == "ok"
        assert _classify("x", 429, 2) == "shed-429"
        assert _classify("x", 503, 2) == "timeout-503"
        assert _classify("TimeoutError: timed out", None, 2) == "hung"


# -------------------------------------------------- validate --epochs


def _ev(seq, reason, ref, tid="t1", epoch=None):
    rec = {"seq": seq, "ts": float(seq), "component": "allocation",
           "reason": reason, "objectRef": ref, "traceId": tid}
    if epoch is not None:
        rec["attrs"] = {"attempt_epoch": str(epoch)}
    return rec


class TestValidateEpochs:
    def test_legal_across_restart(self):
        """Crash mid-ungate: the created→ungated edge lands only after
        the restart marker — legal under --epochs."""
        events = [
            _ev(1, "SliceCreating", "alloc/a", epoch=1),
            _ev(2, "SliceCreated", "alloc/a", epoch=1),
            {"seq": 3, "ts": 3.0, "component": "sim",
             "reason": "CrashRecovered",
             "objectRef": "component/controller"},
            _ev(4, "SliceUngated", "alloc/a", epoch=1),
        ]
        assert validate_events.check_epochs(events) == []

    def test_superseded_epoch_must_end_deleted(self):
        events = [
            _ev(1, "SliceCreating", "alloc/a", epoch=1),
            _ev(2, "SliceCreating", "alloc/a", tid="t2", epoch=2),
            _ev(3, "SliceCreated", "alloc/a", tid="t2", epoch=2),
            _ev(4, "SliceUngated", "alloc/a", tid="t2", epoch=2),
        ]
        errs = validate_events.check_epochs(events)
        assert any("superseded" in e for e in errs), errs
        # ...and clean once the stale epoch is torn down
        events.insert(1, _ev(10, "SliceDeleted", "alloc/a", epoch=1))
        assert validate_events.check_epochs(events) == []

    def test_abandoned_grant_detected(self):
        events = [
            _ev(1, "SliceCreating", "alloc/a", epoch=1),
            _ev(2, "SliceCreated", "alloc/a", epoch=1),
        ]
        errs = validate_events.check_epochs(events)
        assert any("abandoned" in e for e in errs), errs

    def test_illegal_inside_epoch_detected(self):
        events = [
            _ev(1, "SliceCreating", "alloc/a", epoch=1),
            _ev(2, "SliceUngated", "alloc/a", epoch=1),
            _ev(3, "SliceDeleted", "alloc/a", epoch=1),
        ]
        errs = validate_events.check_epochs(events)
        assert any("illegal" in e for e in errs), errs

    def test_stale_deleted_interleaves_with_new_epoch(self):
        """The exact mess a crashed writer leaves: the stale epoch's
        deleted event lands (by seq) in the MIDDLE of the new epoch's
        chain. check_chains would see two trace ids in one epoch;
        --epochs groups by attempt epoch and stays clean."""
        events = [
            _ev(1, "SliceCreating", "alloc/a", tid="t1", epoch=1),
            _ev(2, "SliceCreating", "alloc/a", tid="t2", epoch=2),
            _ev(3, "SliceDeleted", "alloc/a", tid="t1", epoch=1),
            _ev(4, "SliceCreated", "alloc/a", tid="t2", epoch=2),
            _ev(5, "SliceUngated", "alloc/a", tid="t2", epoch=2),
        ]
        assert validate_events.check_epochs(events) == []

    def test_cli_epochs_flag(self, tmp_path):
        import json as _json

        p = tmp_path / "ev.jsonl"
        events = [
            _ev(1, "SliceCreating", "alloc/a", epoch=1),
            _ev(2, "SliceCreated", "alloc/a", epoch=1),
            _ev(3, "SliceUngated", "alloc/a", epoch=1),
        ]
        p.write_text("\n".join(_json.dumps(e) for e in events) + "\n")
        assert validate_events.main([str(p), "--epochs"]) == 0
        p.write_text("\n".join(
            _json.dumps(e) for e in events[:2]) + "\n")
        assert validate_events.main([str(p), "--epochs"]) == 1


# ------------------------------------------------------------- smokes


@pytest.mark.slow
class TestCrashSmoke:
    """The `make chaos-crash-smoke` gate: one kill of each component
    class under load, full invariant sweep after recovery."""

    def test_smoke_controller_kill(self):
        c = _sim().start()
        try:
            # pods land, then the controller dies mid-fan-out of p2
            c.submit("p0", "v5e-1x1")
            settle(c, ["p0"])
            set_crash_plan(
                CrashPlan().arm("controller.write_allocation", 2)
            )
            for i in range(1, 4):
                c.submit(f"p{i}", "v5e-2x1")
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if c.controller.manager._stop.is_set():
                    break
                time.sleep(0.05)
            assert c.controller.manager._stop.is_set(), \
                "crash point never fired"
            set_crash_plan(None)
            c.restart_controller()
            settle(c, [f"p{i}" for i in range(4)])
            assert_no_overlaps(c)
            assert_no_orphans(c)
            assert_epochs_legal("controller kill: ")
        finally:
            c.stop()

    def test_smoke_agent_kill(self):
        c = _sim().start()
        try:
            set_crash_plan(CrashPlan().arm("agent.realize", 1))
            c.submit("a0", "v5e-1x1")
            # wait for the crash (the reservation exists, the CR does
            # not know): the agent manager crash-stops itself
            deadline = time.monotonic() + 15
            crashed = None
            while time.monotonic() < deadline and crashed is None:
                for node, agent in c.agents.items():
                    if agent.manager._stop.is_set():
                        crashed = node
                time.sleep(0.05)
            assert crashed is not None, "agent crash never fired"
            set_crash_plan(None)
            c.restart_agent(crashed)
            settle(c, ["a0"])
            assert_no_overlaps(c)
            assert_no_orphans(c)
            assert_epochs_legal("agent kill: ")
        finally:
            c.stop()

    def test_smoke_replica_kill(self, tmp_path):
        """Kill a serving replica mid-stream under the router: zero
        hung, the ledger reconciles exactly with mid-stream
        disconnects classified ``stream-truncated``, and a fresh
        replica absorbs the rest of the run."""
        import jax
        import jax.numpy as jnp

        from instaslice_tpu.models.lm import ModelConfig, TpuLM
        from instaslice_tpu.serving import ServingEngine, loadgen
        from instaslice_tpu.serving.api_server import ApiServer
        from instaslice_tpu.serving.router import Router

        cfg = ModelConfig(vocab_size=64, d_model=32, n_heads=2,
                          n_layers=2, d_ff=64, dtype=jnp.float32,
                          remat=False)
        m = TpuLM(cfg)
        params = m.init(jax.random.key(0))

        def engine():
            return ServingEngine(m, params, max_batch=4, max_len=96,
                                 prefill_len=8)

        servers = [ApiServer(engine(), block_size=4).start()
                   for _ in range(2)]
        router = Router([s.url for s in servers], poll_interval=0.1,
                        stale_after=1.0, migrate_timeout=3.0).start()
        report: dict = {}
        try:
            t = threading.Thread(target=lambda: report.update(
                loadgen.run(router.url, requests=24, concurrency=4,
                            prompt_len=6, max_tokens=24, vocab=64,
                            stream=True, timeout=30, seed=CHAOS_SEED)
            ))
            t.start()
            time.sleep(1.0)     # let streams get in flight
            victim = servers[0]
            victim.kill()       # power cut: no drain, no terminals
            # a fresh replica joins mid-run (the crash-chaos restart)
            fresh = ApiServer(engine(), block_size=4).start()
            servers.append(fresh)
            router.add_replica(fresh.url)
            router.remove_replica(victim.url)
            t.join(timeout=120)
            assert report, "loadgen never finished"
            out = report["outcomes"]
            # the ledger reconciles exactly; a killed replica may
            # truncate streams but must never hang a client
            assert sum(out.values()) == 24, out
            assert out["hung"] == 0, out
            assert out["ok"] >= 1, out
            # every non-ok outcome of this scenario is a classified
            # crash signature, not an unexplained transport error
            assert out["ok"] + out["stream-truncated"] \
                + out["timeout-503"] + out["shed-429"] \
                + out["transport-error"] == 24, out
        finally:
            router.stop()
            for s in servers:
                try:
                    s.stop()
                except OSError:
                    pass


# ------------------------------------------------- serving crash points


def _tiny_model():
    import jax
    import jax.numpy as jnp

    from instaslice_tpu.models.lm import ModelConfig, TpuLM

    cfg = ModelConfig(vocab_size=64, d_model=32, n_heads=2,
                      n_layers=2, d_ff=64, dtype=jnp.float32,
                      remat=False)
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


def _make_engine(model):
    from instaslice_tpu.serving import ServingEngine

    m, params = model
    return ServingEngine(m, params, max_batch=4, max_len=96,
                         prefill_len=8)


def _stream_tokens(url, body, result):
    import json
    import urllib.request

    body = dict(body)
    body["stream"] = True
    req = urllib.request.Request(
        url + "/v1/completions", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    toks = []
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            buf = b""
            while True:
                chunk = r.read1(65536)
                if not chunk:
                    result["error"] = "stream ended without [DONE]"
                    break
                buf += chunk
                while b"\n\n" in buf:
                    event, buf = buf.split(b"\n\n", 1)
                    line = event.decode().strip()
                    if not line.startswith("data: "):
                        continue
                    data = line[len("data: "):]
                    if data == "[DONE]":
                        result["tokens"] = toks
                        return
                    payload = json.loads(data)
                    if "error" in payload:
                        result["error"] = payload["error"]
                        result["tokens"] = toks
                        return
                    toks.extend(payload["choices"][0]["token_ids"])
    except Exception as e:  # slicelint: disable=broad-except
        result["error"] = f"{type(e).__name__}: {e}"
    result.setdefault("tokens", toks)


class _WedgedReplica:
    """A fake replica that accepts session imports and then wedges on
    the resume — the exact failure the router's migration hop timeout
    exists for. Advertises a prefix digest matching ``prompt`` so
    ``migration_destinations`` ranks it FIRST."""

    def __init__(self, prompt):
        import json
        from http.server import (
            BaseHTTPRequestHandler,
            ThreadingHTTPServer,
        )

        from instaslice_tpu.serving.router import want_hashes

        chains = [want_hashes(list(prompt), 8)]
        hang = threading.Event()

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._json(200, {
                    "replica_id": "wedged", "uptime_seconds": 1.0,
                    "queue_depth": 0, "live_slots": 0,
                    "radix": {"digest": {"granule": 8,
                                         "paths": chains}},
                })

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                self.rfile.read(n)
                if self.path.startswith("/v1/sessions/import"):
                    self._json(200, {"rid": 7})
                    return
                # the wedge: never answer a completion
                hang.wait(60)  # slicelint: disable=sleep-in-loop
                self._json(503, {"error": "wedged"})

        self._hang = hang
        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True)
        self._thread.start()
        host, port = self._srv.server_address[:2]
        self.url = f"http://{host}:{port}"

    def stop(self):
        self._hang.set()
        self._srv.shutdown()
        self._srv.server_close()


@pytest.mark.slow
class TestServingCrashPoints:
    def test_export_crash_kills_replica_cleanly(self):
        """The serve.export crash point: a replica dying mid-session-
        export severs its clients with terminals (never a hang), and
        the fleet keeps serving on the survivor."""
        from instaslice_tpu.serving.api_server import ApiServer
        from instaslice_tpu.serving.router import Router

        model = _tiny_model()
        servers = [ApiServer(_make_engine(model), block_size=4).start()
                   for _ in range(2)]
        router = Router([s.url for s in servers], poll_interval=0.1,
                        stale_after=1.0, migrate_timeout=2.0).start()
        try:
            result: dict = {}
            t = threading.Thread(target=_stream_tokens, args=(
                router.url, {"prompt": [7, 8, 9], "max_tokens": 60},
                result))
            t.start()
            victim = None
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and victim is None:
                for s in servers:
                    if s.scheduler.stats()["live_slots"]:
                        victim = s
                time.sleep(0.02)
            assert victim is not None
            set_crash_plan(CrashPlan().arm("serve.export", 1))
            # trigger the export; the scheduler dies mid-way and the
            # on_fatal hook severs every connection (the export POST's
            # included — tolerate its failure)
            import urllib.request

            req = urllib.request.Request(
                victim.url + "/v1/sessions/export", data=b"{}",
                headers={"Content-Type": "application/json"},
                method="POST")
            try:
                urllib.request.urlopen(req, timeout=10).read()
            except Exception:  # slicelint: disable=broad-except
                pass  # severed mid-request: the point of the crash
            t.join(timeout=30)
            assert not t.is_alive(), "client HUNG on a dead replica"
            # the scheduler thread is dead, not wedged
            assert victim.scheduler.stop_flag.is_set()
            # the fleet still serves via the survivor
            survivor = next(s for s in servers if s is not victim)
            code, out = _post_json(
                router.url, {"prompt": [1, 2], "max_tokens": 4})
            assert code == 200 and out["choices"][0]["token_ids"]
            assert survivor.scheduler.stats() is not None
        finally:
            set_crash_plan(None)
            router.stop()
            for s in servers:
                try:
                    s.stop()
                except OSError:
                    pass

    def test_wedged_migration_dest_falls_back_to_survivor(self):
        """A destination that accepts the import and then wedges: the
        migration hop timeout expires and the session lands on the
        next survivor — token-identical, client none the wiser."""
        from instaslice_tpu.serving.api_server import ApiServer
        from instaslice_tpu.serving.router import Router

        model = _tiny_model()
        m, params = model
        prompt = [5, 9, 2, 7, 11, 3, 8, 6]  # one whole granule
        import jax.numpy as jnp

        toks = list(prompt)
        oracle = []
        for _ in range(40):
            logits = m.apply(params,
                             jnp.asarray(toks, jnp.int32)[None])
            nxt = int(jnp.argmax(logits[0, -1]))
            oracle.append(nxt)
            toks.append(nxt)
        servers = [ApiServer(_make_engine(model), block_size=4).start()
                   for _ in range(2)]
        # warm both replicas (compile the serve path): a cold jit on
        # the survivor must not eat the migration hop timeout
        for s in servers:
            _post_json(s.url, {"prompt": [1, 2, 3], "max_tokens": 2})
        wedged = _WedgedReplica(prompt)
        router = Router([s.url for s in servers] + [wedged.url],
                        poll_interval=0.1, stale_after=5.0,
                        migrate_timeout=3.0).start()
        try:
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and len(
                [r for r in router.replicas() if r.last_poll]
            ) < 3:
                time.sleep(0.05)
            # session-pin the stream to a REAL replica: the wedge's
            # advertised prefix digest must only win the MIGRATION
            # destination ranking, not the initial route
            victim = servers[0]
            router.pin_session("crash-wedge", victim.url)
            result: dict = {}
            t = threading.Thread(target=_stream_tokens, args=(
                router.url, {"prompt": prompt, "max_tokens": 40,
                             "session": "crash-wedge"},
                result))
            t.start()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not \
                    victim.scheduler.stats()["live_slots"]:
                time.sleep(0.02)
            assert victim.scheduler.stats()["live_slots"]
            import urllib.request

            req = urllib.request.Request(
                victim.url + "/v1/sessions/export", data=b"{}",
                headers={"Content-Type": "application/json"},
                method="POST")
            moved = urllib.request.urlopen(req, timeout=10).read()
            assert b'"migrated": 1' in moved or b"1" in moved
            t.join(timeout=60)
            assert not t.is_alive(), "client hung through the wedge"
            assert "error" not in result, result
            assert result["tokens"] == oracle
            # the wedged hop was tried and abandoned; the session
            # landed on the real survivor — resumed zero-re-prefill,
            # or (on a loaded box where even the survivor's hop blows
            # the timeout) via the re-prefill fallback; both terminate
            # the client with the exact tokens
            assert (router.migrations.get("resumed", 0)
                    + router.migrations.get("fallback", 0)) >= 1
        finally:
            wedged.stop()
            router.stop()
            for s in servers:
                try:
                    s.stop()
                except OSError:
                    pass


def _post_json(url, body):
    import json
    import urllib.request

    req = urllib.request.Request(
        url + "/v1/completions", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, json.loads(r.read())


# ----------------------------------------------------------- watchdogs


@pytest.mark.slow
class TestWatchdogs:
    def test_stuck_grant_watchdog_fires_and_replaces(self):
        """Agent dies mid-realize and STAYS dead: the stuck-grant
        watchdog rolls the epoch back (GrantDeadlineExceeded), avoids
        the dead node, and the pod grants on the survivor. The dead
        agent's restart then converges device truth (teardown or
        orphan reap) — zero leaked reservations."""
        c = _sim(stuck_grant_deadline=2.0).start()
        try:
            set_crash_plan(CrashPlan().arm("agent.realize", 1))
            c.submit("w0", "v5e-1x1")
            deadline = time.monotonic() + 15
            crashed = None
            while time.monotonic() < deadline and crashed is None:
                for node, agent in c.agents.items():
                    if agent.manager._stop.is_set():
                        crashed = node
                time.sleep(0.05)
            assert crashed is not None
            set_crash_plan(None)
            # agent stays dead: the watchdog must fire and re-place
            settle(c, ["w0"], timeout=40)
            reasons = [e.reason for e in get_journal().events()]
            assert "GrantDeadlineExceeded" in reasons
            # now the dead agent returns: device truth converges
            c.restart_agent(crashed)
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                try:
                    assert_no_orphans(c)
                    break
                except AssertionError:
                    time.sleep(0.2)
            assert_no_orphans(c)
            assert_no_overlaps(c)
            assert_epochs_legal("stuck grant: ")
        finally:
            c.stop()

    def test_stuck_migration_abort_rolls_back(self):
        """Unit-level abort: a migration idle in `realizing` past the
        deadline is aborted (MigrationAborted) and rolled back —
        bounded: a second stall surrenders to the controller."""
        from instaslice_tpu.controller.defrag import Migration, Repacker

        c = _sim(repack=True, repack_interval=60.0).start()
        try:
            c.submit("v0", "v5e-1x1")
            settle(c, ["v0"])
            rep = c.repacker
            rep.stop()  # drive ticks by hand
            rep.stuck_abort_seconds = 0.05
            aid = next(iter(c.allocations()))
            alloc = c.allocations()[aid]
            mig = Migration(
                alloc_id=aid, group_id="sim-torus-0",
                profile="v5e-1x1", old_box=alloc["box"],
                dest_box=None, target_box=alloc["box"],
                pending_profile="v5e-2x2",
                pods=[], trace_id="t-stuck",
                started=time.monotonic() - 10,
                phase="realizing", epoch=2,
            )
            rep._active[aid] = mig
            time.sleep(0.1)
            rep.run_once()
            # first abort: rollback mode, still active
            assert mig.rollback and mig.phase == "evicting"
            assert rep.migrations_aborted == 1
            reasons = [e.reason for e in get_journal().events()]
            assert "MigrationAborted" in reasons
            # the abort rolled the record back via _mark_deleted
            assert c.allocations()[aid]["status"] in (
                "deleted", "ungated", "created", "creating",
            )
            # second stall: surrendered (bounded abort)
            mig.last_progress = time.monotonic() - 10
            rep.run_once()
            assert aid not in rep._active
            assert rep.migrations_failed >= 1
        finally:
            c.stop()

    def test_warned_stuck_rearms_on_progress(self):
        """Satellite: the stall warning re-arms when a stuck migration
        finally progresses, so a LATER stall warns again."""
        from instaslice_tpu.controller.defrag import Migration

        mig = Migration(
            alloc_id="a", group_id="g", profile="v5e-1x1",
            old_box="b", dest_box=None, target_box="b",
            pending_profile="v5e-2x2", pods=[], trace_id="t",
            started=time.monotonic() - 100,
        )
        mig.warned_stuck = True  # the first stall already warned
        mig.progress()
        assert mig.warned_stuck is False
        assert time.monotonic() - mig.last_progress < 1.0


# ------------------------------------------------------------ kill loop


@pytest.mark.slow
class TestCrashKillLoop:
    def test_kill_loop_every_control_site(self):
        """The acceptance loop: for every control-plane crash point,
        kill→restart under load ends with every pod granted, zero
        double-allocations, zero orphaned device slices, and chains
        legal across restart epochs."""
        print(f"crash kill-loop: CHAOS_SEED={CHAOS_SEED}")
        for site, nth in CONTROL_SITES:
            reset_journal()
            c = _sim(stuck_grant_deadline=5.0).start()
            try:
                # a pod that exercises teardown too: granted, deleted
                c.submit("pre", "v5e-1x1")
                settle(c, ["pre"])
                set_crash_plan(CrashPlan().arm(site, nth))
                pods = []
                for i in range(3):
                    name = f"{site.split('.')[-1]}-{i}"
                    c.submit(name, "v5e-2x1")
                    pods.append(name)
                c.delete_pod("pre")  # drives agent.teardown sites
                # wait for the crash to land (or the load to drain
                # through the site unfired — then arm the next)
                deadline = time.monotonic() + 20
                fired = False
                while time.monotonic() < deadline and not fired:
                    from instaslice_tpu.faults import get_crash_plan

                    stats = get_crash_plan().stats()
                    fired = stats.get(site, {}).get("fired", 0) > 0
                    time.sleep(0.05)
                assert fired, f"{site}:{nth} never fired under load"
                set_crash_plan(None)
                time.sleep(0.3)
                if site.startswith("controller."):
                    c.restart_controller()
                else:
                    for node in list(c.agents):
                        if c.agents[node].manager._stop.is_set():
                            c.restart_agent(node)
                settle(c, pods, timeout=45)
                assert c.wait_gone("pre", timeout=20)
                assert_no_overlaps(c)
                deadline = time.monotonic() + 20
                while time.monotonic() < deadline:
                    try:
                        assert_no_orphans(c)
                        break
                    except AssertionError:
                        time.sleep(0.2)
                assert_no_orphans(c)
                assert_epochs_legal(f"{site}:{nth}: ")
            finally:
                set_crash_plan(None)
                c.stop()

    def test_repacker_kill_recovers_via_orphan_adoption(self):
        """Kill the repacker between drain and re-grant: the restarted
        controller adopts the chip-less ungated pod (CrashRecovered)
        and the blocked big profile still grants."""
        import random

        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from test_defrag import carve_survivors

        random.seed(CHAOS_SEED)
        c = _sim(policy="frag-aware", repack=True, repack_interval=0.1,
                 repack_cooldown=0.4,
                 stuck_grant_deadline=5.0).start()
        try:
            fillers = [f"fill-{i}" for i in range(16)]
            for n in fillers:
                c.submit(n, profile="v5e-1x1")
            settle(c, fillers)
            survivors = carve_survivors(c, set(fillers))
            set_crash_plan(CrashPlan().arm("repacker.migrate", 1))
            c.submit("big-0", profile="v5e-2x2")
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                from instaslice_tpu.faults import get_crash_plan

                if get_crash_plan().stats().get(
                    "repacker.migrate", {}
                ).get("fired"):
                    break
                time.sleep(0.05)
            set_crash_plan(None)
            c.restart_controller()
            settle(c, ["big-0"] + sorted(survivors), timeout=60)
            reasons = [e.reason for e in get_journal().events()]
            assert "CrashRecovered" in reasons
            assert_no_overlaps(c)
            assert_no_orphans(c)
            assert_epochs_legal("repacker kill: ")
        finally:
            c.stop()
