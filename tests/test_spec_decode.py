"""Lossless speculative decoding (docs/SERVING.md "Speculative
decoding"): rejection-sampling distribution identity (seeded,
tolerance-bounded), greedy bit-identity with the pre-rejection path,
adaptive-k ladder behavior, overlapped spec rounds, preempt/resume and
radix-hit token identity under spec, op-stream follower convergence of
accepted counts, and the compile budget over the adaptive-k shape
set."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.serving import ServingEngine
from instaslice_tpu.serving.sampling import speculative_accept


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


@pytest.fixture(scope="module")
def tiny_vocab_model():
    """Small vocab so empirical marginals converge in a few hundred
    trials (the statistical distribution-identity tests)."""
    cfg = ModelConfig(
        vocab_size=16, d_model=16, n_heads=2, n_layers=1, d_ff=32,
        dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(3))


def tv_distance(a, b) -> float:
    return 0.5 * float(np.abs(np.asarray(a) - np.asarray(b)).sum())


class TestRejectionSampler:
    """The mathematical core: speculative_accept's output must be
    distributed exactly as ancestral samples from p, for ANY proposal
    distribution q."""

    V, K, N = 8, 3, 40000

    def _dists(self):
        kq, kp = jax.random.split(jax.random.key(42))
        q = jax.nn.softmax(jax.random.normal(kq, (self.K, self.V)) * 1.5)
        p = jax.nn.softmax(
            jax.random.normal(kp, (self.K + 1, self.V)) * 1.5
        )
        return q, p

    def test_position0_marginal_is_p0(self):
        """Monte Carlo over N keys: the marginal of the first emitted
        token (accepted draft token OR the rejection resample) must be
        p_0 — THE lossless property, independent of q."""
        q, p = self._dists()

        def one(key):
            kd, kr = jax.random.split(key)
            d = jax.random.categorical(
                kd, jnp.log(q), axis=-1
            ).astype(jnp.int32)[None]
            acc, out, lps, final = speculative_accept(
                d, q[None], p[None], kr
            )
            return out[0, 0], acc[0]

        toks, accs = jax.vmap(one)(
            jax.random.split(jax.random.key(7), self.N)
        )
        emp = np.bincount(np.asarray(toks), minlength=self.V) / self.N
        tv = tv_distance(emp, p[0])
        # expected TV at N=40k, V=8 is ~0.006; a biased sampler (e.g.
        # always keeping the draft token) lands far beyond 0.02
        assert tv < 0.02, f"TV(emitted marginal, p0) = {tv}"
        # the draft deliberately disagrees with the target: both
        # branches of the accept-or-resample rule must really fire
        assert 0.0 < float(accs.mean()) < self.K

    def test_identical_p_q_accepts_everything(self):
        _, p = self._dists()
        q = p[: self.K][None]
        d = jnp.argmax(p[: self.K], axis=-1).astype(jnp.int32)[None]
        acc, out, lps, final = speculative_accept(
            d, q, p[None], jax.random.key(0)
        )
        # p == q: accept probability is exactly 1 at every position
        assert int(acc[0]) == self.K
        assert [int(x) for x in out[0, : self.K]] == [
            int(x) for x in d[0]
        ]

    def test_k0_samples_plain_p(self):
        """k=0 (the adaptive floor): no proposals, the single emitted
        token must simply be a sample from p_0 — graceful degradation
        IS plain sampling."""
        _, p = self._dists()

        def one(key):
            acc, out, lps, final = speculative_accept(
                jnp.zeros((1, 0), jnp.int32),
                jnp.zeros((1, 0, self.V)), p[:1][None], key,
            )
            return out[0, 0]

        toks = jax.vmap(one)(jax.random.split(jax.random.key(9), 20000))
        emp = np.bincount(np.asarray(toks), minlength=self.V) / 20000
        assert tv_distance(emp, p[0]) < 0.03

    def test_logprobs_are_log_p_at_emitted(self):
        q, p = self._dists()
        d = jnp.argmax(q, axis=-1).astype(jnp.int32)[None]
        acc, out, lps, final = speculative_accept(
            d, q[None], p[None], jax.random.key(1)
        )
        n = int(acc[0])
        for i in range(n + 1):
            want = float(jnp.log(p[i, int(out[0, i])]))
            assert lps[0, i] == pytest.approx(want, abs=1e-5)


class TestEngineDistributionIdentity:
    """Engine-level statistical identity: a spec engine with a
    DISAGREEING draft, at temperature > 0, must emit tokens whose
    marginal matches the exact tempered target distribution."""

    TRIALS = 600
    PROMPT = [5, 9, 2, 7]
    TEMP = 0.9

    def test_first_spec_token_marginal(self, tiny_vocab_model):
        m, params = tiny_vocab_model
        V = m.cfg.vocab_size
        # exact marginal of generated[1]: sum over g0 of
        # p(g0 | prompt) * p(g1 | prompt + g0), both tempered —
        # admission samples g0, the first spec round emits g1
        logits0 = m.apply(
            params, jnp.asarray(self.PROMPT, jnp.int32)[None]
        )[0, -1]
        p0 = np.asarray(jax.nn.softmax(logits0 / self.TEMP))
        exact = np.zeros(V)
        for g0 in range(V):
            lg = m.apply(
                params,
                jnp.asarray(self.PROMPT + [g0], jnp.int32)[None],
            )[0, -1]
            exact += p0[g0] * np.asarray(jax.nn.softmax(lg / self.TEMP))
        # draft = a DIFFERENT random init: real disagreement, so both
        # acceptance and rejection-resampling paths fire constantly
        draft_params = m.init(jax.random.key(99))
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8, temperature=self.TEMP,
                            draft_model=m, draft_params=draft_params,
                            spec_k=3, seed=11)
        counts = np.zeros(V)
        accepted_any = False
        for _ in range(self.TRIALS):
            rid = eng.add_request(list(self.PROMPT))
            eng.spec_step()
            req = (next(iter(eng.slots.values()))
                   if eng.slots else None)
            assert req is not None and req.request_id == rid
            counts[req.generated[1]] += 1
            accepted_any = accepted_any or eng.spec_accepted > 0
            eng.evict_slot(next(iter(eng.slots)))
        emp = counts / self.TRIALS
        tv = tv_distance(emp, exact)
        # expected TV at 600 trials over V=16 is ~0.09; a broken
        # acceptance rule (greedy acceptance on sampled chains reads
        # ~0.5 here) is far outside 0.2
        assert tv < 0.2, f"TV(spec marginal, exact tempered p) = {tv}"
        assert accepted_any, "draft never accepted — q wiring broken?"
        # partial acceptance: the rejection path really ran
        assert eng.spec_accepted < eng.spec_proposed

    def test_plain_engine_same_marginal_sanity(self, tiny_vocab_model):
        """Anchor: the plain sampled engine's generated[1] marginal
        matches the same exact distribution — so a spec-side failure
        in the test above cannot hide behind oracle error."""
        m, params = tiny_vocab_model
        V = m.cfg.vocab_size
        logits0 = m.apply(
            params, jnp.asarray(self.PROMPT, jnp.int32)[None]
        )[0, -1]
        p0 = np.asarray(jax.nn.softmax(logits0 / self.TEMP))
        exact = np.zeros(V)
        for g0 in range(V):
            lg = m.apply(
                params,
                jnp.asarray(self.PROMPT + [g0], jnp.int32)[None],
            )[0, -1]
            exact += p0[g0] * np.asarray(jax.nn.softmax(lg / self.TEMP))
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8, temperature=self.TEMP,
                            seed=23)
        counts = np.zeros(V)
        for _ in range(self.TRIALS):
            eng.add_request(list(self.PROMPT))
            eng.step()
            req = next(iter(eng.slots.values()))
            counts[req.generated[1]] += 1
            eng.evict_slot(next(iter(eng.slots)))
        assert tv_distance(counts / self.TRIALS, exact) < 0.2


class TestGreedyBitIdentity:
    """temperature -> 0 is a special case of the same code path: the
    chains (and the RNG stream) must stay byte-identical to both the
    plain engine and the pre-rejection greedy spec path."""

    def test_spec_chain_equals_plain_greedy(self, model):
        m, params = model
        plain = ServingEngine(m, params, max_batch=2, max_len=64,
                              prefill_len=8)
        rref = plain.add_request([5, 9, 2, 7])
        ref = plain.decode_block(12)[rref]
        spec = ServingEngine(m, params, max_batch=2, max_len=64,
                             prefill_len=8, draft_model=m,
                             draft_params=params, spec_k=4)
        rid = spec.add_request([5, 9, 2, 7])
        got = []
        while len(got) < 12:
            got.extend(spec.spec_step()[rid])
        assert got[:12] == ref

    def test_greedy_spec_consumes_no_rng(self, model):
        """Greedy rounds must not split the engine RNG: the stream —
        and so every later sampled op — stays identical to the
        pre-rejection-sampling engine."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8, draft_model=m,
                            draft_params=params, spec_k=3)
        eng.add_request([5, 9, 2, 7])
        before = np.asarray(jax.random.key_data(eng._rng)).copy()
        eng.spec_step()
        eng.spec_step()
        after = np.asarray(jax.random.key_data(eng._rng))
        assert (before == after).all()

    def test_sampled_spec_consumes_one_split_per_round(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8, temperature=0.7,
                            draft_model=m, draft_params=params,
                            spec_k=3)
        eng.add_request([5, 9, 2, 7])
        before = np.asarray(jax.random.key_data(eng._rng)).copy()
        eng.spec_step()
        after = np.asarray(jax.random.key_data(eng._rng))
        assert not (before == after).all()


class TestAdaptiveK:
    def test_ladder_starts_at_spec_k_and_holds_on_acceptance(
            self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=128,
                            prefill_len=8, draft_model=m,
                            draft_params=params, spec_k=4)
        assert eng._spec_kset == [0, 1, 2, 4]
        rid = eng.add_request([5, 9, 2, 7])
        assert len(eng.spec_step()[rid]) == 5      # k=4 first round
        for _ in range(4):
            eng.spec_step()
        # self-draft: full acceptance keeps the ladder at the top
        assert eng.spec_plan_k() == 4
        assert eng.spec_accept_ema == pytest.approx(1.0)

    def test_ladder_descends_on_garbage_draft_then_probes(self, model):
        """A draft that never agrees (its embedding table is rolled, so
        it proposes a shifted token stream the target puts no mass on)
        must walk k down to 0 — plain decode, no draft dispatches
        wasted — and then probe k=1 every SPEC_PROBE_EVERY rounds so
        recovery is possible."""
        m, params = model
        # a uniform-logits draft (zeroed final norm) against the
        # sharp copy-machine target: acceptance ~ 1/vocab — the tied
        # embedding makes any permuted/scaled draft cancel back to
        # agreement, so "garbage" must break the OUTPUT head
        garbage = dict(params, ln_f={
            "scale": jnp.zeros_like(params["ln_f"]["scale"])
        })
        eng = ServingEngine(m, params, max_batch=1, max_len=256,
                            prefill_len=8, temperature=1.0,
                            draft_model=m, draft_params=garbage,
                            spec_k=4, seed=5)
        eng.add_request([5, 9, 2, 7])
        ks = []
        for _ in range(40):
            if not eng.slots:
                break
            k = eng.spec_plan_k()
            ks.append(k)
            eng.spec_step(k=k)
        assert 0 in ks, f"ladder never reached the k=0 floor: {ks}"
        zero_runs = [k for k in ks[ks.index(0):]]
        # probes appear among the zero rounds (every 8th), and k
        # never exceeds the ladder's descent path
        assert any(k > 0 for k in zero_runs), \
            f"no probe rounds after hitting the floor: {ks}"
        assert eng.spec_accept_ema < 0.4

    def test_adaptive_off_pins_spec_k(self, model):
        m, params = model
        # a uniform-logits draft (zeroed final norm) against the
        # sharp copy-machine target: acceptance ~ 1/vocab — the tied
        # embedding makes any permuted/scaled draft cancel back to
        # agreement, so "garbage" must break the OUTPUT head
        garbage = dict(params, ln_f={
            "scale": jnp.zeros_like(params["ln_f"]["scale"])
        })
        eng = ServingEngine(m, params, max_batch=1, max_len=256,
                            prefill_len=8, temperature=1.0,
                            draft_model=m, draft_params=garbage,
                            spec_k=4, spec_adaptive=False, seed=5)
        eng.add_request([5, 9, 2, 7])
        for _ in range(10):
            assert eng.spec_plan_k() == 4
            eng.spec_step()

    def test_budget_cap_floors_onto_shape_set(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=128,
                            prefill_len=8, draft_model=m,
                            draft_params=params, spec_k=4)
        eng.add_request([5, 9, 2, 7])
        # cap is in emitted tokens: k <= cap - 1, floored to the set
        assert eng.spec_plan_k(budget_cap=1) == 0
        assert eng.spec_plan_k(budget_cap=2) == 1
        assert eng.spec_plan_k(budget_cap=4) == 2
        assert eng.spec_plan_k(budget_cap=5) == 4
        assert eng.spec_plan_k(budget_cap=100) == 4

    def test_k_shrinks_near_cache_end_and_drains(self, model):
        """The cache-end clamp composes with the shape set: a slot
        near max_len still drains through spec rounds alone, on the
        plain greedy chain."""
        m, params = model
        prompt = list(range(1, 11))
        plain = ServingEngine(m, params, max_batch=1, max_len=16,
                              prefill_len=8)
        plain.add_request(prompt)
        ref = [plain.slots[0].generated[0]]
        while plain.slots:
            ref.extend(plain.step().values())
        spec = ServingEngine(m, params, max_batch=1, max_len=16,
                             prefill_len=8, draft_model=m,
                             draft_params=params, spec_k=8)
        spec.add_request(prompt)
        got = [spec.slots[0].generated[0]]
        for _ in range(32):
            if not spec.slots:
                break
            for seq in spec.spec_step().values():
                got.extend(seq)
        assert not spec.slots
        assert spec.finished[-1].finished_reason == "max_len"
        assert got == ref


class TestOverlappedSpecRounds:
    def test_split_form_matches_unsplit(self, model):
        m, params = model
        one = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, draft_model=m,
                            draft_params=params, spec_k=3)
        r1 = one.add_request([5, 9, 2, 7])
        want = []
        for _ in range(3):
            want.extend(one.spec_step().get(r1, []))
        two = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, draft_model=m,
                            draft_params=params, spec_k=3)
        r2 = two.add_request([5, 9, 2, 7])
        got = []
        for _ in range(3):
            assert two.spec_step_start()
            got.extend(two.spec_step_finish().get(r2, []))
        assert got == want

    def test_drain_pending_lands_inflight_round(self, model):
        """A mutating entry point between start and finish must land
        the in-flight round first — engine state can never be touched
        with a dispatched round's tokens unread."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, draft_model=m,
                            draft_params=params, spec_k=3)
        eng.add_request([5, 9, 2, 7])
        eng.spec_step_start()
        assert eng._pending_spec is not None
        eng.add_request([11, 4])          # drains the pending round
        assert eng._pending_spec is None
        req = next(iter(eng.slots.values()))
        assert len(req.generated) >= 2    # round's tokens landed

    def test_empty_batch_start_is_noop(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, draft_model=m,
                            draft_params=params, spec_k=3)
        assert eng.spec_step_start() is False
        assert eng.spec_step_finish() == {}


class TestRecoverEdges:
    """Crash-consistency edge coverage for ``recover()``
    (docs/RECOVERY.md): parked spec-draft stripes plus a pending
    overlapped dispatch — today only the plain parked path is
    covered elsewhere."""

    def _engine(self, model):
        m, params = model
        return ServingEngine(m, params, max_batch=2, max_len=64,
                             prefill_len=8, draft_model=m,
                             draft_params=params, spec_k=3)

    def test_recover_with_parked_draft_and_pending_spec(self, model):
        from instaslice_tpu.faults import poison_cache

        eng = self._engine(model)
        r1 = eng.add_request([5, 9, 2, 7])
        eng.spec_step()
        slot = next(s for s, r in eng.slots.items()
                    if r.request_id == r1)
        eng.preempt_slot(slot)
        assert eng.parked[r1].draft_stripe is not None
        parked_used = eng.kv.used_blocks()
        r2 = eng.add_request([11, 4])
        assert eng.spec_step_start()      # overlapped round in flight
        assert eng._pending_spec is not None
        poison_cache(eng)
        assert eng.cache_poisoned()
        lost = eng.recover()
        # the live slot is lost, its blocks returned; no stale
        # dispatch survives the recovery
        assert lost == [r2]
        assert eng._pending_spec is None
        assert eng._pending_block is None
        assert not eng.cache_poisoned()
        assert r1 in eng.parked
        assert eng.kv.used_blocks() == parked_used  # zero leak
        # the parked session (draft stripe included) resumes and
        # decodes on the rebuilt caches
        eng.resume_request(r1)
        out = eng.spec_step()
        assert out.get(r1)
        # full teardown returns the pool to empty
        for s in list(eng.slots):
            eng.evict_slot(s)
        eng.radix.reclaim(10 ** 6)
        assert eng.kv.used_blocks() == 0

    def test_recover_with_pending_decode_block(self, model):
        from instaslice_tpu.faults import poison_cache

        eng = self._engine(model)
        r1 = eng.add_request([5, 9, 2, 7])
        eng.spec_step()
        eng.preempt_slot(next(s for s, r in eng.slots.items()
                              if r.request_id == r1))
        r2 = eng.add_request([3, 1, 4])
        assert eng.decode_block_start(4)  # overlapped decode in flight
        assert eng._pending_block is not None
        poison_cache(eng)
        lost = eng.recover()
        assert lost == [r2]
        assert eng._pending_block is None
        assert eng._pending_spec is None
        assert r1 in eng.parked and r1 in eng._tables
        assert set(eng._tables) == {r1}
        eng.resume_request(r1)
        assert eng.decode_block(2)[r1]


class TestTokenIdentityUnderSpec:
    def test_preempt_resume_token_identity(self, model):
        """Park + resume mid-spec must keep the chain on the exact
        greedy oracle — the draft stripe round-trips beside the
        target's."""
        m, params = model
        solo = ServingEngine(m, params, max_batch=1, max_len=64,
                             prefill_len=8)
        [want] = solo.generate([[5, 9, 2, 7]], max_new_tokens=14)
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, draft_model=m,
                            draft_params=params, spec_k=3)
        eng.add_request([5, 9, 2, 7])
        eng.spec_step()
        slot = next(iter(eng.slots))
        rid = eng.preempt_slot(slot)
        # a foreign request churns the cache while ours is parked
        eng.add_request([11, 4])
        eng.spec_step()
        eng.resume_request(rid)
        for _ in range(4):
            eng.spec_step()
        req = next(
            r for r in eng.slots.values() if r.request_id == rid
        )
        n = min(len(req.generated), 14)
        assert req.generated[:n] == want.tokens[:n]

    def test_preempt_resume_sampled_keeps_serving(self, model):
        """At temperature > 0 the rng stream shifts with round
        structure (no bit-oracle exists), but parked draft stripes
        must still restore a position-exact cache: the resumed chain
        keeps decoding with 1:1 logprobs and clean counters."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, temperature=0.8,
                            draft_model=m, draft_params=params,
                            spec_k=3, seed=13)
        eng.add_request([5, 9, 2, 7])
        eng.spec_step()
        rid = eng.preempt_slot(next(iter(eng.slots)))
        eng.spec_step()
        eng.resume_request(rid)
        for _ in range(3):
            eng.spec_step()
        req = next(
            r for r in eng.slots.values() if r.request_id == rid
        )
        assert len(req.logprobs) == len(req.generated)
        assert all(np.isfinite(x) for x in req.logprobs)

    def test_radix_hit_token_identity_under_spec(self, model):
        """An organic radix hit (a completed prompt re-used by a
        longer one) must leave the spec chain byte-equal to a cold
        spec engine — target AND draft stripes write back."""
        m, params = model
        shared = list(range(1, 17))
        prompt = shared + [40, 41]

        def run(eng):
            rid = eng.add_request(list(prompt))
            got = []
            for _ in range(4):
                got.extend(eng.spec_step().get(rid, []))
            return got

        cold = ServingEngine(m, params, max_batch=2, max_len=64,
                             prefill_len=8, draft_model=m,
                             draft_params=params, spec_k=3)
        want = run(cold)
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, draft_model=m,
                            draft_params=params, spec_k=3)
        # teach the cache organically: run the shared head to finish
        r0 = eng.add_request(list(shared))
        slot = next(iter(eng.slots))
        eng.finish_slot(slot, n_keep=1)
        assert eng.prefix_inserted >= 1
        got = run(eng)
        assert eng.prefix_hits == 1
        assert got == want


class TestFollowerConvergence:
    def test_sampled_spec_accepted_counts_converge(self, model):
        """The RNG-stream discipline end to end: a follower replaying
        the op stream (with the driver's planned k pinned into each
        op) must land identical accepted counts, chains, and
        adaptive-EMA state at temperature > 0."""
        from conftest import free_port
        from instaslice_tpu.serving.distributed import (
            DistributedEngine,
            run_follower,
        )

        m, params = model
        draft_params = m.init(jax.random.key(55))

        def mk():
            return ServingEngine(m, params, max_batch=2, max_len=64,
                                 prefill_len=8, temperature=0.7,
                                 draft_model=m,
                                 draft_params=draft_params,
                                 spec_k=4, seed=9)

        driver_eng, follower_eng = mk(), mk()
        port = free_port()
        t = threading.Thread(
            target=run_follower,
            args=(follower_eng, "127.0.0.1", port), daemon=True,
        )
        t.start()
        deng = DistributedEngine(driver_eng, n_followers=1, port=port)
        deng.add_request([5, 9, 2, 7])
        deng.add_request([11, 4])
        for _ in range(3):
            deng.spec_step()
        # the overlap split broadcasts at START like decode_block
        deng.spec_step_start()
        deng.spec_step_finish()
        deng.shutdown()
        t.join(timeout=15)
        assert not t.is_alive()
        assert follower_eng.spec_rounds == driver_eng.spec_rounds == 4
        assert follower_eng.spec_accepted == driver_eng.spec_accepted
        assert follower_eng.spec_proposed == driver_eng.spec_proposed
        assert (follower_eng.spec_accept_ema
                == driver_eng.spec_accept_ema)
        assert set(follower_eng.slots) == set(driver_eng.slots)
        for s in driver_eng.slots:
            assert (follower_eng.slots[s].generated
                    == driver_eng.slots[s].generated)


class TestCompileBudgetAdaptiveK:
    def test_adaptive_sweep_stays_within_budget(self, model):
        """The adaptive-k shape set exercised for real — a
        low-acceptance sampled workload walks the whole ladder, then
        the same engine flips to greedy (temperature is mutable) — and
        the compiled draft/verify programs stay inside
        compile_budget()'s documented bound."""
        m, params = model
        # a uniform-logits draft (zeroed final norm) against the
        # sharp copy-machine target: acceptance ~ 1/vocab — the tied
        # embedding makes any permuted/scaled draft cancel back to
        # agreement, so "garbage" must break the OUTPUT head
        garbage = dict(params, ln_f={
            "scale": jnp.zeros_like(params["ln_f"]["scale"])
        })
        eng = ServingEngine(m, params, max_batch=2, max_len=256,
                            prefill_len=8, temperature=1.0,
                            draft_model=m, draft_params=garbage,
                            spec_k=4, seed=5)
        eng.warm_spec_programs()
        eng.add_request([5, 9, 2, 7])
        for _ in range(30):
            if not eng.slots:
                eng.add_request([5, 9, 2, 7])
            eng.spec_step()
        assert eng._spec_idx == 0          # the ladder really walked
        # greedy variants of the same shape set (mutable temperature)
        eng.temperature = 0.0
        for _ in range(4):
            if not eng.slots:
                eng.add_request([9, 3, 1])
            eng.spec_step()
        budget = eng.compile_budget(block_cap=16)
        got = eng.compiled_programs()
        over = {k: (got[k], budget.get(k, 0)) for k in got
                if got[k] > budget.get(k, 0)}
        assert not over, (
            f"compiled programs exceed the documented bound: {over} "
            f"(all: {got} vs budget {budget})"
        )
        assert budget["spec_draft"] == 2 * len(eng._spec_kset)

    def test_warm_compiles_the_full_current_variant_set(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=128,
                            prefill_len=8, draft_model=m,
                            draft_params=params, spec_k=4)
        eng.warm_spec_programs()
        c0 = eng.compiled_programs()
        assert c0["spec_draft"] == len(eng._spec_kset)
        assert c0["spec_verify"] == len(eng._spec_kset)
        eng.add_request([5, 9, 2, 7])
        for _ in range(6):
            eng.spec_step()
        c1 = eng.compiled_programs()
        # traffic added NOTHING: every dispatched shape was pre-warmed
        assert c1["spec_draft"] == c0["spec_draft"]
        assert c1["spec_verify"] == c0["spec_verify"]

    def test_warm_refuses_live_slots(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, draft_model=m,
                            draft_params=params, spec_k=2)
        eng.add_request([1, 2, 3])
        with pytest.raises(RuntimeError, match="before any admission"):
            eng.warm_spec_programs()


class TestServingPlaneIntegration:
    def test_stats_spec_block_and_metric_export(self, model):
        import json
        import urllib.request

        from instaslice_tpu.metrics.metrics import ServingMetrics
        from instaslice_tpu.serving.api_server import ApiServer

        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8, draft_model=m,
                            draft_params=params, spec_k=3)
        eng.warm_prefill_buckets()
        eng.warm_spec_programs()
        metrics = ServingMetrics()
        with ApiServer(eng, block_size=8, metrics=metrics) as srv:
            req = urllib.request.Request(
                srv.url + "/v1/completions",
                data=json.dumps({"prompt": [9, 3, 1],
                                 "max_tokens": 8}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=60) as r:
                out = json.loads(r.read())
            assert len(out["choices"][0]["token_ids"]) == 8
            with urllib.request.urlopen(srv.url + "/v1/stats",
                                        timeout=10) as r:
                stats = json.loads(r.read())
        spec = stats["spec"]
        assert spec["enabled"] and spec["rounds"] >= 1
        assert spec["k_set"] == [0, 1, 2, 3]
        assert spec["proposed"] >= spec["accepted"] > 0
        assert 0.0 <= spec["acceptance_ema"] <= 1.0
        # delta export really ran (counters are cumulative; the
        # scheduler snapshots like the prefix counters)
        assert srv.scheduler._spec_exported["rounds"] == spec["rounds"]
        if metrics.registry is not None:
            from prometheus_client import generate_latest

            text = generate_latest(metrics.registry).decode()
            for name in ("tpuslice_serve_spec_rounds_total",
                         "tpuslice_serve_spec_proposed_total",
                         "tpuslice_serve_spec_accepted_total",
                         "tpuslice_serve_spec_acceptance_rate"):
                assert name in text

    def test_sampled_http_completion_over_spec_engine(self, model):
        """The removed temperature guard end to end: a sampled spec
        engine behind the real server delivers budget-exact tokens
        with 1:1 logprobs."""
        import json
        import urllib.request

        from instaslice_tpu.serving.api_server import ApiServer

        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8, temperature=0.8,
                            draft_model=m, draft_params=params,
                            spec_k=3, seed=2)
        with ApiServer(eng, block_size=8) as srv:
            req = urllib.request.Request(
                srv.url + "/v1/completions",
                data=json.dumps({"prompt": [5, 9, 2, 7],
                                 "max_tokens": 9,
                                 "logprobs": True}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=60) as r:
                out = json.loads(r.read())
        choice = out["choices"][0]
        assert len(choice["token_ids"]) == 9
        assert len(choice["logprobs"]) == 9

    def test_burst_admission_with_draft_matches_sequential(self, model):
        """Batched prefill now covers draft engines: a burst must be
        token-identical to sequential admission (target AND draft
        caches), spec rounds included."""
        from instaslice_tpu.serving.engine import AdmissionRequest

        m, params = model
        prompts = [[5, 9, 2, 7], list(range(1, 12)), [6, 6, 1]]
        seq = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8, draft_model=m,
                            draft_params=params, spec_k=3,
                            batched_prefill=False)
        for p in prompts:
            seq.add_request(list(p))
        for _ in range(3):
            seq.spec_step()
        burst = ServingEngine(m, params, max_batch=4, max_len=64,
                              prefill_len=8, draft_model=m,
                              draft_params=params, spec_k=3)
        burst.add_requests([
            AdmissionRequest(list(p)) for p in prompts
        ])
        assert burst.prefill_batches >= 1   # the batched program ran
        for _ in range(3):
            burst.spec_step()
        for (s_slot, s_req), (b_slot, b_req) in zip(
            sorted(seq.slots.items()), sorted(burst.slots.items())
        ):
            assert s_slot == b_slot
            assert s_req.generated == b_req.generated

    def test_cli_flags_build_spec_engine(self):
        from instaslice_tpu.serving.api_server import (
            build_engine,
            build_parser,
        )

        argv = ["--vocab-size", "64", "--d-model", "16", "--n-heads",
                "2", "--n-layers", "2", "--d-ff", "32", "--max-len",
                "64", "--prefill-len", "8", "--max-batch", "2",
                "--draft-n-layers", "1", "--spec-k", "3"]
        args = build_parser().parse_args(argv)
        eng = build_engine(args)
        assert eng.draft_model is not None
        assert eng.spec_k == 3
        assert eng.draft_model.cfg.n_layers == 1
        # the shape set compiled at startup (warm_spec_programs wired
        # next to warm_prefill_buckets)
        assert eng.compiled_programs()["spec_draft"] == \
            len(eng._spec_kset)
        args2 = build_parser().parse_args(argv + ["--no-spec"])
        eng2 = build_engine(args2)
        assert eng2.draft_model is None

    def test_spec_k_env_default(self, monkeypatch):
        from instaslice_tpu.serving.api_server import build_parser

        monkeypatch.setenv("TPUSLICE_SPEC_K", "6")
        args = build_parser().parse_args([])
        assert args.spec_k == 6
