"""Pallas w8a16 matmul kernel (ops/quant_matmul.py).

Correctness bars: (1) the kernel matches the dequantize-then-dot oracle
on real kernel logic (interpret mode on CPU) for both weight layouts,
(2) shapes the kernel cannot tile fall back instead of failing, (3) the
decode path of a quantized model routes through qdot with and without
the kernel to the same tokens, and (4) the TP engine stays on the
XLA-shardable path (pallas_call does not auto-partition).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.models.quant import (
    qdot,
    quantize_params,
    quantize_tensor,
)
from instaslice_tpu.ops.quant_matmul import (
    _fit_block,
    quant_matmul,
    quant_matmul_ref,
)
from instaslice_tpu.serving import ServingEngine


def _mk(m, k, n, seed=0, dtype=jnp.float32):
    kx, kw = jax.random.split(jax.random.key(seed))
    x = jax.random.normal(kx, (m, k), dtype)
    w = jax.random.normal(kw, (k, n), jnp.float32)
    qt = quantize_tensor(w)          # contract -2 → scale (1, n)
    return x, qt


class TestKernel:
    @pytest.mark.parametrize("m", [1, 8, 32, 33])
    def test_matches_oracle(self, m):
        x, qt = _mk(m, 256, 384)
        got = quant_matmul(x, qt.q, qt.s, block_k=128, block_n=128)
        want = quant_matmul_ref(x, qt.q, qt.s)
        # blocked k-accumulation reorders the fp32 sums vs one einsum
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_transposed_weight_layout(self):
        """Embedding-table layout: (N, K) int8 with per-row scale."""
        x = jax.random.normal(jax.random.key(1), (16, 256))
        w = jax.random.normal(jax.random.key(2), (384, 256), jnp.float32)
        qt = quantize_tensor(w, reduce_axis=-1)     # scale (384, 1)
        got = quant_matmul(x, qt.q, qt.s, transpose_w=True,
                           block_k=128, block_n=128)
        want = quant_matmul_ref(x, qt.q, qt.s, transpose_w=True)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_scale_exactness(self):
        """Post-accumulation scaling is mathematically identical to
        dequantize-then-dot (scale constant along contraction), and the
        kernel keeps the scale fp32 — strictly tighter than the bf16
        fallback. Verify against an fp64-free fp32 einsum on the raw
        int8 values."""
        x, qt = _mk(8, 128, 128, seed=3)
        got = quant_matmul(x, qt.q, qt.s, block_k=128, block_n=128)
        raw = jnp.einsum("mk,kn->mn", x, qt.q.astype(jnp.float32))
        want = raw * qt.s.astype(jnp.float32)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_bf16_activations(self):
        x, qt = _mk(32, 512, 256, seed=4, dtype=jnp.bfloat16)
        got = quant_matmul(x, qt.q, qt.s, block_k=256, block_n=128)
        want = quant_matmul_ref(x, qt.q, qt.s)
        # the oracle rounds q·s to bf16 pre-dot; the kernel keeps the
        # scale fp32 — the gap is ~sqrt(K)·bf16-eps ABSOLUTE (not
        # relative), so near-zero outputs need the atol headroom
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-2, atol=0.3
        )

    def test_untileable_falls_back(self):
        """K or N with no 128-multiple divisor → reference path, same
        answer, no error."""
        x, qt = _mk(4, 96, 80)      # both < 128
        got = quant_matmul(x, qt.q, qt.s)
        want = quant_matmul_ref(x, qt.q, qt.s)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_contraction_mismatch_raises(self):
        x, qt = _mk(4, 128, 128)
        with pytest.raises(ValueError, match="contraction mismatch"):
            quant_matmul(x[:, :64], qt.q, qt.s)

    def test_fit_block(self):
        assert _fit_block(1024, 4096) == 1024
        assert _fit_block(512, 256) == 256      # clamps to the dim
        assert _fit_block(512, 384) == 384      # whole axis is legal
        assert _fit_block(512, 96) == 0         # lane floor
        # the 7B shapes all tile: d=4096, ff=20480, vocab=32000
        assert _fit_block(1024, 20480) == 1024
        assert _fit_block(512, 32000) == 256    # 512 ∤ 32000, halve once


class TestQdotRouting:
    def test_qdot_kernel_vs_fallback_identical_decisions(self, monkeypatch):
        """qdot(kernel) ≈ qdot(kill-switch) on tileable shapes."""
        x, qt = _mk(8, 128, 256, seed=5)
        with_kernel = qdot(x, qt)
        monkeypatch.setenv("TPUSLICE_QUANT_KERNEL", "0")
        without = qdot(x, qt)
        np.testing.assert_allclose(
            with_kernel, without, rtol=1e-2, atol=1e-2
        )

    def test_qdot_plain_array_passthrough(self):
        x = jax.random.normal(jax.random.key(6), (4, 32))
        w = jax.random.normal(jax.random.key(7), (32, 16))
        np.testing.assert_allclose(
            qdot(x, w), x @ w, rtol=1e-5, atol=1e-5
        )

    def test_qdot_large_m_stays_on_einsum(self):
        """Prefill-sized row counts must not route through the kernel
        (compute-bound; also keeps prefill sharding-friendly)."""
        x, qt = _mk(512, 128, 128, seed=8)
        got = qdot(x, qt)           # > _QDOT_MAX_M → einsum path
        want = quant_matmul_ref(x, qt.q, qt.s)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-2, atol=1e-2
        )


@pytest.fixture(scope="module")
def kernel_model():
    """Dims ≥ 128 so the decode path really exercises the kernel."""
    cfg = ModelConfig(
        vocab_size=256, d_model=128, n_heads=2, n_layers=2, d_ff=256,
        dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


class TestModelDecodeThroughKernel:
    def test_greedy_chain_matches_killswitch(self, kernel_model,
                                             monkeypatch):
        """The serving property: same tokens with the kernel on and off.
        (Greedy argmax over near-tied logits could in principle flip on
        the fp32-scale difference; at these scales it does not — a flip
        here means the kernel is wrong, not unlucky.)"""
        m, params = kernel_model
        qp = quantize_params(params)

        def chain():
            eng = ServingEngine(m, qp, max_batch=2, max_len=64,
                                prefill_len=8)
            rid = eng.add_request([5, 9, 2, 7])
            return eng.decode_block(8)[rid]

        with_kernel = chain()
        monkeypatch.setenv("TPUSLICE_QUANT_KERNEL", "0")
        jax.clear_caches()           # drop the traced kernel programs
        without = chain()
        assert with_kernel == without

    def test_tp_engine_keeps_einsum_path(self, kernel_model):
        """A multi-device mesh must produce a shardable program: the
        engine passes quant_kernel=False, and the decode still works
        sharded end to end."""
        m, params = kernel_model
        qp = quantize_params(params)
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()[:2]).reshape(2), ("model",)
        )
        eng = ServingEngine(m, qp, max_batch=2, max_len=64,
                            prefill_len=8, mesh=mesh)
        assert eng._quant_kernel is False
        rid = eng.add_request([5, 9, 2, 7])
        out = eng.decode_block(6)[rid]
        assert len(out) == 6 and all(0 <= t < 256 for t in out)
