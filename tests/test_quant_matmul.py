"""Pallas w8a16 matmul kernel (ops/quant_matmul.py).

Correctness bars: (1) the kernel matches the dequantize-then-dot oracle
on real kernel logic (interpret mode on CPU) for both weight layouts,
(2) shapes the kernel cannot tile fall back instead of failing, (3) the
decode path of a quantized model routes through qdot with and without
the kernel to the same tokens, and (4) the TP engine stays on the
XLA-shardable path (pallas_call does not auto-partition).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.models.quant import (
    qdot,
    quantize_params,
    quantize_tensor,
)
from instaslice_tpu.ops.quant_matmul import (
    _stripe_block,
    quant_matmul,
    quant_matmul_ref,
)
from instaslice_tpu.serving import ServingEngine


def _mk(m, k, n, seed=0, dtype=jnp.float32):
    kx, kw = jax.random.split(jax.random.key(seed))
    x = jax.random.normal(kx, (m, k), dtype)
    w = jax.random.normal(kw, (k, n), jnp.float32)
    qt = quantize_tensor(w)          # contract -2 → scale (1, n)
    return x, qt


class TestKernel:
    @pytest.mark.parametrize("m", [1, 8, 32, 33])
    def test_matches_oracle(self, m):
        x, qt = _mk(m, 256, 384)
        got = quant_matmul(x, qt.q, qt.s)
        want = quant_matmul_ref(x, qt.q, qt.s)
        # k-stripe accumulation reorders the fp32 sums vs one einsum
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_transposed_weight_layout(self):
        """Embedding-table layout: (N, K) int8 with per-row scale."""
        x = jax.random.normal(jax.random.key(1), (16, 256))
        w = jax.random.normal(jax.random.key(2), (384, 256), jnp.float32)
        qt = quantize_tensor(w, reduce_axis=-1)     # scale (384, 1)
        got = quant_matmul(x, qt.q, qt.s, transpose_w=True)
        want = quant_matmul_ref(x, qt.q, qt.s, transpose_w=True)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_scale_exactness(self):
        """Post-accumulation scaling is mathematically identical to
        dequantize-then-dot (scale constant along contraction), and the
        kernel keeps the scale fp32 — strictly tighter than the bf16
        fallback. Verify against an fp64-free fp32 einsum on the raw
        int8 values."""
        x, qt = _mk(8, 128, 128, seed=3)
        got = quant_matmul(x, qt.q, qt.s)
        raw = jnp.einsum("mk,kn->mn", x, qt.q.astype(jnp.float32))
        want = raw * qt.s.astype(jnp.float32)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_bf16_activations(self):
        x, qt = _mk(32, 512, 256, seed=4, dtype=jnp.bfloat16)
        got = quant_matmul(x, qt.q, qt.s)
        want = quant_matmul_ref(x, qt.q, qt.s)
        # the oracle rounds q·s to bf16 pre-dot; the kernel keeps the
        # scale fp32 — the gap is ~sqrt(K)·bf16-eps ABSOLUTE (not
        # relative), so near-zero outputs need the atol headroom
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-2, atol=0.3
        )

    def test_untileable_falls_back(self):
        """K or N with no 128-multiple divisor → reference path, same
        answer, no error."""
        x, qt = _mk(4, 96, 80)      # both < 128
        got = quant_matmul(x, qt.q, qt.s)
        want = quant_matmul_ref(x, qt.q, qt.s)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_contraction_mismatch_raises(self):
        x, qt = _mk(4, 128, 128)
        with pytest.raises(ValueError, match="contraction mismatch"):
            quant_matmul(x[:, :64], qt.q, qt.s)

    def test_stripe_block(self):
        MB = 1024 * 1024
        # wq (K=4096, N=4096): 1024-row stripes hit the 4 MB tile cap
        assert _stripe_block(4096, 4096) == 1024
        # w_in (K=4096, N=20480): 20 KB rows -> 128-row stripes
        assert _stripe_block(4096, 20480) == 128
        # wk/wv (K=4096, N=1024): whole K in one 4 MB tile
        assert _stripe_block(4096, 1024) == 4096
        # embed vocab axis: 640 | 32000 (halving alone would miss it)
        assert _stripe_block(32000, 4096) == 640
        # no 128-multiple divisor -> 0 (caller falls back)
        assert _stripe_block(96, 4096) == 0
        assert _stripe_block(200, 4096) == 0
        # every candidate fits the transfer ceiling
        for dim, row in ((4096, 4096), (4096, 20480), (32000, 4096)):
            b = _stripe_block(dim, row)
            assert b * row <= 4 * MB


class TestStackedKernel:
    def test_every_layer_matches_sliced_oracle(self):
        """The scalar-prefetch index maps must pick exactly layer li's
        weight tile for every li — an off-by-one here silently serves
        the wrong layer's weights."""
        from instaslice_tpu.ops.quant_matmul import quant_matmul_stacked

        L, K, N = 3, 256, 384
        x = jax.random.normal(jax.random.key(9), (8, K))
        q3 = jax.random.randint(
            jax.random.key(10), (L, K, N), -127, 128, jnp.int8
        )
        s3 = jax.random.uniform(
            jax.random.key(11), (L, 1, N), jnp.float32, 0.01, 0.1
        )
        for li in range(L):
            got = quant_matmul_stacked(x, q3, s3, jnp.int32(li))
            want = quant_matmul_ref(x, q3[li], s3[li])
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_traced_index_inside_scan(self):
        """The in-situ pattern: the layer index is a traced scan value,
        one compiled program serves every layer."""
        from jax import lax

        from instaslice_tpu.ops.quant_matmul import quant_matmul_stacked

        L, K, N = 4, 128, 256
        x = jax.random.normal(jax.random.key(12), (4, K))
        q3 = jax.random.randint(
            jax.random.key(13), (L, K, N), -127, 128, jnp.int8
        )
        s3 = jnp.full((L, 1, N), 0.02, jnp.float32)

        @jax.jit
        def run(x):
            def body(carry, li):
                # no carry feedback: an iterated tanh∘matmul map is
                # chaotic (benign 1e-5 kernel-vs-oracle differences
                # grow ~50× per layer), which would swamp the thing
                # under test — that ys[li] used layer li's weights
                return carry, quant_matmul_stacked(carry, q3, s3, li)

            _, ys = lax.scan(
                body, x, jnp.arange(L, dtype=jnp.int32)
            )
            return ys

        ys = run(x)
        for li in range(L):
            want = quant_matmul_ref(x, q3[li], s3[li])
            np.testing.assert_allclose(
                ys[li], want, rtol=1e-4, atol=1e-4
            )

    def test_untileable_falls_back_to_sliced_einsum(self):
        from instaslice_tpu.ops.quant_matmul import quant_matmul_stacked

        L, K, N = 2, 96, 80          # no 128-multiple divisor
        x = jax.random.normal(jax.random.key(14), (4, K))
        q3 = jax.random.randint(
            jax.random.key(15), (L, K, N), -127, 128, jnp.int8
        )
        s3 = jnp.full((L, 1, N), 0.05, jnp.float32)
        got = quant_matmul_stacked(x, q3, s3, jnp.int32(1))
        want = quant_matmul_ref(x, q3[1], s3[1])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


class TestQdotRouting:
    def test_qdot_kernel_vs_fallback_identical_decisions(self, monkeypatch):
        """qdot(kernel, opt-in) ≈ qdot(default einsum) on tileable
        shapes."""
        x, qt = _mk(8, 128, 256, seed=5)
        monkeypatch.setenv("TPUSLICE_QUANT_KERNEL", "1")
        with_kernel = qdot(x, qt)
        monkeypatch.delenv("TPUSLICE_QUANT_KERNEL")
        without = qdot(x, qt)
        np.testing.assert_allclose(
            with_kernel, without, rtol=1e-2, atol=1e-2
        )

    def test_qdot_plain_array_passthrough(self):
        x = jax.random.normal(jax.random.key(6), (4, 32))
        w = jax.random.normal(jax.random.key(7), (32, 16))
        np.testing.assert_allclose(
            qdot(x, w), x @ w, rtol=1e-5, atol=1e-5
        )

    def test_qdot_large_m_stays_on_einsum(self):
        """Prefill-sized row counts must not route through the kernel
        (compute-bound; also keeps prefill sharding-friendly)."""
        x, qt = _mk(512, 128, 128, seed=8)
        got = qdot(x, qt)           # > _QDOT_MAX_M → einsum path
        want = quant_matmul_ref(x, qt.q, qt.s)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-2, atol=1e-2
        )


@pytest.fixture(scope="module")
def kernel_model():
    """Dims ≥ 128 so the decode path really exercises the kernel."""
    cfg = ModelConfig(
        vocab_size=256, d_model=128, n_heads=2, n_layers=2, d_ff=256,
        dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


class TestModelDecodeThroughKernel:
    def test_greedy_chain_matches_killswitch(self, kernel_model,
                                             monkeypatch):
        """The serving property: same tokens with the kernel on and off.
        (Greedy argmax over near-tied logits could in principle flip on
        the fp32-scale difference; at these scales it does not — a flip
        here means the kernel is wrong, not unlucky.)"""
        m, params = kernel_model
        qp = quantize_params(params)

        def chain():
            eng = ServingEngine(m, qp, max_batch=2, max_len=64,
                                prefill_len=8)
            rid = eng.add_request([5, 9, 2, 7])
            return eng.decode_block(8)[rid]

        monkeypatch.setenv("TPUSLICE_QUANT_KERNEL", "1")
        with_kernel = chain()
        monkeypatch.delenv("TPUSLICE_QUANT_KERNEL")
        jax.clear_caches()           # drop the traced kernel programs
        without = chain()
        assert with_kernel == without

    def test_tp_engine_keeps_einsum_path(self, kernel_model):
        """A multi-device mesh must produce a shardable program: the
        engine passes quant_kernel=False, and the decode still works
        sharded end to end."""
        m, params = kernel_model
        qp = quantize_params(params)
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()[:2]).reshape(2), ("model",)
        )
        eng = ServingEngine(m, qp, max_batch=2, max_len=64,
                            prefill_len=8, mesh=mesh)
        assert eng._quant_kernel is False
        rid = eng.add_request([5, 9, 2, 7])
        out = eng.decode_block(6)[rid]
        assert len(out) == 6 and all(0 <= t < 256 for t in out)
