"""tpuslicectl operator CLI: catalog / plan / status.

``status`` is the `kubectl get` + `nvidia-smi` half of the reference's
README demo transcript (`/root/reference/README.md:190-300`), rebuilt
from the CRs over a real kubeconfig + HTTP.
"""

import json

import pytest

from instaslice_tpu.cli.tpuslicectl import main


class TestServeBench:
    TINY = ["--d-model", "32", "--n-layers", "2", "--n-heads", "2",
            "--d-ff", "64", "--vocab", "64", "--batch", "2",
            "--max-len", "64", "--prefill-len", "8", "--steps", "4"]

    @pytest.mark.parametrize("extra,flags", [
        ([], {"quantized": False, "speculative": False}),
        (["--quantize"], {"quantized": True, "speculative": False}),
        (["--spec"], {"quantized": False, "speculative": True}),
    ])
    def test_modes_report_throughput(self, capsys, extra, flags):
        assert main(["serve-bench"] + self.TINY + extra) == 0
        out = json.loads(capsys.readouterr().out.strip())
        assert out["metric"] == "serve_decode_tokens_per_sec"
        assert out["value"] > 0
        for k, v in flags.items():
            assert out[k] == v
        if "--spec" in extra:
            # 1.0/round is what spec_step emits with ZERO accepted
            # draft tokens — the int8 self-draft of this tiny fp32
            # model must beat that or speculation isn't speculating
            assert out["spec_tokens_per_round"] > 1.0

    def test_quantize_spec_combination_rejected(self, capsys):
        with pytest.raises(SystemExit):
            main(["serve-bench"] + self.TINY + ["--quantize", "--spec"])
        assert "pick one" in capsys.readouterr().err


class TestCatalogAndPlan:
    def test_catalog(self, capsys):
        assert main(["catalog", "v5e"]) == 0
        out = capsys.readouterr().out
        assert "v5e-2x2" in out

    def test_plan(self, capsys):
        assert main(["plan", "v5e", "v5e-2x2", "v5e-1x1"]) == 0
        out = capsys.readouterr().out
        assert "v5e-2x2" in out


class TestStatus:
    @pytest.fixture
    def cluster_kubeconfig(self, tmp_path):
        """A live SimCluster over HTTP + a kubeconfig pointing at it."""
        from instaslice_tpu.sim import SimCluster

        cluster = SimCluster(n_nodes=2, generation="v5e",
                             deletion_grace_seconds=0.2,
                             transport="http")
        cluster.start()
        cfg = {
            "apiVersion": "v1", "kind": "Config",
            "current-context": "sim",
            "contexts": [{"name": "sim",
                          "context": {"cluster": "sim", "user": "u"}}],
            "clusters": [{"name": "sim",
                          "cluster": {"server": cluster.server.url}}],
            "users": [{"name": "u", "user": {"token": "t"}}],
        }
        path = tmp_path / "kubeconfig.yaml"
        path.write_text(json.dumps(cfg))
        try:
            yield cluster, str(path)
        finally:
            cluster.stop()

    def test_status_shows_grant(self, cluster_kubeconfig, capsys):
        cluster, kubeconfig = cluster_kubeconfig
        cluster.submit("status-pod", profile="v5e-2x2")
        assert cluster.wait_phase("status-pod", "Running", timeout=30)
        assert main(["status", "--kubeconfig", kubeconfig]) == 0
        out = capsys.readouterr().out
        assert "node-0" in out and "node-1" in out
        assert "v5e-2x2" in out
        assert "ungated" in out
        assert "status-pod" in out

    def test_status_json(self, cluster_kubeconfig, capsys):
        cluster, kubeconfig = cluster_kubeconfig
        cluster.submit("j-pod", profile="v5e-1x1")
        assert cluster.wait_phase("j-pod", "Running", timeout=30)
        assert main(["status", "--kubeconfig", kubeconfig,
                     "--json"]) == 0
        data = json.loads(capsys.readouterr().out)
        assert len(data["nodes"]) == 2
        assert len(data["slices"]) == 1
        chips = {n["chips"] for n in data["nodes"]}
        assert chips == {8}          # v5e: 8 chips per host

    def test_status_empty_namespace(self, cluster_kubeconfig, capsys):
        _, kubeconfig = cluster_kubeconfig
        assert main(["status", "--kubeconfig", kubeconfig,
                     "--namespace", "nothing-here"]) == 0
        assert "no TpuSlice" in capsys.readouterr().out

    def test_status_multihost_slice_reported_once(self, tmp_path, capsys):
        """A 2-host allocation fans out to both node CRs; status must
        merge it into ONE slice row with both nodes and the union of
        realized parts."""
        from instaslice_tpu.sim import SimCluster

        cluster = SimCluster(n_nodes=2, generation="v5e",
                             deletion_grace_seconds=0.2,
                             transport="http")
        cluster.start()
        try:
            cfg = {
                "apiVersion": "v1", "kind": "Config",
                "current-context": "sim",
                "contexts": [{"name": "sim",
                              "context": {"cluster": "sim", "user": "u"}}],
                "clusters": [{"name": "sim",
                              "cluster": {"server": cluster.server.url}}],
                "users": [{"name": "u", "user": {"token": "t"}}],
            }
            path = tmp_path / "kubeconfig.yaml"
            path.write_text(json.dumps(cfg))
            for w in (0, 1):
                cluster.submit(f"mh-w{w}", profile="v5e-4x4",
                               group="mh", group_size=2)
            for w in (0, 1):
                assert cluster.wait_phase(f"mh-w{w}", "Running",
                                          timeout=30), w
            assert main(["status", "--kubeconfig", str(path),
                         "--json"]) == 0
            data = json.loads(capsys.readouterr().out)
            assert len(data["slices"]) == 1          # merged, not doubled
            s = data["slices"][0]
            assert s["nodes"] == ["node-0", "node-1"]
            assert s["pods"] == ["mh-w0", "mh-w1"]
            assert len(s["realizedOn"]) == 2
        finally:
            cluster.stop()
