"""End-to-end lifecycle tests on the simulated cluster: every state
transition of SURVEY.md §3.1–3.3 plus the BASELINE stress and reshard
configs — controller + agents + fake scheduler all running threaded
against the fake kube API.
"""

import time

import pytest

from instaslice_tpu import GATE_NAME, LEGACY_GATE_NAME, POD_RESOURCE_PREFIX
from instaslice_tpu.sim import SimCluster


@pytest.fixture(params=["fake", "cloudtpu"])
def cluster(request):
    """Single-node cluster, parameterized over the device backend: the
    whole lifecycle tier runs once against the in-process fake and once
    against the Cloud TPU queued-resources wire path (real HTTP to a
    per-node mock API server) — the same gate→grant→handoff→teardown
    contract through both device drivers."""
    c = SimCluster(n_nodes=1, generation="v5e",
                   deletion_grace_seconds=0.3,
                   backend=request.param).start()
    yield c
    c.stop()


@pytest.fixture
def cluster2():
    c = SimCluster(n_nodes=2, generation="v5e", shared_torus=True,
                   deletion_grace_seconds=0.3).start()
    yield c
    c.stop()


class TestGrantLifecycle:
    def test_gated_pod_reaches_running(self, cluster):
        cluster.submit("demo", "v5e-2x2")
        assert cluster.wait_phase("demo", "Running", timeout=10)
        pod = cluster.pod("demo")
        assert pod["spec"].get("schedulingGates") == []
        assert pod["spec"].get("nodeName") == "node-0"
        # allocation reached ungated
        allocs = cluster.allocations()
        assert len(allocs) == 1
        a = next(iter(allocs.values()))
        assert a["status"] == "ungated"
        assert a["profile"] == "v5e-2x2"

    def test_legacy_gated_pod_granted_and_fully_ungated(self, cluster):
        """Migration interop: a pod gated by a reference-era webhook
        (the original misspelled ``org.instaslice/accelarator`` gate)
        must be admitted, granted, and end up with BOTH gate spellings
        removed — a surviving legacy gate would strand it Pending."""
        manifest = cluster.pod_manifest("legacy", "v5e-2x2")
        manifest["spec"]["schedulingGates"] = [
            {"name": LEGACY_GATE_NAME},
            {"name": GATE_NAME},
        ]
        cluster.kube.create("Pod", manifest)
        assert cluster.wait_phase("legacy", "Running", timeout=10)
        assert cluster.pod("legacy")["spec"].get("schedulingGates") == []
        a = next(iter(cluster.allocations().values()))
        assert a["status"] == "ungated"

    def test_configmap_env_handoff(self, cluster):
        cluster.submit("demo", "v5e-2x2")
        assert cluster.wait_phase("demo", "Running", timeout=10)
        cm = cluster.configmap("demo")
        assert cm is not None
        env = cm["data"]
        assert env["TPU_WORKER_ID"] == "0"
        assert env["TPU_CHIPS_PER_HOST_BOUNDS"] == "2,2,1"
        assert env["TPU_HOST_BOUNDS"] == "1,1,1"
        chips = [int(c) for c in env["TPU_VISIBLE_CHIPS"].split(",")]
        assert len(chips) == 4 and len(set(chips)) == 4
        assert env["TPU_SLICE_PROFILE"] == "v5e-2x2"

    def test_device_reservation_made(self, cluster):
        cluster.submit("demo", "v5e-1x1")
        assert cluster.wait_phase("demo", "Running", timeout=10)
        res = cluster.backends["node-0"].list_reservations()
        assert len(res) == 1 and len(res[0].chip_ids) == 1

    def test_node_capacity_patched(self, cluster):
        cluster.submit("demo", "v5e-1x1")
        assert cluster.wait_phase("demo", "Running", timeout=10)
        node = cluster.kube.get("Node", "", "node-0")
        assert node["status"]["capacity"][f"{POD_RESOURCE_PREFIX}demo"] == "1"

    def test_non_tpu_pod_ignored(self, cluster):
        pod = cluster.pod_manifest("plain", "v5e-1x1")
        del pod["metadata"]["annotations"]
        pod["spec"]["containers"][0]["resources"] = {}
        cluster.kube.create("Pod", pod)
        time.sleep(0.5)
        # stays gated forever: not our pod, no allocation written
        assert cluster.allocations() == {}

    def test_resource_limit_profile_extraction(self, cluster):
        pod = cluster.pod_manifest("via-limits", "v5e-2x1")
        del pod["metadata"]["annotations"]
        pod["spec"]["containers"][0]["resources"]["limits"][
            "google.com/tpu-v5e-2x1"
        ] = "1"
        cluster.kube.create("Pod", pod)
        assert cluster.wait_phase("via-limits", "Running", timeout=10)
        a = next(iter(cluster.allocations().values()))
        assert a["profile"] == "v5e-2x1"


class TestTeardown:
    def test_delete_releases_everything(self, cluster):
        cluster.submit("demo", "v5e-2x2")
        assert cluster.wait_phase("demo", "Running", timeout=10)
        cluster.delete_pod("demo")
        assert cluster.wait_gone("demo", timeout=10)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if (
                not cluster.allocations()
                and not cluster.backends["node-0"].list_reservations()
                and cluster.configmap("demo") is None
            ):
                break
            time.sleep(0.05)
        assert cluster.allocations() == {}
        assert cluster.backends["node-0"].list_reservations() == []
        assert cluster.configmap("demo") is None
        node = cluster.kube.get("Node", "", "node-0")
        assert f"{POD_RESOURCE_PREFIX}demo" not in node["status"]["capacity"]

    def test_deletion_grace_respected(self):
        c = SimCluster(n_nodes=1, deletion_grace_seconds=1.0).start()
        try:
            c.submit("demo", "v5e-1x1")
            assert c.wait_phase("demo", "Running", timeout=10)
            t0 = time.monotonic()
            c.delete_pod("demo")
            assert c.wait_gone("demo", timeout=10)
            assert time.monotonic() - t0 >= 0.9
        finally:
            c.stop()

    def test_chips_reusable_after_teardown(self, cluster):
        """Full host, delete, full host again — elasticity smoke."""
        cluster.submit("a", "v5e-4x2")  # 8 chips = whole host
        assert cluster.wait_phase("a", "Running", timeout=10)
        cluster.submit("b", "v5e-4x2")
        time.sleep(0.3)
        assert cluster.pod_phase("b") == "Pending"  # no capacity
        cluster.delete_pod("a")
        assert cluster.wait_gone("a", timeout=10)
        assert cluster.wait_phase("b", "Running", timeout=10)


class TestFailureHandling:
    def test_device_failure_marks_failed_then_retries(self, cluster):
        if cluster.mock_servers:
            # cloudtpu: the queued resource lands in FAILED after
            # provisioning — the agent must map that to allocation
            # `failed` exactly like a fake reserve error
            cluster.mock_servers["node-0"].fail_next_create(1)
        else:
            cluster.backends["node-0"].inject_failures("reserve", 1)
        cluster.submit("demo", "v5e-1x1")
        # failed → torn down → retried → eventually Running
        assert cluster.wait_phase("demo", "Running", timeout=15)

    def test_cloudtpu_failed_resource_retried_elsewhere(self):
        """The FAILED queued-resource contract end-to-end across nodes:
        node-0's cloud API fails every create, so the controller's
        failed-allocation repair must re-place the pod on node-1
        (reference error contract:
        ``instaslice_daemonset.go:95-231,233-270``)."""
        c = SimCluster(n_nodes=2, generation="v5e", shared_torus=True,
                       deletion_grace_seconds=0.3,
                       backend="cloudtpu").start()
        try:
            c.mock_servers["node-0"].fail_next_create(100)
            c.submit("demo", "v5e-1x1")
            assert c.wait_phase("demo", "Running", timeout=25)
            assert c.backends["node-1"].list_reservations()
            assert c.backends["node-0"].list_reservations() == []
        finally:
            c.stop()

    def test_force_deleted_pod_reaped(self, cluster):
        cluster.submit("demo", "v5e-2x2")
        assert cluster.wait_phase("demo", "Running", timeout=10)
        # force-delete: rip the finalizer out and delete in one shot
        pod = cluster.pod("demo")
        pod["metadata"]["finalizers"] = []
        cluster.kube.update("Pod", pod)
        cluster.kube.delete("Pod", "default", "demo")
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if not cluster.allocations():
                break
            time.sleep(0.05)
        assert cluster.allocations() == {}
        assert cluster.backends["node-0"].list_reservations() == []


class TestStressAndPacking:
    def test_baseline_stress_8_pods(self, cluster2):
        """BASELINE configs[3]: 8 concurrent mixed pods on a v5e-16."""
        mix = [("p0", "v5e-2x2"), ("p1", "v5e-2x1"), ("p2", "v5e-2x1"),
               ("p3", "v5e-2x1"), ("p4", "v5e-1x1"), ("p5", "v5e-1x1"),
               ("p6", "v5e-1x1"), ("p7", "v5e-1x1")]
        for name, prof in mix:
            cluster2.submit(name, prof)
        for name, _ in mix:
            assert cluster2.wait_phase(name, "Running", timeout=20), name
        # no double-grant on the devices
        for node, backend in cluster2.backends.items():
            claimed = [c for r in backend.list_reservations()
                       for c in r.chip_ids]
            assert len(claimed) == len(set(claimed))
        total = sum(
            len(r.chip_ids)
            for b in cluster2.backends.values()
            for r in b.list_reservations()
        )
        assert total == 4 + 2 * 3 + 1 * 4

    def test_elastic_reshard(self, cluster):
        """BASELINE configs[4]: preempt a 2x2, re-grant as 4x 1x1 without
        agent restart."""
        cluster.submit("big", "v5e-2x2")
        cluster.submit("fill", "v5e-2x2")  # host is 2x4: both fit
        assert cluster.wait_phase("big", "Running", timeout=10)
        assert cluster.wait_phase("fill", "Running", timeout=10)
        smalls = [f"small-{i}" for i in range(4)]
        for s in smalls:
            cluster.submit(s, "v5e-1x1")
        time.sleep(0.4)
        for s in smalls:
            assert cluster.pod_phase(s) == "Pending"
        cluster.delete_pod("big")
        assert cluster.wait_gone("big", timeout=10)
        for s in smalls:
            assert cluster.wait_phase(s, "Running", timeout=15), s
        assert cluster.pod_phase("fill") == "Running"  # undisturbed


class TestMultiHost:
    def test_4x4_group_spans_two_hosts(self, cluster2):
        """A v5e-4x4 slice needs both hosts: two pods in one group, one
        per host, consistent worker env."""
        cluster2.submit("w-0", "v5e-4x4", group="job-a", group_size=2)
        cluster2.submit("w-1", "v5e-4x4", group="job-a", group_size=2)
        assert cluster2.wait_phase("w-0", "Running", timeout=20)
        assert cluster2.wait_phase("w-1", "Running", timeout=20)
        allocs = cluster2.allocations()
        assert len(allocs) == 1
        a = next(iter(allocs.values()))
        assert a["status"] == "ungated"
        assert set(a["parts"]) == {"node-0", "node-1"}
        cm0 = cluster2.configmap("w-0")["data"]
        cm1 = cluster2.configmap("w-1")["data"]
        assert {cm0["TPU_WORKER_ID"], cm1["TPU_WORKER_ID"]} == {"0", "1"}
        assert cm0["TPU_WORKER_HOSTNAMES"] == "w-0,w-1"
        assert cm0["TPU_HOST_BOUNDS"] == "2,1,1"
        assert cm0["TPU_CHIPS_PER_HOST_BOUNDS"] == "2,4,1"
        # both hosts fully reserved
        for b in cluster2.backends.values():
            assert sum(len(r.chip_ids) for r in b.list_reservations()) == 8
        # pods landed on *different* nodes
        n0 = cluster2.pod("w-0")["spec"]["nodeName"]
        n1 = cluster2.pod("w-1")["spec"]["nodeName"]
        assert {n0, n1} == {"node-0", "node-1"}

    def test_shared_handoff_name_in_group_is_rejected(self, cluster2):
        """A template-stamped identical handoff-name across a group would
        make agents overwrite each other's worker env; the controller must
        refuse the allocation and surface the error on the pod."""
        from instaslice_tpu.controller.gates import HANDOFF_ANNOTATION

        shared = {HANDOFF_ANNOTATION: "shared-name"}
        cluster2.submit("g-0", "v5e-4x4", group="job-x", group_size=2,
                        annotations=shared)
        cluster2.submit("g-1", "v5e-4x4", group="job-x", group_size=2,
                        annotations=shared)
        deadline = time.monotonic() + 10
        err = None
        while time.monotonic() < deadline and not err:
            for name in ("g-0", "g-1"):
                ann = cluster2.pod(name)["metadata"].get("annotations", {})
                err = err or ann.get("tpu.instaslice.dev/error")
            time.sleep(0.1)
        assert err and "handoff-name" in err, err
        assert cluster2.pod_phase("g-0") == "Pending"
        assert cluster2.pod_phase("g-1") == "Pending"
        assert not cluster2.allocations()

    def test_group_teardown_releases_both_hosts(self, cluster2):
        cluster2.submit("w-0", "v5e-4x4", group="job-a", group_size=2)
        cluster2.submit("w-1", "v5e-4x4", group="job-a", group_size=2)
        assert cluster2.wait_phase("w-0", "Running", timeout=20)
        assert cluster2.wait_phase("w-1", "Running", timeout=20)
        cluster2.delete_pod("w-0")
        cluster2.delete_pod("w-1")
        assert cluster2.wait_gone("w-0", timeout=10)
        assert cluster2.wait_gone("w-1", timeout=10)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if not cluster2.allocations() and all(
                not b.list_reservations()
                for b in cluster2.backends.values()
            ):
                break
            time.sleep(0.05)
        assert cluster2.allocations() == {}
        for b in cluster2.backends.values():
            assert b.list_reservations() == []


class TestReviewRegressions:
    def test_surplus_group_pod_annotated(self, cluster2):
        """A 3rd pod beyond group-size=2 must get an error annotation,
        not a silent livelock."""
        cluster2.submit("w-0", "v5e-4x4", group="job-a", group_size=2)
        cluster2.submit("w-1", "v5e-4x4", group="job-a", group_size=2)
        cluster2.submit("w-2", "v5e-4x4", group="job-a", group_size=2)
        assert cluster2.wait_phase("w-0", "Running", timeout=20)
        assert cluster2.wait_phase("w-1", "Running", timeout=20)
        deadline = time.monotonic() + 20
        ann = {}
        while time.monotonic() < deadline:
            ann = cluster2.pod("w-2")["metadata"].get("annotations", {})
            if "tpu.instaslice.dev/error" in ann:
                break
            time.sleep(0.05)
        assert "surplus" in ann.get("tpu.instaslice.dev/error", "")

    def test_late_surplus_pod_annotated(self, cluster2):
        """Surplus detection must also work when the extra pod reconciles
        AFTER its peers were granted and ungated — gated-peer counting
        alone would requeue it forever (the silent livelock)."""
        cluster2.submit("w-0", "v5e-4x4", group="job-b", group_size=2)
        cluster2.submit("w-1", "v5e-4x4", group="job-b", group_size=2)
        assert cluster2.wait_phase("w-0", "Running", timeout=20)
        assert cluster2.wait_phase("w-1", "Running", timeout=20)
        cluster2.submit("w-2", "v5e-4x4", group="job-b", group_size=2)
        deadline = time.monotonic() + 20
        ann = {}
        while time.monotonic() < deadline:
            ann = cluster2.pod("w-2")["metadata"].get("annotations", {})
            if "tpu.instaslice.dev/error" in ann:
                break
            time.sleep(0.05)
        assert "surplus" in ann.get("tpu.instaslice.dev/error", "")

    def test_raced_reserve_released_on_teardown(self, cluster2):
        """Reserve succeeds on node B while node A's failure marks the
        allocation FAILED->DELETED: B's reservation must not leak."""
        cluster2.backends["node-0"].inject_failures("reserve", 1)
        cluster2.submit("w-0", "v5e-4x4", group="j", group_size=2)
        cluster2.submit("w-1", "v5e-4x4", group="j", group_size=2)
        # retry loop should eventually land both pods
        assert cluster2.wait_phase("w-0", "Running", timeout=20)
        assert cluster2.wait_phase("w-1", "Running", timeout=20)
        total = sum(
            len(r.chip_ids)
            for b in cluster2.backends.values()
            for r in b.list_reservations()
        )
        assert total == 16  # exactly one 4x4, no leaked duplicates


class TestDevicePluginLifecycle:
    """Controller → agent → device plugin in ONE flow: the slice plugins
    serve realized reservations as per-profile devices over real gRPC
    unix sockets, and the sim scheduler plays kubelet when binding."""

    def test_allocate_matches_handoff_env(self):
        with SimCluster(n_nodes=1, device_plugins=True) as sim:
            sim.submit("dp-pod", "v5e-2x2", device_resource=True)
            assert sim.wait_phase("dp-pod", "Running", timeout=20)
            ann = sim.pod("dp-pod")["metadata"]["annotations"]
            cm = sim.configmap("dp-pod")
            assert cm is not None
            visible = cm["data"]["TPU_VISIBLE_CHIPS"]
            # kubelet's device fence == the controller's carve: the env
            # the plugin injected names exactly the handoff's chips
            assert ann["tpu.instaslice.dev/kubelet-env-chips"] == visible
            assert ann["tpu.instaslice.dev/chips"] == visible
            # and the injected device nodes are those chips' paths
            inv = sim.backends["node-0"].discover()
            got_paths = sorted(
                ann["tpu.instaslice.dev/device-paths"].split(",")
            )
            want_paths = sorted(
                inv.chip_paths[int(c)] for c in visible.split(",")
            )
            assert got_paths == want_paths
            # full teardown still works with the plugin tier active
            sim.delete_pod("dp-pod")
            assert sim.wait_gone("dp-pod", timeout=20)

    def test_two_pods_get_disjoint_devices(self):
        with SimCluster(n_nodes=1, device_plugins=True) as sim:
            sim.submit("dp-a", "v5e-2x2", device_resource=True)
            sim.submit("dp-b", "v5e-2x2", device_resource=True)
            assert sim.wait_phase("dp-a", "Running", timeout=20)
            assert sim.wait_phase("dp-b", "Running", timeout=20)
            chips_a = sim.pod("dp-a")["metadata"]["annotations"][
                "tpu.instaslice.dev/kubelet-env-chips"]
            chips_b = sim.pod("dp-b")["metadata"]["annotations"][
                "tpu.instaslice.dev/kubelet-env-chips"]
            assert chips_a and chips_b
            assert not (set(chips_a.split(",")) & set(chips_b.split(",")))
            # same-profile slice devices are fungible to kubelet, so each
            # pod's grant must be SOME realized reservation (the plugin's
            # injected TPU_VISIBLE_CHIPS override makes kubelet's pick
            # authoritative); together they cover both reservations
            reserved = {
                ",".join(str(c) for c in r.chip_ids)
                for r in sim.backends["node-0"].list_reservations()
            }
            assert {chips_a, chips_b} == reserved


class TestDemoCli:
    def test_demo_main_inproc(self, capsys):
        from instaslice_tpu.cli.demo import main

        assert main(["--profile", "v5e-1x1", "--nodes", "1"]) == 0
        out = capsys.readouterr().out
        assert '"demo": "ok"' in out
        assert "TPU_VISIBLE_CHIPS" in out
