"""Repetition penalty + min-p (``serving/sampling.py`` + engine wiring).

Penalty semantics bar (HF/vLLM): tokens in the prompt or generated so
far are pushed down BEFORE temperature/filters — including tokens
sampled earlier inside the same on-device decode block, which is the
part a naive pre-block snapshot would get wrong.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.serving import ServingEngine
from instaslice_tpu.serving.sampling import (
    apply_repetition_penalty,
    filter_logits,
)

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


class TestTransforms:
    def test_penalty_pushes_seen_down_both_signs(self):
        logits = jnp.asarray([[2.0, -2.0, 1.0, -1.0]])
        seen = jnp.asarray([[True, True, False, False]])
        out = apply_repetition_penalty(logits, seen, 2.0)
        np.testing.assert_allclose(
            np.asarray(out[0]), [1.0, -4.0, 1.0, -1.0]
        )

    def test_penalty_one_is_identity(self):
        logits = jax.random.normal(jax.random.key(0), (2, 8))
        seen = jnp.ones((2, 8), bool)
        np.testing.assert_allclose(
            np.asarray(apply_repetition_penalty(logits, seen, 1.0)),
            np.asarray(logits), rtol=1e-6,
        )

    def test_min_p_keeps_argmax_and_filters_tail(self):
        logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
        out = filter_logits(logits, min_p=0.5)   # floor = 0.25
        kept = np.asarray(out[0]) > -1e8
        np.testing.assert_array_equal(kept, [True, True, False, False])
        # min_p = 1.0 degrades to greedy, never to empty
        out = filter_logits(logits, min_p=1.0)
        assert int((np.asarray(out[0]) > -1e8).sum()) == 1

    def test_min_p_noop(self):
        logits = jax.random.normal(jax.random.key(1), (2, 16))
        np.testing.assert_allclose(
            np.asarray(filter_logits(logits, min_p=0.0)),
            np.asarray(logits.astype(jnp.float32)), rtol=1e-6,
        )


class TestEngineWiring:
    def test_greedy_penalty_suppresses_repetition(self, model):
        """The plain greedy chain repeats a token; the penalized chain
        must produce something different once that token is seen — and
        the block path must agree with the step-by-step path (the
        in-scan seen update)."""
        m, params = model
        prompt = [5, 9, 2]

        def run(penalty, use_block):
            eng = ServingEngine(m, params, max_batch=1, max_len=64,
                                prefill_len=8,
                                repetition_penalty=penalty)
            [rid] = [eng.add_request(prompt)]
            if use_block:
                eng.decode_block(9)
            else:
                for _ in range(9):
                    eng.step()
            return eng.slots[next(iter(eng.slots))].generated

        plain = run(1.0, use_block=True)
        stepped = run(1.5, use_block=False)
        blocked = run(1.5, use_block=True)
        assert stepped == blocked         # in-scan seen == host seen
        assert stepped != plain           # the penalty did something
        # (no stronger claim: HF's penalty demotes a seen token but
        # need not dethrone it, so immediate repeats remain possible)

    def test_slot_reuse_resets_seen(self, model):
        """A freed slot's seen set must not leak into the next request
        (same engine, same slot, different prompt)."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=64,
                            prefill_len=8, repetition_penalty=1.5)
        eng.add_request([5, 9, 2])
        eng.decode_block(4)
        eng.finish_slot(next(iter(eng.slots)))
        eng.add_request([7, 7, 7])
        eng.decode_block(4)
        second = eng.slots[next(iter(eng.slots))].generated
        # oracle: a FRESH engine serving only the second prompt
        fresh = ServingEngine(m, params, max_batch=1, max_len=64,
                              prefill_len=8, repetition_penalty=1.5)
        fresh.add_request([7, 7, 7])
        fresh.decode_block(4)
        assert second == fresh.slots[next(iter(fresh.slots))].generated

    def test_validation(self, model):
        m, params = model
        with pytest.raises(ValueError, match="min_p"):
            ServingEngine(m, params, min_p=1.5)
        with pytest.raises(ValueError, match="repetition_penalty"):
            ServingEngine(m, params, repetition_penalty=0.0)
        with pytest.raises(ValueError, match="speculative"):
            ServingEngine(m, params, repetition_penalty=1.5,
                          draft_model=m, draft_params=params)

    def test_penalty_is_construction_only(self, model):
        """Unlike temperature/top_k/top_p, mutating the penalty cannot
        take effect (seen-tracking is decided at construction) — so it
        must raise instead of being silently ignored."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=1, max_len=32,
                            prefill_len=8)
        with pytest.raises(AttributeError):
            eng.repetition_penalty = 1.5

    def test_min_p_sampled_engine_runs(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, temperature=0.9, min_p=0.2)
        [res] = eng.generate([[5, 9, 2]], max_new_tokens=6)
        assert len(res.tokens) == 6
