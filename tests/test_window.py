"""Sliding-window attention (``ModelConfig.window``), Mistral-family.

Semantics bar: position i attends exactly [max(0, i-window+1), i] —
identical to full causal while S <= window, provably different beyond
it, and the incremental decode path must agree with the full forward
token for token (the mask is applied in two different formulations).
"""

import jax
import jax.numpy as jnp
import pytest

from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.serving import ServingEngine

pytestmark = pytest.mark.slow


def cfg_with(window: int) -> ModelConfig:
    return ModelConfig(
        vocab_size=64, d_model=32, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=64, dtype=jnp.float32, remat=False, window=window,
    )


def greedy_reference(model, params, prompt, n_new):
    toks = list(prompt)
    out = []
    for _ in range(n_new):
        logits = model.apply(params, jnp.asarray(toks, jnp.int32)[None])
        t = int(jnp.argmax(logits[0, -1]))
        out.append(t)
        toks.append(t)
    return out


class TestWindowSemantics:
    def test_equals_full_causal_within_window(self):
        toks = jax.random.randint(jax.random.key(1), (2, 8), 0, 64)
        params = TpuLM(cfg_with(0)).init(jax.random.key(0))
        full = TpuLM(cfg_with(0)).apply(params, toks)
        win = TpuLM(cfg_with(8)).apply(params, toks)   # S == window
        assert float(jnp.abs(full - win).max()) < 1e-5

    def test_window_actually_truncates(self):
        toks = jax.random.randint(jax.random.key(1), (2, 16), 0, 64)
        params = TpuLM(cfg_with(0)).init(jax.random.key(0))
        full = TpuLM(cfg_with(0)).apply(params, toks)
        win = TpuLM(cfg_with(4)).apply(params, toks)
        # early positions (inside every window) agree; late ones differ
        assert float(jnp.abs(full[:, :4] - win[:, :4]).max()) < 1e-5
        assert float(jnp.abs(full[:, -1] - win[:, -1]).max()) > 1e-4

    def test_first_window_positions_see_everything_available(self):
        """Position i < window has fewer than `window` predecessors —
        the mask must admit all of them (no off-by-one at the start)."""
        toks = jax.random.randint(jax.random.key(2), (1, 6), 0, 64)
        params = TpuLM(cfg_with(0)).init(jax.random.key(0))
        win = TpuLM(cfg_with(3)).apply(params, toks)
        # recompute position 2 (window exactly covers 0..2) from the
        # full model on the 3-token prefix: must match
        full_prefix = TpuLM(cfg_with(0)).apply(params, toks[:, :3])
        assert float(jnp.abs(win[:, 2] - full_prefix[:, 2]).max()) < 1e-5

    def test_validation(self):
        with pytest.raises(ValueError, match="window"):
            cfg_with(-1)
        with pytest.raises(ValueError, match="ring"):
            ModelConfig(n_heads=4, window=8, ring_attention=True)


class TestWindowDecode:
    def test_incremental_matches_full_forward(self):
        cfg = cfg_with(5)
        m = TpuLM(cfg)
        params = m.init(jax.random.key(0))
        toks = jax.random.randint(jax.random.key(1), (2, 12), 0, 64)
        full = m.apply(params, toks)
        cache = m.init_cache(2, 32)
        lengths = jnp.zeros(2, jnp.int32)
        lg, cache = m.apply_with_cache(params, toks[:, :4], cache,
                                       lengths)
        assert float(jnp.abs(lg - full[:, :4]).max()) < 1e-4
        lengths = lengths + 4
        for t in range(4, 12):
            lg, cache = m.apply_with_cache(
                params, toks[:, t:t + 1], cache, lengths
            )
            assert float(jnp.abs(lg[:, 0] - full[:, t]).max()) < 1e-4, t
            lengths = lengths + 1

    def test_banded_read_equals_prefix_read(self):
        """The windowed band read (vmapped dynamic_slice) is a pure
        HBM optimization: forcing the full-prefix path via a window as
        wide as the cache must give identical logits to the banded
        path of an equivalent narrow-window model."""
        toks = jax.random.randint(jax.random.key(3), (2, 10), 0, 64)
        cfg = cfg_with(4)
        m = TpuLM(cfg)
        params = m.init(jax.random.key(0))
        # banded: window 4, cache 32 → band (4+T-1) < 32 is taken
        cache = m.init_cache(2, 32)
        lengths = jnp.zeros(2, jnp.int32)
        lg_band, cache = m.apply_with_cache(params, toks[:, :10], cache,
                                            lengths)
        # full-prefix: same model but attend bucket equal to the band
        # is unreachable, so recompute via the no-cache forward
        full = m.apply(params, toks)
        assert float(jnp.abs(lg_band - full).max()) < 1e-4

    def test_quantized_cache_with_window(self):
        """int8 KV + banded window reads compose (the band slices the
        int8 values AND their scales)."""
        cfg = cfg_with(5)
        m = TpuLM(cfg)
        params = m.init(jax.random.key(0))
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, kv_quant=True)
        prompt = [5, 9, 2, 7]
        [res] = eng.generate([prompt], max_new_tokens=8)
        ref = greedy_reference(m, params, prompt, 8)
        agree = sum(1 for a, b in zip(res.tokens, ref) if a == b)
        assert agree >= 6, (res.tokens, ref)

    def test_engine_matches_oracle(self):
        cfg = cfg_with(6)
        m = TpuLM(cfg)
        params = m.init(jax.random.key(0))
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        prompt = [5, 9, 2, 7, 11, 3]
        [res] = eng.generate([prompt], max_new_tokens=10)
        assert res.tokens == greedy_reference(m, params, prompt, 10)
