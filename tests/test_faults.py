"""FaultPlan unit tier: determinism, schedules, env parsing, and the
kube/device wrappers — the machinery the chaos tiers trust."""

import pytest

from instaslice_tpu.device import FakeTpuBackend
from instaslice_tpu.device.backend import DeviceError
from instaslice_tpu.faults import (
    FaultPlan,
    FaultyBackend,
    FaultyKubeClient,
    InjectedApiError,
)
from instaslice_tpu.kube import FakeKube


def pod(name, ns="default"):
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": ns},
        "spec": {}, "status": {},
    }


class TestFaultPlan:
    def test_deterministic_given_seed(self):
        def sequence(seed):
            plan = FaultPlan(seed).site("s", probability=0.3)
            return [plan.fire("s") for _ in range(50)]

        assert sequence(7) == sequence(7)
        assert sequence(7) != sequence(8)

    def test_at_calls_schedule_is_exact(self):
        plan = FaultPlan(0).site("s", at_calls={2, 4})
        fired = [plan.fire("s") is not None for _ in range(5)]
        assert fired == [False, True, False, True, False]

    def test_max_fires_caps(self):
        plan = FaultPlan(0).site("s", probability=1.0, max_fires=2)
        fired = [plan.fire("s") is not None for _ in range(5)]
        assert fired == [True, True, False, False, False]

    def test_unregistered_site_never_fires(self):
        plan = FaultPlan(0)
        assert all(plan.fire("nope") is None for _ in range(10))
        assert plan.stats()["nope"]["calls"] == 10

    def test_from_env_grammar(self):
        plan = FaultPlan.from_env(
            "seed=42;kube.request:p=0.5,kinds=http-503|conn-reset;"
            "engine.decode:at=1|3,kinds=poison;device.reserve:p=0.1,max=2"
        )
        assert plan.seed == 42
        assert plan.sites["kube.request"].probability == 0.5
        assert plan.sites["kube.request"].kinds == (
            "http-503", "conn-reset",
        )
        assert plan.sites["engine.decode"].at_calls == frozenset({1, 3})
        assert plan.sites["device.reserve"].max_fires == 2

    def test_from_env_empty_is_none(self):
        assert FaultPlan.from_env("") is None
        assert FaultPlan.from_env("   ") is None

    def test_from_env_rejects_unknown_key(self):
        with pytest.raises(ValueError):
            FaultPlan.from_env("s:bogus=1")


class TestFaultyKubeClient:
    def test_injects_api_errors(self):
        plan = FaultPlan(0).site(
            "kube.request", at_calls={1}, kinds=("http-503",),
        )
        c = FaultyKubeClient(FakeKube(), plan)
        with pytest.raises(InjectedApiError) as ei:
            c.create("Pod", pod("a"))
        assert ei.value.code == 503
        # next call goes through; the store never saw the failed one
        c.create("Pod", pod("a"))
        assert c.get("Pod", "default", "a")["metadata"]["name"] == "a"

    def test_injects_connection_reset(self):
        plan = FaultPlan(0).site(
            "kube.request", at_calls={1}, kinds=("conn-reset",),
        )
        c = FaultyKubeClient(FakeKube(), plan)
        with pytest.raises(ConnectionResetError):
            c.list("Pod")

    def test_watch_disconnect_truncates_stream(self):
        store = FakeKube()
        for i in range(6):
            store.create("Pod", pod(f"p{i}"))
        plan = FaultPlan(0).site(
            "kube.watch", at_calls={3}, kinds=("disconnect",),
        )
        c = FaultyKubeClient(store, plan)
        events = list(c.watch("Pod", timeout=0.05))
        # the replay burst alone is 6 ADDED + a BOOKMARK: the injected
        # disconnect cut it at 2 delivered events
        assert len(events) == 2


class TestFaultyBackend:
    def test_injects_device_errors_and_passthrough(self):
        plan = FaultPlan(0).site(
            "device.reserve", at_calls={1}, kinds=("error",),
        )
        b = FaultyBackend(FakeTpuBackend(), plan)
        with pytest.raises(DeviceError):
            b.reserve("s1", [0, 1])
        r = b.reserve("s1", [0, 1])          # second attempt lands
        assert r.chip_ids == (0, 1)
        assert [x.slice_uuid for x in b.list_reservations()] == ["s1"]
        b.release("s1")
        # test helpers pass through the wrapper
        b.inject_failures("reserve", 1)
        with pytest.raises(DeviceError):
            b.reserve("s2", [2])

    def test_chip_fail_kind_marks_chip_unhealthy(self):
        plan = FaultPlan(3).site(
            "device.health", at_calls={1}, kinds=("chip-fail",),
        )
        b = FaultyBackend(FakeTpuBackend(), plan)
        health = b.chip_health()
        assert not all(health.values())       # one chip went down
