"""Grouped-query attention (``ModelConfig.n_kv_heads``).

The Llama-3-class serving layout: Hkv KV heads shared by n_heads/Hkv
query heads each, shrinking the decode KV cache — the dominant HBM
stream at high concurrency — by that group factor. Correctness bar:
the grouped contraction must be numerically identical to attention
over explicitly repeated K/V, through every path (plain forward,
incremental decode, the serving engine, int8 KV, tensor parallelism).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from instaslice_tpu.models.lm import ModelConfig, TpuLM, _attention
from instaslice_tpu.serving import ServingEngine

pytestmark = pytest.mark.slow

CFG = ModelConfig(
    vocab_size=64, d_model=32, n_heads=4, n_kv_heads=2, n_layers=2,
    d_ff=64, dtype=jnp.float32, remat=False,
)


@pytest.fixture(scope="module")
def model():
    m = TpuLM(CFG)
    return m, m.init(jax.random.key(0))


def greedy_reference(model, params, prompt, n_new):
    toks = list(prompt)
    out = []
    for _ in range(n_new):
        logits = model.apply(params, jnp.asarray(toks, jnp.int32)[None])
        t = int(jnp.argmax(logits[0, -1]))
        out.append(t)
        toks.append(t)
    return out


class TestGroupedAttentionMath:
    def test_grouped_equals_repeated_kv(self):
        ks = jax.random.split(jax.random.key(3), 3)
        q = jax.random.normal(ks[0], (2, 8, 4, 16))
        k = jax.random.normal(ks[1], (2, 8, 2, 16))
        v = jax.random.normal(ks[2], (2, 8, 2, 16))
        grouped = _attention(q, k, v, impl="xla")
        ref = _attention(
            q, jnp.repeat(k, 2, axis=2), jnp.repeat(v, 2, axis=2),
            impl="xla",
        )
        assert float(jnp.abs(grouped - ref).max()) < 1e-5

    def test_bad_head_ratio_rejected(self):
        with pytest.raises(ValueError, match="divisor"):
            ModelConfig(n_heads=4, n_kv_heads=3)
        with pytest.raises(ValueError, match="divisor"):
            ModelConfig(n_heads=8, n_kv_heads=-8)


class TestGqaModel:
    def test_param_shapes_shrink(self, model):
        _, params = model
        assert params["blocks"]["wq"].shape == (2, 32, 32)
        assert params["blocks"]["wk"].shape == (2, 32, 16)   # Hkv·hd
        assert params["blocks"]["wv"].shape == (2, 32, 16)

    def test_cache_stores_only_kv_heads(self, model):
        m, _ = model
        cache = m.init_cache(2, 16)
        assert cache["k"].shape == (2, 2, 2, 16, 8)          # Hkv=2, head-major
        qc = m.init_cache(2, 16, quant=True)
        assert qc["k"].shape == (2, 2, 2, 16, 8)
        assert qc["k_s"].shape == (2, 2, 2, 16)

    def test_incremental_matches_full_forward(self, model):
        m, params = model
        toks = jax.random.randint(jax.random.key(1), (2, 12), 0, 64)
        full = m.apply(params, toks)
        cache = m.init_cache(2, 32)
        lengths = jnp.zeros(2, jnp.int32)
        lg, cache = m.apply_with_cache(params, toks[:, :5], cache,
                                       lengths)
        assert float(jnp.abs(lg - full[:, :5]).max()) < 1e-4
        lengths = lengths + 5
        for t in range(5, 12):
            lg, cache = m.apply_with_cache(
                params, toks[:, t:t + 1], cache, lengths
            )
            assert float(jnp.abs(lg[:, 0] - full[:, t]).max()) < 1e-4
            lengths = lengths + 1

    def test_train_step_runs(self, model):
        """GQA composes with the training path (grad flows through the
        grouped contraction and the shrunken projections)."""
        from jax.sharding import Mesh

        from instaslice_tpu.models.train import make_train_step

        mesh = Mesh(
            np.array(jax.devices()[:1]).reshape(1, 1, 1),
            ("data", "seq", "model"),
        )
        init_fn, step_fn = make_train_step(TpuLM(CFG), mesh)
        state = init_fn(jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 64)
        state, loss = step_fn(state, tokens)
        assert bool(jnp.isfinite(loss))


class TestGqaServing:
    def test_engine_matches_oracle(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        prompt = [5, 9, 2, 7]
        [res] = eng.generate([prompt], max_new_tokens=8)
        assert res.tokens == greedy_reference(m, params, prompt, 8)

    def test_engine_int8_kv_close_to_oracle(self, model):
        """int8 KV on the grouped cache: same storage quant, 1/G heads."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, kv_quant=True)
        prompt = [5, 9, 2, 7]
        [res] = eng.generate([prompt], max_new_tokens=8)
        ref = greedy_reference(m, params, prompt, 8)
        # quantized cache may flip late argmaxes; the prefix must hold
        agree = sum(1 for a, b in zip(res.tokens, ref) if a == b)
        assert agree >= 6, (res.tokens, ref)

    def test_tensor_parallel_over_kv_heads(self, model):
        """TP mesh of 2: both query heads (4) and KV heads (2) divide;
        grouped decode under sharding matches the oracle."""
        from jax.sharding import Mesh

        m, params = model
        mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, mesh=mesh)
        prompt = [5, 9, 2, 7]
        [res] = eng.generate([prompt], max_new_tokens=8)
        assert res.tokens == greedy_reference(m, params, prompt, 8)

    def test_tp_rejects_indivisible_kv_heads(self, model):
        from jax.sharding import Mesh

        m, params = model
        cfg = ModelConfig(
            vocab_size=64, d_model=32, n_heads=4, n_kv_heads=1,
            n_layers=1, d_ff=64, dtype=jnp.float32, remat=False,
        )
        m1 = TpuLM(cfg)
        mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
        with pytest.raises(ValueError, match="kv_heads"):
            ServingEngine(m1, m1.init(jax.random.key(0)), max_batch=2,
                          max_len=32, prefill_len=8, mesh=mesh)
