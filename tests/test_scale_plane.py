"""Fleet-scale control-plane tier (docs/SCALING.md): the informer cache,
sharded reconcile workers, coalesced CR writes, workqueue compaction,
per-shard Lease leadership, and the fleet-agent sim — the machinery that
turns the serial re-list loop into a 1k-node control plane.
"""

from __future__ import annotations

import threading
import time

import pytest

from instaslice_tpu.kube import CoalescedWriter, FakeKube, Informer, NotFound
from instaslice_tpu.kube.client import Conflict
from instaslice_tpu.utils.reconcile import (
    Manager,
    ShardedQueue,
    WorkQueue,
    shard_for,
)


def pod(name, ns="default", gated=True, labels=None, group=""):
    from instaslice_tpu import GATE_NAME
    from instaslice_tpu.api.constants import GROUP_ANNOTATION

    ann = {GROUP_ANNOTATION: group} if group else {}
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": ns,
            "uid": f"uid-{name}",
            "labels": labels or {},
            "annotations": ann,
        },
        "spec": {
            "schedulingGates": (
                [{"name": GATE_NAME}] if gated else []
            ),
        },
        "status": {"phase": "Pending"},
    }


def wait_until(fn, timeout=5.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(every)
    return False


# =========================================================== informer


class TestInformer:
    def test_sync_list_get_and_watch_updates(self):
        kube = FakeKube()
        kube.create("Pod", pod("a"))
        inf = Informer(kube, "Pod").start()
        try:
            assert inf.wait_synced(5)
            assert inf.get("default", "a")["metadata"]["name"] == "a"
            kube.create("Pod", pod("b"))
            assert wait_until(lambda: inf.get("default", "b") is not None)
            assert len(inf.list()) == 2
            kube.delete("Pod", "default", "a")
            assert wait_until(lambda: inf.get("default", "a") is None)
            assert [o["metadata"]["name"] for o in inf.list()] == ["b"]
        finally:
            inf.stop()

    def test_secondary_index_tracks_membership(self):
        kube = FakeKube()
        inf = Informer(
            kube, "Pod",
            indexers={"by-phase": lambda o: [
                o.get("status", {}).get("phase", "")
            ]},
        ).start()
        try:
            assert inf.wait_synced(5)
            kube.create("Pod", pod("a"))
            assert wait_until(
                lambda: len(inf.by_index("by-phase", "Pending")) == 1
            )
            # index keys move with the object
            kube.patch("Pod", "default", "a",
                       {"status": {"phase": "Running"}})
            assert wait_until(
                lambda: len(inf.by_index("by-phase", "Running")) == 1
            )
            assert inf.by_index("by-phase", "Pending") == []
            kube.delete("Pod", "default", "a")
            assert wait_until(
                lambda: inf.by_index("by-phase", "Running") == []
            )
        finally:
            inf.stop()

    def test_transform_cached_per_version(self):
        calls = []

        def parse(obj):
            calls.append(obj["metadata"]["resourceVersion"])
            return obj["metadata"]["name"].upper()

        kube = FakeKube()
        kube.create("Pod", pod("a"))
        inf = Informer(kube, "Pod", transform=parse).start()
        try:
            assert inf.wait_synced(5)
            before = len(calls)
            for _ in range(10):
                assert inf.list_transformed() == ["A"]
            # reads never re-parse; only a new version does
            assert len(calls) == before
        finally:
            inf.stop()

    def test_write_through_visible_immediately_and_stale_ignored(self):
        kube = FakeKube()
        kube.create("Pod", pod("a"))
        inf = Informer(kube, "Pod").start()
        try:
            assert inf.wait_synced(5)
            old = inf.get("default", "a")
            stored = kube.patch("Pod", "default", "a",
                                {"metadata": {"labels": {"x": "1"}}})
            inf.write_through(stored)
            got = inf.get("default", "a")
            assert got["metadata"]["labels"] == {"x": "1"}
            # replaying the OLD version (watch catching up) can't regress
            inf.write_through(old)
            assert inf.get("default", "a")["metadata"]["labels"] == {
                "x": "1"
            }
        finally:
            inf.stop()

    def test_resync_relist_does_not_bump_index_versions(self):
        # an equal-rv re-delivery (what every resync relist is) must
        # not re-transform or invalidate derived memos
        calls = []
        kube = FakeKube()
        kube.create("Pod", pod("a"))
        inf = Informer(
            kube, "Pod", resync_period=0.1,
            indexers={"by-ns": lambda o: [
                o.get("metadata", {}).get("namespace", "")
            ]},
            transform=lambda o: calls.append(1) or o,
        ).start()
        try:
            assert inf.wait_synced(5)
            v0 = inf.index_version("by-ns", "default")
            parses0 = len(calls)
            time.sleep(0.5)  # several resync relists
            assert inf.index_version("by-ns", "default") == v0
            assert len(calls) == parses0
            # a REAL change still bumps + re-parses
            kube.patch("Pod", "default", "a",
                       {"metadata": {"labels": {"x": "1"}}})
            assert wait_until(
                lambda: inf.index_version("by-ns", "default") > v0
            )
            assert len(calls) > parses0
        finally:
            inf.stop()

    def test_relist_diff_synthesizes_deletes_to_handlers(self):
        kube = FakeKube()
        kube.create("Pod", pod("a"))
        kube.create("Pod", pod("b"))
        inf = Informer(kube, "Pod", resync_period=0.2)
        seen = []
        lock = threading.Lock()

        def handler(event, obj):
            with lock:
                seen.append((event, obj["metadata"]["name"]))

        inf.add_handler(handler)
        inf.start()
        try:
            assert inf.wait_synced(5)
            # delete straight from the store, then drop the event from
            # history so only a relist diff can reveal it
            with kube._lock:
                del kube._objects[("Pod", "default", "b")]
                kube._history.clear()
                kube._snapshots.clear()
            assert wait_until(
                lambda: inf.get("default", "b") is None, timeout=5
            )
            with lock:
                assert ("DELETED", "b") in seen
        finally:
            inf.stop()


# ==================================================== fake copy-on-read


class TestFakeCopyOnRead:
    def test_list_mutation_cannot_corrupt_store(self):
        kube = FakeKube()
        kube.create("Pod", pod("a"))
        listed = kube.list("Pod")[0]
        # scribble all over the returned snapshot
        listed["metadata"]["name"] = "evil"
        listed["spec"]["schedulingGates"] = []
        listed["status"]["phase"] = "Hacked"
        # the store (and every write path reading it) is untouched
        fresh = kube.get("Pod", "default", "a")
        assert fresh["metadata"]["name"] == "a"
        assert fresh["spec"]["schedulingGates"]
        assert fresh["status"]["phase"] == "Pending"
        # a write still round-trips cleanly and invalidates the snapshot
        kube.patch("Pod", "default", "a", {"status": {"phase": "Running"}})
        relisted = kube.list("Pod")[0]
        assert relisted["metadata"]["name"] == "a"
        assert relisted["status"]["phase"] == "Running"

    def test_list_reuses_snapshot_until_write(self):
        kube = FakeKube()
        kube.create("Pod", pod("a"))
        first = kube.list("Pod")[0]
        second = kube.list("Pod")[0]
        assert first is second  # one deepcopy per version, not per read
        kube.patch("Pod", "default", "a", {"metadata": {"labels": {"x": "1"}}})
        third = kube.list("Pod")[0]
        assert third is not first
        # get() callers mutate their copy (update_with_retry contract):
        # always private
        g1 = kube.get("Pod", "default", "a")
        g2 = kube.get("Pod", "default", "a")
        assert g1 is not g2


# ========================================================== workqueue


class TestWorkQueueCompaction:
    def test_heap_bounded_under_repeated_delayed_readds(self):
        q = WorkQueue()
        # the same key re-added with ever-earlier due times: every add
        # strands a stale heap entry; compaction must keep the heap
        # proportional to the live key count
        for i in range(5000):
            q.add("hot", delay=10.0 - i * 0.001)
        for i in range(64):
            q.add(f"k{i}", delay=5.0)
        assert len(q) == 65
        assert q.heap_size() < 1000, q.heap_size()

    def test_earliest_due_still_wins_after_compaction(self):
        q = WorkQueue()
        for i in range(200):
            q.add("a", delay=2.0 - i * 0.005)
        q.add("b", delay=0.0)
        assert q.get(timeout=1.0) == "b"
        q.add("a", delay=0.0)  # supersede to immediate
        assert q.get(timeout=1.0) == "a"
        assert len(q) == 0

    def test_sharded_queue_routes_stably(self):
        sq = ShardedQueue(4)
        keys = [f"key-{i}" for i in range(100)]
        for k in keys:
            sq.add(k)
        assert len(sq) == 100
        for k in keys:
            # same key, same shard — per-key ordering's foundation
            assert shard_for(k, 4) == shard_for(k, 4)
        sq.close()


# ============================================== manager resync + shards


class _CountingClient(FakeKube):
    """FakeKube that counts watch establishments + replays."""

    preferred_watch_timeout = 0.05

    def __init__(self):
        super().__init__()
        self.watch_calls = []

    def watch(self, kind, namespace=None, replay=True, timeout=None,
              resource_version=None):
        self.watch_calls.append(replay)
        return super().watch(
            kind, namespace=namespace, replay=replay, timeout=timeout,
            resource_version=resource_version,
        )


class TestManagerResync:
    def test_resync_fires_on_period_not_reestablishment(self):
        client = _CountingClient()
        client.create("Pod", pod("a"))
        fired = []
        lock = threading.Lock()

        def mapper(event, obj):
            with lock:
                fired.append(event)
            return []

        mgr = Manager(
            "t", client, reconcile=lambda key: None,
            watches=[("Pod", None, mapper)],
            resync_period=300.0, error_backoff=0.01,
        )
        mgr.start()
        try:
            # let the first establishment (relist + log-tail replay)
            # finish, then count its map-func fires
            assert wait_until(lambda: len(client.watch_calls) >= 2,
                              timeout=5)
            with lock:
                adds_after_first = fired.count("ADDED")
            assert adds_after_first >= 1
            # ...then several more re-establishments (0.05s timeout)...
            assert wait_until(lambda: len(client.watch_calls) >= 8,
                              timeout=5)
            with lock:
                adds = fired.count("ADDED")
            # ...which must NOT replay: resync_period hasn't elapsed,
            # so re-establishing resumes from the last resourceVersion
            # without re-mapping the object
            assert adds == adds_after_first, fired
            assert client.watch_calls[0] is True
            assert not any(client.watch_calls[1:8])
        finally:
            mgr.stop()

    def test_resync_refires_after_period(self):
        client = _CountingClient()
        client.create("Pod", pod("a"))
        fired = []
        lock = threading.Lock()

        def mapper(event, obj):
            with lock:
                fired.append(event)
            return []

        mgr = Manager(
            "t", client, reconcile=lambda key: None,
            watches=[("Pod", None, mapper)],
            resync_period=0.15, error_backoff=0.01,
        )
        mgr.start()
        try:
            assert wait_until(
                lambda: fired.count("ADDED") >= 3, timeout=5
            ), fired
        finally:
            mgr.stop()


class TestShardedWorkers:
    def test_per_key_ordering_with_cross_key_parallelism(self):
        client = FakeKube()
        active = {}
        overlaps = []
        parallel_seen = [0]
        lock = threading.Lock()

        def reconcile(key):
            with lock:
                if active.get(key):
                    overlaps.append(key)  # per-key concurrency = bug
                active[key] = True
                busy = sum(1 for v in active.values() if v)
                parallel_seen[0] = max(parallel_seen[0], busy)
            time.sleep(0.02)
            with lock:
                active[key] = False
            return None

        mgr = Manager(
            "t", client, reconcile=reconcile, watches=[], workers=4,
        )
        mgr.start()
        try:
            keys = [f"pod-{i}" for i in range(12)]
            for _ in range(6):
                for k in keys:
                    mgr.queue.add(k)
                time.sleep(0.03)
            assert mgr.wait_idle(timeout=10)
            assert overlaps == [], overlaps
            # distinct keys genuinely ran concurrently
            assert parallel_seen[0] > 1
            assert mgr.error_count == 0
            assert mgr.reconcile_count >= 12
        finally:
            mgr.stop()

    def test_same_key_burst_never_overlaps(self):
        client = FakeKube()
        running = [0]
        max_running = [0]
        lock = threading.Lock()

        def reconcile(key):
            with lock:
                running[0] += 1
                max_running[0] = max(max_running[0], running[0])
            time.sleep(0.01)
            with lock:
                running[0] -= 1
            return 0.005 if key == "again" else None

        mgr = Manager(
            "t", client, reconcile=reconcile, watches=[], workers=8,
        )
        mgr.start()
        try:
            for _ in range(30):
                mgr.queue.add("again")
                time.sleep(0.002)
            assert wait_until(lambda: mgr.reconcile_count >= 5)
        finally:
            mgr.stop()
        assert max_running[0] == 1, max_running[0]


class TestShardLeases:
    def test_two_replicas_split_shards_exclusively(self):
        kube = FakeKube()
        seen = {"m1": set(), "m2": set()}
        lock = threading.Lock()

        def rec(owner):
            def reconcile(key):
                with lock:
                    seen[owner].add(key)
                return None
            return reconcile

        def mk(owner):
            return Manager(
                owner, kube, reconcile=rec(owner), watches=[],
                workers=2,
                shard_lease={
                    "namespace": "ns",
                    "prefix": "ctl",
                    "identity": owner,
                    "lease_seconds": 2.0,
                    "retry_seconds": 0.05,
                },
            )

        m1, m2 = mk("m1"), mk("m2")
        m1.start()
        # m1 grabs both shard leases first
        assert wait_until(
            lambda: len(m1._electors) == 2
            and all(e.is_leader.is_set() for e in m1._electors.values()),
            timeout=5,
        )
        m2.start()
        try:
            keys = [f"k{i}" for i in range(16)]
            for k in keys:
                m1.queue.add(k)
                m2.queue.add(k)
            assert wait_until(lambda: len(seen["m1"]) == 16, timeout=5)
            time.sleep(0.3)
            # m2 holds no lease: its queues must not drain
            assert seen["m2"] == set()
            # every shard Lease names m1
            for i in range(2):
                lease = kube.get("Lease", "ns", f"ctl-shard-{i}")
                assert lease["spec"]["holderIdentity"] == "m1"
        finally:
            m1.stop()
            m2.stop()

    def test_failover_hands_shard_to_second_replica(self):
        kube = FakeKube()
        got = []
        lock = threading.Lock()

        def reconcile(key):
            with lock:
                got.append(key)
            return None

        def mk(owner):
            return Manager(
                owner, kube, reconcile=reconcile, watches=[],
                workers=1,
                shard_lease={
                    "namespace": "ns",
                    "prefix": "ctl",
                    "identity": owner,
                    "lease_seconds": 0.4,
                    "retry_seconds": 0.05,
                },
            )

        m1, m2 = mk("m1"), mk("m2")
        m1.start()
        assert wait_until(
            lambda: m1._electors
            and m1._electors[0].is_leader.is_set(), timeout=5,
        )
        m2.start()
        m2.queue.add("after-failover")
        time.sleep(0.2)
        assert got == []  # m2 waits for the lease
        m1.stop()  # releases the lease -> m2 takes over
        try:
            assert wait_until(lambda: "after-failover" in got, timeout=10)
        finally:
            m2.stop()


# ===================================================== coalesced writes


class TestCoalescedWriter:
    def _cr(self, kube, name="node-0"):
        kube.create("TpuSlice", {
            "apiVersion": "tpu.instaslice.dev/v1alpha1",
            "kind": "TpuSlice",
            "metadata": {"name": name, "namespace": "ns"},
            "spec": {"counters": {}},
        })

    def test_concurrent_mutations_all_land_with_fewer_roundtrips(self):
        kube = FakeKube()
        self._cr(kube)
        # model a real API server's write latency: while the elected
        # leader's round-trip is in flight, the other callers' mutations
        # pile into the next batch (the in-process fake commits too fast
        # to observe batching otherwise)
        real_update = kube.update

        def slow_update(kind, obj):
            time.sleep(0.01)
            return real_update(kind, obj)

        kube.update = slow_update
        w = CoalescedWriter(kube, "TpuSlice", "ns")
        n = 24
        barrier = threading.Barrier(n)
        errors = []

        def worker(i):
            def mut(obj):
                obj["spec"]["counters"][f"w{i}"] = i
                return obj

            barrier.wait()
            try:
                out = w.apply("node-0", mut)
                assert out is not None
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5)
        assert not errors
        stored = kube.get("TpuSlice", "ns", "node-0")
        assert len(stored["spec"]["counters"]) == n
        # the whole point: mutations shared round-trips
        assert w.commits < w.ops, (w.commits, w.ops)

    def test_abort_returns_none_and_skips_write(self):
        kube = FakeKube()
        self._cr(kube)
        w = CoalescedWriter(kube, "TpuSlice", "ns")
        rv = kube.get("TpuSlice", "ns", "node-0")["metadata"][
            "resourceVersion"
        ]
        assert w.apply("node-0", lambda obj: None) is None
        assert kube.get("TpuSlice", "ns", "node-0")["metadata"][
            "resourceVersion"
        ] == rv

    def test_notfound_raises_in_caller(self):
        kube = FakeKube()
        w = CoalescedWriter(kube, "TpuSlice", "ns")
        with pytest.raises(NotFound):
            w.apply("missing", lambda obj: obj)

    def test_per_op_fence_blocks_only_the_deposed_op(self):
        from instaslice_tpu.kube.client import Fenced

        kube = FakeKube()
        self._cr(kube)
        w = CoalescedWriter(kube, "TpuSlice", "ns")

        def mut_ok(obj):
            obj["spec"]["counters"]["ok"] = 1
            return obj

        def mut_deposed(obj):  # pragma: no cover - must never run
            obj["spec"]["counters"]["deposed"] = 1
            return obj

        # the fence travels with the op: even though the committing
        # thread is this (lease-holding) one, the deposed op is refused
        results = {}

        def deposed_caller():
            try:
                w.apply("node-0", mut_deposed, fence=lambda: False)
            except Fenced:
                results["fenced"] = True

        t = threading.Thread(target=deposed_caller)
        t.start()
        out = w.apply("node-0", mut_ok, fence=lambda: True)
        t.join(5)
        assert out is not None
        assert results.get("fenced") is True
        stored = kube.get("TpuSlice", "ns", "node-0")
        assert stored["spec"]["counters"] == {"ok": 1}

    def test_conflict_retry_reapplies_batch(self):
        kube = FakeKube()
        self._cr(kube)
        # interleave an external writer: first update attempt conflicts
        real_update = kube.update
        raced = [False]

        def racing_update(kind, obj):
            if not raced[0]:
                raced[0] = True
                fresh = kube.get("TpuSlice", "ns", "node-0")
                fresh["spec"]["counters"]["external"] = 99
                real_update(kind, fresh)  # bumps rv under the caller
            return real_update(kind, obj)

        kube.update = racing_update
        w = CoalescedWriter(kube, "TpuSlice", "ns")

        def mut(obj):
            obj["spec"]["counters"]["mine"] = 1
            return obj

        out = w.apply("node-0", mut)
        assert out is not None
        stored = kube.get("TpuSlice", "ns", "node-0")
        assert stored["spec"]["counters"] == {"external": 99, "mine": 1}


# ======================================================== fleet-scale sim


class TestFleetScaleSim:
    def test_fleet_sim_grants_burst_with_sharded_workers(self):
        from instaslice_tpu.sim import SimCluster

        n_pods = 24
        with SimCluster(
            n_nodes=12, generation="v5e", nodes_per_group=2,
            fleet_agents=True, agent_workers=4, workers=4,
            deletion_grace_seconds=0.2, health_interval=0,
        ) as c:
            for i in range(n_pods):
                c.submit(f"burst-{i}", profile="v5e-1x1")
            for i in range(n_pods):
                assert c.wait_phase(f"burst-{i}", "Running", timeout=30), \
                    f"burst-{i}: {c.pod_phase(f'burst-{i}')}"
            # lazy node construction: agents exist only for nodes whose
            # CRs carried work (allocation-less CR events map to no key)
            assert c.fleet is not None
            assert 1 <= len(c.fleet._agents) <= 12
            assert c.controller.manager.error_count == 0
            # no double-allocation anywhere: every allocation's box is
            # disjoint per torus group
            from instaslice_tpu.topology.placement import Box
            by_group = {}
            for m in c.kube.list("TpuSlice", namespace=c.namespace):
                gid = m["spec"].get("torusGroup") or m["metadata"]["name"]
                for aid, a in m["spec"].get("allocations", {}).items():
                    by_group.setdefault(gid, {})[aid] = a["box"]
            placed = sum(len(v) for v in by_group.values())
            assert placed >= n_pods // 2  # grants happened at all
            for gid, boxes in by_group.items():
                items = sorted(boxes.items())
                for i, (aid_a, ka) in enumerate(items):
                    for aid_b, kb in items[i + 1:]:
                        assert not Box.from_key(ka).overlaps(
                            Box.from_key(kb)
                        ), (gid, aid_a, aid_b)

    def test_bind_latency_delays_running(self):
        from instaslice_tpu.sim import SimCluster

        with SimCluster(
            n_nodes=1, generation="v5e", deletion_grace_seconds=0.2,
            bind_latency=0.5,
        ) as c:
            t0 = time.monotonic()
            c.submit("slowbind", profile="v5e-1x1")
            assert c.wait_phase("slowbind", "Running", timeout=20)
            # the simulated kubelet waited its latency before binding
            assert time.monotonic() - t0 >= 0.5


class TestOverlapGuard:
    def test_write_allocation_refuses_overlapping_box(self):
        from instaslice_tpu.api import AllocationDetails, PodRef
        from instaslice_tpu.sim import SimCluster

        with SimCluster(n_nodes=1, generation="v5e",
                        deletion_grace_seconds=0.2) as c:
            c.submit("first", profile="v5e-2x2")
            assert c.wait_phase("first", "Running", timeout=20)
            allocs = c.allocations()
            assert len(allocs) == 1
            box = next(iter(allocs.values()))["box"]
            # forge a second allocation claiming the same chips
            forged = AllocationDetails(
                alloc_id="forged",
                pods=[PodRef(pod_uuid="uid-forged", pod_name="forged",
                             namespace="default", worker_id=0)],
                profile="v5e-2x2",
                torus_group="node-0",
                box=box,
                parts={"node-0": (0, box)},
            )
            ok = c.controller._write_allocation(forged)
            assert ok is False
            assert "forged" not in c.allocations()
