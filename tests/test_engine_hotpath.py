"""Engine hot path (docs/SERVING.md "Engine hot path"): batched
multi-slot prefill, the single-adapter decode fast path, and the
host/device overlap seam — every variant TOKEN-IDENTICAL to the path
it replaces (including across a preempt/resume cycle), the compiled-
program set bounded by the documented budget, and the new dispatch
forms replaying over the multi-host op stream."""

import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from instaslice_tpu.metrics.metrics import ServingMetrics, render
from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.models.lora import LoraConfig, init_lora
from instaslice_tpu.serving import AdmissionRequest, ServingEngine
from instaslice_tpu.serving.api_server import ApiServer


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=64, dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


def greedy_reference(model, params, prompt, n_new):
    toks = list(prompt)
    out = []
    for _ in range(n_new):
        logits = model.apply(params, jnp.asarray(toks, jnp.int32)[None])
        t = int(jnp.argmax(logits[0, -1]))
        out.append(t)
        toks.append(t)
    return out


def _adapter(cfg, key, scale=0.4):
    lcfg = LoraConfig(rank=4)
    ad = init_lora(jax.random.key(key), cfg, lcfg)
    for t in lcfg.targets:
        ad["blocks"][t]["b"] = (
            jax.random.normal(jax.random.key(key + 50),
                              ad["blocks"][t]["b"].shape) * scale
        )
    return ad


def _snapshot(eng):
    """Comparable engine output state: per-slot chains + logprobs."""
    return {
        s: (r.request_id, r.prompt, r.generated, r.logprobs)
        for s, r in sorted(eng.slots.items())
    }


class TestBatchedPrefillTokenIdentity:
    PROMPTS = [[5, 9, 2, 7], list(range(1, 20)), [3] * 11, [7, 7]]

    def _run(self, m, params, batched, temperature=0.0, fork=False,
             prefix=None):
        eng = ServingEngine(m, params, max_batch=8, max_len=64,
                            prefill_len=8, kv_block_size=8, seed=3,
                            temperature=temperature,
                            batched_prefill=batched)
        if prefix:
            eng.register_prefix(prefix)
        reqs = [AdmissionRequest(p) for p in self.PROMPTS]
        if fork:
            reqs.append(AdmissionRequest([9, 8, 7], n=2))
        if batched:
            eng.add_requests(reqs)
        else:
            for r in reqs:
                eng.add_request_n(r.prompt, r.n, stop=r.stop,
                                  adapter=r.adapter)
        for _ in range(3):
            eng.decode_block(4)
        return _snapshot(eng), eng

    def test_greedy_byte_equal(self, model):
        """A burst admitted through ONE dispatch chain produces the
        byte-identical chains (tokens AND logprobs) the per-slot
        admission path produces — the oracle-exactness gate for
        tentpole (a)."""
        m, params = model
        a, _ = self._run(m, params, batched=False)
        b, eb = self._run(m, params, batched=True)
        assert a == b
        assert eb.prefill_batches >= 1
        # the 19-token prompt runs 3 chunk rounds; a burst of 4 costs
        # 2 bucketed dispatches + 1 lone-row per-slot call (the final
        # round has one participant and rides the plain prefill
        # program), not 7 sequential chunk calls
        assert eb.prefill_batches == 2
        assert eb.prefill_rows == 7

    def test_sampled_and_forked_byte_equal(self, model):
        """temperature > 0: first-token sampling runs per request in
        burst order, so even the RNG stream matches the sequential
        path — and n>1 forks ride the burst too."""
        m, params = model
        a, _ = self._run(m, params, batched=False, temperature=0.8,
                         fork=True)
        b, _ = self._run(m, params, batched=True, temperature=0.8,
                         fork=True)
        assert a == b

    def test_prefix_hit_joins_burst_mid_chunk(self, model):
        """A prefix-hit request enters the chunk rounds at its boundary
        chunk (its stripe was written first) — same tokens, fewer
        prefill rows."""
        m, params = model
        prefix = list(range(1, 9))                 # one chunk
        ps = [prefix + [40, 41, 42], list(range(20, 1, -1))]
        for batched in (False, True):
            eng = ServingEngine(m, params, max_batch=4, max_len=64,
                                prefill_len=8, kv_block_size=8,
                                batched_prefill=batched)
            eng.register_prefix(prefix)
            if batched:
                eng.add_requests([AdmissionRequest(p) for p in ps])
            else:
                for p in ps:
                    eng.add_request(p)
            assert eng.prefix_hits == 1
            eng.decode_block(4)
            if batched:
                got_b = _snapshot(eng)
            else:
                got_a = _snapshot(eng)
        assert got_a == got_b

    def test_across_preempt_resume_cycle(self, model):
        """The satellite contract: batched and per-slot admission stay
        byte-equal through park → foreign traffic → resume — the
        stripe round-trip composes with the batched prefill."""
        m, params = model

        def run(batched):
            eng = ServingEngine(m, params, max_batch=2, max_len=64,
                                prefill_len=8, kv_block_size=8,
                                batched_prefill=batched)
            reqs = [AdmissionRequest([5, 9, 2, 7]),
                    AdmissionRequest([11, 13, 17])]
            if batched:
                rids = [r[0] for r in eng.add_requests(reqs)]
            else:
                rids = [eng.add_request(r.prompt) for r in reqs]
            for _ in range(4):
                eng.step()
            slot0 = next(s for s, r in eng.slots.items()
                         if r.request_id == rids[0])
            eng.preempt_slot(slot0)
            for _ in range(3):
                eng.step()
            # a second burst runs while rids[0] is parked
            if batched:
                eng.add_requests([AdmissionRequest([2, 4, 6])])
            else:
                eng.add_request([2, 4, 6])
            eng.finish_slot(next(
                s for s, r in eng.slots.items()
                if r.request_id == rids[1]
            ))
            eng.resume_request(rids[0])
            for _ in range(5):
                eng.step()
            return _snapshot(eng), eng.finished

        a, fa = run(False)
        b, fb = run(True)
        assert a == b
        assert [(f.request_id, f.tokens, f.logprobs) for f in fa] == \
               [(f.request_id, f.tokens, f.logprobs) for f in fb]

    def test_oracle_chain_through_batched_path(self, model):
        """Absolute anchor, not just A/B: the batched path reproduces
        the incremental-decode oracle."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8, batched_prefill=True)
        prompts = [[5, 9, 2, 7], list(range(1, 12))]
        rid_lists = eng.add_requests(
            [AdmissionRequest(p) for p in prompts]
        )
        for _ in range(2):
            eng.decode_block(4)
        for p, (rid,) in zip(prompts, rid_lists):
            req = next(r for r in eng.slots.values()
                       if r.request_id == rid)
            assert req.generated == greedy_reference(m, params, p, 9)

    def test_burst_all_or_nothing_on_capacity(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8, batched_prefill=True)
        with pytest.raises(RuntimeError, match="free slot"):
            eng.add_requests([AdmissionRequest([1, 2])
                              for _ in range(3)])
        assert not eng.slots
        assert eng.kv.used_blocks() == 0


class TestRadixTokenIdentity:
    """The PR 11 oracle gate: a prompt admitted through a RADIX hit
    (organically cached by an earlier completion, no registration)
    produces byte-identical chains — tokens, logprobs, RNG stream — to
    a cold engine prefilling everything, on the sequential AND the
    batched-prefill admission path."""

    # wave 1 populates the tree (shared 16-token head, two depths);
    # wave 2 shares prefixes at different depths and joins mid-tree
    HEAD = list(range(1, 17))
    WAVE1 = [HEAD + [40, 41, 42], HEAD + list(range(17, 25)) + [50]]
    WAVE2 = [HEAD + [33, 34], HEAD + list(range(17, 25)) + [60, 61],
             [9, 8, 7, 6]]

    def _run(self, m, params, radix, batched, temperature=0.0):
        eng = ServingEngine(m, params, max_batch=8, max_len=64,
                            prefill_len=8, kv_block_size=8, seed=5,
                            temperature=temperature,
                            radix_cache=radix,
                            batched_prefill=batched)

        def admit(prompts):
            reqs = [AdmissionRequest(p) for p in prompts]
            if batched:
                eng.add_requests(reqs)
            else:
                for r in reqs:
                    eng.add_request_n(r.prompt, r.n)

        admit(self.WAVE1)
        eng.decode_block(4)
        for slot in list(eng.slots):
            eng.finish_slot(slot)          # completions feed the tree
        admit(self.WAVE2)
        eng.decode_block(4)
        chains = _snapshot(eng)
        finished = [(f.request_id, f.tokens, f.logprobs)
                    for f in eng.finished]
        return chains, finished, eng

    @pytest.mark.parametrize("batched", [False, True])
    def test_radix_hits_byte_equal_to_cold(self, model, batched):
        m, params = model
        cold, cold_fin, ec = self._run(m, params, radix=False,
                                       batched=batched)
        hot, hot_fin, eh = self._run(m, params, radix=True,
                                     batched=batched)
        assert hot == cold
        assert hot_fin == cold_fin
        assert ec.prefix_hits == 0
        # wave 2's two HEAD-sharers hit the organically-learned tree
        assert eh.prefix_hits == 2
        assert eh.prefix_inserted >= 1
        assert eh.prefix_tokens_saved > 0

    @pytest.mark.parametrize("batched", [False, True])
    def test_sampled_radix_hits_keep_the_rng_stream(self, model,
                                                    batched):
        """temperature > 0: a radix hit must not shift the RNG stream —
        the sampled chains stay byte-equal to the cold engine's."""
        m, params = model
        cold, cold_fin, _ = self._run(m, params, radix=False,
                                      batched=batched, temperature=0.8)
        hot, hot_fin, eh = self._run(m, params, radix=True,
                                     batched=batched, temperature=0.8)
        assert hot == cold
        assert hot_fin == cold_fin
        assert eh.prefix_hits == 2

    def test_burst_joins_mid_tree_at_distinct_depths(self, model):
        """One burst whose requests match cached prefixes at DIFFERENT
        depths (8 and 24 tokens) plus a cold row: each joins the chunk
        rounds at its own boundary, chains oracle-exact."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=8, max_len=64,
                            prefill_len=8, kv_block_size=8,
                            radix_cache=True, batched_prefill=True)
        seeds = [[5, 9, 2, 7] + [11] * 5,
                 list(range(1, 25)) + [40]]
        eng.add_requests([AdmissionRequest(p) for p in seeds])
        for slot in list(eng.slots):
            eng.finish_slot(slot)
        burst = [[5, 9, 2, 7] + [11] * 4 + [12, 13],  # 8-token hit
                 list(range(1, 25)) + [50, 51],    # 24-token hit
                 [60, 61, 62]]                     # cold
        rid_lists = eng.add_requests([AdmissionRequest(p)
                                      for p in burst])
        assert eng.prefix_hits == 2
        eng.decode_block(4)
        for p, (rid,) in zip(burst, rid_lists):
            req = next(r for r in eng.slots.values()
                       if r.request_id == rid)
            assert req.generated == greedy_reference(m, params, p, 5)

    def test_decoded_insertion_serves_multi_turn(self, model):
        """radix_decoded: turn 2's prompt = turn 1's prompt + its
        completion + new text — the whole history is a cache hit and
        the chain stays oracle-exact."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8, kv_block_size=8,
                            radix_cache=True, radix_decoded=True)
        turn1 = list(range(1, 13))                 # 12 tokens
        rid = eng.add_request(turn1)
        eng.decode_block(8)
        req = next(r for r in eng.slots.values() if r.request_id == rid)
        answer = list(req.generated)
        slot = next(s for s, r in eng.slots.items()
                    if r.request_id == rid)
        eng.finish_slot(slot)
        # 12 + 9 generated - 1 pending = 20 resident → 16 cached
        turn2 = turn1 + answer + [30, 31]
        eng.add_request(turn2)
        assert eng.prefix_hits == 1
        assert eng.prefix_tokens_saved >= 16
        eng.decode_block(4)
        req2 = next(iter(eng.slots.values()))
        assert req2.generated == greedy_reference(m, params, turn2, 5)


class TestSingleAdapterFastPath:
    def _engine(self, m, params, cfg, fast):
        return ServingEngine(
            m, params, max_batch=4, max_len=64, prefill_len=8,
            lora_adapters=[_adapter(cfg, 1), _adapter(cfg, 2)],
            adapter_fastpath=fast, seed=2,
        )

    def test_uniform_adapter_byte_equal_and_selected(self, model):
        """All live slots on one adapter: the single-adapter variant
        dispatches (counter proves it) and the chains + logprobs are
        byte-equal to the gathered path — for a real adapter AND for
        base-only."""
        m, params = model
        cfg = m.cfg
        for aid in (1, 0):
            outs = []
            for fast in (True, False):
                eng = self._engine(m, params, cfg, fast)
                for _ in range(3):
                    eng.add_request([5, 9, 3, 7, 2], adapter=aid)
                eng.step()
                eng.decode_block(6)
                outs.append((_snapshot(eng), eng.fastpath_rounds,
                             eng.gathered_rounds))
            (a, fast_rounds, g0), (b, f0, gathered_rounds) = outs
            assert a == b
            assert fast_rounds == 2 and g0 == 0
            assert f0 == 0 and gathered_rounds == 2

    def test_mixed_adapters_fall_back_to_gather(self, model):
        m, params = model
        eng = self._engine(m, params, m.cfg, fast=True)
        for aid in (0, 1, 2):
            eng.add_request([5, 9, 3], adapter=aid)
        eng.decode_block(4)
        assert eng.fastpath_rounds == 0
        assert eng.gathered_rounds == 1
        # and when the mixed-adapter slots drain to one, the next
        # round re-selects the fast path (host-side, per round)
        for s, r in list(eng.slots.items()):
            if eng._slot_adapter_host[s] != 1:
                eng.evict_slot(s)
        eng.decode_block(4)
        assert eng.fastpath_rounds == 1


class TestOverlap:
    def test_split_decode_equals_sync(self, model):
        m, params = model

        def run(split):
            eng = ServingEngine(m, params, max_batch=4, max_len=64,
                                prefill_len=8, seed=1)
            for p in ([5, 9, 2, 7], [1, 2, 3]):
                eng.add_request(p)
            for _ in range(3):
                if split:
                    eng.decode_block_start(4)
                    eng.decode_block_finish()
                else:
                    eng.decode_block(4)
            return _snapshot(eng)

        assert run(True) == run(False)

    def test_drain_pending_guards_mutations(self, model):
        """Any mutating call with a block in flight lands the block
        first — its tokens are never lost, new state never corrupts
        the readback bookkeeping."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8)
        rid = eng.add_request([5, 9, 2, 7])
        eng.decode_block_start(4)
        eng.add_request([1, 2, 3])          # drains the pending block
        assert eng._pending_block is None
        req = next(r for r in eng.slots.values()
                   if r.request_id == rid)
        # admission token + the drained block's 4: the full greedy chain
        assert req.generated == greedy_reference(
            m, params, [5, 9, 2, 7], 5
        )

    def test_http_oracle_exact_with_overlap(self, model):
        """End to end over the real server with overlap ON (the
        default): responses stay oracle-exact."""
        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8)
        with ApiServer(eng, block_size=4, overlap=True) as srv:
            body = json.dumps({"prompt": [5, 9, 2, 7],
                               "max_tokens": 6}).encode()
            req = urllib.request.Request(
                f"{srv.url}/v1/completions", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=60) as r:
                out = json.loads(r.read())
            assert out["choices"][0]["token_ids"] == greedy_reference(
                m, params, [5, 9, 2, 7], 6
            )
            assert srv.scheduler.overlap is True

    def test_recover_clears_pending_block(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=2, max_len=64,
                            prefill_len=8)
        eng.add_request([1, 2, 3])
        eng.decode_block_start(2)
        eng.recover()
        assert eng._pending_block is None
        assert eng.decode_block_finish() == {}


class TestCompileBudget:
    def test_mixed_workload_stays_within_documented_bound(self, model):
        """The "bounded compiled-program set" claim, asserted for the
        first time: a workload mixing jittered prompt lengths, forks,
        preempt/resume, prefix hits, and BOTH adapters compiles no
        more programs per dispatch form than compile_budget()
        documents."""
        m, params = model
        cfg = m.cfg
        eng = ServingEngine(
            m, params, max_batch=4, max_len=64, prefill_len=8,
            kv_block_size=8,
            lora_adapters=[_adapter(cfg, 1), _adapter(cfg, 2)],
        )
        eng.register_prefix(list(range(1, 9)))
        # jittered burst over both adapters + base
        eng.add_requests([
            AdmissionRequest([5, 9, 2], adapter=1),
            AdmissionRequest(list(range(1, 15)), adapter=2),
            AdmissionRequest(list(range(1, 9)) + [40, 41]),  # prefix
        ])
        eng.decode_block(4)
        eng.step()
        # preempt / foreign fill / resume
        slot = next(iter(eng.slots))
        rid = eng.preempt_slot(slot)
        eng.add_request_n([9, 8, 7], 2)       # fork
        eng.decode_block(2)
        for s, r in list(eng.slots.items()):
            if len(r.prompt) == 3:
                eng.evict_slot(s)
                break
        eng.resume_request(rid)
        eng.decode_block(8)
        budget = eng.compile_budget(block_cap=8)
        got = eng.compiled_programs()
        over = {k: (got[k], budget.get(k, 0)) for k in got
                if got[k] > budget.get(k, 0)}
        assert not over, (
            f"compiled programs exceed the documented bound: {over} "
            f"(all: {got} vs budget {budget})"
        )
        # and the workload really exercised the new forms
        assert got["prefill_batch"] >= 1
        assert got["decode_block"] >= 2     # gathered + single variants

    def test_budget_math_matches_config(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=8, max_len=64,
                            prefill_len=8, kv_block_size=16)
        b = eng.compile_budget(block_cap=16)
        assert b["prefill"] == 1
        assert b["prefill_batch"] == 3      # buckets 2,4,8 (1 = plain)
        assert b["decode"] == 1             # no adapters: no variant
        # pow2 step counts (1..16 -> 5) x attend buckets
        assert b["decode_block"] == 5 * 1
        assert b["read_stripe"] == 64 // 8 + 64 // 16


class TestDistributedBurst:
    def test_follower_replays_add_requests(self, model):
        """The new dispatch form rides the op stream: a burst admitted
        on the driver replays as the identical burst on the follower
        (same bucketed dispatches, convergent state) — and the
        overlap split broadcasts at START."""
        from conftest import free_port
        from instaslice_tpu.serving.distributed import (
            DistributedEngine,
            run_follower,
        )

        m, params = model

        def mk():
            return ServingEngine(m, params, max_batch=4, max_len=64,
                                 prefill_len=8, kv_block_size=8,
                                 batched_prefill=True)

        driver_eng, follower_eng = mk(), mk()
        port = free_port()
        t = threading.Thread(
            target=run_follower,
            args=(follower_eng, "127.0.0.1", port), daemon=True,
        )
        t.start()
        deng = DistributedEngine(driver_eng, n_followers=1, port=port)
        deng.add_requests([
            AdmissionRequest([5, 9, 2, 7]),
            AdmissionRequest(list(range(1, 12))),
        ])
        deng.decode_block_start(3)
        deng.decode_block_finish()
        deng.add_requests([AdmissionRequest([1, 2, 3])])
        deng.decode_block(2)
        deng.shutdown()
        t.join(timeout=15)
        assert not t.is_alive()
        assert set(follower_eng.slots) == set(driver_eng.slots)
        for s in driver_eng.slots:
            assert (follower_eng.slots[s].generated
                    == driver_eng.slots[s].generated)
        assert (follower_eng.prefill_batches
                == driver_eng.prefill_batches >= 1)
        assert (follower_eng.kv.used_blocks()
                == driver_eng.kv.used_blocks())


class TestHotPathObservability:
    def test_stats_and_metrics_exports(self, model):
        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8, batched_prefill=True)
        metrics = ServingMetrics()
        with ApiServer(eng, block_size=4, metrics=metrics) as srv:
            body = json.dumps({"prompt": [5, 9, 2], "max_tokens": 4})
            req = urllib.request.Request(
                f"{srv.url}/v1/completions", data=body.encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=60) as r:
                assert r.status == 200
            with urllib.request.urlopen(f"{srv.url}/v1/stats",
                                        timeout=10) as r:
                stats = json.loads(r.read())
        engine = stats["engine"]
        assert engine["batched_prefill"] is True
        assert engine["adapter_fastpath"] is True
        assert "prefill_batches" in engine
        assert "compiled_programs" in engine
        assert stats["overlap"] in (True, False)
        assert "utilization_legacy" not in stats["kv"]
        body = render(metrics)
        if body:
            assert "tpuslice_serve_dispatch_gap_seconds" in body
            assert "tpuslice_serve_prefill_batch_occupancy" in body
            assert "tpuslice_serve_kv_cache_utilization_legacy" \
                not in body

    def test_scheduler_burst_admits_in_one_engine_call(self, model):
        """Submit a burst while the scheduler thread is paused at
        admission: the round admits every fitting request through ONE
        add_requests (prefill_batches grows, per-request bookkeeping
        lands for all)."""
        from instaslice_tpu.serving.scheduler import Pending, Scheduler

        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8, batched_prefill=True)
        sched = Scheduler(eng, block_size=4)
        ps = [Pending([5, 9, 2, 7], 4), Pending(list(range(1, 12)), 4),
              Pending([3, 3, 3], 4)]
        for p in ps:
            sched.submit(p)
        sched._pump()
        sched._admit()
        assert len(eng.slots) == 3
        assert eng.prefill_batches >= 1
        assert all(p.first_token_at is not None for p in ps)
        # drive to completion so the ledger closes
        deadline = time.monotonic() + 30
        while any(not p.done.is_set() for p in ps):
            sched._round()
            assert time.monotonic() < deadline
        assert all(p.results for p in ps)

    def test_burst_failure_retries_per_request(self, model):
        """A transient fault inside the all-or-nothing burst must not
        500 every co-admitted client: the scheduler retries each
        request alone and they all complete."""
        from instaslice_tpu.serving.scheduler import Pending, Scheduler

        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8, batched_prefill=True)
        calls = {"n": 0}
        real = eng.add_requests

        def flaky(reqs):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("RESOURCE_EXHAUSTED: injected")
            return real(reqs)

        eng.add_requests = flaky
        sched = Scheduler(eng, block_size=4)
        ps = [Pending([5, 9, 2, 7], 4), Pending([1, 2, 3], 4)]
        for p in ps:
            sched.submit(p)
        deadline = time.monotonic() + 30
        while any(not p.done.is_set() for p in ps):
            sched._round()
            assert time.monotonic() < deadline
        assert calls["n"] == 1          # burst tried once, then singles
        assert all(p.results and not p.error for p in ps)

    def test_chunk_budget_defers_long_non_latency_prompts(self, model):
        """Chunk scheduling: while a latency-class request is decoding,
        a long best-effort prompt (chunks > budget) waits instead of
        stalling the round — and admits once the batch drains."""
        from instaslice_tpu.serving.scheduler import Pending, Scheduler

        m, params = model
        eng = ServingEngine(m, params, max_batch=4, max_len=64,
                            prefill_len=8, batched_prefill=True)
        sched = Scheduler(
            eng, block_size=4,
            tenants="gold:1:latency:5.0,bronze:1:best-effort",
            prefill_chunk_budget=1,
        )
        pg = Pending([5, 9, 2, 7], 8, tenant="gold")
        sched.submit(pg)
        sched._pump()
        sched._admit()
        assert len(eng.slots) == 1
        # a short gold + a long bronze arrive together: gold rides the
        # burst, the 3-chunk bronze waits (budget 1, latency live)
        pg2 = Pending([1, 2, 3], 8, tenant="gold")
        pb = Pending(list(range(1, 20)), 4, tenant="bronze")
        sched.submit(pg2)
        sched.submit(pb)
        sched._pump()
        sched._admit()
        admitted = {r.request_id for r in eng.slots.values()}
        assert pg2.rid_index and not pb.rid_index, admitted
        # once nothing is admitted ahead of it, the long prompt goes
        # (first in order, batch empty -> no starvation)
        deadline = time.monotonic() + 30
        while not pb.rid_index:
            sched._round()
            assert time.monotonic() < deadline
