"""RealKubeClient over the HTTP-served fake API — the envtest analog.

Exercises the actual wire path (URL building, JSON verbs, merge-patch
content types, status subresource, error payload mapping, streaming watch
parsing with bookmarks and rv resume) that the in-process fake bypasses.
"""

import threading
import time

import pytest

from instaslice_tpu import KIND
from instaslice_tpu.kube import FakeKube
from instaslice_tpu.kube.client import (
    AlreadyExists,
    Conflict,
    NotFound,
    update_with_retry,
)
from instaslice_tpu.kube.httptest import FakeApiServer
from instaslice_tpu.kube.real import RealKubeClient


def pod(name, ns="default", **meta):
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": ns, **meta},
        "spec": {},
        "status": {},
    }


@pytest.fixture
def wired():
    store = FakeKube()
    with FakeApiServer(store) as srv:
        yield RealKubeClient(srv.url), store


class TestVerbs:
    def test_create_get_list_delete(self, wired):
        c, _ = wired
        c.create("Pod", pod("a"))
        assert c.get("Pod", "default", "a")["metadata"]["name"] == "a"
        assert len(c.list("Pod", namespace="default")) == 1
        c.delete("Pod", "default", "a")
        with pytest.raises(NotFound):
            c.get("Pod", "default", "a")

    def test_error_mapping(self, wired):
        c, _ = wired
        c.create("Pod", pod("a"))
        with pytest.raises(AlreadyExists):
            c.create("Pod", pod("a"))
        v1 = c.get("Pod", "default", "a")
        v2 = c.get("Pod", "default", "a")
        v1["spec"]["x"] = 1
        c.update("Pod", v1)
        v2["spec"]["x"] = 2
        with pytest.raises(Conflict):
            c.update("Pod", v2)

    def test_merge_patch_and_status_subresource(self, wired):
        c, _ = wired
        c.create("Node", {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": "n0", "namespace": ""},
            "status": {"capacity": {}},
        })
        c.patch("Node", "", "n0", {"metadata": {"labels": {"a": "b"}}})
        c.patch_status("Node", "", "n0", {"capacity": {"x": "1"}})
        got = c.get("Node", "", "n0")
        assert got["metadata"]["labels"] == {"a": "b"}
        assert got["status"]["capacity"] == {"x": "1"}

    def test_custom_resource_roundtrip(self, wired):
        c, _ = wired
        c.create(KIND, {
            "apiVersion": "tpu.instaslice.dev/v1alpha1",
            "kind": KIND,
            "metadata": {"name": "node-0", "namespace": "ns"},
            "spec": {"generation": "v5e"},
            "status": {},
        })
        got = c.get(KIND, "ns", "node-0")
        assert got["spec"]["generation"] == "v5e"

    def test_label_selector(self, wired):
        c, _ = wired
        c.create("Pod", pod("a", labels={"app": "x"}))
        c.create("Pod", pod("b", labels={"app": "y"}))
        assert len(c.list("Pod", label_selector={"app": "x"})) == 1

    def test_update_with_retry_through_http(self, wired):
        c, _ = wired
        c.create("Pod", pod("ctr"))
        c.patch("Pod", "default", "ctr", {"spec": {"n": 0}})

        def worker():
            for _ in range(10):
                def mut(obj):
                    obj["spec"]["n"] += 1
                    return obj
                update_with_retry(c, "Pod", "default", "ctr", mut,
                                  attempts=50)

        ths = [threading.Thread(target=worker) for _ in range(4)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert c.get("Pod", "default", "ctr")["spec"]["n"] == 40


class TestWatch:
    def test_list_watch_stream(self, wired):
        c, store = wired
        c.create("Pod", pod("a"))
        events = []
        done = threading.Event()

        def consume():
            for ev in c.watch("Pod", namespace="default", timeout=1.0):
                events.append(ev)
                if sum(1 for e, _ in events if e != "BOOKMARK") >= 3:
                    break
            done.set()

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.3)
        store.create("Pod", pod("b"))
        store.delete("Pod", "default", "b")
        assert done.wait(10), events
        names = [(e, o["metadata"].get("name")) for e, o in events
                 if e != "BOOKMARK"]
        assert ("ADDED", "a") in names
        assert ("ADDED", "b") in names
        assert ("DELETED", "b") in names

    def test_resume_after_gap(self, wired):
        c, store = wired
        c.create("Pod", pod("a"))
        burst = list(c.watch("Pod", namespace="default", timeout=0.5))
        bookmarks = [o for e, o in burst if e == "BOOKMARK"]
        assert bookmarks, burst
        rv = bookmarks[-1]["metadata"]["resourceVersion"]
        # events while no watch is established
        store.create("Pod", pod("b"))
        store.delete("Pod", "default", "b")
        resumed = []
        for ev in c.watch("Pod", namespace="default", replay=False,
                          timeout=0.5, resource_version=rv):
            resumed.append(ev)
            if sum(1 for e, _ in resumed if e != "BOOKMARK") >= 2:
                break
        names = [(e, o["metadata"].get("name")) for e, o in resumed
                 if e != "BOOKMARK"]
        assert ("ADDED", "b") in names
        assert ("DELETED", "b") in names
        assert ("ADDED", "a") not in names  # before the resume point


class TestElectionOverHttp:
    """Lease-based leader election over the real wire: timestamp
    serialization/round-tripping (RFC3339 strings, the integer
    leaseDurationSeconds field, the millisecond annotation) is exercised
    where it can actually break — VERDICT r2 found the in-process fake
    masked exactly this class of bug."""

    def test_second_elector_blocks_until_release(self):
        from instaslice_tpu.utils.election import LeaderElector

        store = FakeKube()
        with FakeApiServer(store) as srv:
            a = LeaderElector(RealKubeClient(srv.url), "ns", "lease", "A",
                              lease_seconds=0.5, retry_seconds=0.02)
            b = LeaderElector(RealKubeClient(srv.url), "ns", "lease", "B",
                              lease_seconds=0.5, retry_seconds=0.02)
            assert a.acquire()
            stop = threading.Event()
            got = {}

            def contend():
                got["b"] = b.acquire(stop)

            t = threading.Thread(target=contend, daemon=True)
            t.start()
            time.sleep(0.15)
            assert "b" not in got          # A renews; B stays blocked
            assert a._try_acquire_or_renew()
            a.release()
            t.join(5)
            assert got.get("b") is True    # released lease flips to B
            lease = store.get("Lease", "ns", "lease")
            assert lease["spec"]["holderIdentity"] == "B"
            b.release()

    def test_handover_over_http(self):
        """The round-2 red test, over the wire: A wedges, lease expires,
        B takes it, A's renew loop reports loss and steps down."""
        from instaslice_tpu.utils.election import LeaderElector

        store = FakeKube()
        with FakeApiServer(store) as srv:
            a = LeaderElector(RealKubeClient(srv.url), "ns", "lease", "A",
                              lease_seconds=0.3, retry_seconds=0.02)
            b = LeaderElector(RealKubeClient(srv.url), "ns", "lease", "B",
                              lease_seconds=0.3, retry_seconds=0.02)
            assert a.acquire()
            # the integer spec field stays schema-valid while the precise
            # sub-second duration rides the annotation
            lease = store.get("Lease", "ns", "lease")
            assert lease["spec"]["leaseDurationSeconds"] >= 1
            lost = threading.Event()
            a._stop.set()                  # wedge A's renewals
            time.sleep(0.4)
            assert b.acquire()
            b.start_renewing(on_lost=lambda: None)
            try:
                a._stop.clear()
                a.start_renewing(on_lost=lost.set)
                assert lost.wait(5.0), "old leader never noticed deposition"
                assert not a.is_leader.is_set()
                assert b.is_leader.is_set()
                lease = store.get("Lease", "ns", "lease")
                assert lease["spec"]["holderIdentity"] == "B"
            finally:
                a._stop.set()
                b.release()


class TestSimClusterOverHttp:
    """Full grant lifecycle with controller + agents + submitter each on
    their own RealKubeClient connection (separate processes in spirit)."""

    def test_grant_and_teardown_over_http(self):
        from instaslice_tpu.sim import SimCluster

        with SimCluster(n_nodes=2, generation="v5e",
                        deletion_grace_seconds=0.2,
                        transport="http") as c:
            c.submit("http-e2e", profile="v5e-2x2")
            assert c.wait_phase("http-e2e", "Running", timeout=30)
            cm = c.configmap("http-e2e")
            assert cm and "TPU_CHIPS_PER_HOST_BOUNDS" in cm["data"]
            c.delete_pod("http-e2e")
            assert c.wait_gone("http-e2e", timeout=30)
            # the CR-side erase trails the pod's finalizer removal: the
            # agent tears down, then the controller erases the record
            deadline = time.monotonic() + 30
            while c.allocations() and time.monotonic() < deadline:
                time.sleep(0.05)
            assert c.allocations() == {}
