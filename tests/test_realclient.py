"""RealKubeClient over the HTTP-served fake API — the envtest analog.

Exercises the actual wire path (URL building, JSON verbs, merge-patch
content types, status subresource, error payload mapping, streaming watch
parsing with bookmarks and rv resume) that the in-process fake bypasses.
"""

import threading
import time

import pytest

from instaslice_tpu import KIND
from instaslice_tpu.kube import FakeKube
from instaslice_tpu.kube.client import (
    AlreadyExists,
    Conflict,
    NotFound,
    update_with_retry,
)
from instaslice_tpu.kube.httptest import FakeApiServer
from instaslice_tpu.kube.real import RealKubeClient


def pod(name, ns="default", **meta):
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": ns, **meta},
        "spec": {},
        "status": {},
    }


@pytest.fixture
def wired():
    store = FakeKube()
    with FakeApiServer(store) as srv:
        yield RealKubeClient(srv.url), store


class TestVerbs:
    def test_create_get_list_delete(self, wired):
        c, _ = wired
        c.create("Pod", pod("a"))
        assert c.get("Pod", "default", "a")["metadata"]["name"] == "a"
        assert len(c.list("Pod", namespace="default")) == 1
        c.delete("Pod", "default", "a")
        with pytest.raises(NotFound):
            c.get("Pod", "default", "a")

    def test_error_mapping(self, wired):
        c, _ = wired
        c.create("Pod", pod("a"))
        with pytest.raises(AlreadyExists):
            c.create("Pod", pod("a"))
        v1 = c.get("Pod", "default", "a")
        v2 = c.get("Pod", "default", "a")
        v1["spec"]["x"] = 1
        c.update("Pod", v1)
        v2["spec"]["x"] = 2
        with pytest.raises(Conflict):
            c.update("Pod", v2)

    def test_merge_patch_and_status_subresource(self, wired):
        c, _ = wired
        c.create("Node", {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": "n0", "namespace": ""},
            "status": {"capacity": {}},
        })
        c.patch("Node", "", "n0", {"metadata": {"labels": {"a": "b"}}})
        c.patch_status("Node", "", "n0", {"capacity": {"x": "1"}})
        got = c.get("Node", "", "n0")
        assert got["metadata"]["labels"] == {"a": "b"}
        assert got["status"]["capacity"] == {"x": "1"}

    def test_custom_resource_roundtrip(self, wired):
        c, _ = wired
        c.create(KIND, {
            "apiVersion": "tpu.instaslice.dev/v1alpha1",
            "kind": KIND,
            "metadata": {"name": "node-0", "namespace": "ns"},
            "spec": {"generation": "v5e"},
            "status": {},
        })
        got = c.get(KIND, "ns", "node-0")
        assert got["spec"]["generation"] == "v5e"

    def test_label_selector(self, wired):
        c, _ = wired
        c.create("Pod", pod("a", labels={"app": "x"}))
        c.create("Pod", pod("b", labels={"app": "y"}))
        assert len(c.list("Pod", label_selector={"app": "x"})) == 1

    def test_update_with_retry_through_http(self, wired):
        c, _ = wired
        c.create("Pod", pod("ctr"))
        c.patch("Pod", "default", "ctr", {"spec": {"n": 0}})

        def worker():
            for _ in range(10):
                def mut(obj):
                    obj["spec"]["n"] += 1
                    return obj
                update_with_retry(c, "Pod", "default", "ctr", mut,
                                  attempts=50)

        ths = [threading.Thread(target=worker) for _ in range(4)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        assert c.get("Pod", "default", "ctr")["spec"]["n"] == 40


class TestWatch:
    def test_list_watch_stream(self, wired):
        c, store = wired
        c.create("Pod", pod("a"))
        events = []
        done = threading.Event()

        def consume():
            for ev in c.watch("Pod", namespace="default", timeout=1.0):
                events.append(ev)
                if sum(1 for e, _ in events if e != "BOOKMARK") >= 3:
                    break
            done.set()

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.3)
        store.create("Pod", pod("b"))
        store.delete("Pod", "default", "b")
        assert done.wait(10), events
        names = [(e, o["metadata"].get("name")) for e, o in events
                 if e != "BOOKMARK"]
        assert ("ADDED", "a") in names
        assert ("ADDED", "b") in names
        assert ("DELETED", "b") in names

    def test_resume_after_gap(self, wired):
        c, store = wired
        c.create("Pod", pod("a"))
        burst = list(c.watch("Pod", namespace="default", timeout=0.5))
        bookmarks = [o for e, o in burst if e == "BOOKMARK"]
        assert bookmarks, burst
        rv = bookmarks[-1]["metadata"]["resourceVersion"]
        # events while no watch is established
        store.create("Pod", pod("b"))
        store.delete("Pod", "default", "b")
        resumed = []
        for ev in c.watch("Pod", namespace="default", replay=False,
                          timeout=0.5, resource_version=rv):
            resumed.append(ev)
            if sum(1 for e, _ in resumed if e != "BOOKMARK") >= 2:
                break
        names = [(e, o["metadata"].get("name")) for e, o in resumed
                 if e != "BOOKMARK"]
        assert ("ADDED", "b") in names
        assert ("DELETED", "b") in names
        assert ("ADDED", "a") not in names  # before the resume point
