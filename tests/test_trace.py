"""Tracing subsystem tests: span recording, error capture, file output,
and end-to-end spans from a simulated cluster run."""

import json
import threading

import pytest

from instaslice_tpu.sim import SimCluster
from instaslice_tpu.utils.trace import (
    Tracer,
    get_tracer,
    reset_tracer,
)


class TestTracer:
    def test_span_records_duration_and_attrs(self):
        t = Tracer()
        with t.span("op", key="a"):
            pass
        [s] = t.spans()
        assert s.name == "op" and s.attrs == {"key": "a"}
        assert s.duration_ms >= 0
        assert t.counts() == {"op": 1}

    def test_error_captured_and_reraised(self):
        t = Tracer()
        with pytest.raises(ValueError):
            with t.span("bad"):
                raise ValueError("boom")
        [s] = t.spans()
        assert "ValueError: boom" in s.error

    def test_ring_bounded(self):
        t = Tracer(capacity=10)
        for i in range(25):
            with t.span("op", i=i):
                pass
        assert len(t.spans()) == 10
        assert t.counts()["op"] == 25  # counters survive eviction

    def test_file_output(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        t = Tracer(trace_file=path)
        with t.span("op", key="x"):
            pass
        t.close()
        [rec] = [json.loads(line) for line in open(path)]
        assert rec["name"] == "op" and rec["attrs"] == {"key": "x"}

    def test_thread_safety(self):
        t = Tracer(capacity=100)

        def worker():
            for _ in range(200):
                with t.span("op"):
                    pass

        ths = [threading.Thread(target=worker) for _ in range(8)]
        for th in ths:
            th.start()
        for th in ths:
            th.join()
        assert t.counts()["op"] == 1600

    def test_summary(self):
        t = Tracer()
        for _ in range(3):
            with t.span("a"):
                pass
        s = t.summary()
        assert s["a"]["count"] == 3 and s["a"]["maxMs"] >= s["a"]["p50Ms"]
        assert s["a"]["p50Ms"] <= s["a"]["p95Ms"] <= s["a"]["maxMs"]


class TestTraceStructure:
    """Parent/child spans, trace ids, and the cross-thread record path."""

    def test_nested_span_inherits_trace_and_parents(self):
        t = Tracer()
        with t.span("parent") as p:
            with t.span("child") as c:
                pass
        assert p.trace_id and p.span_id and not p.parent_id
        assert c.trace_id == p.trace_id
        assert c.parent_id == p.span_id

    def test_explicit_trace_id_reroots_out_of_ambient(self):
        t = Tracer()
        with t.span("ambient") as a:
            with t.span("other", trace_id="tid-x") as s:
                pass
        assert s.trace_id == "tid-x"
        # a cross-trace parent link would orphan the span in its own
        # trace: the ambient span must NOT become the parent
        assert s.parent_id == ""
        assert a.trace_id != "tid-x"

    def test_explicit_same_trace_parents_to_ambient(self):
        t = Tracer()
        with t.span("a", trace_id="T") as a:
            with t.span("b", trace_id="T") as b:
                pass
        assert b.parent_id == a.span_id and b.trace_id == "T"

    def test_context_does_not_leak_across_threads(self):
        t = Tracer()
        seen = {}

        def worker():
            with t.span("bg") as s:
                seen["span"] = s

        with t.span("fg") as fg:
            th = threading.Thread(target=worker)
            th.start()
            th.join()
        assert seen["span"].trace_id != fg.trace_id
        assert not seen["span"].parent_id

    def test_record_cross_thread_root_assembly(self):
        t = Tracer()
        rec = t.record("serve.request", 12.5, trace_id="T",
                       span_id="root1", outcome="ok")
        kid = t.record("serve.queue", 2.0, trace_id="T",
                       parent_id="root1")
        assert rec.trace_id == kid.trace_id == "T"
        assert kid.parent_id == "root1"
        got = t.trace("T")
        assert {s.name for s in got} == {"serve.request", "serve.queue"}
        # trace() orders by wall start: the root's backdated start
        # (now - duration) puts it before the child recorded after it
        assert got[0].name == "serve.request"

    def test_trace_query_and_slowest(self):
        t = Tracer()
        t.record("a", 5.0, trace_id="T1", span_id="s1")
        t.record("b", 50.0, trace_id="T2", span_id="s2")
        t.record("c", 1.0, trace_id="T2", span_id="s3",
                 parent_id="s2")
        assert [s.name for s in t.trace("T2")] == ["b", "c"] or \
            {s.name for s in t.trace("T2")} == {"b", "c"}
        slow = t.slowest(2, roots_only=True)
        assert [s.name for s in slow] == ["b", "a"]  # c is a child

    def test_file_output_carries_trace_fields(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        t = Tracer(trace_file=path)
        with t.span("parent", trace_id="T") as p:
            with t.span("child"):
                pass
        t.close()
        recs = [json.loads(line) for line in open(path)]
        child = next(r for r in recs if r["name"] == "child")
        assert child["traceId"] == "T"
        assert child["parentId"] == p.span_id


class TestLifecycle:
    def test_close_idempotent_and_span_after_close_safe(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        t = Tracer(trace_file=path)
        with t.span("before"):
            pass
        t.close()
        t.close()  # idempotent
        with t.span("after"):  # must not raise on the closed handle
            pass
        assert {s.name for s in t.spans()} == {"before", "after"}
        recs = [json.loads(line) for line in open(path)]
        assert [r["name"] for r in recs] == ["before"]

    def test_reset_tracer_swaps_default_and_rereads_env(
        self, tmp_path, monkeypatch
    ):
        path = str(tmp_path / "trace.jsonl")
        first = get_tracer()
        monkeypatch.setenv("TPUSLICE_TRACE_FILE", path)
        reset_tracer()
        second = get_tracer()
        assert second is not first  # env re-read on the fresh default
        with second.span("op"):
            pass
        monkeypatch.delenv("TPUSLICE_TRACE_FILE")
        reset_tracer()  # closes second's handle
        assert get_tracer() is not second
        [rec] = [json.loads(line) for line in open(path)]
        assert rec["name"] == "op"


class TestEndToEndSpans:
    def test_sim_run_produces_reconcile_and_device_spans(self):
        tracer = get_tracer()
        tracer.clear()
        with SimCluster(n_nodes=1, deletion_grace_seconds=0.2) as c:
            c.submit("demo", "v5e-1x1")
            assert c.wait_phase("demo", "Running", timeout=10)
            c.delete_pod("demo")
            assert c.wait_gone("demo", timeout=10)
        counts = tracer.counts()
        assert counts.get("controller.reconcile", 0) > 0
        assert counts.get("agent-node-0.reconcile", 0) > 0
        assert counts.get("device.reserve", 0) == 1
        assert counts.get("device.release", 0) >= 1
