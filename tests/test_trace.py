"""Tracing subsystem tests: span recording, error capture, file output,
and end-to-end spans from a simulated cluster run."""

import json
import threading

import pytest

from instaslice_tpu.sim import SimCluster
from instaslice_tpu.utils.trace import Tracer, get_tracer


class TestTracer:
    def test_span_records_duration_and_attrs(self):
        t = Tracer()
        with t.span("op", key="a"):
            pass
        [s] = t.spans()
        assert s.name == "op" and s.attrs == {"key": "a"}
        assert s.duration_ms >= 0
        assert t.counts() == {"op": 1}

    def test_error_captured_and_reraised(self):
        t = Tracer()
        with pytest.raises(ValueError):
            with t.span("bad"):
                raise ValueError("boom")
        [s] = t.spans()
        assert "ValueError: boom" in s.error

    def test_ring_bounded(self):
        t = Tracer(capacity=10)
        for i in range(25):
            with t.span("op", i=i):
                pass
        assert len(t.spans()) == 10
        assert t.counts()["op"] == 25  # counters survive eviction

    def test_file_output(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        t = Tracer(trace_file=path)
        with t.span("op", key="x"):
            pass
        t.close()
        [rec] = [json.loads(line) for line in open(path)]
        assert rec["name"] == "op" and rec["attrs"] == {"key": "x"}

    def test_thread_safety(self):
        t = Tracer(capacity=100)

        def worker():
            for _ in range(200):
                with t.span("op"):
                    pass

        ths = [threading.Thread(target=worker) for _ in range(8)]
        for th in ths:
            th.start()
        for th in ths:
            th.join()
        assert t.counts()["op"] == 1600

    def test_summary(self):
        t = Tracer()
        for _ in range(3):
            with t.span("a"):
                pass
        s = t.summary()
        assert s["a"]["count"] == 3 and s["a"]["maxMs"] >= s["a"]["p50Ms"]


class TestEndToEndSpans:
    def test_sim_run_produces_reconcile_and_device_spans(self):
        tracer = get_tracer()
        tracer.clear()
        with SimCluster(n_nodes=1, deletion_grace_seconds=0.2) as c:
            c.submit("demo", "v5e-1x1")
            assert c.wait_phase("demo", "Running", timeout=10)
            c.delete_pod("demo")
            assert c.wait_gone("demo", timeout=10)
        counts = tracer.counts()
        assert counts.get("controller.reconcile", 0) > 0
        assert counts.get("agent-node-0.reconcile", 0) > 0
        assert counts.get("device.reserve", 0) == 1
        assert counts.get("device.release", 0) >= 1
