"""Weight-only int8 quantization (models/quant.py).

Correctness bars: (1) dequantization error is per-channel bounded, (2)
the quantized model's full forward and KV-cache incremental forward
agree EXACTLY (same weights, two code paths — the serving property that
must not drift), (3) the engine serves a quantized model end to end,
including sharded over a mesh via prefix-tree sharding of (q, s).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from instaslice_tpu.models.lm import ModelConfig, TpuLM
from instaslice_tpu.models.quant import (
    QuantizedTensor,
    quantize_params,
    quantize_tensor,
)
from instaslice_tpu.serving import ServingEngine


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        dtype=jnp.float32, remat=False,
    )
    m = TpuLM(cfg)
    return m, m.init(jax.random.key(0))


class TestQuantizeTensor:
    def test_roundtrip_error_bounded(self):
        w = jax.random.normal(jax.random.key(0), (64, 32), jnp.float32)
        qt = quantize_tensor(w)
        err = jnp.abs(qt.dequantize() - w)
        # per-output-channel scale: error <= scale/2 per element
        per_chan = jnp.max(jnp.abs(w), axis=-2, keepdims=True) / 127.0
        assert bool(jnp.all(err <= per_chan * 0.5 + 1e-7))

    def test_pytree_roundtrip(self):
        qt = quantize_tensor(jnp.ones((8, 4)))
        leaves, treedef = jax.tree.flatten(qt)
        assert len(leaves) == 2
        back = jax.tree.unflatten(treedef, leaves)
        assert isinstance(back, QuantizedTensor)
        assert back.q.shape == (8, 4)

    def test_quantize_params_structure(self, model):
        _, params = model
        qp = quantize_params(params)
        assert isinstance(qp["blocks"]["wq"], QuantizedTensor)
        assert isinstance(qp["embed"], QuantizedTensor)
        assert qp["blocks"]["wq"].q.dtype == jnp.int8
        # norms stay full precision
        assert isinstance(qp["blocks"]["ln1"]["scale"], jax.Array)
        assert qp["blocks"]["ln1"]["scale"].dtype == jnp.float32
        # idempotent
        qp2 = quantize_params(qp)
        assert qp2["blocks"]["wq"] is qp["blocks"]["wq"]

    def test_scale_axes(self, model):
        _, params = model
        qp = quantize_params(params)
        L, D, K = params["blocks"]["wq"].shape
        assert qp["blocks"]["wq"].s.shape == (L, 1, K)   # per out channel
        V, D = params["embed"].shape
        assert qp["embed"].s.shape == (V, 1)             # per vocab row


class TestQuantizedForward:
    def test_logits_close_to_full_precision(self, model):
        m, params = model
        toks = jax.random.randint(jax.random.key(1), (2, 16), 0, 64)
        full = m.apply(params, toks)
        quant = m.apply(quantize_params(params), toks)
        rel = float(
            jnp.linalg.norm(quant - full) / jnp.linalg.norm(full)
        )
        assert rel < 0.05, rel

    def test_cache_path_matches_full_forward_exactly(self, model):
        """The serving invariant: with the SAME quantized weights, the
        incremental KV-cache forward equals the full forward."""
        m, params = model
        qp = quantize_params(params)
        toks = jax.random.randint(jax.random.key(2), (2, 12), 0, 64)
        full = m.apply(qp, toks)
        cache = m.init_cache(2, 32)
        lengths = jnp.zeros(2, jnp.int32)
        lg, cache = m.apply_with_cache(qp, toks[:, :5], cache, lengths)
        assert float(jnp.abs(lg - full[:, :5]).max()) < 1e-4
        lengths = lengths + 5
        for t in range(5, 12):
            lg, cache = m.apply_with_cache(
                qp, toks[:, t:t + 1], cache, lengths
            )
            assert float(jnp.abs(lg[:, 0] - full[:, t]).max()) < 1e-4
            lengths = lengths + 1


class TestQuantizedServing:
    def _greedy_ref(self, m, qp, prompt, n):
        toks = list(prompt)
        out = []
        for _ in range(n):
            logits = m.apply(qp, jnp.asarray(toks, jnp.int32)[None])
            t = int(jnp.argmax(logits[0, -1]))
            out.append(t)
            toks.append(t)
        return out

    def test_engine_serves_quantized(self, model):
        m, params = model
        qp = quantize_params(params)
        eng = ServingEngine(m, qp, max_batch=2, max_len=64, prefill_len=8)
        prompt = [5, 9, 2, 7]
        rid = eng.add_request(prompt)
        got = eng.decode_block(6)[rid]
        assert got == self._greedy_ref(m, qp, prompt, 7)[1:7]

    def test_engine_tp_quantized(self, model):
        from jax.sharding import Mesh

        m, params = model
        qp = quantize_params(params)
        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("model",))
        eng = ServingEngine(m, qp, max_batch=2, max_len=64,
                            prefill_len=8, mesh=mesh)
        # (q, s) really sharded by the prefix-tree specs
        wq = eng.params["blocks"]["wq"]
        shard = next(iter(wq.q.addressable_shards))
        assert shard.data.shape[-1] == wq.q.shape[-1] // 2
        prompt = [5, 9, 2, 7]
        rid = eng.add_request(prompt)
        got = eng.decode_block(6)[rid]
        assert got == self._greedy_ref(m, qp, prompt, 7)[1:7]


class TestKvCacheQuant:
    """int8 KV cache: the other half of quantized serving — at high
    concurrency the cache, not the weights, dominates decode HBM
    traffic."""

    def test_quant_cache_close_to_full_forward(self, model):
        m, params = model
        toks = jax.random.randint(jax.random.key(2), (2, 12), 0, 64)
        full = m.apply(params, toks)
        cache = m.init_cache(2, 32, quant=True)
        assert cache["k"].dtype == jnp.int8
        assert cache["k_s"].shape == (2, 2, 2, 32)    # (L, B, H, S)
        lg, cache = m.apply_with_cache(
            params, toks, cache, jnp.zeros(2, jnp.int32)
        )
        rel = float(jnp.linalg.norm(lg - full) / jnp.linalg.norm(full))
        assert rel < 0.02, rel

    def test_incremental_decode_consistent(self, model):
        """Chunked prefill + per-token decode over the quantized cache
        tracks the full forward at quantization tolerance."""
        m, params = model
        toks = jax.random.randint(jax.random.key(3), (2, 12), 0, 64)
        full = m.apply(params, toks)
        cache = m.init_cache(2, 32, quant=True)
        lengths = jnp.zeros(2, jnp.int32)
        lg, cache = m.apply_with_cache(params, toks[:, :5], cache, lengths)
        lengths = lengths + 5
        for t in range(5, 12):
            lg, cache = m.apply_with_cache(
                params, toks[:, t:t + 1], cache, lengths
            )
            rel = float(
                jnp.linalg.norm(lg[:, 0] - full[:, t])
                / jnp.linalg.norm(full[:, t])
            )
            assert rel < 0.02, (t, rel)
            lengths = lengths + 1

    def test_engine_kv_quant_deterministic_and_in_range(self, model):
        """The int8-KV engine is deterministic and produces valid
        tokens. (No exact-match against the fp-cache engine: KV quant is
        deliberately lossy — near-tied logits may argmax differently, so
        equality would be seed-luck, not a property.)"""
        m, params = model
        prompt = [5, 9, 2, 7]
        chains = []
        for _ in range(2):
            eng = ServingEngine(m, params, max_batch=2, max_len=64,
                                prefill_len=8, kv_quant=True)
            rid = eng.add_request(prompt)
            chains.append(eng.decode_block(6)[rid])
        assert chains[0] == chains[1]
        assert len(chains[0]) == 6
        assert all(0 <= t < 64 for t in chains[0])

    def test_engine_tp_weights_and_kv_quant(self, model):
        from jax.sharding import Mesh

        m, params = model
        qp = quantize_params(params)
        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("model",))
        eng = ServingEngine(m, qp, max_batch=2, max_len=64,
                            prefill_len=8, kv_quant=True, mesh=mesh)
        rid = eng.add_request([5, 9, 2, 7])
        out = eng.decode_block(6)[rid]
        assert len(out) == 6 and all(0 <= t < 64 for t in out)
