"""Token dataset + host-sharded loader + tpuslice-train
(``models/data.py``, ``cli/train_main.py``).

The loader's contract is determinism: batches are a pure function of
the step number, so checkpoint resume needs no loader state and an
interrupted run continues bit-identically (same bar as
``tests/test_checkpoint.py``).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from instaslice_tpu.models.data import (
    HostShardedTokens,
    Prefetcher,
    TokenDataset,
    write_token_file,
)

pytestmark = pytest.mark.slow


@pytest.fixture(params=[".npy", ".u16", ".u32"])
def token_file(request, tmp_path):
    path = str(tmp_path / f"toks{request.param}")
    rng = np.random.default_rng(7)
    write_token_file(path, rng.integers(0, 250, size=4000))
    return path


class TestTokenDataset:
    def test_rows_and_determinism(self, token_file):
        ds = TokenDataset(token_file, seq_len=16, seed=1)
        assert ds.n_rows == 4000 // 17
        b1 = ds.batch(3, 4)
        b2 = TokenDataset(token_file, seq_len=16, seed=1).batch(3, 4)
        np.testing.assert_array_equal(b1, b2)
        assert b1.shape == (4, 17) and b1.dtype == np.int32

    def test_epoch_reshuffles_but_covers(self, token_file):
        ds = TokenDataset(token_file, seq_len=16, seed=1)
        n = ds.n_rows
        epoch0 = [ds.row_at(i)[0] for i in range(n)]
        epoch1 = [ds.row_at(n + i)[0] for i in range(n)]
        # same multiset of rows (full coverage), different order
        assert sorted(epoch0) == sorted(epoch1)
        assert epoch0 != epoch1

    def test_host_offsets_tile_the_global_batch(self, token_file):
        ds = TokenDataset(token_file, seq_len=16, seed=1)
        whole = ds.batch(5, 8)
        parts = [ds.batch(5, 4, offset=o, global_batch=8)
                 for o in (0, 4)]
        np.testing.assert_array_equal(np.concatenate(parts), whole)

    def test_bad_inputs(self, tmp_path, token_file):
        with pytest.raises(ValueError, match="suffix"):
            TokenDataset(str(tmp_path / "x.bin"), 8)
        with pytest.raises(ValueError, match="row"):
            path = str(tmp_path / "tiny.u16")
            write_token_file(path, np.arange(4))
            TokenDataset(path, seq_len=16)
        ds = TokenDataset(token_file, seq_len=16)
        with pytest.raises(ValueError, match="exceeds"):
            ds.batch(0, 8, offset=4, global_batch=8)


class TestHostShardedTokens:
    def test_sharded_batch_matches_dataset(self, token_file):
        from jax.sharding import Mesh

        ds = TokenDataset(token_file, seq_len=16, seed=1)
        mesh = Mesh(
            np.array(jax.devices()[:2]).reshape(2, 1, 1),
            ("data", "seq", "model"),
        )
        loader = HostShardedTokens(ds, mesh, global_batch=4)
        arr = loader.batch_for_step(2)
        assert arr.shape == (4, 17)
        np.testing.assert_array_equal(np.asarray(arr), ds.batch(2, 4))
        # sharded over the data axis
        assert arr.sharding.spec[0] == "data"


class TestPrefetcher:
    def test_sequential_and_close(self):
        pf = Prefetcher(lambda s: s * 10, start_step=3)
        got = [next(pf) for _ in range(4)]
        assert got == [(3, 30), (4, 40), (5, 50), (6, 60)]
        pf.close()

    def test_error_propagates(self):
        def boom(s):
            raise RuntimeError("disk gone")

        pf = Prefetcher(boom, start_step=0)
        with pytest.raises(RuntimeError, match="disk gone"):
            next(pf)
        pf.close()


class TestTrainCli:
    # conftest pins 8 virtual CPU devices; default mesh puts all of
    # them on the data axis, so the global batch must divide by 8
    ARGS = ["--seq-len", "24", "--global-batch", "8", "--d-model", "32",
            "--n-heads", "4", "--n-kv-heads", "2", "--n-layers", "2",
            "--d-ff", "64", "--vocab-size", "128", "--log-every", "100"]

    def _run(self, capsys, extra):
        from instaslice_tpu.cli.train_main import main

        assert main(extra + self.ARGS) == 0
        out = capsys.readouterr().out.strip().splitlines()[-1]
        return json.loads(out)

    def test_synthetic_end_to_end(self, capsys):
        out = self._run(capsys, ["--synthetic", "20000", "--steps", "4"])
        assert out["steps"] == 4
        assert out["value"] > 0
        assert np.isfinite(out["final_loss"])

    def test_checkpoint_resume_is_bit_identical(self, capsys, tmp_path):
        """3 steps + save, resume for 3 more == 6 uninterrupted steps.
        Batches derive from the step counter, so the interrupted stream
        must replay exactly."""
        data = str(tmp_path / "corpus.u16")
        write_token_file(
            data, np.random.default_rng(5).integers(0, 120, size=20000)
        )
        ck_a = str(tmp_path / "ck_interrupted")
        self._run(capsys, ["--data", data, "--steps", "3",
                           "--checkpoint", ck_a, "--save-every", "100"])
        resumed = self._run(
            capsys, ["--data", data, "--steps", "6",
                     "--checkpoint", ck_a, "--save-every", "100"]
        )
        assert resumed["steps"] == 6
        straight = self._run(capsys, ["--data", data, "--steps", "6"])
        assert resumed["final_loss"] == pytest.approx(
            straight["final_loss"], abs=1e-6
        )

    def test_tp_mesh(self, capsys):
        out = self._run(
            capsys,
            ["--synthetic", "20000", "--steps", "2", "--tp", "2"],
        )
        assert out["mesh"]["model"] == 2
        assert np.isfinite(out["final_loss"])
