"""Process-level e2e: real binaries against the real-HTTP fake API.

The reference's e2e tier builds images, loads them into KinD, deploys,
and polls the controller pod to Running
(``/root/reference/test/e2e/e2e_test.go:32-122``). No container runtime
or cluster exists in this environment, so this tier runs the SAME
programs the images ENTRYPOINT (``tpuslice-controller`` /
``tpuslice-agent`` console scripts, via their argparse mains) as real OS
processes wired to a :class:`FakeApiServer` through a real kubeconfig
file — covering process bootstrap, kubeconfig parsing, leader election,
probe + metrics servers, boot discovery, and the full grant lifecycle
across process boundaries. Only kubelet/etcd realism is missing.
"""

import json
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request
from pathlib import Path

import pytest
from conftest import free_port, wait_until

from instaslice_tpu import GATE_NAME, POD_RESOURCE_PREFIX
from instaslice_tpu.controller.gates import PROFILE_ANNOTATION
from instaslice_tpu.kube import FakeKube, NotFound
from instaslice_tpu.kube.httptest import FakeApiServer

NS = "instaslice-tpu-system"


def _http_ok(url: str) -> bool:
    try:
        with urllib.request.urlopen(url, timeout=1) as r:
            return r.status == 200
    except Exception:
        return False


def _http_body(url: str) -> str:
    with urllib.request.urlopen(url, timeout=2) as r:
        return r.read().decode()


def _kubeconfig(tmpdir: str, url: str) -> str:
    cfg = {
        "apiVersion": "v1",
        "kind": "Config",
        "current-context": "e2e",
        "contexts": [
            {"name": "e2e", "context": {"cluster": "fake", "user": "u"}}
        ],
        "clusters": [{"name": "fake", "cluster": {"server": url}}],
        "users": [{"name": "u", "user": {"token": "e2e-token"}}],
    }
    path = Path(tmpdir) / "kubeconfig.yaml"
    path.write_text(json.dumps(cfg))  # yaml parses json
    return str(path)


def _gated_pod(name: str, profile: str) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": "default",
            "uid": f"uid-{name}",
            "annotations": {PROFILE_ANNOTATION: profile},
            "finalizers": ["tpu.instaslice.dev/accelerator"],
        },
        "spec": {
            "schedulingGates": [{"name": GATE_NAME}],
            "containers": [{
                "name": "main",
                "image": "jax-smoke",
                "resources": {
                    "limits": {f"{POD_RESOURCE_PREFIX}{name}": "1"}
                },
                "envFrom": [{"configMapRef": {"name": name}}],
            }],
        },
        "status": {"phase": "Pending"},
    }


class _MiniScheduler(threading.Thread):
    """The kube-scheduler role: bind ungated Pending pods to the node
    advertising their per-pod extended resource and mark them Running
    (container start is out of scope, as in the sim tier)."""

    def __init__(self, store: FakeKube):
        super().__init__(daemon=True)
        self.store = store
        self.stop_flag = threading.Event()
        self.last_error: str = ""

    def run(self):
        while not self.stop_flag.wait(0.05):
            try:
                for pod in self.store.list("Pod"):
                    md = pod["metadata"]
                    if (
                        md.get("deletionTimestamp")
                        or pod.get("spec", {}).get("schedulingGates")
                        or pod.get("status", {}).get("phase") != "Pending"
                    ):
                        continue
                    wanted = None
                    for c in pod["spec"].get("containers", []):
                        for k in (c.get("resources", {})
                                  .get("limits", {})):
                            if k.startswith(POD_RESOURCE_PREFIX):
                                wanted = k
                    node = None
                    for n in self.store.list("Node"):
                        cap = n.get("status", {}).get("capacity", {}) or {}
                        if wanted and cap.get(wanted) == "1":
                            node = n["metadata"]["name"]
                    if node:
                        self.store.patch(
                            "Pod", md["namespace"], md["name"],
                            {"spec": {"nodeName": node},
                             "status": {"phase": "Running"}},
                        )
            except Exception as e:  # surfaced via diag on test timeout
                self.last_error = f"{type(e).__name__}: {e}"


@pytest.fixture(params=["fake", "cloudtpu"])
def wired_processes(request):
    """FakeApiServer + controller & agent as real subprocesses, their
    stdout/stderr captured to log files (PIPE would deadlock on chatty
    children and lose diagnostics). Parameterized over the device
    backend: the cloudtpu leg starts a queued-resources mock API in the
    test process and points the agent subprocess at it via
    ``TPUSLICE_CLOUDTPU_API`` — the whole OS-process stack driving the
    cloud wire path."""
    backend = request.param
    mock = None
    if backend == "cloudtpu":
        from instaslice_tpu.device.cloudtpu_mock import CloudTpuMockServer

        mock = CloudTpuMockServer(provision_polls=1).start()
    store = FakeKube()
    store.create("Node", {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": "node-0", "namespace": ""},
        "status": {"capacity": {}, "allocatable": {}},
    })
    sched = _MiniScheduler(store)
    with FakeApiServer(store) as srv, \
            tempfile.TemporaryDirectory(prefix="e2e-") as tmp:
        kc = _kubeconfig(tmp, srv.url)
        c_probe, a_probe = free_port(), free_port()
        c_metrics = free_port()
        env = {
            "PATH": "/usr/bin:/bin",
            "PYTHONPATH": str(Path(__file__).resolve().parent.parent),
            "NODE_NAME": "node-0",
        }
        if mock is not None:
            env["TPUSLICE_CLOUDTPU_API"] = mock.url
        logs = {}
        procs = []
        for name, cmd in (
            ("controller",
             [sys.executable, "-m", "instaslice_tpu.cli.controller_main",
              "--kubeconfig", kc, "--namespace", NS,
              "--deletion-grace-seconds", "0.5", "--leader-elect",
              "--metrics-bind-address", f"127.0.0.1:{c_metrics}",
              "--health-probe-bind-address", f"127.0.0.1:{c_probe}"]),
            ("agent",
             [sys.executable, "-m", "instaslice_tpu.cli.agent_main",
              "--kubeconfig", kc, "--namespace", NS,
              "--node-name", "node-0", "--backend", backend,
              "--metrics-bind-address", "127.0.0.1:0",
              "--health-probe-bind-address", f"127.0.0.1:{a_probe}"]),
        ):
            logs[name] = open(Path(tmp) / f"{name}.log", "w+")
            procs.append(subprocess.Popen(
                cmd, env=env,
                stdout=logs[name], stderr=subprocess.STDOUT,
            ))

        def diag() -> str:
            parts = [f"scheduler error: {sched.last_error or 'none'}"]
            for pname, f in logs.items():
                f.flush()
                tail = Path(f.name).read_text()[-800:]
                parts.append(f"--- {pname} log tail ---\n{tail}")
            return "\n".join(parts)

        sched.start()
        try:
            yield store, c_probe, a_probe, c_metrics, procs, diag
        finally:
            sched.stop_flag.set()
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
            for f in logs.values():
                f.close()
            if mock is not None:
                mock.stop()


class TestProcessE2E:
    def test_grant_lifecycle_across_processes(self, wired_processes):
        store, c_probe, a_probe, c_metrics, procs, diag = wired_processes
        ctl, agent = procs

        # reference-style readiness poll (e2e_test.go:84-118 polls the
        # controller pod to Running; here: its readyz endpoint)
        wait_until(lambda: _http_ok(f"http://127.0.0.1:{c_probe}/readyz"),
                   30, "controller ready", diag)
        wait_until(lambda: _http_ok(f"http://127.0.0.1:{a_probe}/readyz"),
                   30, "agent ready", diag)

        # leader election really ran over the wire
        lease = store.get("Lease", NS, "tpuslice-controller-leader")
        assert lease["spec"]["holderIdentity"]

        # agent boot discovery created the per-node CR
        wait_until(lambda: _exists(store, "TpuSlice", NS, "node-0"),
                   15, "boot discovery CR", diag)

        # grant: gated pod → allocated → realized → ungated → Running
        store.create("Pod", _gated_pod("e2e-pod", "v5e-2x2"))
        wait_until(
            lambda: store.get("Pod", "default", "e2e-pod")
            .get("status", {}).get("phase") == "Running",
            30, "pod Running", diag,
        )
        cm = store.get("ConfigMap", "default", "e2e-pod")
        assert "TPU_VISIBLE_CHIPS" in cm["data"]

        # the metrics endpoint serves the north-star metric family
        body = _http_body(f"http://127.0.0.1:{c_metrics}/metrics")
        assert "tpuslice" in body

        # teardown: delete → finalizer released → allocation erased
        store.delete("Pod", "default", "e2e-pod")
        wait_until(lambda: not _exists(store, "Pod", "default", "e2e-pod"),
                   30, "pod gone", diag)
        wait_until(
            lambda: not store.get("TpuSlice", NS, "node-0")["spec"]
            .get("allocations"),
            30, "allocation erased", diag,
        )

        # clean shutdown with exit code 0 (SIGTERM handlers)
        for p in (ctl, agent):
            p.terminate()
        assert ctl.wait(timeout=15) == 0, diag()
        assert agent.wait(timeout=15) == 0, diag()


def _exists(store, kind, ns, name) -> bool:
    try:
        store.get(kind, ns, name)
        return True
    except NotFound:
        return False
