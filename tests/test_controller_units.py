"""Unit tests for controller gate/profile logic and agent handoff env —
the pieces with reference-bug history (SURVEY.md §7 quirks)."""

import pytest

from instaslice_tpu import GATE_NAME, LEGACY_GATE_NAME
from instaslice_tpu.agent.handoff import slice_env
from instaslice_tpu.api import AllocationDetails, PodRef
from instaslice_tpu.controller.gates import (
    extract_profile,
    is_pod_gated,
    pod_group,
)
from instaslice_tpu.topology import (
    FirstFitPolicy,
    NodeGrid,
    Occupancy,
    TorusGroup,
    parse_profile_name,
)
from instaslice_tpu.topology.grid import get_generation


def gated_pod(**kw):
    p = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": "p", "namespace": "default", "uid": "u1"},
        "spec": {"schedulingGates": [{"name": GATE_NAME}], "containers": []},
        "status": {"phase": "Pending"},
    }
    p.update(kw)
    return p


class TestGateDetection:
    def test_gated(self):
        assert is_pod_gated(gated_pod())

    def test_no_status_at_all(self):
        """Reference crashes on pods with empty Conditions
        (instaslice_controller.go:389); we must not."""
        p = gated_pod()
        del p["status"]
        assert is_pod_gated(p)

    def test_other_gate(self):
        p = gated_pod()
        p["spec"]["schedulingGates"] = [{"name": "someone-else"}]
        assert not is_pod_gated(p)

    def test_running_not_gated(self):
        p = gated_pod()
        p["status"]["phase"] = "Running"
        assert not is_pod_gated(p)

    def test_deleting_not_gated(self):
        p = gated_pod()
        p["metadata"]["deletionTimestamp"] = 123.0
        assert not is_pod_gated(p)

    def test_legacy_reference_gate_admitted(self):
        """Migration interop: a pod gated by a reference-era webhook
        carries the original (misspelled) org.instaslice gate and must
        still be picked up — otherwise a migration strands it Pending."""
        p = gated_pod()
        p["spec"]["schedulingGates"] = [{"name": LEGACY_GATE_NAME}]
        assert is_pod_gated(p)


class TestProfileExtraction:
    def test_annotation(self):
        p = gated_pod()
        p["metadata"]["annotations"] = {
            "tpu.instaslice.dev/profile": "v5e-2x2"
        }
        assert extract_profile(p).name == "v5e-2x2"

    def test_resource_limit(self):
        p = gated_pod()
        p["spec"]["containers"] = [
            {"resources": {"limits": {"google.com/tpu-v5e-2x1": "1"}}}
        ]
        assert extract_profile(p).name == "v5e-2x1"

    def test_3d_resource_limit(self):
        p = gated_pod()
        p["spec"]["containers"] = [
            {"resources": {"limits": {"google.com/tpu-v4-2x2x2": "1"}}}
        ]
        assert extract_profile(p).name == "v4-2x2x2"

    def test_no_tpu(self):
        p = gated_pod()
        p["spec"]["containers"] = [
            {"resources": {"limits": {"cpu": "1"}}}
        ]
        assert extract_profile(p) is None

    def test_malformed_raises(self):
        p = gated_pod()
        p["metadata"]["annotations"] = {
            "tpu.instaslice.dev/profile": "v5e-3x3"
        }
        with pytest.raises(ValueError):
            extract_profile(p)

    def test_group_parsing(self):
        p = gated_pod()
        assert pod_group(p) == ("", 1)
        p["metadata"]["annotations"] = {
            "tpu.instaslice.dev/group": "job",
            "tpu.instaslice.dev/group-size": "2",
        }
        assert pod_group(p) == ("job", 2)
        p["metadata"]["annotations"]["tpu.instaslice.dev/group-size"] = "x"
        with pytest.raises(ValueError):
            pod_group(p)


class TestSliceEnv:
    def make_alloc(self, profile="v5e-2x2", n_pods=1):
        gen = get_generation("v5e")
        if profile == "v5e-4x4":
            g = TorusGroup(
                "g", gen, (4, 4, 1),
                {"node-0": NodeGrid(gen, host_offset=(0, 0, 0)),
                 "node-1": NodeGrid(gen, host_offset=(2, 0, 0))},
            )
        else:
            g = TorusGroup.single_host("node-0", gen)
        pl = FirstFitPolicy().choose(
            g, parse_profile_name(profile), Occupancy(g)
        )
        pods = [PodRef(f"u{i}", f"w-{i}", "default", i) for i in range(n_pods)]
        return AllocationDetails.from_placement(pl, pods, alloc_id="a1")

    def test_single_host_env(self):
        alloc = self.make_alloc()
        env = slice_env(alloc, alloc.pods[0], "node-0", "v5e")
        assert env["TPU_WORKER_ID"] == "0"
        assert env["TPU_HOST_BOUNDS"] == "1,1,1"
        assert env["TPU_CHIPS_PER_HOST_BOUNDS"] == "2,2,1"
        assert env["TPU_VISIBLE_CHIPS"] == "0,1,2,3"
        assert env["TPU_ACCELERATOR_TYPE"] == "v5e-2x2"

    def test_multi_host_env(self):
        alloc = self.make_alloc("v5e-4x4", n_pods=2)
        env0 = slice_env(alloc, alloc.pods[0], "node-0", "v5e")
        env1 = slice_env(alloc, alloc.pods[1], "node-1", "v5e")
        assert env0["TPU_HOST_BOUNDS"] == env1["TPU_HOST_BOUNDS"] == "2,1,1"
        assert env0["TPU_WORKER_HOSTNAMES"] == "w-0,w-1"
        assert env0["TPU_VISIBLE_CHIPS"] == env1["TPU_VISIBLE_CHIPS"] == \
            "0,1,2,3,4,5,6,7"

    def test_unknown_worker_raises(self):
        alloc = self.make_alloc()
        ghost = PodRef("ux", "ghost", "default", 7)
        with pytest.raises(ValueError, match="no part serving worker"):
            slice_env(alloc, ghost, "node-0", "v5e")


class TestDiscovery:
    def test_boot_creates_cr_and_adopts_dangling(self):
        from instaslice_tpu.agent.discovery import discover_node
        from instaslice_tpu.device import FakeTpuBackend
        from instaslice_tpu.kube import FakeKube

        kube = FakeKube()
        backend = FakeTpuBackend(generation="v5e")
        backend.seed_dangling("zombie", [6, 7])
        ts = discover_node(kube, backend, "node-0", "sys")
        assert ts.status.processed
        assert len(ts.spec.chips) == 8
        assert any(p["name"] == "v5e-2x2" for p in ts.spec.profiles)
        assert "zombie" in ts.spec.prepared
        assert ts.spec.prepared["zombie"].pod_uuid == ""
        # second boot: idempotent, no duplicate adoption
        ts2 = discover_node(kube, backend, "node-0", "sys")
        assert list(ts2.spec.prepared) == ["zombie"]

    def test_dangling_blocks_placement_e2e(self):
        """An adopted zombie slice's chips must be unplaceable."""
        import time
        from instaslice_tpu.sim import SimCluster

        c = SimCluster(n_nodes=1, deletion_grace_seconds=0.2)
        c.backends["node-0"].seed_dangling("zombie", list(range(8)))
        c.start()
        try:
            c.submit("p", "v5e-1x1")
            time.sleep(0.6)
            assert c.pod_phase("p") == "Pending"
        finally:
            c.stop()
