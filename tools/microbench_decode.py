"""On-chip decomposition of the 7B decode step (the b32 gap).

The 2026-07-31 capture left a question PERF.md could only hypothesize
about: serving_7b steps cost 16.9 / 23.1 / 35.7 ms at batch 8/16/32 —
~0.78 ms per row beyond the weight floor. Candidate binders: the int8
KV cache's dequantize (XLA materializes dot operands, so reading int8
KV costs int8-read + compute-dtype write + re-read), the per-row
vmapped cache writes (scatters), or plain VPU attention work.

This tool separates them by measuring the REAL engine's block-decode
throughput across {kv_quant on/off} × {attend_len 256/1024} × batch:

- kv_quant OFF removes the dequant (bf16 KV feeds the dot directly) at
  2× the cache bytes: if int8-KV's dequant materialization dominates,
  bf16 KV WINS despite more bytes (5 effective byte-passes vs 2);
- attend_len scaling isolates the KV-read term from per-row costs that
  do not touch the cache depth (writes, rope, sampling).

OOM is a RESULT (bf16 KV at batch 32 × 1024 may not fit next to 6.8 GB
of weights): reported, not raised. Claims the host TPU flock.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def measure(model, params, batch: int, kv_quant: bool,
            attend_len: int, n_steps: int = 64,
            max_len: int = 1024) -> dict:
    from instaslice_tpu.bench_tpu import _is_oom, _readback_rtt
    from instaslice_tpu.serving import ServingEngine

    out = {"batch": batch, "kv_quant": kv_quant,
           "attend_len": attend_len}
    eng = None
    try:
        eng = ServingEngine(model, params, max_batch=batch,
                            max_len=max_len, prefill_len=128,
                            kv_quant=kv_quant)
        for _ in range(batch):
            eng.add_request([1, 2, 3])
        # warm to a depth such that BOTH the compile block and the
        # timed block sit inside the target attend bucket (block
        # length and bucket are compile keys — timing a first-call
        # block would bill its compile as step time)
        warm = max(1, attend_len - 3 - 2 * n_steps - 8)
        eng.decode_block(warm)
        eng.decode_block(n_steps)          # compile + warm this program
        rtt = _readback_rtt()
        t0 = time.perf_counter()
        got = eng.decode_block(n_steps)
        dt = time.perf_counter() - t0 - rtt
        toks = sum(len(v) for v in got.values())
        out["step_ms"] = round(dt / n_steps * 1000, 2)
        out["tokens_per_sec"] = round(toks / dt, 1)
        out["rtt_ms"] = round(rtt * 1000, 1)
    except Exception as e:  # noqa: BLE001 - OOM is a result here
        if not _is_oom(e):
            raise
        out["result"] = "OOM"
    finally:
        del eng                       # free the KV cache before the next
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, nargs="+", default=[8, 32])
    ap.add_argument("--steps", type=int, default=64)
    ap.add_argument("--max-len", type=int, default=1024)
    ap.add_argument("--attends", type=int, nargs="+",
                    default=[256, 1024])
    ap.add_argument("--kv-quant-only", action="store_true")
    args = ap.parse_args(argv)

    from instaslice_tpu.utils.tpulock import TpuBusyError, TpuClaim

    try:
        claim = TpuClaim().acquire(timeout=10)
    except TpuBusyError as e:
        print(f"TPU busy: {e}", file=sys.stderr)
        return 1
    try:
        import jax

        if jax.default_backend() != "tpu":
            print("not on TPU; refusing", file=sys.stderr)
            return 1
        import jax.numpy as jnp

        from instaslice_tpu.bench_tpu import _init_quantized_params
        from instaslice_tpu.models.lm import ModelConfig, TpuLM

        cfg = ModelConfig(
            vocab_size=32000, d_model=4096, n_heads=32, n_kv_heads=8,
            n_layers=32, d_ff=20480, max_seq_len=2048,
            dtype=jnp.bfloat16, remat=False,
        )
        params = _init_quantized_params(cfg)
        model = TpuLM(cfg)
        for batch in args.batches:
            for kv_quant in ((True,) if args.kv_quant_only
                             else (True, False)):
                for attend in args.attends:
                    r = measure(model, params, batch, kv_quant,
                                attend, n_steps=args.steps,
                                max_len=args.max_len)
                    print(json.dumps(r), flush=True)
        return 0
    finally:
        claim.release()


if __name__ == "__main__":
    sys.exit(main())
