#!/usr/bin/env python
"""Deploy-plane validation without a cluster or a container runtime.

The reference's e2e builds images, loads them into KinD, and `make
deploy`s the kustomize tree (/root/reference/test/e2e/e2e_test.go:84-118).
Neither docker nor kind exists in this environment, so this is the
dry-run equivalent, split into the same two halves:

1. **Manifest apply** — render `config/default` (a small kustomize
   emulator: resource recursion + strategic-merge patches keyed by
   containers[].name) and APPLY every document through RealKubeClient →
   FakeApiServer over real HTTP: URL building, JSON bodies, create
   semantics. Then cross-checks `kubectl` would do server-side:
   selector↔template labels, serviceAccount references, Service
   targetPort names, namespace consistency.
2. **Image build plan** — every Dockerfile COPY source exists, every
   ENTRYPOINT binary is a console script declared in pyproject.toml,
   every image referenced by a workload is produced by `make
   docker-build`.

Run via `make test-deploy`; exits non-zero on the first failure class.
"""

from __future__ import annotations

import copy
import glob
import os
import re
import sys

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

FAILURES: list = []


def check(ok: bool, what: str) -> None:
    print(("PASS " if ok else "FAIL ") + what)
    if not ok:
        FAILURES.append(what)


# --------------------------------------------------------------- kustomize

def render(dir_path: str) -> list:
    """Emulate `kustomize build`: recurse resources, apply patches."""
    kfile = os.path.join(dir_path, "kustomization.yaml")
    with open(kfile) as f:
        k = yaml.safe_load(f)
    docs: list = []
    for res in k.get("resources", []):
        target = os.path.normpath(os.path.join(dir_path, res))
        if os.path.isdir(target):
            docs.extend(render(target))
        else:
            with open(target) as f:
                docs.extend(d for d in yaml.safe_load_all(f) if d)
    for patch in k.get("patches", []):
        ppath = os.path.normpath(os.path.join(dir_path, patch["path"]))
        with open(ppath) as f:
            for pdoc in yaml.safe_load_all(f):
                if pdoc:
                    docs = [_apply_patch(d, pdoc) for d in docs]
    return docs


def _apply_patch(doc: dict, patch: dict) -> dict:
    if (
        doc.get("kind") != patch.get("kind")
        or doc.get("metadata", {}).get("name")
        != patch.get("metadata", {}).get("name")
    ):
        return doc
    return _strategic_merge(copy.deepcopy(doc), patch)


def _strategic_merge(base, patch):
    """Enough of strategic-merge for this tree: dicts merge recursively;
    `containers` lists merge by item name; other lists replace."""
    if isinstance(base, dict) and isinstance(patch, dict):
        out = dict(base)
        for key, pval in patch.items():
            if key in ("apiVersion", "kind"):
                continue
            if key == "containers" and isinstance(pval, list):
                merged = {c.get("name"): c for c in base.get(key, [])}
                for pc in pval:
                    name = pc.get("name")
                    merged[name] = _strategic_merge(
                        merged.get(name, {}), pc
                    )
                out[key] = list(merged.values())
            elif key in base:
                out[key] = _strategic_merge(base[key], pval)
            else:
                out[key] = pval
        return out
    return copy.deepcopy(patch)


# ----------------------------------------------------------------- checks

def iter_pod_specs(doc):
    kind = doc.get("kind")
    if kind in ("Deployment", "DaemonSet", "StatefulSet", "Job"):
        yield doc["spec"]["template"]
    elif kind == "Pod":
        yield doc


def console_scripts() -> set:
    with open(os.path.join(REPO, "pyproject.toml")) as f:
        text = f.read()
    m = re.search(r"\[project\.scripts\](.*?)(\n\[|\Z)", text, re.S)
    return {
        line.split("=")[0].strip()
        for line in (m.group(1) if m else "").splitlines()
        if "=" in line
    }


def check_apply(docs: list) -> None:
    """Apply every rendered doc over real HTTP against the fake server."""
    from instaslice_tpu.kube import FakeKube
    from instaslice_tpu.kube.httptest import FakeApiServer
    from instaslice_tpu.kube.real import RealKubeClient

    store = FakeKube()
    with FakeApiServer(store) as srv:
        client = RealKubeClient(srv.url)
        for doc in docs:
            kind = doc.get("kind", "?")
            name = doc.get("metadata", {}).get("name", "?")
            try:
                client.create(kind, doc)
                check(True, f"apply {kind}/{name}")
            except Exception as e:  # slicelint: disable=broad-except
                # not swallowed: check() tallies + prints the failure
                check(False, f"apply {kind}/{name}: {e}")


def check_cross_references(docs: list) -> None:
    by_kind: dict = {}
    for d in docs:
        by_kind.setdefault(d.get("kind"), []).append(d)

    for doc in docs:
        kind = doc.get("kind")
        name = doc.get("metadata", {}).get("name")
        if kind in ("Deployment", "DaemonSet"):
            sel = doc["spec"]["selector"]["matchLabels"]
            labels = doc["spec"]["template"]["metadata"]["labels"]
            check(
                all(labels.get(k) == v for k, v in sel.items()),
                f"{kind}/{name}: selector matches template labels",
            )
            sa = doc["spec"]["template"]["spec"].get("serviceAccountName")
            if sa:
                sas = {s["metadata"]["name"]
                       for s in by_kind.get("ServiceAccount", [])}
                check(sa in sas, f"{kind}/{name}: serviceAccount {sa} exists")
        if kind == "Service":
            # every named targetPort must exist on a selected workload
            sel = doc["spec"].get("selector", {})
            port_names = set()
            for d in docs:
                for tpl in iter_pod_specs(d):
                    tlabels = tpl.get("metadata", {}).get("labels", {})
                    if sel and all(tlabels.get(k) == v
                                   for k, v in sel.items()):
                        for c in tpl["spec"].get("containers", []):
                            for p in c.get("ports", []) or []:
                                if p.get("name"):
                                    port_names.add(p["name"])
            for p in doc["spec"].get("ports", []):
                tp = p.get("targetPort")
                if isinstance(tp, str):
                    check(
                        tp in port_names,
                        f"Service/{name}: targetPort {tp!r} resolves "
                        f"(have {sorted(port_names)})",
                    )
        if kind in ("ClusterRoleBinding", "RoleBinding"):
            ref = doc["roleRef"]["name"]
            role_kind = doc["roleRef"]["kind"]
            names = {r["metadata"]["name"]
                     for r in by_kind.get(role_kind, [])}
            check(ref in names, f"{kind}/{name}: roleRef {ref} exists")

    # the auth-proxy patch must have landed: no workload may expose the
    # plain metrics bind on all interfaces
    for doc in by_kind.get("Deployment", []):
        for tpl in iter_pod_specs(doc):
            for c in tpl["spec"].get("containers", []):
                if c.get("name") == "manager":
                    check(
                        any("--metrics-bind-address=127.0.0.1" in a
                            for a in c.get("args", [])),
                        "manager metrics bound to localhost "
                        "(kube-rbac-proxy fronting)",
                    )


def check_build_plane(docs: list) -> None:
    scripts = console_scripts()
    check(bool(scripts), f"console scripts declared: {sorted(scripts)}")

    with open(os.path.join(REPO, "Makefile")) as f:
        mk = f.read()
    # expand `VAR ?= default` style Makefile vars used in image tags
    mkvars = dict(re.findall(r"^(\w+)\s*\?=\s*(\S+)", mk, re.M))
    images_built = set()
    for m in re.finditer(r"-t\s+(\S+)\s", mk):
        img = re.sub(
            r"\$\((\w+)\)", lambda v: mkvars.get(v.group(1), ""),
            m.group(1),
        )
        images_built.add(img.split(":")[0])

    for df in sorted(glob.glob(os.path.join(REPO, "Dockerfile.*"))):
        base = os.path.basename(df)
        with open(df) as f:
            lines = f.read().splitlines()
        for line in lines:
            m = re.match(r"^\s*COPY\s+(?!--from)(\S+)\s+\S+", line)
            if m:
                src = m.group(1)
                check(
                    os.path.exists(os.path.join(REPO, src)),
                    f"{base}: COPY source {src} exists",
                )
            m = re.match(r'^\s*ENTRYPOINT\s+\["([^"]+)"', line)
            if m:
                check(
                    m.group(1) in scripts,
                    f"{base}: entrypoint {m.group(1)} is a console script",
                )

    for doc in docs:
        for tpl in iter_pod_specs(doc):
            for c in tpl["spec"].get("containers", []):
                img = c.get("image", "").split(":")[0]
                if img.startswith("instaslice-tpu"):
                    df = f"Dockerfile.{img.split('-')[-1]}"
                    check(
                        os.path.exists(os.path.join(REPO, df)),
                        f"image {img} has {df}",
                    )
                    check(
                        img in images_built,
                        f"image {img} is built by `make docker-build` "
                        f"(builds {sorted(images_built)})",
                    )
                cmd = (c.get("command") or [None])[0]
                if cmd and cmd.startswith("tpuslice"):
                    check(
                        cmd in scripts,
                        f"{doc['metadata']['name']}: command {cmd} "
                        "is a console script",
                    )


def main() -> int:
    docs = render(os.path.join(REPO, "config", "default"))
    check(len(docs) >= 10, f"rendered {len(docs)} manifests")
    check_apply(docs)
    check_cross_references(docs)
    check_build_plane(docs)
    # samples must also apply (they're what users kubectl apply first)
    sample_docs = []
    for path in sorted(glob.glob(os.path.join(REPO, "samples", "*.yaml"))):
        with open(path) as f:
            sample_docs.extend(d for d in yaml.safe_load_all(f) if d)
    check_apply([d for d in sample_docs
                 if d.get("kind") in ("Pod", "ConfigMap", "Service")])
    print(
        f"\n{'FAILED' if FAILURES else 'OK'}: "
        f"{len(FAILURES)} failures"
    )
    return 1 if FAILURES else 0


if __name__ == "__main__":
    sys.exit(main())
