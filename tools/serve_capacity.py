"""One-command serving capacity curve: boot ``tpuslice-serve``, sweep
concurrency with ``tpuslice-loadgen``, emit the PERF.md table.

The on-chip half of the serving story (VERDICT r3 #8): the engine-side
bench (``bench_tpu``) measures the decode loop; THIS measures what a
slice's users experience — queueing + HTTP + scheduling — as a
throughput/latency curve over concurrency, against a live server on
whatever accelerator the host has (the server takes the host-wide TPU
claim itself; run it only when ``python bench.py`` is not running).

Usage::

    python tools/serve_capacity.py                    # 871M bf16, b32
    python tools/serve_capacity.py --quantize         # int8 W + KV
    python tools/serve_capacity.py --sweep 1,2,4,8,16,32
    python tools/serve_capacity.py --markdown >> docs/PERF.md

Prints one JSON line per concurrency level and, with ``--markdown``,
the ready-to-paste table.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request


def _wait_healthy(url: str, proc: subprocess.Popen,
                  timeout: float) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"server exited rc={proc.returncode} before healthy"
            )
        try:
            urllib.request.urlopen(f"{url}/healthz", timeout=2)
            return
        except (OSError, http.client.HTTPException):
            # server not accepting yet, or it crashed mid-reply
            # (BadStatusLine/IncompleteRead are not OSError): deadline-
            # bounded startup poll of a child process
            time.sleep(1.0)  # slicelint: disable=sleep-in-loop
    raise RuntimeError(f"server not healthy within {timeout:.0f}s")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="serve_capacity")
    ap.add_argument("--sweep", default="1,2,4,8,16,32")
    ap.add_argument("--requests-per-level", type=int, default=48)
    ap.add_argument("--prompt-len", type=int, default=64)
    ap.add_argument("--max-tokens", type=int, default=64)
    ap.add_argument("--port", type=int, default=18400)
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--d-model", type=int, default=2048)
    ap.add_argument("--n-heads", type=int, default=16)
    ap.add_argument("--n-kv-heads", type=int, default=0)
    ap.add_argument("--n-layers", type=int, default=16)
    ap.add_argument("--d-ff", type=int, default=8192)
    ap.add_argument("--quantize", action="store_true")
    ap.add_argument("--boot-timeout", type=float, default=600.0,
                    help="first compiles on a cold chip are slow")
    ap.add_argument("--markdown", action="store_true",
                    help="also print the PERF.md table")
    args = ap.parse_args(argv)

    levels = [int(x) for x in args.sweep.split(",") if x.strip()]
    url = f"http://127.0.0.1:{args.port}"
    serve_cmd = [
        sys.executable, "-m", "instaslice_tpu.serving.api_server",
        "--host", "127.0.0.1", "--port", str(args.port),
        "--max-batch", str(args.max_batch),
        "--d-model", str(args.d_model),
        "--n-heads", str(args.n_heads),
        "--n-kv-heads", str(args.n_kv_heads),
        "--n-layers", str(args.n_layers),
        "--d-ff", str(args.d_ff),
    ]
    if args.quantize:
        serve_cmd.append("--quantize")
    log_path = os.environ.get("TPUSLICE_CAPACITY_LOG",
                              "/tmp/serve_capacity.log")
    rows = []
    with open(log_path, "ab") as log:
        srv = subprocess.Popen(serve_cmd, stdout=log, stderr=log)
        try:
            _wait_healthy(url, srv, args.boot_timeout)
            from instaslice_tpu.serving import loadgen

            # warmup: compile prefill + decode before the first timed
            # level, or its p95 records the 20-40s compile, not serving
            loadgen.run(url, requests=2, concurrency=1,
                        prompt_len=args.prompt_len,
                        max_tokens=args.max_tokens,
                        vocab=32000, stream=True, timeout=600.0)
            for c in levels:
                # scale request count with concurrency so high levels
                # still see steady state, capped for wall time
                n = max(args.requests_per_level, 4 * c)
                row = loadgen.run(
                    url, requests=n, concurrency=c,
                    prompt_len=args.prompt_len,
                    max_tokens=args.max_tokens,
                    vocab=32000, stream=True, timeout=300.0,
                )
                rows.append(row)
                # in --markdown mode raw rows go to stderr: the
                # documented `--markdown >> docs/PERF.md` must capture
                # ONLY the table
                print(json.dumps(row), flush=True,
                      file=sys.stderr if args.markdown else sys.stdout)
        finally:
            # SIGINT, not SIGKILL: the server is a TPU claimant and a
            # hard kill leaves the stale remote claim that wedges the
            # tunnel (docs/PERF.md)
            srv.send_signal(signal.SIGINT)
            try:
                srv.wait(timeout=30)
            except subprocess.TimeoutExpired:
                srv.kill()
                srv.wait()
    if args.markdown and rows:
        q = "int8 W+KV" if args.quantize else "bf16"
        print(f"\n| concurrency | client tok/s | p50 (s) | p95 (s) | "
              f"TTFT p50 (s) | errors |  <!-- {args.d_model}d x "
              f"{args.n_layers}L {q}, {args.prompt_len}p+"
              f"{args.max_tokens}g -->")
        print("|---|---|---|---|---|---|")
        for r in rows:
            print(f"| {r['concurrency']} | {r['client_tokens_per_sec']} "
                  f"| {r['value']} | {r['p95_latency']} "
                  f"| {r.get('ttft_p50', '-')} | {r['errors']} |")
    return 0


if __name__ == "__main__":
    sys.exit(main())
