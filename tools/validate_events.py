"""Validate a ``TPUSLICE_EVENT_FILE`` JSONL dump (and optionally
produce one first).

``python tools/validate_events.py EVENTS.jsonl`` checks the structural
invariants every consumer of the flight-recorder format (``tpuslice
events`` / ``describe pod``, the debug endpoints, docs/OBSERVABILITY.md
tooling) relies on:

- every line parses as a JSON object with ``seq``, ``ts``,
  ``component``, and ``reason``;
- ``seq`` values are unique (the ring is the ordering authority; file
  line order may interleave across threads);
- every ``reason`` comes from the ``api/constants.py`` catalog;
- allocation transition chains are complete and ordered: for each
  ``alloc/<id>``, the status sequence (split into epochs at each fresh
  ``creating`` — a controller retry tears down and re-places under a
  new trace) follows the legal transition graph, every transition
  event carries a non-empty ``traceId``, one trace id spans the whole
  epoch, and any granted epoch shows creating → created → ungated in
  order.

Transition events are emitted at the ``set_status`` decision point; a
CR write can still lose an optimistic-concurrency race, so chaos-grade
callers pass ``strict=False`` to :func:`check_chains`, which forgives a
"phantom" edge that is legal from an *earlier* status of the same epoch
(a stale read whose write never landed). The ``make events-check``
drive is quiet enough to validate strictly.

``--drive`` first GENERATES the file: a SimCluster grants one clean pod
and one pod whose first chip reservation fails (injected device error →
``failed`` epoch → retry → grant), renders ``tpuslice describe pod``
for both against the live fake API (asserting the merged
event/audit/trace timeline), then runs a short loadgen burst plus a
drain/undrain cycle through a live ApiServer. This is the
``make events-check`` gate, next to ``trace-check`` in ``make test``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # run as tools/validate_events.py
    sys.path.insert(0, REPO)

from instaslice_tpu.api.constants import (  # noqa: E402
    EVENT_REASONS,
    REASON_ADMITTED,
    REASON_APISERVER_UNREACHABLE,
    REASON_CRASH_RECOVERED,
    REASON_DEGRADED_ENTERED,
    REASON_DEGRADED_EXITED,
    REASON_DRAIN_BEGIN,
    REASON_DRAIN_END,
    REASON_WRITE_FENCED,
    TRANSITION_REASONS,
)

#: reason → allocation status value (the inverse of TRANSITION_REASONS)
TRANSITION_STATUS = {r: s for s, r in TRANSITION_REASONS.items()}


def _legal_edges() -> Dict[str, set]:
    from instaslice_tpu.api.types import _TRANSITIONS

    return {
        old.value: {new.value for new in news}
        for old, news in _TRANSITIONS.items()
    }


def check_chains(events: List[dict], strict: bool = True) -> List[str]:
    """Transition-chain invariants over parsed event dicts (the journal
    ring's ``to_dict`` shape == the JSONL shape). Reusable by the chaos
    tier against the in-memory ring."""
    errors: List[str] = []
    legal = _legal_edges()
    by_alloc: Dict[str, List[dict]] = {}
    for rec in events:
        ref = rec.get("objectRef", "")
        if rec.get("reason") in TRANSITION_STATUS and \
                ref.startswith("alloc/"):
            by_alloc.setdefault(ref, []).append(rec)

    for ref, recs in sorted(by_alloc.items()):
        recs.sort(key=lambda r: r.get("seq", 0))
        # epochs: each fresh `creating` after prior history is a
        # re-placement (retry) — chains restart there
        epochs: List[List[dict]] = []
        cur: List[dict] = []
        for rec in recs:
            if TRANSITION_STATUS[rec["reason"]] == "creating" and cur:
                epochs.append(cur)
                cur = []
            cur.append(rec)
        if cur:
            epochs.append(cur)
        for n, epoch in enumerate(epochs):
            statuses = [TRANSITION_STATUS[r["reason"]] for r in epoch]
            if statuses[0] != "creating":
                errors.append(
                    f"{ref} epoch {n}: chain starts at "
                    f"{statuses[0]!r}, not 'creating'"
                )
                continue
            seen = {statuses[0]}
            prev = statuses[0]
            for st in statuses[1:]:
                if st == prev:  # idempotent re-emit (conflict retry)
                    continue
                if st in legal[prev]:
                    seen.add(st)
                    prev = st
                    continue
                # stale-read phantom: legal from an EARLIER status of
                # this epoch — tolerated only in non-strict mode. The
                # phantom may be EITHER side of the illegal pair (a
                # failed that lost to a concurrent promote reads as
                # creating→failed→created→ungated), so re-anchor the
                # chain on the tolerated status rather than keeping
                # the possibly-phantom prev.
                if not strict and any(st in legal[s] for s in seen):
                    seen.add(st)
                    prev = st
                    continue
                errors.append(
                    f"{ref} epoch {n}: illegal transition "
                    f"{prev!r} -> {st!r} (chain {statuses})"
                )
                prev = st
                seen.add(st)
            tids = {r.get("traceId", "") for r in epoch}
            if "" in tids:
                errors.append(
                    f"{ref} epoch {n}: transition event without a "
                    "traceId — the grant trace link is broken"
                )
            elif len(tids) > 1:
                errors.append(
                    f"{ref} epoch {n}: {len(tids)} trace ids in one "
                    f"epoch ({sorted(tids)})"
                )
            if "ungated" in statuses:
                order = [statuses.index(s)
                         for s in ("creating", "created", "ungated")
                         if s in statuses]
                if len(order) < 3 or order != sorted(order):
                    errors.append(
                        f"{ref} epoch {n}: granted without a complete "
                        f"creating->created->ungated chain ({statuses})"
                    )
    return errors


def check_epochs(events: List[dict]) -> List[str]:
    """Crash-chaos chain invariants (``--epochs``, docs/RECOVERY.md):
    transition chains must stay legal *across restart epochs*.

    Chains are split on two boundaries: the ``attempt_epoch`` attr the
    transition choke point stamps (the precise placement-epoch fence)
    and ``CrashRecovered`` markers (component restarts and recovery
    re-grants emit them; they split an alloc's chain for events
    predating the attr). Within a group the walk is lenient the way
    ``check_chains(strict=False)`` is — crash journals are full of
    catch-up re-writes and stale-read phantoms — but two invariants
    are strict:

    - each restart epoch's transitions must be reachable (legal from
      the previous status or some earlier status of the same group);
    - no grant chain may be ABANDONED: every superseded attempt epoch
      must end ``deleted``, and the final attempt epoch must end
      ``ungated`` (granted) or ``deleted`` (cleanly torn down).
    """
    errors: List[str] = []
    legal = _legal_edges()
    global_marks = sorted(
        r.get("seq", 0) for r in events
        if r.get("reason") == REASON_CRASH_RECOVERED
        and str(r.get("objectRef", "")).startswith("component/")
    )
    alloc_marks: Dict[str, List[int]] = {}
    by_alloc: Dict[str, List[dict]] = {}
    for rec in events:
        ref = str(rec.get("objectRef", ""))
        if not ref.startswith("alloc/"):
            continue
        if rec.get("reason") == REASON_CRASH_RECOVERED:
            alloc_marks.setdefault(ref, []).append(rec.get("seq", 0))
        elif rec.get("reason") in TRANSITION_STATUS:
            by_alloc.setdefault(ref, []).append(rec)

    for ref, recs in sorted(by_alloc.items()):
        recs.sort(key=lambda r: r.get("seq", 0))
        marks = sorted(set(global_marks) | set(alloc_marks.get(ref, [])))

        def group_of(rec) -> int:
            attr = (rec.get("attrs") or {}).get("attempt_epoch")
            if attr is not None:
                return int(attr)
            # pre-attr events: the count of markers before this seq is
            # its restart-epoch ordinal (kept distinct from real
            # attempt epochs by the negative sign)
            seq = rec.get("seq", 0)
            return -sum(1 for m in marks if m < seq) - 1

        groups: Dict[int, List[dict]] = {}
        for rec in recs:
            groups.setdefault(group_of(rec), []).append(rec)
        # order groups chronologically by their first seq
        ordered = sorted(
            groups.items(), key=lambda kv: kv[1][0].get("seq", 0)
        )
        final_statuses: List[str] = []
        for gid, grecs in ordered:
            seen: set = set()
            prev: Optional[str] = None
            for rec in grecs:
                st = TRANSITION_STATUS[rec["reason"]]
                if prev is None or st == "creating":
                    # a fresh creating restarts the sub-chain (retry
                    # re-placement inside one attempt epoch)
                    seen = {st}
                    prev = st
                    continue
                if st == prev:
                    continue
                if st in legal[prev] or any(
                    st in legal[s] for s in seen
                ):
                    seen.add(st)
                    prev = st
                    continue
                errors.append(
                    f"{ref} attempt-epoch group {gid}: illegal "
                    f"transition {prev!r} -> {st!r}"
                )
                seen.add(st)
                prev = st
            final_statuses.append(prev or "")
        for st in final_statuses[:-1]:
            if st != "deleted":
                errors.append(
                    f"{ref}: superseded attempt epoch abandoned in "
                    f"{st!r} (must end 'deleted')"
                )
        if final_statuses and final_statuses[-1] not in (
            "ungated", "deleted"
        ):
            errors.append(
                f"{ref}: grant chain abandoned in "
                f"{final_statuses[-1]!r} without a terminal reason"
            )
    return errors


def check_nemesis(events: List[dict]) -> List[str]:
    """Partition-chaos invariants (``--nemesis``, docs/RECOVERY.md):
    replay the journal across partition epochs and prove split-brain
    safety end to end. Every nemesis scenario ends in a timed heal, so
    the journal under inspection must describe a CONVERGED run:

    - degraded-mode pairing: every ``DegradedModeEntered`` is preceded
      by an ``ApiServerUnreachable`` from the same component and
      followed by a heal-side ``DegradedModeExited`` (the agent's
      durable-truth reconcile on heal emits it);
    - fence attribution: every ``WriteFenced`` event names the
      component whose stale-epoch write was refused;
    - no grant double-placed: at any journal instant at most ONE
      allocation per pod (linked through the ``Admitted`` event's
      trace id) is in the granted state — a second simultaneous grant
      means a deposed leader's write slipped the epoch fence;
    - no slice leaks: every allocation chain ends granted (still
      serving) or ``deleted`` (torn down) — an alloc abandoned
      mid-flight past heal is a leaked chip reservation.
    """
    errors: List[str] = []

    # degraded-mode pairing (per component, in seq order)
    open_degraded: Dict[str, int] = {}
    unreachable: Dict[str, int] = {}
    for rec in events:
        comp = str(rec.get("component", ""))
        reason = rec.get("reason")
        if reason == REASON_APISERVER_UNREACHABLE:
            unreachable[comp] = unreachable.get(comp, 0) + 1
        elif reason == REASON_DEGRADED_ENTERED:
            if comp not in unreachable:
                errors.append(
                    f"{comp}: DegradedModeEntered without a preceding "
                    "ApiServerUnreachable — the trigger is unjournaled"
                )
            open_degraded[comp] = open_degraded.get(comp, 0) + 1
        elif reason == REASON_DEGRADED_EXITED:
            if not open_degraded.get(comp):
                errors.append(
                    f"{comp}: DegradedModeExited without a matching "
                    "DegradedModeEntered"
                )
            else:
                open_degraded[comp] -= 1
        elif reason == REASON_WRITE_FENCED and not comp:
            errors.append(
                f"seq {rec.get('seq')}: WriteFenced without a "
                "component — the deposed writer is unattributable"
            )
    for comp, n in sorted(open_degraded.items()):
        if n:
            errors.append(
                f"{comp}: {n} DegradedModeEntered never paired with a "
                "heal-side DegradedModeExited — the scenario must end "
                "healed and reconciled"
            )

    # double-place + leak sweep across partition epochs
    trace_pod: Dict[str, str] = {}
    for rec in events:
        if rec.get("reason") == REASON_ADMITTED and rec.get("traceId"):
            trace_pod[rec["traceId"]] = str(rec.get("objectRef", ""))
    granted: Dict[str, str] = {}    # owner pod -> alloc ref holding the grant
    status: Dict[str, str] = {}     # alloc ref -> last status
    for rec in events:
        st = TRANSITION_STATUS.get(rec.get("reason", ""))
        ref = str(rec.get("objectRef", ""))
        if st is None or not ref.startswith("alloc/"):
            continue
        tid = rec.get("traceId", "")
        # an alloc without an Admitted link degrades to per-trace
        # (then per-alloc) grouping — still catches same-grant splits
        pod = trace_pod.get(tid, tid or ref)
        status[ref] = st
        if st == "ungated":
            cur = granted.get(pod)
            if cur is not None and cur != ref:
                errors.append(
                    f"{pod}: double-placed — {cur} and {ref} granted "
                    "simultaneously (a stale-epoch write slipped the "
                    "lease fence)"
                )
            granted[pod] = ref
        elif st in ("deleted", "failed", "creating") \
                and granted.get(pod) == ref:
            # a grant holder leaving the granted state releases the
            # slot (creating = a fresh retry epoch for the same id)
            del granted[pod]
    for ref in sorted(status):
        if status[ref] not in ("deleted", "ungated"):
            errors.append(
                f"{ref}: slice leak — chain ends {status[ref]!r} "
                "after heal (neither granted nor torn down)"
            )
    return errors


def validate(path: str, strict: bool = True,
             epochs: bool = False) -> dict:
    """Structural + chain validation of one JSONL file. ``errors`` must
    stay empty for the file to pass. ``epochs=True`` swaps the chain
    check for :func:`check_epochs` (crash-chaos journals: chains legal
    across restart epochs, no abandoned grants)."""
    errors: List[str] = []
    events: List[dict] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                errors.append(f"line {lineno}: unparseable JSONL: {e}")
                continue
            if not isinstance(rec, dict):
                errors.append(f"line {lineno}: not a JSON object")
                continue
            missing = [k for k in ("seq", "ts", "component", "reason")
                       if k not in rec]
            if missing:
                errors.append(f"line {lineno}: missing {missing}")
                continue
            if rec["reason"] not in EVENT_REASONS:
                errors.append(
                    f"line {lineno}: unknown reason {rec['reason']!r} "
                    "— reasons live in instaslice_tpu/api/constants.py"
                )
            events.append(rec)

    seqs = [r["seq"] for r in events]
    if len(seqs) != len(set(seqs)):
        dupes = sorted({s for s in seqs if seqs.count(s) > 1})
        errors.append(f"duplicate seq values: {dupes[:10]}")
    events.sort(key=lambda r: r["seq"])
    if epochs:
        errors.extend(check_epochs(events))
    else:
        errors.extend(check_chains(events, strict=strict))

    reasons: Dict[str, int] = {}
    for rec in events:
        reasons[rec["reason"]] = reasons.get(rec["reason"], 0) + 1
    return {
        "file": path,
        "events": len(events),
        "reasons": reasons,
        "errors": errors,
        "_events": events,
    }


def check_drive_expectations(report: dict, granted_text: str,
                             faulted_text: str) -> None:
    """--drive extra: the file must PROVE the flight-recorder contract,
    not just parse. Appends to ``report['errors']``."""
    events = report["_events"]
    reasons = report["reasons"]

    # a granted chain whose Admitted event shares the grant's trace id
    granted = False
    for rec in events:
        if rec.get("reason") == TRANSITION_REASONS["ungated"]:
            tid = rec.get("traceId", "")
            if tid and any(
                r.get("reason") == REASON_ADMITTED
                and r.get("traceId") == tid
                for r in events
            ):
                granted = True
                break
    if not granted:
        report["errors"].append(
            "no granted chain links an Admitted event to its "
            "SliceUngated transition by trace id"
        )
    if "failed" not in {
        TRANSITION_STATUS.get(r.get("reason", "")) for r in events
    }:
        report["errors"].append(
            "no failed epoch in the drive — the injected device fault "
            "never surfaced as a SliceFailed transition"
        )
    for want in (REASON_DRAIN_BEGIN, REASON_DRAIN_END):
        if not reasons.get(want):
            report["errors"].append(
                f"serving plane emitted no {want} event"
            )
    for label, text, needles in (
        ("granted", granted_text,
         ("SliceUngated", "controller.allocate", "Admitted")),
        ("faulted", faulted_text,
         ("SliceFailed", "SliceRealizeFailed")),
    ):
        if label == "faulted":
            ok = any(n in text for n in needles)
        else:
            ok = all(n in text for n in needles)
        if not ok:
            report["errors"].append(
                f"describe-pod rendering for the {label} pod is missing "
                f"expected entries {needles}; got:\n{text}"
            )


def drive(path: str) -> tuple:
    """Produce ``path``: one clean grant, one faulted-then-retried
    grant, describe-pod renderings for both, then a serving burst with
    a drain/undrain cycle — all recorded to the file. Returns the two
    describe renderings."""
    if os.path.exists(path):
        os.unlink(path)
    trace_path = tempfile.mktemp(prefix="tpuslice-events-check-trace.",
                                 suffix=".jsonl")
    os.environ["TPUSLICE_EVENT_FILE"] = path
    os.environ["TPUSLICE_TRACE_FILE"] = trace_path
    from instaslice_tpu.obs.journal import reset_journal
    from instaslice_tpu.utils.trace import reset_tracer

    reset_journal()  # re-read the env: events now stream to `path`
    reset_tracer()
    granted_text = faulted_text = ""
    try:
        from instaslice_tpu.cli.tpuslicectl import (
            describe_pod,
            render_describe,
        )
        from instaslice_tpu.sim import SimCluster

        with SimCluster(n_nodes=1, deletion_grace_seconds=0.2) as c:
            # the faulted pod: its first chip reservation raises, so the
            # allocation runs creating → failed → deleted, then the
            # controller re-places it and the retry epoch grants
            c.backends["node-0"].inject_failures("reserve", 1)
            c.submit("events-faulted", "v5e-1x1")
            assert c.wait_phase("events-faulted", "Running", timeout=30), \
                "faulted sim pod never recovered to Running"
            c.submit("events-granted", "v5e-1x1")
            assert c.wait_phase("events-granted", "Running", timeout=30), \
                "sim pod never reached Running"
            granted_text = render_describe(describe_pod(
                c.kube, "events-granted", events_path=path,
                trace_path=trace_path,
            ))
            faulted_text = render_describe(describe_pod(
                c.kube, "events-faulted", events_path=path,
                trace_path=trace_path,
            ))
            c.delete_pod("events-granted")
            c.delete_pod("events-faulted")
            assert c.wait_gone("events-granted", timeout=30)
            assert c.wait_gone("events-faulted", timeout=30)

        import jax
        import jax.numpy as jnp

        from instaslice_tpu.models.lm import ModelConfig, TpuLM
        from instaslice_tpu.serving import ServingEngine, loadgen
        from instaslice_tpu.serving.api_server import ApiServer

        cfg = ModelConfig(vocab_size=64, d_model=32, n_heads=2,
                          n_layers=2, d_ff=64, dtype=jnp.float32,
                          remat=False)
        model = TpuLM(cfg)
        eng = ServingEngine(model, model.init(jax.random.key(0)),
                            max_batch=4, max_len=64, prefill_len=8)
        with ApiServer(eng, block_size=4) as srv:
            report = loadgen.run(srv.url, requests=6, concurrency=2,
                                 prompt_len=4, max_tokens=4, vocab=64,
                                 stream=False, timeout=60)
            assert report["outcomes"]["hung"] == 0, report
            assert report["ok"] > 0, report
            srv.drain(0.5)
            assert srv.wait_drained(10), "drain never quiesced"
            srv.undrain()
    finally:
        del os.environ["TPUSLICE_EVENT_FILE"]
        del os.environ["TPUSLICE_TRACE_FILE"]
        reset_journal()  # close the file handle (and detach the env)
        reset_tracer()
        if os.path.exists(trace_path):
            os.unlink(trace_path)
    return granted_text, faulted_text


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="validate_events")
    ap.add_argument("file", help="event JSONL path")
    ap.add_argument("--drive", action="store_true",
                    help="first generate the file by running a sim "
                         "grant + an injected-fault retry + a serving "
                         "drain cycle with TPUSLICE_EVENT_FILE set, "
                         "then also check the flight-recorder contract")
    ap.add_argument("--lenient", action="store_true",
                    help="tolerate stale-read phantom transitions "
                         "(chaos-grade files)")
    ap.add_argument("--epochs", action="store_true",
                    help="crash-chaos mode: split chains on "
                         "attempt-epoch stamps / CrashRecovered "
                         "markers, require each restart epoch legal "
                         "and no grant chain abandoned without a "
                         "terminal reason (docs/RECOVERY.md)")
    ap.add_argument("--nemesis", action="store_true",
                    help="partition-chaos mode (composes with the "
                         "chain check): degraded-mode entries pair "
                         "with heal-side exits, WriteFenced events "
                         "attribute the deposed writer, no grant is "
                         "double-placed across partition epochs, no "
                         "slice leaks past heal")
    args = ap.parse_args(argv)
    granted_text = faulted_text = ""
    if args.drive:
        granted_text, faulted_text = drive(args.file)
    report = validate(args.file, strict=not args.lenient,
                      epochs=args.epochs)
    if args.nemesis:
        report["errors"].extend(check_nemesis(report["_events"]))
    if args.drive:
        check_drive_expectations(report, granted_text, faulted_text)
    print(json.dumps({
        "file": report["file"],
        "events": report["events"],
        "reasons": report["reasons"],
        "errors": report["errors"][:20],
        "ok": not report["errors"],
    }))
    return 0 if not report["errors"] else 1


if __name__ == "__main__":
    sys.exit(main())
