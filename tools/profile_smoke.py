"""Continuous-profiler CI smoke (``make profile-smoke``, < 60 s).

Stands up one CPU-sized serving replica and proves the contracts
docs/OBSERVABILITY.md "Profiling" promises:

1. **Bounded overhead** — the same loadgen workload runs twice in one
   process (shared jit caches): once with the profiler disarmed, once
   armed. The armed arm must keep >= 95% of the unprofiled arm's
   client tokens/sec (retries absorb CPU-scheduler noise and residual
   cold jit shapes in CI).
2. **Ledger reconciliation** — over the armed window, the scheduler's
   ``rounds_total`` delta equals the profiler's ``rounds_recorded``
   and the ``tpuslice_serve_profile_rounds_total`` counter; after
   quiesce the ring stops growing (idle wait-loops leak zero records).
3. **Valid Chrome trace export** — ``chrome_trace`` over the armed
   window's rounds/events/spans round-trips through JSON and contains
   at least one full round lane (a ``round/*`` slice plus its segment
   slices) for Perfetto to render.
4. **Waterfall** — at least one request's waterfall stitches from the
   rings with a terminal outcome and at least one stage.
5. **No mid-traffic compiles** — with ``TPUSLICE_COMPILE_GRACE``
   pinned low and a warm-up burst of the same traffic shape, the
   armed measured window journals zero ``CompileObserved`` events
   (a cold mid-run compile would both fail this gate and wreck the
   overhead bound — the two assertions back each other up).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # run as tools/profile_smoke.py
    sys.path.insert(0, REPO)

# the lazy first-dispatch decode compiles ride the warm-up burst; the
# grace window must close BEFORE the measured arms so a compile there
# would be loudly journaled (set before any scheduler is constructed)
GRACE_S = 2.0
os.environ["TPUSLICE_COMPILE_GRACE"] = str(GRACE_S)

#: the profile-smoke gate: armed tok/s >= OVERHEAD_FLOOR x unprofiled
OVERHEAD_FLOOR = 0.95

LOADGEN = dict(requests=24, concurrency=6, prompt_len=12,
               max_tokens=16, vocab=64, stream=True, timeout=60)


def check(cond: bool, msg: str, **ctx) -> None:
    if not cond:
        raise AssertionError(
            f"{msg}" + (f" | {json.dumps(ctx, default=str)}" if ctx
                        else "")
        )


def quiesce(sched, timeout: float = 10.0) -> None:
    import threading

    pacer = threading.Event()
    deadline = time.monotonic() + timeout
    eng = sched.engine
    while time.monotonic() < deadline and (
        eng.slots or sched.queue.qsize() or sched._ready
    ):
        pacer.wait(0.02)
    check(not eng.slots, "engine never quiesced",
          slots=len(eng.slots))


def run_arm(url: str, seed: int) -> dict:
    from instaslice_tpu.serving import loadgen

    report = loadgen.run(url, seed=seed, **LOADGEN)
    check(report["outcomes"]["hung"] == 0, "hung requests",
          outcomes=report["outcomes"])
    check(report["ok"] == LOADGEN["requests"],
          "not every request succeeded",
          report={k: report[k] for k in ("ok", "outcomes", "errors")})
    return report


def validate_chrome_trace(doc: dict) -> None:
    """Structural Chrome-trace-event validity + >= 1 full round lane:
    a ``round/*`` complete slice and segment slices under the same
    scheduler pid."""
    evs = doc.get("traceEvents")
    check(isinstance(evs, list) and evs, "traceEvents missing/empty")
    for ev in evs:
        check(ev.get("ph") in ("X", "i", "M"),
              "unknown trace-event phase", event=ev)
        check("pid" in ev and "ts" in ev, "trace event missing pid/ts",
              event=ev)
        if ev["ph"] == "X":
            check("dur" in ev and "tid" in ev and "name" in ev,
                  "complete event missing dur/tid/name", event=ev)
    rounds = [e for e in evs if e.get("ph") == "X"
              and str(e.get("name", "")).startswith("round/")]
    check(len(rounds) >= 1, "no round/* slice in the trace")
    round_pids = {e["pid"] for e in rounds}
    segs = [e for e in evs if e.get("cat") == "segment"
            and e.get("pid") in round_pids]
    check(len(segs) >= 1, "round lane has no segment slices")
    # dispatch must appear: a trace without the decode/spec dispatch
    # segment is a rounds-only skeleton, not a timeline
    check(any(e.get("name") == "dispatch" for e in segs),
          "no dispatch segment in any round",
          names=sorted({e.get("name") for e in segs}))


def main() -> int:
    import jax
    import jax.numpy as jnp

    from instaslice_tpu.api.constants import REASON_COMPILE_OBSERVED
    from instaslice_tpu.models.lm import ModelConfig, TpuLM
    from instaslice_tpu.obs.journal import get_journal
    from instaslice_tpu.obs.profiler import (
        Profiler,
        chrome_trace,
        debug_profile_payload,
        reset_profiler,
        waterfall_payload,
    )
    from instaslice_tpu.serving import ServingEngine
    from instaslice_tpu.serving.api_server import ApiServer

    t_start = time.time()
    cfg = ModelConfig(vocab_size=64, d_model=64, n_heads=2, n_layers=2,
                      d_ff=128, dtype=jnp.float32, remat=False)
    model = TpuLM(cfg)
    params = model.init(jax.random.key(0))
    eng = ServingEngine(model, params, max_batch=8, max_len=64,
                        prefill_len=8)
    # the warm window: prefill buckets (and spec shapes, none here)
    # compile before traffic; lazy decode shapes ride the warm-up burst
    eng.warm_prefill_buckets()
    eng.warm_spec_programs()

    prof = Profiler(armed=False)
    reset_profiler(prof)
    journal = get_journal()
    try:
        with ApiServer(eng, block_size=8, request_timeout=60) as srv:
            sched = srv.scheduler
            check(sched.profiler is prof,
                  "scheduler did not pick up the process profiler")

            # ---- warm-up: same traffic shape as the measured arms,
            # then wait out the compile grace window
            run_arm(srv.url, seed=7)
            quiesce(sched)
            time.sleep(GRACE_S + 0.3)

            ratio = 0.0
            for attempt in (1, 2, 3):
                # ---- arm A: unprofiled
                prof.disarm()
                rep_off = run_arm(srv.url, seed=8 + attempt)
                quiesce(sched)
                off_tps = rep_off["client_tokens_per_sec"]

                # ---- arm B: armed (fresh ring; ledger from here)
                rounds0 = sched.rounds_total
                rec0 = prof.rounds_recorded
                compiles0 = journal.counts().get(
                    REASON_COMPILE_OBSERVED, 0)
                prof.arm()
                rep_on = run_arm(srv.url, seed=20 + attempt)
                quiesce(sched)
                prof.disarm()
                on_tps = rep_on["client_tokens_per_sec"]

                ratio = on_tps / off_tps if off_tps else 0.0
                if ratio >= OVERHEAD_FLOOR:
                    break
                print(json.dumps({"retry": attempt, "ratio":
                                  round(ratio, 4)}), flush=True)
            check(ratio >= OVERHEAD_FLOOR,
                  f"armed arm kept < {OVERHEAD_FLOOR:.0%} of "
                  "unprofiled tok/s",
                  off_tps=off_tps, on_tps=on_tps,
                  ratio=round(ratio, 4))

            # ---- ledger reconciliation over the armed window
            recorded = prof.rounds_recorded - rec0
            rounds_delta = sched.rounds_total - rounds0
            check(recorded > 0, "armed arm recorded no rounds")
            check(recorded == rounds_delta,
                  "profiler ring != scheduler round counter",
                  recorded=recorded, rounds_total_delta=rounds_delta)
            counter = sched.metrics.profile_rounds
            metric_val = getattr(counter, "_value", None)
            if metric_val is not None:   # real prometheus counter
                check(int(metric_val.get()) == prof.rounds_recorded,
                      "profile_rounds metric != ring recorded",
                      metric=metric_val.get(),
                      recorded=prof.rounds_recorded)
            # zero ring entries leaked after quiesce: idle wait-loops
            # must not record
            settle = prof.rounds_recorded
            time.sleep(0.25)
            check(prof.rounds_recorded == settle,
                  "ring grew while idle",
                  before=settle, after=prof.rounds_recorded)

            # ---- zero mid-traffic compiles after warm-up
            compiles = journal.counts().get(
                REASON_COMPILE_OBSERVED, 0) - compiles0
            check(compiles == 0,
                  "CompileObserved during the measured window",
                  events=[e.to_dict() for e in journal.events(
                      reason=REASON_COMPILE_OBSERVED)])

            # ---- chrome trace export round-trips and renders a lane
            payload = debug_profile_payload({"n": ["512"]})
            doc = chrome_trace(rounds=payload["recent"],
                               events=payload["recentEvents"])
            doc = json.loads(json.dumps(doc))   # must survive JSON
            validate_chrome_trace(doc)

            # ---- >= 1 waterfall from a recorded round's rid
            rids = []
            for rec in prof.rounds():
                rids.extend(rec.meta.get("rids") or [])
            check(rids, "no rids in any round record")
            w = waterfall_payload(str(rids[-1]))
            check(w["outcome"] != "", "waterfall has no outcome",
                  waterfall=w)
            check(len(w["stages"]) >= 1, "waterfall has no stages",
                  waterfall=w)
            check(len(w["rounds"]) >= 1,
                  "waterfall joined no round records", waterfall=w)

            # ---- the HTTP surface serves the same payloads
            import urllib.request

            with urllib.request.urlopen(
                srv.url + "/v1/debug/profile?n=4", timeout=5
            ) as r:
                served = json.loads(r.read())
            check(served["rounds"] == prof.rounds_recorded,
                  "/v1/debug/profile drifted from the ring")
            with urllib.request.urlopen(
                srv.url + f"/v1/debug/profile?rid={rids[-1]}",
                timeout=5,
            ) as r:
                served_w = json.loads(r.read())
            check(served_w["traceId"] == w["traceId"],
                  "HTTP waterfall != in-process waterfall")

            print(json.dumps({
                "profile_smoke": "ok",
                "off_tokens_per_sec": off_tps,
                "on_tokens_per_sec": on_tps,
                "ratio": round(ratio, 4),
                "rounds_recorded": recorded,
                "trace_events": len(doc["traceEvents"]),
                "waterfall_outcome": w["outcome"],
                "wall_s": round(time.time() - t_start, 1),
            }))
            return 0
    finally:
        reset_profiler()


if __name__ == "__main__":
    sys.exit(main())
