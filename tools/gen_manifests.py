#!/usr/bin/env python
"""Regenerate config/crd/bases from the in-code schema.

The reference generates its CRD with controller-gen from Go struct markers
(``api/v1alpha1/instaslice_types.go`` → ``config/crd/bases/
inference.codeflare.dev_instaslices.yaml``); here the single source of
truth is :func:`instaslice_tpu.api.crd.crd_manifest` and this script is
the ``make manifests`` analog. ``tests/test_manifests.py`` fails if the
checked-in YAML drifts from the code.
"""

from __future__ import annotations

import os
import sys

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def crd_path() -> str:
    from instaslice_tpu import GROUP, PLURAL

    return os.path.join(
        REPO, "config", "crd", "bases", f"{PLURAL}.{GROUP}.yaml"
    )


def render_crd() -> str:
    from instaslice_tpu.api.crd import crd_manifest

    return yaml.safe_dump(crd_manifest(), sort_keys=False)


def main() -> int:
    path = crd_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    content = render_crd()
    if "--check" in sys.argv:
        with open(path) as f:
            if f.read() != content:
                print(f"{path} is stale; run tools/gen_manifests.py",
                      file=sys.stderr)
                return 1
        return 0
    with open(path, "w") as f:
        f.write(content)
    print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
