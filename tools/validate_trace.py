"""Validate a ``TPUSLICE_TRACE_FILE`` JSONL dump (and optionally
produce one first).

``python tools/validate_trace.py TRACE.jsonl`` checks structural
invariants every consumer of the trace format (``tpuslice
trace-summary``, docs/OBSERVABILITY.md tooling) relies on:

- every line parses as a JSON object with ``name``, ``start``, and
  ``durationMs``;
- no negative durations;
- no orphan spans: a non-empty ``parentId`` must name a ``spanId``
  that exists in the same trace (span completion order means parents
  are written AFTER their children — the whole file is one unit);
- no duplicate ``spanId`` within a trace.

``--drive`` first GENERATES the file by running the observability
path end to end in-process — a SimCluster pod grant/teardown plus a
short loadgen burst against a live ApiServer, with
``TPUSLICE_TRACE_FILE`` pointed at the output — then additionally
asserts the propagation contract: one trace id links
``controller.allocate`` → ``device.reserve`` → ``controller.ungate``
(the grant), and every ``serve.request`` root has child spans in its
trace (the serving plane). This is the ``make trace-check`` gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # run as tools/validate_trace.py
    sys.path.insert(0, REPO)


def validate(path: str) -> dict:
    """Structural validation. Returns a report dict; ``errors`` is the
    list that must stay empty for the file to pass."""
    errors: List[str] = []
    spans: List[dict] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                errors.append(f"line {lineno}: unparseable JSONL: {e}")
                continue
            if not isinstance(rec, dict):
                errors.append(f"line {lineno}: not a JSON object")
                continue
            missing = [k for k in ("name", "start", "durationMs")
                       if k not in rec]
            if missing:
                errors.append(f"line {lineno}: missing {missing}")
                continue
            if rec["durationMs"] < 0:
                errors.append(
                    f"line {lineno}: negative duration "
                    f"{rec['durationMs']} on span {rec['name']!r}"
                )
            spans.append(rec)

    # per-trace span-id index for orphan + duplicate detection
    by_trace: Dict[str, Dict[str, dict]] = {}
    for rec in spans:
        tid = rec.get("traceId", "")
        sid = rec.get("spanId", "")
        if not sid:
            continue
        ids = by_trace.setdefault(tid, {})
        if sid in ids:
            errors.append(
                f"duplicate spanId {sid!r} in trace {tid!r} "
                f"({ids[sid]['name']!r} vs {rec['name']!r})"
            )
        ids[sid] = rec
    for rec in spans:
        pid = rec.get("parentId", "")
        if pid and pid not in by_trace.get(rec.get("traceId", ""), {}):
            errors.append(
                f"orphan span {rec['name']!r} "
                f"(spanId {rec.get('spanId')!r}): parentId {pid!r} "
                f"not in trace {rec.get('traceId')!r}"
            )

    names: Dict[str, int] = {}
    for rec in spans:
        names[rec["name"]] = names.get(rec["name"], 0) + 1
    return {
        "file": path,
        "spans": len(spans),
        "traces": len(by_trace),
        "names": names,
        "errors": errors,
        # the parsed spans, for check_propagation: re-reading the file
        # would crash on exactly the corrupt lines validate() already
        # reported, hiding the real finding behind a traceback
        "_spans": spans,
    }


def check_propagation(report: dict) -> None:
    """--drive extra: the trace file must PROVE end-to-end propagation,
    not just parse. Appends to ``report['errors']``."""
    spans = report["_spans"]
    by_trace: Dict[str, List[dict]] = {}
    for rec in spans:
        by_trace.setdefault(rec.get("traceId", ""), []).append(rec)

    # one grant trace spans controller → device → ungate
    grant_ok = any(
        {"controller.allocate", "device.reserve", "controller.ungate"}
        <= {s["name"] for s in trace}
        for trace in by_trace.values()
    )
    if not grant_ok:
        report["errors"].append(
            "no trace links controller.allocate + device.reserve + "
            "controller.ungate — grant-path propagation is broken"
        )
    # every serving request's trace has children beside the root
    roots = [s for s in spans if s["name"] == "serve.request"]
    if not roots:
        report["errors"].append("no serve.request spans in the file")
    for root in roots:
        kids = [s for s in by_trace.get(root.get("traceId", ""), [])
                if s.get("parentId")]
        if not kids:
            report["errors"].append(
                f"serve.request trace {root.get('traceId')!r} has no "
                "child spans — serving-plane propagation is broken"
            )


def drive(path: str) -> None:
    """Produce ``path``: a pod grant/teardown in the sim plus a short
    loadgen burst against a live ApiServer, all traced to the file."""
    if os.path.exists(path):
        os.unlink(path)
    os.environ["TPUSLICE_TRACE_FILE"] = path
    from instaslice_tpu.utils.trace import reset_tracer

    reset_tracer()  # re-read the env: all spans now stream to `path`
    try:
        from instaslice_tpu.sim import SimCluster

        with SimCluster(n_nodes=1, deletion_grace_seconds=0.2) as c:
            c.submit("trace-check", "v5e-1x1")
            assert c.wait_phase("trace-check", "Running", timeout=30), \
                "sim pod never reached Running"
            c.delete_pod("trace-check")
            assert c.wait_gone("trace-check", timeout=30), \
                "sim pod never tore down"

        import jax
        import jax.numpy as jnp

        from instaslice_tpu.models.lm import ModelConfig, TpuLM
        from instaslice_tpu.serving import ServingEngine, loadgen
        from instaslice_tpu.serving.api_server import ApiServer

        cfg = ModelConfig(vocab_size=64, d_model=32, n_heads=2,
                          n_layers=2, d_ff=64, dtype=jnp.float32,
                          remat=False)
        model = TpuLM(cfg)
        eng = ServingEngine(model, model.init(jax.random.key(0)),
                            max_batch=4, max_len=64, prefill_len=8)
        with ApiServer(eng, block_size=4) as srv:
            report = loadgen.run(srv.url, requests=6, concurrency=2,
                                 prompt_len=4, max_tokens=4, vocab=64,
                                 stream=False, timeout=60)
            assert report["outcomes"]["hung"] == 0, report
            assert report["ok"] > 0, report
    finally:
        del os.environ["TPUSLICE_TRACE_FILE"]
        reset_tracer()  # close the file handle (and detach the env)


def check_fleet(paths: List[str]) -> dict:
    """``--fleet``: merge every collected file into ONE
    :class:`~instaslice_tpu.obs.telemetry.TraceStitcher` store and
    check for orphan parents ACROSS files. Per-file validation can
    pass while the fleet view is broken — a child span's parent may
    live in another process's file; only the merged view proves the
    collection set is complete."""
    from instaslice_tpu.obs.telemetry import TraceStitcher

    stitcher = TraceStitcher()
    total = 0
    for path in paths:
        total += stitcher.ingest_file(path)
    orphans = stitcher.orphans()
    return {
        "files": len(paths),
        "spans_ingested": total,
        "traces": len(stitcher.trace_ids()),
        "orphans": len(orphans),
        "orphan_examples": [
            {"name": s.get("name"), "traceId": s.get("traceId"),
             "parentId": s.get("parentId")}
            for s in orphans[:10]
        ],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="validate_trace")
    ap.add_argument("file", nargs="+",
                    help="trace JSONL path(s); several only with "
                         "--fleet")
    ap.add_argument("--drive", action="store_true",
                    help="first generate the file by running the sim "
                         "+ a short serving loadgen with "
                         "TPUSLICE_TRACE_FILE set, then also check "
                         "the propagation contract")
    ap.add_argument("--fleet", action="store_true",
                    help="merge every given file into one stitched "
                         "store and fail on orphan parents ACROSS "
                         "files (the fleet-collection completeness "
                         "check)")
    args = ap.parse_args(argv)
    if len(args.file) > 1 and not args.fleet:
        ap.error("multiple files need --fleet")
    if args.drive:
        drive(args.file[0])
    report = validate(args.file[0])
    for extra in args.file[1:]:
        sub = validate(extra)
        report["spans"] += sub["spans"]
        report["traces"] += sub["traces"]
        report["errors"] += sub["errors"]
    if args.fleet:
        # per-file orphan findings are FALSE failures in fleet mode: a
        # child's parent legitimately lives in another process's file;
        # the merged store below is the authoritative orphan check
        report["errors"] = [
            e for e in report["errors"]
            if not e.startswith("orphan span ")
        ]
    if args.drive:
        check_propagation(report)
    out = {
        "file": report["file"] if len(args.file) == 1 else args.file,
        "spans": report["spans"],
        "traces": report["traces"],
        "span_names": len(report["names"]),
        "errors": report["errors"][:20],
    }
    if args.fleet:
        fleet = check_fleet(args.file)
        out["fleet"] = fleet
        if fleet["orphans"]:
            report["errors"].append(
                f"{fleet['orphans']} orphan parent(s) across the "
                f"merged fleet store"
            )
            out["errors"] = report["errors"][:20]
    out["ok"] = not report["errors"]
    print(json.dumps(out))
    return 0 if not report["errors"] else 1


if __name__ == "__main__":
    sys.exit(main())
